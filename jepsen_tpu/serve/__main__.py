"""``python -m jepsen_tpu.serve`` — run the resident checker daemon.

Equivalent to ``jepsen-tpu serve --checker``; exists so the client's
auto-start (``JEPSEN_TPU_SERVICE=auto``, ``bench.py
--against-service``) has a suite-independent entry point.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.serve",
        description="resident checker service (doc/checker-service.md)",
    )
    p.add_argument("--host", default=None, help="bind address "
                   "(default 127.0.0.1 — the seam is local)")
    p.add_argument("--port", type=int, default=None,
                   help="TCP port (default JEPSEN_TPU_SERVE_PORT or 8519)")
    p.add_argument("--window", type=int, default=None,
                   help="resident dispatch-window bound "
                   "(default JEPSEN_TPU_ENGINE_WINDOW or 4)")
    p.add_argument("--max-queue", type=int, default=None,
                   help="admission bound: queued client runs before "
                   "/check answers 503 (default 8)")
    p.add_argument("--wal", default=None,
                   help="verdict write-ahead log path (default "
                   "JEPSEN_TPU_WAL or verdict-wal.jsonl; 'off' "
                   "disables crash-safe resumption)")
    p.add_argument("--supervise", action="store_true",
                   help="run the daemon as a supervised child and "
                   "restart it on abnormal exit (crash recovery; "
                   "doc/checker-service.md)")
    p.add_argument("--fleet", type=int, default=1, metavar="N",
                   help="with --supervise: run N daemons on ports "
                   "--port..--port+N-1 with per-member WAL/journal "
                   "paths and one shared AOT cache (doc/"
                   "checker-service.md \"Fleet tier\")")
    args = p.parse_args(argv)

    from . import daemon, protocol

    if args.fleet > 1 and not args.supervise:
        print("--fleet requires --supervise", file=sys.stderr)
        return 2
    if args.supervise:
        # re-exec ourselves minus the supervisor flags; the child
        # inherits the environment, so journal/WAL/jit-cache paths
        # carry over and a restart resumes where the crash left off
        raw = list(argv if argv is not None else sys.argv[1:])
        child = []
        skip = False
        for a in raw:
            if skip:
                skip = False
                continue
            if a == "--supervise":
                continue
            if a == "--fleet":
                skip = True
                continue
            if a.startswith("--fleet="):
                continue
            child.append(a)
        if args.fleet > 1:
            return daemon.supervise_fleet(args.fleet, child,
                                          base_port=args.port)
        return daemon.supervise(child)
    kw = {}
    if args.wal is not None:
        kw["wal_path"] = (
            None if args.wal.lower() in ("0", "false", "off", "no", "")
            else args.wal
        )
    daemon.serve(
        host=args.host or protocol.DEFAULT_HOST,
        port=args.port,
        window=args.window,
        max_queue_runs=args.max_queue,
        block=True,
        **kw,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
