"""``python -m jepsen_tpu.serve`` — run the resident checker daemon.

Equivalent to ``jepsen-tpu serve --checker``; exists so the client's
auto-start (``JEPSEN_TPU_SERVICE=auto``, ``bench.py
--against-service``) has a suite-independent entry point.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.serve",
        description="resident checker service (doc/checker-service.md)",
    )
    p.add_argument("--host", default=None, help="bind address "
                   "(default 127.0.0.1 — the seam is local)")
    p.add_argument("--port", type=int, default=None,
                   help="TCP port (default JEPSEN_TPU_SERVE_PORT or 8519)")
    p.add_argument("--window", type=int, default=None,
                   help="resident dispatch-window bound "
                   "(default JEPSEN_TPU_ENGINE_WINDOW or 4)")
    p.add_argument("--max-queue", type=int, default=None,
                   help="admission bound: queued client runs before "
                   "/check answers 503 (default 8)")
    args = p.parse_args(argv)

    from . import daemon, protocol

    daemon.serve(
        host=args.host or protocol.DEFAULT_HOST,
        port=args.port,
        window=args.window,
        max_queue_runs=args.max_queue,
        block=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
