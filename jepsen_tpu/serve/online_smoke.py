"""Online-checking smoke: ``python -m jepsen_tpu.serve.online_smoke``.

Brings a resident checker daemon up in-process (ephemeral port, a
verdict WAL in a temp directory) and proves the live-verification
acceptance gates on both kernel routes (dense automaton, and the
generic frontier kernel via an explicit closure cap):

- **early detection**: a batch containing injected violations
  (``synth.generate_history(corrupt=True)``) is fed incrementally
  through one ``POST /feed`` session, and the first ``valid? ==
  False`` verdict for that session arrives on a concurrent ``GET
  /watch`` subscription strictly BEFORE the feed is closed — the
  monitor sees the violation while the "run" is still in flight;
- **verdict byte-equality**: the settled results the feed close
  returns are byte-identical (canonical JSON) to the in-process
  ``wgl.check_batch`` of the same batch — streaming ingest changes
  *when* violations surface, never *what* the verdict is;
- **op-granularity ingest**: the same gates hold when the session is
  fed raw history events (invocations AND completions, in
  history-append order — the interpreter shipper's wire shape)
  instead of whole histories, with the assembled-history verdict at
  close byte-identical to the batch check of that history;
- **telemetry**: the feed/watch metric families
  (``jepsen_feed_sessions_total``, ``jepsen_feed_deltas_total``,
  ``jepsen_feed_ingest_lag_seconds``, ``jepsen_watch_events_total``)
  record on ``/metrics``, and the run-level
  ``jepsen_run_first_violation_seconds`` gauge is set once verdicts
  settle.

Wired into ``make online-smoke`` / ``make check``.  Exit codes: 0 ok,
1 any gate failed.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time


def main(argv=None) -> int:
    from jepsen_tpu import models as m
    from jepsen_tpu import obs
    from jepsen_tpu.ops import wgl
    from jepsen_tpu.serve import CheckerDaemon, ServiceClient
    from jepsen_tpu.serve.smoke import _canon, _corpus_b, _metric_value

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    obs.enable(reset=True)
    model = m.cas_register(0)
    batch = _corpus_b()  # contains corrupt (violating) histories
    configs = {
        "dense": dict(slot_cap=32, max_dispatch=4),
        "frontier": dict(slot_cap=32, max_dispatch=4, max_closure=9),
    }

    tmp = tempfile.mkdtemp(prefix="jepsen-online-")
    daemon = CheckerDaemon(port=0,
                           wal_path=os.path.join(tmp, "wal.jsonl"))
    daemon.start(block=False)
    try:
        client = ServiceClient(port=daemon.port)
        check(client.healthy(), "daemon did not come up healthy")

        def spawn_watcher():
            """A /watch subscriber from the current WAL tail; events
            accumulate as (arrival monotonic, offset, row)."""
            events = []
            start = daemon.status().get("wal_rows", 0) - 1

            def _tail():
                try:
                    for off, row in client.watch(last_id=start,
                                                 timeout=10.0):
                        events.append((time.monotonic(), off, row))
                except Exception:  # noqa: BLE001 — thread must not die loud
                    pass

            threading.Thread(target=_tail, daemon=True).start()
            return events

        def first_violation(events, sid):
            for t, off, row in list(events):
                if (row.get("req") == sid
                        and (row.get("result") or {}).get("valid?")
                        is False):
                    return t
            return None

        def await_violation(events, sid, wait_s=15.0):
            deadline = time.monotonic() + wait_s
            while time.monotonic() < deadline:
                t = first_violation(events, sid)
                if t is not None:
                    return t
                time.sleep(0.05)
            return None

        # == gate 1+2: incremental history feed, both kernel routes ==
        for route, kw in configs.items():
            expected = wgl.check_batch(model, batch, **kw)
            events = spawn_watcher()
            time.sleep(0.3)  # let the subscriber attach
            session = client.open_feed(model, kw)
            for h in batch:
                session.append(histories=[h], t_inv=time.time())
            # the violation must be on the wire BEFORE the close
            t_violation = await_violation(events, session.sid)
            check(t_violation is not None,
                  f"{route}: no violation verdict reached /watch "
                  "while the feed was open")
            t_close = time.monotonic()
            results = session.close()
            check(t_violation is not None and t_violation < t_close,
                  f"{route}: violation event did not precede close")
            check(len(results) == len(batch),
                  f"{route}: feed close returned {len(results)} "
                  f"results for {len(batch)} histories")
            check(_canon(results) == _canon(expected),
                  f"{route}: streamed verdicts diverged from the "
                  "in-process batch check")

        # == gate 3: op-granularity ingest (the shipper wire shape) ==
        kw = configs["dense"]
        expected = wgl.check_batch(model, batch, **kw)
        bad_i = next(i for i, r in enumerate(expected)
                     if r.get("valid?") is False)
        bad_h = batch[bad_i]
        events = spawn_watcher()
        time.sleep(0.3)
        session = client.open_feed(model, kw)
        op_dicts = bad_h.to_dicts()
        for i in range(0, len(op_dicts), 5):
            session.append(ops=op_dicts[i:i + 5], t_inv=time.time())
        t_violation = await_violation(events, session.sid)
        check(t_violation is not None,
              "ops feed: no violation verdict reached /watch while "
              "the feed was open")
        t_close = time.monotonic()
        results = session.close()
        check(t_violation is not None and t_violation < t_close,
              "ops feed: violation event did not precede close")
        check(results and _canon(results[-1:])
              == _canon(wgl.check_batch(model, [bad_h], **kw)),
              "ops feed: assembled-history verdict diverged from the "
              "in-process check")

        # == gate 4: telemetry ==
        mtext = client.metrics_text()
        for name in ("jepsen_feed_sessions_total",
                     "jepsen_feed_deltas_total",
                     "jepsen_feed_histories_total",
                     "jepsen_watch_events_total"):
            check((_metric_value(mtext, name) or 0) > 0,
                  f"/metrics missing live {name}")
        check((_metric_value(
            mtext, "jepsen_feed_ingest_lag_seconds_count") or 0) > 0,
            "ingest-lag histogram never observed a delta")
        reg = obs.registry()
        check(reg.value("jepsen_run_first_verdict_seconds") is not None,
              "jepsen_run_first_verdict_seconds gauge never set")
        check(reg.value("jepsen_run_first_violation_seconds")
              is not None,
              "jepsen_run_first_violation_seconds gauge never set")
    finally:
        daemon.stop()

    if failures:
        for f_ in failures:
            print(f"online-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "online-smoke: ok (dense + frontier routes; injected violation "
        "reached /watch before feed close, streamed verdicts "
        "byte-identical to the batch check, op-granularity ingest "
        "matched, feed/watch telemetry live)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
