"""Wire protocol for the resident checker service.

The service seam is deliberately boring: JSON-with-tuples
(:mod:`jepsen_tpu.codec` — the same encoding client payloads already
use) over local HTTP.  The paper's ``check(self, test, history,
opts)`` protocol stays the client API; this module only defines how a
batch crosses the process boundary to the daemon that owns the
device.

Endpoints (doc/checker-service.md):

- ``POST /check`` — body ``{"model": <wire model>, "histories":
  [[<op dict>, ...], ...], "opts": {...}}`` → ``{"results": [...],
  "diag": {...}}``.  Results are exactly the dicts
  ``engine.pipeline.run`` produces for the same batch (serve-smoke
  pins byte-equality of the two paths).
- ``GET /healthz`` — liveness: ``{"ok": true, "platform": ...}``.
- ``GET /status`` — queue depth, in-flight, counters, uptime.
- ``GET /metrics`` — live Prometheus exposition
  (``obs.render_prom``), the same formatter as ``metrics.prom``.
- ``POST /feed`` — streaming ingest (doc/checker-service.md "Online
  checking"): one body schema, discriminated by ``"op"`` —
  ``open`` (model + opts → ``{"session": id}``), ``append`` (history
  or op-dict deltas under a session, idempotent by ``seq``), and
  ``close`` (final merged results, byte-identical to a ``/check`` of
  the same work).
- ``GET /watch`` — settled verdicts as server-sent events tailing the
  verdict WAL; ``Last-Event-ID`` (= WAL row offset) resumes a
  reconnecting watcher without replaying anything twice.
- ``POST /shutdown`` — drain in-flight work, then stop.

Model serialization covers every model with a device ``ModelSpec``
plus the plain seeds the workloads construct; anything else makes
:func:`model_to_wire` raise ``UnsupportedModel`` and the client falls
back to the in-process engine — the service never guesses at state it
cannot round-trip.

``opts`` keys mirror ``wgl.check_batch`` keyword arguments
(``frontier``, ``slot_cap``, ``max_closure``, ``escalation``,
``oracle_fallback``, ``sufficient_rung``, ``max_dispatch``).
``oracle_budget_s`` is deliberately NOT serviceable: the budget is a
wall-clock deadline whose semantics assume the run's own serial drain
pass; concurrent service clients sharing the GIL would burn it
unpredictably faster, so budgeted runs stay in-process (the client
enforces this, see :meth:`ServiceClient.check_batch`).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .. import codec
from ..history import History

#: default TCP port for the local daemon (loopback only); override
#: with JEPSEN_TPU_SERVE_PORT or --port
DEFAULT_PORT = 8519
DEFAULT_HOST = "127.0.0.1"

#: check_batch kwargs a client may forward over the wire
CHECK_OPTS = (
    "frontier", "slot_cap", "max_closure", "escalation",
    "oracle_fallback", "sufficient_rung", "max_dispatch",
)


class UnsupportedModel(ValueError):
    """The model's state cannot be round-tripped over the wire; the
    caller should fall back to the in-process engine."""


def _plain(v):
    """Reject values the codec would mangle (sets, objects, non-string
    dict keys — JSON stringifies those silently) early, so unsupported
    model state falls back instead of corrupting."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return type(v)(_plain(x) for x in v)
    if isinstance(v, (dict,)):
        for k in v:
            if not isinstance(k, str):
                # JSON would turn key 0 into "0" and the daemon would
                # reconstruct a DIFFERENT model — use _kv_pairs for
                # state dicts whose keys are arbitrary values
                raise UnsupportedModel(
                    f"non-string dict key in model state: {k!r}")
        return {k: _plain(x) for k, x in v.items()}
    if isinstance(v, frozenset):
        # order-normalized: wire form is a sorted list (the models
        # using frozensets — unordered queue — are order-free)
        return sorted((_plain(x) for x in v), key=repr)
    raise UnsupportedModel(f"unserializable model state: {v!r}")


def _kv_pairs(d: dict) -> list:
    """Lossless wire form for a state dict with arbitrary keys: a
    sorted ``[key, value]`` pair list.  JSON object keys are always
    strings, so ``{0: 0}`` through a plain dict would come back as
    ``{"0": 0}`` — a different model and therefore wrong verdicts
    (multi-register workloads key registers by int, synth.py)."""
    return sorted(
        ([_plain(k), _plain(v)] for k, v in d.items()), key=repr
    )


def _from_kv_pairs(pairs) -> dict:
    return {tuple(k) if isinstance(k, list) else k: v for k, v in pairs}


def model_to_wire(model) -> dict:
    """Serialize a model for the wire; raises :class:`UnsupportedModel`
    for models whose state has no registered extraction."""
    from .. import models as m
    from ..models import locks as lock_models

    if isinstance(model, m.Register) and not isinstance(model, m.CASRegister):
        return {"type": "register", "value": _plain(model.value)}
    if isinstance(model, m.CASRegister):
        return {"type": "cas-register", "value": _plain(model.value)}
    if type(model) is m.Mutex:
        return {"type": "mutex", "locked": bool(model.locked)}
    if isinstance(model, m.MultiRegister):
        # kv-pair form, NOT a JSON object: register keys are commonly
        # ints (synth's multi_register({k: 0 ...})) and JSON object
        # keys stringify silently — a different model, wrong verdicts
        return {"type": "multi-register",
                "values": _kv_pairs(model._as_dict())}
    if isinstance(model, m.FIFOQueue):
        return {"type": "fifo-queue", "items": _plain(list(model.items))}
    if isinstance(model, m.UnorderedQueue):
        return {"type": "unordered-queue",
                "items": _plain(model.items)}
    if type(model) is m.MultiMutex:
        # held set is order-free, like the unordered queue's multiset
        return {"type": "multi-mutex", "held": _plain(model.held)}
    if type(model) is lock_models.OwnerMutex:
        return {"type": "owner-mutex", "owner": _plain(model.owner)}
    raise UnsupportedModel(
        f"no wire form for model {type(model).__name__}; "
        "the client runs this batch in-process"
    )


def model_from_wire(d: dict):
    from .. import models as m
    from ..models import locks as lock_models

    t = d.get("type")
    if t == "register":
        return m.register(d.get("value"))
    if t == "cas-register":
        return m.cas_register(d.get("value"))
    if t == "mutex":
        return m.mutex() if not d.get("locked") else m.Mutex(True)
    if t == "multi-register":
        return m.multi_register(_from_kv_pairs(d.get("values") or []))
    if t == "fifo-queue":
        return m.FIFOQueue(tuple(d.get("items") or ()))
    if t == "unordered-queue":
        return m.UnorderedQueue(frozenset(d.get("items") or ()))
    if t == "multi-mutex":
        return m.MultiMutex(frozenset(d.get("held") or ()))
    if t == "owner-mutex":
        return lock_models.OwnerMutex(d.get("owner"))
    raise UnsupportedModel(f"unknown wire model type {t!r}")


def histories_to_wire(histories) -> List[list]:
    return [h.to_dicts() for h in histories]


def histories_from_wire(dicts: List[list]) -> List[History]:
    out = []
    for ds in dicts:
        h = History.from_dicts(ds)
        out.append(h)
    return out


def sanitize_results(results: List[Optional[dict]]) -> List[dict]:
    """Engine result dicts, made wire-safe: JSON-native leaves pass
    through untouched (verdict byte-equality with the in-process path
    depends on it); anything exotic an oracle analysis attached
    (model objects in sampled configs, exceptions) degrades to repr."""
    out = []
    for r in results:
        out.append({k: _wire_safe(v) for k, v in (r or {}).items()})
    return out


def _wire_safe(v):
    if isinstance(v, (str, bool)) or v is None:
        return v
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, (list, tuple)):
        return type(v)(_wire_safe(x) for x in v)
    if isinstance(v, dict):
        return {str(k): _wire_safe(x) for k, x in v.items()}
    try:  # numpy scalars
        import numpy as np

        if isinstance(v, np.generic):
            return v.item()
    except Exception:  # noqa: BLE001 — repr fallback below
        pass
    return repr(v)


def encode_body(payload: Any) -> bytes:
    return codec.encode(payload)


def decode_body(data: bytes) -> Any:
    return codec.decode(data)


class WireGraph:
    """Server-side view of one encoded screen graph: the same duck
    shape ``ops.cycles.screen_graphs`` consumes
    (:class:`jepsen_tpu.elle.encode.EncodedGraph` client-side)."""

    __slots__ = ("rel", "n", "masks", "nonadj")

    def __init__(self, rel, masks, nonadj):
        import numpy as np

        self.rel = np.asarray(rel, dtype=np.uint8)
        self.n = self.rel.shape[0]
        self.masks = tuple(int(m) for m in masks)
        self.nonadj = tuple((int(w), int(r)) for w, r in nonadj)


def request_id() -> str:
    """A fresh idempotent request id — the client mints one per
    logical request and reuses it verbatim across retries, so the
    daemon can dedupe a retried ``/check``/``/elle`` (never
    double-counting it) and key the request's verdict-WAL rows."""
    import uuid

    return uuid.uuid4().hex


def elle_request(encs, trace_ctx: Optional[Dict[str, Any]] = None,
                 req: Optional[str] = None) -> bytes:
    """Build a ``POST /elle`` body from encoded graphs
    (:class:`jepsen_tpu.elle.encode.EncodedGraph`): per graph the
    uint8 relation-bit matrix plus its canonical filter profile.
    ``trace_ctx`` (obs.propagate) rides along so the daemon's spans
    link back to the caller's trace; ``req`` is the idempotent
    request id (:func:`request_id`) the daemon dedupes retries by."""
    body = {
        "graphs": [
            {
                "rel": [[int(x) for x in row] for row in enc.rel],
                "masks": list(enc.masks),
                "nonadj": [list(p) for p in enc.nonadj],
            }
            for enc in encs
        ],
    }
    if trace_ctx:
        body["trace_ctx"] = dict(trace_ctx)
    if req:
        body["req"] = req
    return encode_body(body)


def elle_graphs_from_wire(items) -> List[WireGraph]:
    return [
        WireGraph(g["rel"], g.get("masks") or (),
                  g.get("nonadj") or ())
        for g in items
    ]


def elle_results_to_wire(results) -> list:
    """Per-graph screen masks as JSON: members/walks aligned with the
    request's canonical (sorted) masks/nonadj tuples; ``None`` (graph
    past the dispatch budget) crosses as null so the client keeps that
    graph on its CPU path."""
    out = []
    for r in results:
        if r is None:
            out.append(None)
            continue
        out.append({
            "members": [
                [int(b) for b in r.members[m]] for m in sorted(r.members)
            ],
            "walks": [
                [int(b) for b in r.walks[q]] for q in sorted(r.walks)
            ],
        })
    return out


def elle_results_from_wire(items, encs) -> list:
    """Client-side inverse of :func:`elle_results_to_wire`, re-keyed
    by each graph's own canonical masks (the wire order IS the sorted
    tuple order both sides computed independently)."""
    import numpy as np

    from ..ops.cycles import ScreenResult

    out = []
    for enc, item in zip(encs, items):
        if item is None:
            out.append(None)
            continue
        members = {
            m: np.asarray(row, dtype=bool)
            for m, row in zip(sorted(enc.masks), item["members"])
        }
        walks = {
            q: np.asarray(row, dtype=bool)
            for q, row in zip(sorted(enc.nonadj), item["walks"])
        }
        out.append(ScreenResult(members, walks))
    return out


def check_request(model, histories, opts: Optional[Dict[str, Any]] = None,
                  trace_ctx: Optional[Dict[str, Any]] = None,
                  req: Optional[str] = None) -> bytes:
    """Build a ``POST /check`` body; raises :class:`UnsupportedModel`
    when the model (or an opt) has no wire form.  ``trace_ctx``
    (obs.propagate ``{"trace_id", "parent_sid"}``) is optional and
    never affects verdicts: it only tags the daemon-side spans so one
    service-routed run exports one stitched Chrome trace.  ``req`` is
    the idempotent request id (:func:`request_id`): a retried request
    carries the same id, so the daemon can answer from its completed-
    response cache or resume the request's verdict-WAL rows instead of
    double-counting the work."""
    wire_opts = _check_opts_to_wire(opts)
    body = {
        "model": model_to_wire(model),
        "histories": histories_to_wire(histories),
        "opts": wire_opts,
    }
    if trace_ctx:
        body["trace_ctx"] = dict(trace_ctx)
    if req:
        body["req"] = req
    return encode_body(body)


def _check_opts_to_wire(opts: Optional[Dict[str, Any]]) -> dict:
    """Validate + normalize serviceable check opts (the shared half of
    :func:`check_request` / :func:`feed_open_request`)."""
    wire_opts = {}
    for k, v in (opts or {}).items():
        if k not in CHECK_OPTS:
            raise UnsupportedModel(f"opt {k!r} is not serviceable")
        if k == "escalation" and v is not None:
            v = list(v)
        wire_opts[k] = v
    return wire_opts


def feed_open_request(model, opts: Optional[Dict[str, Any]] = None,
                      trace_ctx: Optional[Dict[str, Any]] = None,
                      req: Optional[str] = None) -> bytes:
    """Build a ``POST /feed`` session-open body.  ``req`` doubles as
    the session's verdict-WAL run id: a feed session re-opened after a
    daemon crash under the SAME id replays its settled partitions
    instead of re-dispatching them (same resume contract as /check
    retries).  Model/opts validation mirrors :func:`check_request` —
    an unserviceable model or opt raises :class:`UnsupportedModel`
    before any bytes hit the wire."""
    body = {
        "op": "open",
        "model": model_to_wire(model),
        "opts": _check_opts_to_wire(opts),
    }
    if trace_ctx:
        body["trace_ctx"] = dict(trace_ctx)
    if req:
        body["req"] = req
    return encode_body(body)


def feed_append_request(session: str, seq: int,
                        histories=None, ops=None,
                        t_inv: Optional[float] = None) -> bytes:
    """Build a ``POST /feed`` delta-append body.  ``seq`` is the
    session-monotonic delta number — the daemon acks an
    already-ingested seq without re-dispatching, so a client may
    retry an append after a lost response.  A delta carries whole
    ``histories`` (checked incrementally as independent rows) and/or
    raw completed-op dicts ``ops`` (the interpreter's live shipper —
    accumulated server-side and probed per partition as they arrive).
    ``t_inv`` is the wall-clock invoke time of the delta's oldest op,
    feeding the ``jepsen_feed_ingest_lag_seconds`` detect-minus-invoke
    histogram."""
    body: Dict[str, Any] = {"op": "append", "session": session,
                            "seq": int(seq)}
    if histories:
        body["histories"] = histories_to_wire(histories)
    if ops:
        body["ops"] = list(ops)
    if t_inv is not None:
        body["t_inv"] = float(t_inv)
    return encode_body(body)


def feed_close_request(session: str, seq: int,
                       req: Optional[str] = None) -> bytes:
    """Build a ``POST /feed`` session-close body: the daemon runs the
    authoritative final check (op-mode sessions check the complete
    assembled history; history-mode sessions are already fully
    settled), drains oracles, and answers with merged results
    byte-identical to a ``/check`` of the same work.  ``req`` keys the
    close response in the idempotent-retry cache."""
    body: Dict[str, Any] = {"op": "close", "session": session,
                            "seq": int(seq)}
    if req:
        body["req"] = req
    return encode_body(body)
