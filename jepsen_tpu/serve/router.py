"""The fleet routing front: one process that owns NO device, only the
map from request shape keys to the fleet member daemons that do.

Why a router (ROADMAP item 1, doc/checker-service.md "Fleet tier"):
one resident daemon amortizes jit compiles across runs, but its win
evaporates the moment same-shape traffic is sprayed across N daemons —
every member pays its own cold compile for every shape.  The router
**rendezvous-hashes** each request's shape key (the wire model +
planning opts + the pow2 history-length bucket multiset for ``/check``;
the graph vertex-bucket multiset for ``/elle``), so same-shape traffic
from different clients lands on ONE member's resident executor and
coalesces there, while different shapes spread across the fleet.
Rendezvous (highest-random-weight) hashing gives the bounded-movement
property the fleet needs: adding or removing one member re-routes only
that member's share of keys (tests/test_router.py pins it).

Robustness semantics, in hash order:

- a member's **tripped breaker** (serve.client.CircuitBreaker — the
  same class, the same taxonomy) spills that key's traffic to the next
  member in rendezvous order (``jepsen_route_spillover_total``);
- a **connection-level failure** mid-forward records on the breaker
  and reroutes the request to the next candidate in the SAME request
  (``jepsen_route_reroutes_total``) — safe because clients send
  idempotent request ids, so a request that half-ran on a dying member
  is recomputed (or WAL-replayed) by the sibling, never double-counted;
- a **dead member** is marked down by the background ``/healthz``
  prober within one probe interval (``JEPSEN_TPU_ROUTE_PROBE_INTERVAL``)
  and its keys re-route without waiting for a connection error;
- **admission-control 503s propagate untouched** — backpressure is the
  member's verdict about its own queue, and the client's in-process
  fallback (not a blind retry on a sibling that may be equally loaded)
  is the correct relief valve;
- ``/feed`` sessions are **pinned**: a session's state (the growing
  DecomposedRun) lives on the member that opened it, so appends/closes
  follow the pin and a dead pinned member answers 503 rather than
  silently re-opening an empty session elsewhere.

The router never decodes results and never re-encodes bodies — raw
bytes pass through both ways (verdict byte-equality with the
in-process engine survives routing by construction); the body is
decoded ONCE, read-only, to derive the shape key.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from .. import obs
from . import protocol
from .client import (DEFAULT_CLIENT_TIMEOUT_S, breaker_for, probe_healthz)

#: how often the background prober sweeps member /healthz (seconds);
#: a dead member's keys re-route within one interval
DEFAULT_PROBE_INTERVAL_S = 1.0
#: per-probe timeout — short: the probe is loopback/LAN liveness, not
#: device work
DEFAULT_PROBE_TIMEOUT_S = 0.5


def _env_pos_float(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, default))
    except ValueError:
        return default
    return v if v > 0 else default


#: weighted-rendezvous floor: a fully busy member keeps a sliver of
#: weight so it still wins SOME keys (total starvation would dump its
#: whole share on siblings at once — the opposite of bounded movement)
MIN_ROUTE_WEIGHT = 0.05

#: sha1 digests span [0, 2^160); +1/+2 keep the fraction strictly
#: inside (0, 1) so log() below is finite and negative
_HASH_SPAN = float(1 << 160)


def weight_from_busy(busy: Optional[float]) -> float:
    """Routing weight for a reported device-busy ratio:
    ``max(MIN_ROUTE_WEIGHT, 1 − clamp(busy, 0, 1))``.  ``None`` — no
    report at all — stays NEUTRAL (1.0): weighting punishes only a
    member that positively reports load, never one that fails to
    report.  Shared with the ``status`` fleet table so the operator
    view prints the same number the prober feeds into
    :func:`rendezvous_order`."""
    if busy is None:
        return 1.0
    return max(MIN_ROUTE_WEIGHT, 1.0 - min(1.0, max(0.0, busy)))


def rendezvous_order(members: List[str], key: str,
                     weights: Optional[Dict[str, float]] = None,
                     ) -> List[str]:
    """Members by descending rendezvous (highest-random-weight) score
    for ``key``.  Each (member, key) pair scores independently, so
    removing a member re-ranks NOTHING among the survivors — only the
    removed member's keys move, each to its own second choice — and a
    new member takes exactly the keys it now wins.  sha1 here is a
    uniform hash, not a security boundary.

    ``weights`` (member → weight, default/missing = 1.0) scales each
    member's score the standard weighted-rendezvous way: the digest
    becomes a uniform fraction u ∈ (0, 1) and the score is
    ``-w / ln(u)``, so a member's expected key share is proportional
    to its weight.  The transform is monotone in u, so with equal
    weights the ordering is EXACTLY the unweighted descending-digest
    order (the legacy tests keep pinning it), and lowering only one
    member's weight moves only keys that member was winning — the
    per-member analogue of the membership bounded-movement property
    (the busy-ratio prober feeds this; doc/checker-service.md)."""
    def score(m: str):
        h = int(hashlib.sha1(f"{m}|{key}".encode()).hexdigest(), 16)
        w = 1.0
        if weights:
            w = max(MIN_ROUTE_WEIGHT, float(weights.get(m, 1.0)))
        u = (h + 1.0) / (_HASH_SPAN + 2.0)
        return (-w / math.log(u), h)

    return sorted(members, key=score, reverse=True)


def _pow2_bucket(n: int) -> int:
    """The planner's shape-coalescing intuition, router-side: history
    lengths (and graph vertex counts) pad to buckets, so two batches
    whose lengths share pow2 buckets compile the same executables."""
    return 1 << (max(1, int(n)) - 1).bit_length()


def check_route_key(payload: dict) -> str:
    """The ``/check`` shape key: wire model + the serviceable planning
    opts + the sorted pow2 history-length bucket multiset — a
    deterministic, cheap stand-in for the (E, C) buckets the planner
    will derive, computable without encoding anything.  Same model +
    opts + length profile ⇒ same compiled executables ⇒ one member."""
    opts = payload.get("opts") or {}
    buckets = sorted(
        _pow2_bucket(len(h)) for h in (payload.get("histories") or [])
    )
    return json.dumps(
        ["check", payload.get("model"),
         {k: opts.get(k) for k in protocol.CHECK_OPTS if k in opts},
         buckets],
        sort_keys=True, default=repr)


def elle_route_key(payload: dict) -> str:
    """The ``/elle`` shape key: the sorted pow2 vertex-bucket multiset
    of the batch's relation matrices (the screen pads graphs to vertex
    buckets, so the bucket profile determines the executables)."""
    buckets = sorted(
        _pow2_bucket(len(g.get("rel") or ())) for g in
        (payload.get("graphs") or [])
    )
    return json.dumps(["elle", buckets], sort_keys=True)


class RouteError(Exception):
    """Connection-level forward failure — the reroute trigger (HTTP
    error codes are NOT this: a member's 503/500 is an answer)."""


class Router:
    """The routing front.  ``start(block=False)`` returns once the
    listener and prober are up; ``port`` then holds the bound port."""

    def __init__(
        self,
        members: List[str],
        host: str = protocol.DEFAULT_HOST,
        port: int = 0,
        *,
        probe_interval_s: Optional[float] = None,
        probe_timeout_s: Optional[float] = None,
        forward_timeout_s: float = DEFAULT_CLIENT_TIMEOUT_S,
    ):
        if not members:
            raise ValueError("a router needs at least one --member")
        self.members = list(dict.fromkeys(members))  # repeatable, deduped
        self.host = host
        self.port = port
        self.probe_interval_s = (
            _env_pos_float("JEPSEN_TPU_ROUTE_PROBE_INTERVAL",
                           DEFAULT_PROBE_INTERVAL_S)
            if probe_interval_s is None else probe_interval_s
        )
        self.probe_timeout_s = (
            _env_pos_float("JEPSEN_TPU_ROUTE_PROBE_TIMEOUT",
                           DEFAULT_PROBE_TIMEOUT_S)
            if probe_timeout_s is None else probe_timeout_s
        )
        self.forward_timeout_s = forward_timeout_s
        self.t_start = time.time()
        self._lock = threading.Lock()
        #: prober-maintained liveness map; a member starts optimistic
        #: (True) so the first request needn't wait a probe interval
        self._up: Dict[str, bool] = {m: True for m in self.members}  # jt: guarded-by(_lock)
        #: prober-maintained routing weights (1 − busy ratio from the
        #: member's /status live block); a member starts — and on any
        #: stale/unreachable status falls back to — neutral 1.0, so
        #: weighting can only ever shift keys AWAY from a member that
        #: positively reported itself busy
        self._weights: Dict[str, float] = {m: 1.0 for m in self.members}  # jt: guarded-by(_lock)
        #: /feed session pins: sid -> member owning the session state
        self._pins: Dict[str, str] = {}  # jt: guarded-by(_lock)
        self._stopping = threading.Event()
        self._server: Optional[ThreadingHTTPServer] = None
        self._prober: Optional[threading.Thread] = None

    # -- membership (prober thread) ----------------------------------------

    def _probe_loop(self) -> None:  # jt: thread-entry
        while not self._stopping.is_set():
            self.probe_once()
            self._stopping.wait(self.probe_interval_s)

    def probe_once(self) -> int:
        """One /healthz sweep over the membership; returns the number
        of members currently up.  Public so tests and the smoke can
        force a deterministic sweep instead of sleeping an interval.

        The sweep doubles as the busy-ratio refresh: each live member's
        ``/status`` live block reports ``device_busy_ratio`` (its
        flight-recorder duty cycle), and the routing weight becomes
        ``max(MIN_ROUTE_WEIGHT, 1 − busy)`` — a saturated member sheds
        a proportional share of its keys to rendezvous runners-up while
        idle members keep their full share.  A member whose status is
        unreachable, stale, or busy-free stays NEUTRAL (1.0): weighting
        never punishes a member for failing to report, only for
        positively reporting load (down members are already handled by
        the liveness partition in :meth:`_candidates`)."""
        n_up = 0
        for m in self.members:
            ok = probe_healthz(m, timeout=self.probe_timeout_s)
            if ok:
                n_up += 1
            else:
                obs.count("jepsen_route_probe_failures_total", member=m)
            weight = 1.0
            if ok:
                weight = weight_from_busy(self._member_busy_ratio(m))
            obs.gauge_set("jepsen_route_weight", weight, member=m)
            with self._lock:
                self._up[m] = ok
                self._weights[m] = weight
        obs.gauge_set("jepsen_route_members_up", n_up)
        return n_up

    def _member_busy_ratio(self, member: str) -> Optional[float]:
        """One member's ``device_busy_ratio`` from its ``/status`` live
        block, or None when the member doesn't answer, answers
        something that isn't a status body, or reports no numeric
        ratio.  Never raises — a malformed status must read as
        'neutral', not take the prober thread down."""
        try:
            with urllib.request.urlopen(
                    f"http://{member}/status",
                    timeout=self.probe_timeout_s) as resp:
                payload = json.loads(resp.read().decode("utf-8"))
            busy = (payload.get("live") or {}).get("device_busy_ratio")
            return float(busy) if isinstance(busy, (int, float)) else None
        except Exception:  # noqa: BLE001 — any failure mode = neutral
            return None

    def _candidates(self, key: str) -> List[str]:
        """Every member in spill order for ``key``: live members by
        rendezvous rank first (the winner's own rank ordering IS the
        spillover order), then down members by rank as a last resort —
        the prober can lag a just-revived member by one interval, and
        trying a marked-down member beats refusing outright when the
        whole fleet looks dark."""
        with self._lock:
            up = dict(self._up)
            weights = dict(self._weights)
        order = rendezvous_order(self.members, key, weights)
        return ([m for m in order if up.get(m)]
                + [m for m in order if not up.get(m)])

    # -- forwarding (handler threads) --------------------------------------

    def _send(self, member: str, path: str,
              body: bytes) -> Tuple[int, bytes]:
        """Forward raw bytes to one member; HTTP error statuses are
        ANSWERS (returned as-is — a 503 is the member's admission
        verdict), connection-level failures raise :class:`RouteError`."""
        req = urllib.request.Request(
            f"http://{member}{path}", data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(
                    req, timeout=self.forward_timeout_s) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise RouteError(f"{member}: {e!r}") from e

    def _split(self, member: str) -> Tuple[str, int]:
        host, _, port = member.rpartition(":")
        return host, int(port)

    def forward(self, path: str, body: bytes, key: str,
                pinned: Optional[str] = None) -> Tuple[int, bytes]:
        """Route one request: try candidates in rendezvous/spill order
        (or only the pinned member, for session traffic whose state
        cannot move).  Breaker-open members spill without a connection
        attempt; connection failures record on the breaker and reroute
        within this same request — idempotent request ids make the
        retry-through-reroute safe (the sibling recomputes or
        WAL-replays, never double-counts)."""
        code, resp, _ = self._forward(path, body, key, pinned)
        return code, resp

    def _forward(self, path: str, body: bytes, key: str,
                 pinned: Optional[str] = None,
                 ) -> Tuple[int, bytes, Optional[str]]:
        cands = [pinned] if pinned is not None else self._candidates(key)
        errors = []
        for member in cands:
            br = breaker_for(*self._split(member))
            if not br.allow(
                    lambda m=member: probe_healthz(
                        m, timeout=self.probe_timeout_s)):
                obs.count("jepsen_route_spillover_total", member=member)
                errors.append(f"{member}: breaker open")
                continue
            try:
                code, resp = self._send(member, path, body)
            except RouteError as e:
                br.record_failure()
                with self._lock:
                    self._up[member] = False
                obs.count("jepsen_route_reroutes_total", member=member)
                errors.append(str(e))
                continue
            br.record_success()
            obs.count("jepsen_route_requests_total", member=member)
            return code, resp, member
        # every candidate refused or died: the client's transparent
        # seam treats this 503 like any admission refusal and falls
        # back to its in-process engine
        return 503, protocol.encode_body({
            "error": "no live fleet member",
            "members": list(self.members),
            "detail": errors[-3:],
        }), None

    # -- per-endpoint routing ----------------------------------------------

    def route_check(self, body: bytes) -> Tuple[int, bytes]:
        try:
            key = check_route_key(protocol.decode_body(body))
        except Exception:  # noqa: BLE001 — malformed body: still
            # forward (ONE deterministic member via the fallback key),
            # so the 400 taxonomy comes from a daemon, not from a
            # second hand-rolled validator here
            key = "check|malformed"
        return self.forward("/check", body, key)

    def route_elle(self, body: bytes) -> Tuple[int, bytes]:
        try:
            key = elle_route_key(protocol.decode_body(body))
        except Exception:  # noqa: BLE001 — malformed body, as above
            key = "elle|malformed"
        return self.forward("/elle", body, key)

    def route_feed(self, body: bytes) -> Tuple[int, bytes]:
        """Session-affine routing: ``open`` rendezvous-hashes its
        (model, opts) key and pins the returned session id to the
        member that answered; ``append``/``close`` follow the pin
        (falling back to hashing the session id when the pin is gone —
        a restarted router re-derives the same member the same way the
        reopened session would)."""
        try:
            payload = protocol.decode_body(body)
            fop = payload.get("op")
        except Exception:  # noqa: BLE001 — malformed body, as above
            return self.forward("/feed", body, "feed|malformed")
        if fop == "open":
            key = json.dumps(
                ["feed", payload.get("model"), payload.get("opts")],
                sort_keys=True, default=repr)
            code, resp, member = self._forward("/feed", body, key)
            if code == 200 and member is not None:
                try:
                    sid = protocol.decode_body(resp).get("session")
                except Exception:  # noqa: BLE001 — not a session ack
                    sid = None
                if sid:
                    with self._lock:
                        self._pins[sid] = member
            return code, resp
        sid = payload.get("session")
        with self._lock:
            pinned = self._pins.get(sid)
        if pinned:
            code, resp = self.forward("/feed", body, None, pinned=pinned)
        else:
            code, resp = self.forward("/feed", body, f"feed-session|{sid}")
        if fop == "close" and code == 200:
            with self._lock:
                self._pins.pop(sid, None)
        return code, resp

    # -- status -------------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            up = dict(self._up)
            weights = dict(self._weights)
            pins = len(self._pins)
        return {
            "role": "router",
            "ok": any(up.values()),
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self.t_start, 1),
            "members": [
                {
                    "member": m,
                    "up": bool(up.get(m)),
                    "weight": weights.get(m, 1.0),
                    "breaker": breaker_for(*self._split(m)).state(),
                }
                for m in self.members
            ],
            "feed_pins": pins,
            "probe_interval_s": self.probe_interval_s,
        }

    # -- lifecycle ------------------------------------------------------------

    def start(self, block: bool = True) -> "Router":
        obs.enable()  # live /metrics needs the registry recording
        handler = _make_handler(self)
        self._server = ThreadingHTTPServer((self.host, self.port), handler)  # jt: allow[concurrency-unguarded-shared] — written before listener/prober threads start (Thread.start publication)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._prober = threading.Thread(
            target=self._probe_loop, name="jepsen-route-probe",
            daemon=True,
        )
        self._prober.start()
        if block:
            print(
                f"jepsen-tpu fleet router on "
                f"http://{self.host}:{self.port}/ -> "
                f"{', '.join(self.members)} (pid {os.getpid()})"
            )
            try:
                self._server.serve_forever()  # jt: allow[net-timeout] — the accept loop IS the process; shutdown() ends it
            finally:
                self.stop()
        else:
            threading.Thread(
                target=self._server.serve_forever, daemon=True
            ).start()
        return self

    def request_shutdown(self) -> dict:
        """Stop the router (members keep serving — stopping THEM is a
        per-member ``jepsen_tpu shutdown --daemon`` decision, never a
        side effect of losing the front)."""
        already = self._stopping.is_set()
        self._stopping.set()
        if not already:
            threading.Thread(target=self._finish_stop, daemon=True).start()
        return {"ok": True, "role": "router"}

    def _finish_stop(self) -> None:  # jt: thread-entry
        time.sleep(0.05)
        if self._server is not None:
            self._server.shutdown()

    def stop(self) -> None:
        self._stopping.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        if self._prober is not None:
            self._prober.join(timeout=5)


def _make_handler(router: Router):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _reply(self, code: int, body: bytes,
                   ctype: str = "application/json"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, code: int, payload: dict):
            self._reply(code, protocol.encode_body(payload))

        def do_GET(self):  # noqa: N802 — http.server API, jt: thread-entry
            try:
                if self.path == "/healthz":
                    st = router.status()
                    self._reply_json(200 if st["ok"] else 500, {
                        "ok": st["ok"], "role": "router",
                        "uptime_s": st["uptime_s"],
                    })
                elif self.path == "/status":
                    self._reply_json(200, router.status())
                elif self.path == "/metrics":
                    self._reply(200, obs.render_prom().encode(),
                                "text/plain; version=0.0.4")
                else:
                    self._reply_json(404, {"error": "not found"})
            except BrokenPipeError:
                pass

        def do_POST(self):  # noqa: N802 — http.server API, jt: thread-entry
            try:
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                if self.path == "/check":
                    code, resp = router.route_check(body)
                    self._reply(code, resp)
                elif self.path == "/elle":
                    code, resp = router.route_elle(body)
                    self._reply(code, resp)
                elif self.path == "/feed":
                    code, resp = router.route_feed(body)
                    self._reply(code, resp)
                elif self.path == "/shutdown":
                    self._reply_json(200, router.request_shutdown())
                else:
                    self._reply_json(404, {"error": "not found"})
            except BrokenPipeError:
                pass

        def log_message(self, fmt, *args):
            pass  # the router's obs metrics are the log of record

    return Handler


def main(argv=None) -> int:
    """``python -m jepsen_tpu.serve.router`` / ``jepsen_tpu route``."""
    import argparse

    p = argparse.ArgumentParser(
        prog="jepsen_tpu route",
        description="fleet routing front (doc/checker-service.md "
                    "\"Fleet tier\")",
    )
    p.add_argument("--member", action="append", required=True,
                   metavar="HOST:PORT",
                   help="fleet member daemon (repeatable)")
    p.add_argument("--host", default=protocol.DEFAULT_HOST)
    p.add_argument("--port", type=int, default=protocol.DEFAULT_PORT,
                   help="router bind port (default 8519 — clients "
                   "point JEPSEN_TPU_SERVE_PORT here unchanged)")
    args = p.parse_args(argv)
    Router(args.member, host=args.host, port=args.port).start(block=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
