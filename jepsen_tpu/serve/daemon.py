"""The resident checker daemon: one process owns the device, the
compiled-kernel cache, and the oracle worker pool; many client runs
share them.

Why a daemon: every ``cli test`` run pays backend init and per-shape
re-jit from scratch — the r01–r05 bench rows show init alone can eat
the accelerator win.  Keeping the mesh and jit cache resident
amortizes both across runs and users; the ``check(...)`` seam stays
the client API (jepsen_tpu.serve.client), so tests don't change.

Architecture (doc/checker-service.md):

- **Request handlers** (one HTTP thread per client, stdlib
  ``ThreadingHTTPServer``) do the *pure planning half*: decode the
  batch, run the P-compositionality front-end
  (:class:`~jepsen_tpu.engine.decompose.DecomposedRun` — partitionable
  histories split into per-partition sub-histories right here), and
  encode each stream into raw shape buckets
  (:meth:`~jepsen_tpu.engine.planning.Planner.encode_buckets`) — all
  parallel-safe host work.  Unencodable histories hit the shared
  oracle pool immediately, before the request even queues.
- **The device thread** owns the *execution half*: ONE resident
  :class:`~jepsen_tpu.engine.execution.Executor` (created on this
  thread — the dispatch window is owner-thread confined).  It pops
  whole admission-queue backlogs, groups compatible requests (same
  wire model + planning opts), **coalesces same-(E, C) buckets across
  runs** (:func:`~jepsen_tpu.engine.planning.merge_buckets`) into
  shared dispatch chunks, and signals each request's ``device_done``
  event when its rows have settled.  Per-row ``(ctx, idx)`` tokens
  route every verdict back to its own client.
- **Backpressure**: admission is bounded by queued request count AND
  queued history rows; past either bound ``/check`` answers 503 and
  the client falls back to its in-process engine.  In-flight HBM
  needs no extra policy — the shared executor inherits the
  footprint-safe chunk caps (frontier chunks take 1/window of
  ``fn.safe_dispatch``), so coalesced load can never hold more
  concurrent HBM than the crash-calibrated single-dispatch budget.
- **Coalescing is backpressure-driven**: a lone request dispatches
  immediately (zero added latency); requests arriving while the
  device is busy pile up in the queue and merge into the next device
  batch.  ``JEPSEN_TPU_SERVE_COALESCE_WAIT`` adds a bounded gather
  window for deterministic coalescing in tests/smoke.

Shutdown drains: ``POST /shutdown`` stops admission, the device
thread finishes every queued request, handlers flush their responses,
then the HTTP server stops.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..engine import decompose, execution, planning
from ..obs import drift as obs_drift
from ..obs import journal as obs_journal
from ..obs import profiling as obs_profiling
from ..obs import propagate
from . import protocol

#: admission bounds: queued (not yet device-processed) requests and
#: histories; past either, /check answers 503 "backlogged" and the
#: client falls back in-process
DEFAULT_MAX_QUEUE_RUNS = 8
DEFAULT_MAX_QUEUE_ROWS = 65536

#: how long a handler waits for the device thread before answering 500
DEFAULT_REQUEST_TIMEOUT_S = 600.0


#: idle-window WAL auto-compaction threshold: past this size the
#: device thread's housekeeping turn rewrites the verdict WAL down to
#: the rows still replayable (JEPSEN_TPU_WAL_COMPACT_BYTES overrides;
#: 0 disables)
DEFAULT_WAL_COMPACT_BYTES = 32 * 1024 * 1024


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class _Stream:
    """One planning stream of a request: a tag (``"main"`` for
    pass-through histories under the wire model, ``"sub"`` for the
    decomposition front-end's per-partition sub-histories), the
    representative (model, spec) that plans it, and its raw encoded
    buckets.  Same-tag streams from a compatible group merge across
    runs — decomposed sub-histories coalesce into shared dispatches
    exactly like whole histories do."""

    __slots__ = ("tag", "model", "spec", "buckets", "order")

    def __init__(self, tag, model, spec, buckets, order):
        self.tag = tag
        self.model = model
        self.spec = spec
        self.buckets = buckets
        self.order = order


class _NoOracles:
    """Oracle-interface stub for requests that never submit oracle
    work (the Elle screen requests): the batch-failure and abandon
    paths can treat every queued request uniformly."""

    def abandon_oracles(self) -> int:
        return 0


_NO_ORACLES = _NoOracles()


class _ElleRequest:
    """One admitted /elle screen batch: encoded relation-bit graphs
    whose (vertex bucket, filter profile) buckets COALESCE ACROSS
    REQUESTS inside ``ops.cycles.screen_graphs`` — Elle traffic from
    concurrent runs shares dispatch rows through the resident
    executor exactly like history buckets do."""

    kind = "elle"
    __slots__ = ("graphs", "rows", "n", "t_admitted", "device_done",
                 "error", "diag", "abandoned", "results", "run",
                 "trace_id")

    def __init__(self, graphs, trace_id: Optional[str] = None):
        self.graphs = graphs
        self.rows = self.n = len(graphs)
        self.t_admitted = time.perf_counter()
        self.device_done = threading.Event()
        self.error: Optional[str] = None
        self.diag: dict = {}
        self.abandoned = False
        self.results: Optional[list] = None
        self.run = _NO_ORACLES
        #: caller's trace id (obs.propagate) — tags this request's
        #: daemon-side spans + journal rows; None when untraced
        self.trace_id = trace_id


class _Request:
    """One admitted /check batch, in flight between a handler thread
    and the device thread.  Handler-side state is written before the
    queue put; device-side results are read only after ``device_done``
    (the Event provides the happens-before edge).  ``run`` is the
    batch's :class:`~jepsen_tpu.engine.decompose.DecomposedRun` —
    result routing, oracle hand-off, and the AND-at-settle merge all
    live there; ``streams`` carry its encoded buckets."""

    kind = "check"
    __slots__ = ("run", "streams", "group_key", "model",
                 "plan_opts", "exec_opts", "n", "rows", "t_admitted",
                 "device_done", "error", "diag", "abandoned", "trace_id",
                 "replayed")

    def __init__(self, run, streams, group_key, model, plan_opts,
                 exec_opts, n, trace_id: Optional[str] = None):
        self.run = run
        self.streams = streams
        #: client-visible batch size vs rows actually queued for the
        #: device thread: decomposition multiplies encoded rows by the
        #: partition fanout, and the row-budget backpressure must see
        #: the REAL queue footprint, not the parent count
        self.rows = sum(len(ctx.histories) for _t, ctx in run.streams())
        self.group_key = group_key
        self.model = model
        self.plan_opts = plan_opts
        self.exec_opts = exec_opts
        self.n = n
        self.t_admitted = time.perf_counter()
        self.device_done = threading.Event()
        self.error: Optional[str] = None
        self.diag: dict = {}
        #: handler gave up (refused post-planning, or timed out): the
        #: device thread must skip it and nobody drains its oracles
        self.abandoned = False
        #: caller's trace id (obs.propagate); None when untraced
        self.trace_id = trace_id
        #: result slots pre-filled from the verdict WAL (a retried
        #: request after a daemon restart) — surfaced in the diag
        self.replayed = 0


class _FeedDelta(_Request):
    """One admitted ``/feed`` delta: a :class:`_Request` whose streams
    carry ONLY the rows the delta's ``DecomposedRun.extend`` just
    created (``rows`` is overridden to the delta row count — the row
    budget must see the queued footprint, not the whole session), and
    whose ``kind`` keeps feed traffic out of the /check request
    counters."""

    kind = "feed"
    __slots__ = ()


class _FeedSession:
    """One open streaming-ingest session (``POST /feed``): the
    session's :class:`~jepsen_tpu.engine.decompose.DecomposedRun`
    grows by ``extend`` per delta.  ``lock`` serializes deltas — the
    run's planning/execution phase contract allows exactly one delta
    in flight per session (concurrent appends to one session would
    race the device thread's result assignment)."""

    __slots__ = ("sid", "run", "model", "plan_opts", "exec_opts",
                 "group_key", "trace_id", "lock", "last_seq", "ops",
                 "history_idx", "probe_idx", "probed_n", "prior",
                 "t_open")

    def __init__(self, sid, run, model, plan_opts, exec_opts,
                 group_key, trace_id, prior):
        self.sid = sid
        self.run = run
        self.model = model
        self.plan_opts = plan_opts
        self.exec_opts = exec_opts
        self.group_key = group_key
        self.trace_id = trace_id
        self.lock = threading.Lock()
        #: highest ingested delta seq — a retried append (same seq,
        #: response lost on the wire) acks without re-dispatching
        self.last_seq = -1
        #: op-mode accumulator: raw completed-op event dicts in
        #: shipped (real-time) order; probes check the assembled
        #: prefix history as it grows
        self.ops: List[dict] = []
        #: run indices of client-fed whole histories, in feed order —
        #: what close() returns results for
        self.history_idx: List[int] = []
        #: run index + coverage of the latest op-prefix probe (close
        #: reuses it as the final verdict when no ops arrived since)
        self.probe_idx: Optional[int] = None
        self.probed_n = 0
        #: WAL rows a previous daemon life settled under this session
        #: id — replayed into each delta's fresh slots
        self.prior = prior
        self.t_open = time.time()


class AdmissionState:
    """The routing/admission half of a daemon, split from device
    ownership (ROADMAP item 1): the bounded queue and row budget, the
    idempotent-retry response cache, quarantined routes, open feed
    sessions, watcher accounting, and the stop flag — everything a
    request touches BEFORE the device thread owns it, behind ONE
    condition.  Device ownership (executor, mesh, jit cache) lives on
    :class:`CheckerDaemon`'s device thread; nothing here reaches for
    process-global device state, which is exactly why N daemons per
    host (``--supervise --fleet N``) are just N ``(AdmissionState,
    executor)`` pairs on distinct ports/WALs/journals."""

    def __init__(self, max_queue_runs: int, max_queue_rows: int):
        self.max_queue_runs = max_queue_runs
        self.max_queue_rows = max_queue_rows
        #: ONE condition guards every piece of handler/device shared
        #: state (queue, row budget, stats) — and doubles as the
        #: device thread's wake-up signal
        self._wake = threading.Condition()
        self._stopping = threading.Event()
        self._queue: List[_Request] = []  # jt: guarded-by(_wake)
        self._queued_rows = 0  # jt: guarded-by(_wake)
        self._in_flight = 0  # jt: guarded-by(_wake)
        self.stats = {  # jt: guarded-by(_wake)
            "requests": 0, "histories": 0, "rejected": 0,
            "coalesced": 0, "batches": 0, "warm_dispatches": 0,
            "cold_dispatches": 0, "errors": 0,
            "elle_requests": 0, "elle_graphs": 0,
            "quarantined_rows": 0, "replayed": 0, "deduped": 0,
            "feed_sessions": 0, "feed_deltas": 0, "feed_histories": 0,
            "watch_events": 0, "wal_compactions": 0,
        }
        #: open streaming-ingest sessions by session id
        self._feeds: Dict[str, _FeedSession] = {}  # jt: guarded-by(_wake)
        #: live /watch subscribers (SSE handler threads)
        self._watchers = 0  # jt: guarded-by(_wake)
        #: completed-response cache for idempotent retries: a client
        #: retry (same request id) of an ALREADY-ANSWERED request is
        #: served from here without touching the device or the
        #: counters — retried work is never double-counted
        self._done: "OrderedDict[str, Tuple[int, dict]]" = OrderedDict()  # jt: guarded-by(_wake)
        self._done_cap = 128
        #: quarantined (kernel, E, C) routes: a device fault on one
        #: route degrades THAT route to the CPU oracle instead of
        #: failing whole batches (graceful degradation); values are
        #: the triggering error repr
        self._quarantine: Dict[Tuple, str] = {}  # jt: guarded-by(_wake)

    # -- admission (handler threads) --------------------------------------

    def precheck(self, n_rows: int) -> bool:
        """Cheap capacity check BEFORE the planning half: a request
        that would be refused must not pay decode+encode (nor submit
        oracle searches the pool would burn for nobody) just to hear
        503.  The authoritative check is :meth:`admit` — this one only
        sheds the obvious overload early, so the race window between
        the two is a single in-flight planning pass, not the whole
        backlog.  ``n_rows`` here is the parent history count (the
        decomposition fanout is unknowable pre-planning); admit()
        re-checks against the real post-decomposition row count."""
        with self._wake:
            return not (
                self._stopping.is_set()
                or len(self._queue) >= self.max_queue_runs
                or self._queued_rows + n_rows > self.max_queue_rows
            )

    def admit(self, req: _Request) -> bool:
        with self._wake:
            if self._stopping.is_set():
                return False
            # the authoritative row budget counts req.rows — the
            # encoded rows actually queued (decomposition fans a
            # parent history out into per-partition sub-rows; see
            # _Request.rows) — while precheck's pre-planning
            # estimate can only see the parent count
            if (len(self._queue) >= self.max_queue_runs
                    or self._queued_rows + req.rows > self.max_queue_rows):
                self.stats["rejected"] += 1
                obs.count("jepsen_serve_rejected_total")
                return False
            self._queue.append(req)
            self._queued_rows += req.rows
            if req.kind == "elle":
                # graphs are not histories: the /check throughput
                # accounting must not inflate from screen traffic
                self.stats["elle_requests"] += 1
                self.stats["elle_graphs"] += req.n
                obs.count("jepsen_serve_elle_requests_total")
                obs.count("jepsen_serve_elle_graphs_total", req.n)
            elif req.kind == "feed":
                # feed deltas count under jepsen_feed_* at ingest
                # completion (_feed_dispatch), not here: a delta is
                # not a /check request and must not inflate its stats
                pass
            else:
                self.stats["requests"] += 1
                self.stats["histories"] += req.n
                obs.count("jepsen_serve_requests_total")
                obs.count("jepsen_serve_histories_total", req.n)
            obs.gauge_set("jepsen_serve_queue_depth", len(self._queue))
            self._wake.notify()
            return True

    # -- the device-thread side -------------------------------------------

    def take_batch(self, coalesce_wait_s: float) -> List[_Request]:
        """Pop the whole current backlog (the coalescing unit), waiting
        up to ``coalesce_wait_s`` after the first arrival for company."""
        with self._wake:
            idle_waits = 0
            while not self._queue:
                if self._stopping.is_set():
                    return []
                self._wake.wait(timeout=0.2)
                idle_waits += 1
                if not self._queue and idle_waits >= 5:
                    # ~1 s with no admissions: hand the device loop a
                    # housekeeping turn (WAL auto-compaction) instead
                    # of camping on the condition forever
                    return []
            if coalesce_wait_s > 0:
                deadline = time.monotonic() + coalesce_wait_s
                while (len(self._queue) < self.max_queue_runs
                       and not self._stopping.is_set()):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._wake.wait(timeout=remaining)
            batch = self._queue
            self._queue = []
            self._queued_rows = 0
            self._in_flight = len(batch)
            obs.gauge_set("jepsen_serve_queue_depth", 0)
            return batch

    def drain_queue(self) -> List[_Request]:
        """Take everything still queued (the device-thread-failed
        path): the caller fails each request itself."""
        with self._wake:
            queued, self._queue = self._queue, []
            self._queued_rows = 0
            return queued

    # -- idempotent retries (handler threads) ------------------------------

    def dedup_hit(self, req_id) -> Optional[Tuple[int, dict]]:
        """Serve a retried request id from the completed-response
        cache: a client retry of an ALREADY-ANSWERED request (the
        response was lost on the wire, not the work) is answered from
        here without touching the device or inflating the request
        counters — retried work is never double-counted."""
        if not req_id:
            return None
        with self._wake:
            hit = self._done.get(req_id)
            if hit is None:
                return None
            self._done.move_to_end(req_id)
            self.stats["deduped"] += 1
        obs.count("jepsen_serve_request_dedup_total")
        return hit

    def dedup_store(self, req_id, code: int, payload: dict) -> None:
        if not req_id or code != 200:
            # only durable successes are idempotent-replayable; a
            # retried failure should retry the actual work
            return
        with self._wake:
            self._done[req_id] = (code, payload)
            self._done.move_to_end(req_id)
            while len(self._done) > self._done_cap:
                self._done.popitem(last=False)

    # -- graceful degradation (device thread) ------------------------------

    def mark_quarantined(self, routes, err) -> int:
        """Record device-faulted (kernel, E, C) routes: subsequent
        buckets on a quarantined route go straight to the CPU oracle
        instead of re-hitting the faulty compile/dispatch — one bad
        route degrades, the daemon and every other route keep serving
        (doc/checker-service.md "Failure modes & recovery")."""
        with self._wake:
            fresh = [r for r in routes if r not in self._quarantine]
            for r in fresh:
                self._quarantine[r] = repr(err)
            n_q = len(self._quarantine)
        if fresh:
            obs.count("jepsen_serve_quarantine_total", len(fresh))
            obs.gauge_set("jepsen_serve_quarantined_routes", n_q)
        return len(fresh)


class CheckerDaemon:
    """The resident service.  ``start(block=False)`` returns once the
    device thread is ready; ``port`` then holds the bound port (useful
    with port=0 in tests)."""

    def __init__(
        self,
        host: str = protocol.DEFAULT_HOST,
        port: int = protocol.DEFAULT_PORT,
        *,
        window: Optional[int] = None,
        mesh=None,
        max_queue_runs: Optional[int] = None,
        max_queue_rows: Optional[int] = None,
        coalesce_wait_s: Optional[float] = None,
        cost_fn=None,
        journal_path: Optional[str] = None,
        journal_max_bytes: int = obs_journal.DEFAULT_MAX_BYTES,
        wal_path: Optional[str] = None,
        wal_compact_bytes: Optional[int] = None,
        drift: bool = True,
        drift_threshold: Optional[float] = None,
        profile_dir: str = "profiles",
        aot_cache_dir: Optional[str] = None,
    ):
        #: per-bucket device-cost estimator driving largest-first
        #: dispatch of coalesced work.  The default is the
        #: calibration-aware planning.estimated_cost: with a tuned
        #: artifact active (doc/tuning.md) it serves the MEASURED
        #: per-(kernel, E, C, F, rows) cost table, else the analytic
        #: proxy — pass cost_fn= to override either
        self.cost_fn = cost_fn or planning.estimated_cost
        self.host = host
        self.port = port
        self.window = window
        self.mesh = mesh
        # `is None`, not truthiness: --max-queue 0 means "refuse all
        # new work", which must not silently become the default bound
        max_runs = (
            int(os.environ.get("JEPSEN_TPU_SERVE_MAX_QUEUE",
                               DEFAULT_MAX_QUEUE_RUNS))
            if max_queue_runs is None else max_queue_runs
        )
        max_rows = (
            DEFAULT_MAX_QUEUE_ROWS if max_queue_rows is None
            else max_queue_rows
        )
        #: the routing/admission half (ROADMAP item 1 split): queue,
        #: budgets, retry cache, quarantine, feed/watch registries —
        #: everything shared between handler threads and the device
        #: thread.  Device ownership stays below on the device thread.
        self.admission = AdmissionState(max_runs, max_rows)
        self.coalesce_wait_s = (
            coalesce_wait_s
            if coalesce_wait_s is not None
            else _env_float("JEPSEN_TPU_SERVE_COALESCE_WAIT", 0.0)
        )
        #: dispatch-journal destination (obs.journal): None = off — the
        #: constructor default, so in-process/test daemons never write
        #: to cwd by accident; the `serve()` CLI entry defaults it ON
        self.journal_path = journal_path
        self.journal_max_bytes = journal_max_bytes
        #: cost-model drift sentinel (obs.drift): rides the journal
        #: stream, so it only arms when the journal is on; `drift=False`
        #: (or falsy JEPSEN_TPU_DRIFT at the `serve()` entry) disables
        self.drift = drift
        self.drift_threshold = drift_threshold
        #: where `POST /profile` captures land when the request names
        #: no directory (each capture gets a timestamped subdir)
        self.profile_dir = profile_dir
        #: verdict-WAL destination (obs.journal.VerdictWAL): None = off
        #: (constructor default, like the dispatch journal); the
        #: `serve()` entry wires it from JEPSEN_TPU_WAL.  On a fresh
        #: start the existing file becomes the replay index — a
        #: restarted daemon re-dispatches only unsettled partitions of
        #: retried requests (doc/checker-service.md "Failure modes &
        #: recovery")
        self.wal_path = wal_path
        self._wal: Optional[obs_journal.VerdictWAL] = None
        self._wal_replay: Dict[str, dict] = {}
        #: idle-window auto-compaction threshold (bytes; 0 disables) —
        #: the device thread's housekeeping turn checks it
        self.wal_compact_bytes = (
            _env_int("JEPSEN_TPU_WAL_COMPACT_BYTES",
                     DEFAULT_WAL_COMPACT_BYTES)
            if wal_compact_bytes is None else wal_compact_bytes
        )
        #: shared on-disk AOT executable cache (serve.aotcache): the
        #: device thread records every cold compile here and pre-warms
        #: matching entries at startup, so a supervisor-restarted
        #: daemon's first request runs with zero cold dispatches.
        #: None = off (constructor default, like the journal/WAL); the
        #: `serve()` entry wires it from JEPSEN_TPU_SERVE_AOT_CACHE
        self.aot_cache_dir = aot_cache_dir
        self._aot_warmed = 0
        self._aot_matched = 0
        self._aot_recorder = None
        self.t_start = time.time()
        self._server: Optional[ThreadingHTTPServer] = None
        self._device_thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._platform: Optional[str] = None
        self._fatal: Optional[str] = None
        #: devices the resident executor shards across (set by the
        #: device thread once the executor exists; None = not ready)
        self._n_devices: Optional[int] = None

    # -- AdmissionState delegation ------------------------------------------
    # The handler/device code below predates the split and still says
    # `self._wake` / `self._queue` / `self.stats`; these forwarders keep
    # that surface (and the public ctor/status contract) stable while
    # the state itself lives on `self.admission`.

    @property
    def _wake(self):
        return self.admission._wake

    @property
    def _stopping(self):
        return self.admission._stopping

    @property
    def stats(self):
        return self.admission.stats

    @property
    def max_queue_runs(self) -> int:
        return self.admission.max_queue_runs

    @property
    def max_queue_rows(self) -> int:
        return self.admission.max_queue_rows

    @property
    def _queue(self):
        return self.admission._queue

    @property
    def _queued_rows(self) -> int:
        return self.admission._queued_rows

    @property
    def _in_flight(self) -> int:
        return self.admission._in_flight

    @_in_flight.setter
    def _in_flight(self, v: int) -> None:
        self.admission._in_flight = v

    @property
    def _watchers(self) -> int:
        return self.admission._watchers

    @_watchers.setter
    def _watchers(self, v: int) -> None:
        self.admission._watchers = v

    @property
    def _done(self):
        return self.admission._done

    @property
    def _feeds(self):
        return self.admission._feeds

    @property
    def _quarantine(self):
        return self.admission._quarantine

    # -- admission (handler threads) ---------------------------------------

    def precheck_admit(self, n_rows: int) -> bool:
        return self.admission.precheck(n_rows)

    def admit(self, req: _Request) -> bool:
        return self.admission.admit(req)

    # -- the device thread ---------------------------------------------------

    def _take_batch(self) -> List[_Request]:
        return self.admission.take_batch(self.coalesce_wait_s)

    def _device_loop(self) -> None:  # jt: thread-entry
        """The resident execution half: owns the device, the dispatch
        window, and the jit cache for the daemon's whole life."""
        try:
            from ..platform import ensure_usable_backend

            ensure_usable_backend()
            import jax

            # the assignments below are published to handler threads
            # by `_ready.set()` / `start()`'s `_ready.wait()` — no
            # handler can observe them mid-write
            self._platform = jax.devices()[0].platform  # jt: allow[concurrency-unguarded-shared] — published via _ready (see above)
            # created HERE: the dispatch window is owner-thread
            # confined to the device thread
            executor = execution.Executor(self.window, mesh=self.mesh)
            # the executor auto-resolves a slice mesh when none was
            # passed (parallel.mesh.engine_default_mesh); adopt the
            # RESOLVED mesh so /status advertises what actually runs
            # and mesh-matched client requests can be serviced
            self.mesh = executor.mesh  # jt: allow[concurrency-unguarded-shared] — published via _ready
            self._n_devices = executor.n_devices  # jt: allow[concurrency-unguarded-shared] — published via _ready
            if self.aot_cache_dir:
                # cold-start elimination: replay the shared manifest ON
                # this thread, BEFORE /healthz goes ready — a restarted
                # daemon's first request then runs with zero cold
                # dispatches — and hook the recorder so every cold
                # compile this life pays is warm next life (fleet-wide:
                # the manifest and the XLA cache under it are shared)
                from . import aotcache

                try:
                    warmed, matched = aotcache.warm(executor,
                                                    self.aot_cache_dir)
                    self._aot_warmed = warmed  # jt: allow[concurrency-unguarded-shared] — published via _ready
                    self._aot_matched = matched  # jt: allow[concurrency-unguarded-shared] — published via _ready
                    self._aot_recorder = aotcache.Recorder(  # jt: allow[concurrency-unguarded-shared] — published via _ready
                        self.aot_cache_dir,
                        list(self.mesh.devices.shape)
                        if self.mesh is not None else [1],
                    )
                    executor.on_cold_compile = self._aot_recorder
                except Exception:  # noqa: BLE001 — the cache is an
                    # optimization: a damaged dir means a cold start,
                    # never a dead daemon
                    executor.reset()
        except Exception as e:  # noqa: BLE001 — surface via /healthz + 500s
            self._fatal = repr(e)  # jt: allow[concurrency-unguarded-shared] — published via _ready
            self._ready.set()
            self._fail_all_queued()
            return
        self._ready.set()
        while True:
            batch = self._take_batch()
            if not batch:
                if self._stopping.is_set():
                    return  # drained: every admitted request settled
                self._maybe_compact_wal()
                continue
            try:
                self._process_batch(executor, batch)
                with self._wake:
                    self._in_flight = 0
            except Exception as e:  # noqa: BLE001 — one bad batch must
                # not kill the daemon; its unsettled requests answer 500
                # (requests whose group already settled keep their
                # results — their handlers may have responded).  The
                # resident executor's transient state is discarded:
                # carrying the failed batch's in-flight dispatches or
                # parked escalations forward would poison the NEXT
                # batch (see Executor.reset)
                executor.reset()
                n_err = 0
                for req in batch:
                    if not req.device_done.is_set():
                        req.error = repr(e)
                        # the 500'd client re-runs in-process; cancel
                        # its queued oracle searches instead of letting
                        # them burn the shared pool for nobody
                        req.run.abandon_oracles()
                        req.device_done.set()
                        n_err += 1
                with self._wake:
                    self.stats["errors"] += n_err
                    self._in_flight = 0

    def _maybe_compact_wal(self) -> None:
        """Idle-window WAL auto-compaction (device thread only): past
        :attr:`wal_compact_bytes` the verdict WAL rewrites down to the
        rows still replayable — the request ids in the completed-
        response cache plus every open feed session.  ``compact()``
        swaps via ``.tmp`` + ``os.replace`` under the WAL's own lock,
        so concurrent handler appends stay safe and a kill -9
        mid-compaction leaves the original file intact (the chaos
        harness pins this); ``/watch`` followers detect the rewrite
        (WalTail's inode/size check) and restart from offset 0 —
        re-delivery is safe because verdicts are monotone and every
        row carries its full (req, stream, idx) identity."""
        wal = self._wal
        if wal is None or self.wal_compact_bytes <= 0:
            return
        try:
            if os.path.getsize(wal.path) <= self.wal_compact_bytes:
                return
        except OSError:
            return
        with self._wake:
            keep = set(self._done) | set(self._feeds)
        try:
            wal.compact(keep_reqs=keep)
        except OSError:
            return  # disk trouble: keep serving, retry next idle turn
        with self._wake:
            self.stats["wal_compactions"] += 1
        obs.count("jepsen_serve_wal_compactions_total")

    def _fail_all_queued(self) -> None:
        for req in self.admission.drain_queue():
            req.error = f"device thread failed: {self._fatal}"
            req.device_done.set()

    def _process_batch(self, executor, batch: List[_Request]) -> None:
        """Group compatible requests, coalesce same-shape buckets across
        runs, dispatch each group through the shared window."""
        with self._wake:
            self.stats["batches"] += 1
        groups: Dict[Tuple, List[_Request]] = {}
        group_order: List[Tuple] = []
        elle_reqs: List[_ElleRequest] = []
        for req in batch:
            if req.abandoned:
                # handler gave up (timeout): skip its work and cancel
                # the oracle searches its planning already submitted —
                # safe here, the device thread is the run's only owner
                req.run.abandon_oracles()
                continue
            if isinstance(req, _ElleRequest):
                elle_reqs.append(req)
                continue
            if req.group_key not in groups:
                groups[req.group_key] = []
                group_order.append(req.group_key)
            groups[req.group_key].append(req)
        batch_attrs = {"requests": len(batch),
                       "groups": len(group_order) + bool(elle_reqs)}
        batch_ids = ",".join(sorted(
            {r.trace_id for r in batch if getattr(r, "trace_id", None)}))
        if batch_ids:
            batch_attrs[propagate.ATTR_TRACE_IDS] = batch_ids
        with obs.span("serve/batch", cat="serve", **batch_attrs):
            if elle_reqs:
                self._process_elle(executor, elle_reqs)
                for req in elle_reqs:
                    req.device_done.set()
            # plan every group first (pure host work), then dispatch
            # groups largest summed-estimated-cost first: a group's
            # cost is the SUM over its planned buckets' rows — so a
            # high-fanout decomposed request, whose parent cost lives
            # spread across many per-partition sub-buckets, schedules
            # by its real total instead of arrival order (the ROADMAP
            # items 3+4 partition-aware scheduling leftover).  The
            # stable sort keeps arrival order on ties.
            planned = {
                gkey: self._plan_group(groups[gkey]) for gkey in group_order
            }
            group_order.sort(
                key=lambda k: sum(self.cost_fn(pb) for pb in planned[k][0]),
                reverse=True,
            )
            for gkey in group_order:
                reqs = groups[gkey]
                self._dispatch_group(executor, reqs, *planned[gkey])
                for req in reqs:
                    if req.abandoned:
                        # handler timed out while this group ran: no
                        # one will drain these futures (a set() after
                        # this check races only a just-expiring wait —
                        # bounded to already-submitted futures)
                        req.run.abandon_oracles()
                    req.device_done.set()

    def _process_elle(self, executor, reqs: List[_ElleRequest]) -> None:
        """The Elle screen arm of a device batch: graphs from every
        queued /elle request screen through ONE ``screen_graphs`` pass
        over the resident executor, so same-(bucket, profile) buckets
        coalesce across runs into shared dispatches."""
        from ..ops import cycles as ops_cycles

        if len(reqs) > 1:
            obs.count("jepsen_serve_elle_coalesced_total", len(reqs))
        for req in reqs:
            # admission→dispatch: the queue-wait the /status live view
            # and item 3's admission-control signal key on
            obs.observe("jepsen_serve_queue_wait_seconds",
                        time.perf_counter() - req.t_admitted)
        attrs = {"graphs": sum(r.n for r in reqs)}
        trace_ids = ",".join(
            sorted({r.trace_id for r in reqs if r.trace_id}))
        if trace_ids:
            attrs[propagate.ATTR_TRACE_IDS] = trace_ids
        executor.journal_context = {
            "coalesced": len(reqs), "trace_id": trace_ids}
        encs = [g for req in reqs for g in req.graphs]
        with obs.span("serve/screen", cat="serve", **attrs):
            results = ops_cycles.screen_graphs(encs, executor=executor)
        lo = 0
        for req in reqs:
            req.results = results[lo:lo + req.n]
            req.diag = {
                "coalesced_with": len(reqs) - 1,
                "graphs": req.n,
                "queue_wait_s": round(
                    time.perf_counter() - req.t_admitted, 4),
            }
            lo += req.n

    def _plan_group(self, reqs: List[_Request]):
        """The pure planning half of one compatible group: merge per
        STREAM TAG — a decomposed request carries a "main"
        (pass-through, wire-model spec) and a "sub" (per-partition
        sub-model spec) stream, and only same-spec buckets may stack —
        but within a tag, buckets coalesce across every run in the
        group, so concurrent decomposed requests share dispatch rows
        exactly like whole histories do."""
        first = reqs[0]
        tags: List[str] = []
        for req in reqs:
            for st in req.streams:
                if st.tag not in tags:
                    tags.append(st.tag)
        planned = []
        n_buckets = 0
        for tag in tags:
            streams = [st for req in reqs for st in req.streams
                       if st.tag == tag]
            rep = streams[0]
            planner = planning.Planner(
                rep.model, spec=rep.spec, bucketed=True,
                **first.plan_opts,
            )
            merged, order = planning.merge_buckets(
                (st.buckets, st.order) for st in streams
            )
            n_buckets += len(order)
            for key in order:
                encs, tokens = merged[key]
                pb = planner.plan_rows(key, encs, tokens)
                if pb is not None:
                    planned.append(pb)
        return planned, n_buckets

    def _dispatch_group(self, executor, reqs: List[_Request],
                        planned: list, n_buckets: int) -> None:
        first = reqs[0]
        for req in reqs:
            # admission→dispatch: the queue-wait the /status live view
            # and item 3's admission-control signal key on
            obs.observe("jepsen_serve_queue_wait_seconds",
                        time.perf_counter() - req.t_admitted)
        if len(reqs) > 1:
            # counted per COMPATIBLE group, not per backlog pop:
            # requests that merely shared a device batch but sat in
            # different groups (different model/opts) shared zero
            # dispatch rows and must not inflate the coalescing
            # evidence the serve-smoke gate keys on
            with self._wake:
                self.stats["coalesced"] += len(reqs)
            obs.count("jepsen_serve_coalesced_requests_total", len(reqs))
        # the resident executor adopts this group's execution policy;
        # groups run strictly one after another (with a drain between),
        # so the mutation never races a dispatch
        executor.escalation = first.exec_opts["escalation"]
        executor.sufficient_rung = first.exec_opts["sufficient_rung"]
        executor.max_dispatch = first.exec_opts["max_dispatch"]
        trace_ids = ",".join(
            sorted({r.trace_id for r in reqs if r.trace_id}))
        executor.journal_context = {
            "coalesced": len(reqs), "trace_id": trace_ids}
        attrs = {"requests": len(reqs), "buckets": n_buckets}
        if trace_ids:
            # a shared dispatch belongs to EVERY participating run's
            # trace: /trace?ctx= matches any member of this attr
            attrs[propagate.ATTR_TRACE_IDS] = trace_ids
        pc0 = dict(executor.phase_counts)
        # dispatch EVERY planned bucket largest-estimated-cost first
        # across both streams: big buckets keep the window occupied
        # while small ones fill the tail (ROADMAP item 4's scheduling
        # direction).  The cost fn is the daemon's pluggable seam for
        # a learned per-shape model (planning.estimated_cost docs);
        # verdicts are order-independent by the engine contract, so
        # reordering is purely a throughput decision.
        planned.sort(key=self.cost_fn, reverse=True)
        with self._wake:
            quarantined = set(self._quarantine)
        # graceful degradation: a device fault on one (kernel, E, C)
        # route quarantines THAT route to the CPU oracle — this group
        # keeps dispatching its other routes, and the batch answers
        # 200 with oracle verdicts where the device failed, instead of
        # resetting the executor and 500ing everyone (the whole-batch
        # reset in _device_loop stays as the last resort).  Routed
        # tokens are deduped so a row salvaged from the in-flight
        # window never double-submits an oracle search.
        routed = set()

        def _oracle_route(tokens) -> int:
            n = 0
            for ctx, idx in tokens:
                key = (id(ctx), idx)
                if key in routed or ctx.settled(idx):
                    continue
                routed.add(key)
                ctx.route_oracle(idx, "oracle", "quarantined")
                n += 1
            return n

        n_quarantined = 0
        with obs.span("serve/dispatch", cat="serve", **attrs):
            for pb in planned:
                route = (pb.plan.kernel, pb.plan.E, pb.plan.C)
                if route not in quarantined:
                    try:
                        executor.submit(pb)
                        continue
                    except Exception as e:  # noqa: BLE001 — degrade the route
                        self._mark_quarantined([route], e)
                        quarantined.add(route)
                        n_quarantined += _oracle_route(
                            self._salvage_executor(executor))
                n_quarantined += _oracle_route(pb.rows)
            try:
                executor.drain()
            except Exception as e:  # noqa: BLE001 — fault surfaced at sync
                # the drain exposed an in-flight fault: quarantine the
                # routes the window was still carrying and salvage
                # their rows to the oracle pool
                routes = {(ch["plan"].kernel, ch["plan"].E, ch["plan"].C)
                          for ch in executor._chunks.values()}
                routes |= {(esc[0].kernel, esc[0].E, esc[0].C)
                           for esc in executor._pending_escalations}
                self._mark_quarantined(routes or {("?", 0, 0)}, e)
                quarantined.update(routes)
                n_quarantined += _oracle_route(
                    self._salvage_executor(executor))
        if n_quarantined:
            with self._wake:
                self.stats["quarantined_rows"] += n_quarantined
        warm = executor.phase_counts["execute"] - pc0["execute"]
        cold = executor.phase_counts["compile"] - pc0["compile"]
        if warm:
            # a warm hit = a dispatch that reused an already-compiled
            # (fn, shape) — the re-jit the resident cache saves
            obs.count("jepsen_serve_warm_hits_total", warm)
        with self._wake:
            self.stats["warm_dispatches"] += warm
            self.stats["cold_dispatches"] += cold
        for req in reqs:
            req.diag = {
                "coalesced_with": len(reqs) - 1,
                "warm_dispatches": warm,
                "cold_dispatches": cold,
                "queue_wait_s": round(
                    time.perf_counter() - req.t_admitted, 4),
                "buckets": n_buckets,
                "partitions": req.run.n_partitions,
            }

    # -- status -------------------------------------------------------------

    def status(self) -> dict:
        from .. import tune

        with self._wake:
            stats = dict(self.stats)
            depth = len(self._queue)
            in_flight = self._in_flight
            quarantine = [{"route": str(k), "error": v}
                          for k, v in self._quarantine.items()]
            feed_open = len(self._feeds)
            watchers = self._watchers
        total = stats["warm_dispatches"] + stats["cold_dispatches"]
        cal = tune.active()
        reg = obs.registry()
        # the live windowed view (obs.metrics slot rings): last-60 s
        # rates + queue-wait + device-busy fraction — what `top` and
        # the web panel render, and what a cumulative counter can't say
        busy_s = (reg.window_seconds_sum("jepsen_kernel_compile_seconds")
                  + reg.window_seconds_sum("jepsen_kernel_execute_seconds"))
        qw_mean = reg.window_mean("jepsen_serve_queue_wait_seconds")
        lag_mean = reg.window_mean("jepsen_feed_ingest_lag_seconds")
        live = {
            "requests_per_s": round(
                reg.window_rate("jepsen_serve_requests_total"), 4),
            "histories_per_s": round(
                reg.window_rate("jepsen_serve_histories_total"), 4),
            "elle_graphs_per_s": round(
                reg.window_rate("jepsen_serve_elle_graphs_total"), 4),
            "dispatches_per_s": round(
                reg.window_rate("jepsen_kernel_dispatches_total"), 4),
            "queue_wait_mean_s": (
                round(qw_mean, 4) if qw_mean is not None else None),
            "device_busy_ratio": round(
                min(1.0, busy_s / 60.0), 4),
            "feed_deltas_per_s": round(
                reg.window_rate("jepsen_feed_deltas_total"), 4),
            "watch_events_per_s": round(
                reg.window_rate("jepsen_watch_events_total"), 4),
            "feed_lag_mean_s": (
                round(lag_mean, 4) if lag_mean is not None else None),
        }
        journal = obs_journal.active()
        sentinel = obs_drift.active()
        return {
            # the resident calibration (doc/tuning.md): the artifact id
            # steering this daemon's window / union-mode / cost-ordered
            # dispatch, or None when running on pinned defaults —
            # CheckerDaemon(cost_fn=...) defaults to the calibration-
            # aware planning.estimated_cost, so the tuned cost table
            # drives largest-cost-first scheduling resident-side
            "calibration": cal.calibration_id if cal is not None else None,
            "ok": self._fatal is None,
            "error": self._fatal,
            "pid": os.getpid(),
            "platform": self._platform,
            "uptime_s": round(time.time() - self.t_start, 1),
            "window": self.window or execution.default_window(),
            # the resident mesh: what slice-matched clients (serve.
            # client mesh-shape servicing) compare their request
            # against; n_devices=1 + mesh_shape=None = single-device
            "n_devices": self._n_devices,
            "mesh_shape": (
                list(self.mesh.devices.shape)
                if self.mesh is not None else None
            ),
            "queue_depth": depth,
            "in_flight": in_flight,
            "max_queue_runs": self.max_queue_runs,
            "max_queue_rows": self.max_queue_rows,
            "stopping": self._stopping.is_set(),
            "warm_hit_ratio": round(stats["warm_dispatches"] / total, 4)
            if total else None,
            "journal_path": journal.path if journal else None,
            "journal_rows": journal.written if journal else 0,
            # cost-model drift sentinel (obs.drift): per-shape EWMA
            # residuals vs the calibration/proxy estimate, the worst-
            # shape aggregate score, and the retune recommendation —
            # None when the journal (and so the sentinel) is off
            "drift": sentinel.snapshot() if sentinel is not None else None,
            # degraded (kernel, E, C) routes currently served by the
            # CPU oracle, with the device error that tripped each
            "quarantine": quarantine,
            "wal_path": self._wal.path if self._wal else None,
            "wal_rows": self._wal.written if self._wal else 0,
            # the AOT executable cache (serve.aotcache): entries warmed
            # at startup vs entries matching this daemon's fingerprint
            # + mesh, and executables recorded this life — the fleet
            # tier's zero-cold-start evidence
            "aot": ({
                "dir": self.aot_cache_dir,
                "warmed": self._aot_warmed,
                "matched": self._aot_matched,
                "recorded": (self._aot_recorder.recorded
                             if self._aot_recorder is not None else 0),
            } if self.aot_cache_dir else None),
            # the online-monitor surface: open ingest sessions and
            # live /watch subscribers (doc/checker-service.md
            # "Online checking")
            "feed_open": feed_open,
            "watch_subscribers": watchers,
            "live": live,
            **stats,
        }

    def trace_dump(self, trace_id: str) -> dict:
        """The ``GET /trace?ctx=`` payload: finished daemon spans
        belonging to one trace (tagged directly, or via the comma-
        joined trace_ids attr a coalesced dispatch carries), plus the
        clock metadata (pid, wall_origin, origin_ns) the client's
        ``obs.propagate.adopt`` needs to rebase them at export."""
        t = obs.tracer()
        spans = [d for d in (rec.to_dict() for rec in t.finished())
                 if propagate.span_matches(d, trace_id)]
        return {"spans": spans, "pid": os.getpid(),
                "wall_origin": t.wall_origin, "origin_ns": t.origin_ns}

    # -- lifecycle ------------------------------------------------------------

    def start(self, block: bool = True) -> "CheckerDaemon":
        obs.enable()  # live /metrics needs the registry recording
        if self.journal_path:
            obs_journal.configure(self.journal_path,
                                  self.journal_max_bytes)
            if self.drift:
                # warm start: a restarted daemon rescores the rows its
                # previous life journalled, so the drift view survives
                # a crash exactly like the WAL's verdicts do
                sentinel = obs_drift.configure(self.drift_threshold)
                sentinel.scan(self.journal_path)
        if self.wal_path:
            # build the replay index BEFORE the writer reopens the
            # file: every verdict a previous daemon life settled is
            # replayed into retried requests instead of re-dispatched
            self._wal_replay = obs_journal.replay_index(self.wal_path)  # jt: allow[concurrency-unguarded-shared] — written before serve/device threads start (Thread.start publication)
            self._wal = obs_journal.VerdictWAL(self.wal_path)  # jt: allow[concurrency-unguarded-shared] — written before serve/device threads start (Thread.start publication)
        handler = _make_handler(self)
        self._server = ThreadingHTTPServer((self.host, self.port), handler)  # jt: allow[concurrency-unguarded-shared] — written before serve/device threads start (Thread.start publication)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._device_thread = threading.Thread(
            target=self._device_loop, name="jepsen-serve-device",
            daemon=True,
        )
        self._device_thread.start()
        self._ready.wait()  # jt: allow[net-timeout] — own device thread signals after jit warmup; bounding startup is the supervisor's job
        if block:
            print(
                f"jepsen-tpu checker service on "
                f"http://{self.host}:{self.port}/ (pid {os.getpid()})"
            )
            try:
                self._server.serve_forever()  # jt: allow[net-timeout] — the accept loop IS the process; shutdown() ends it
            finally:
                self.stop()
        else:
            threading.Thread(
                target=self._server.serve_forever, daemon=True
            ).start()
        return self

    def request_shutdown(self) -> dict:
        """Stop admitting, let the device thread drain, then stop the
        HTTP server from a helper thread (the handler that called this
        still needs to flush its response)."""
        with self._wake:
            already = self._stopping.is_set()
            self._stopping.set()
            draining = len(self._queue)
            self._wake.notify_all()
        if not already:
            threading.Thread(target=self._finish_stop, daemon=True).start()
        return {"ok": True, "draining": draining}

    def _finish_stop(self) -> None:  # jt: thread-entry
        if self._device_thread is not None:
            self._device_thread.join(timeout=DEFAULT_REQUEST_TIMEOUT_S)
        # tiny grace so in-flight handlers (incl. the /shutdown one)
        # finish writing before the listener dies
        time.sleep(0.05)
        if self._server is not None:
            self._server.shutdown()

    def stop(self) -> None:
        """Synchronous teardown (tests): drain + stop + join."""
        self.request_shutdown()
        if self._device_thread is not None:
            self._device_thread.join(timeout=30)
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()

    # -- idempotent retries (handler threads) --------------------------------

    def _dedup_hit(self, req_id) -> Optional[Tuple[int, dict]]:
        return self.admission.dedup_hit(req_id)

    def _dedup_store(self, req_id, code: int, payload: dict) -> None:
        self.admission.dedup_store(req_id, code, payload)

    # -- graceful degradation (device thread) --------------------------------

    def _mark_quarantined(self, routes, err) -> int:
        return self.admission.mark_quarantined(routes, err)

    def _salvage_executor(self, executor) -> list:
        """Capture the in-flight chunks' row tokens, then reset the
        window: after a device fault the executor's transient state is
        poisoned (see Executor.reset), but the un-settled rows it was
        carrying can still get verdicts from the oracle pool."""
        pending = [tok for ch in executor._chunks.values()
                   for tok in ch["rows"]]
        # parked escalations carry rows too — reset() would drop them
        pending += [tok for esc in executor._pending_escalations
                    for tok in esc[2]]
        executor.reset()
        return pending

    # -- the /check entry (handler threads) ----------------------------------

    def handle_profile(self, body: bytes) -> Tuple[int, dict]:
        """``POST /profile``: one bounded on-demand device-profiling
        window (obs.profiling) on the serving process — jax.profiler
        trace + per-device memory high-water — without stopping the
        daemon.  Runs on the handler thread: capture is passive (no
        device dispatch of its own), so in-flight checking traffic IS
        the workload being profiled."""
        try:
            req = protocol.decode_body(body) if body else {}
        except Exception as e:  # noqa: BLE001 — malformed client input
            return 400, {"error": f"bad request: {e!r}"}
        if not isinstance(req, dict):
            return 400, {"error": "bad request: body must be an object"}
        try:
            seconds = float(req.get("seconds", 1.0))
        except (TypeError, ValueError):
            return 400, {"error": "bad request: seconds must be a number"}
        label = str(req.get("label") or "")
        out_dir = req.get("dir")
        if not out_dir:
            stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            name = f"{stamp}-{label}" if label else stamp
            out_dir = os.path.join(self.profile_dir, name)
        try:
            manifest = obs_profiling.capture(out_dir, seconds=seconds,
                                             label=label)
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            return 500, {"error": f"profile capture failed: {e!r}"}
        return 200, {"ok": True, "dir": out_dir, "manifest": manifest}

    def handle_check(self, body: bytes) -> Tuple[int, dict]:
        if self._fatal is not None:
            return 500, {"error": f"device thread failed: {self._fatal}"}
        try:
            payload = protocol.decode_body(body)
            model = protocol.model_from_wire(payload["model"])
            histories = protocol.histories_from_wire(payload["histories"])
            opts = payload.get("opts") or {}
        except Exception as e:  # noqa: BLE001 — malformed client input
            return 400, {"error": f"bad request: {e!r}"}
        ctx = propagate.parse_ctx(payload.get("trace_ctx"))
        attrs = {"histories": len(histories)}
        if ctx:
            # the daemon half of the cross-seam trace: tagged so a
            # later GET /trace?ctx= can slice this request's spans out
            # and obs.export can stitch a flow event to the client span
            attrs[propagate.ATTR_TRACE_ID] = ctx["trace_id"]
            attrs[propagate.ATTR_ROLE] = "daemon"
            attrs["parent_sid"] = ctx["parent_sid"]
        with obs.span("serve/check", cat="serve", **attrs):
            return self._check_flow(payload, model, histories, opts,
                                    ctx["trace_id"] if ctx else None)

    def _check_opts(self, wire_model: dict, opts: dict):
        """Resolve a request's planning/execution option dicts and its
        compatible-group key (shared between /check and /feed so a
        feed delta coalesces with check traffic under the same model
        and options)."""
        from ..ops import wgl

        plan_opts = {
            "slot_cap": opts.get("slot_cap", wgl.DEFAULT_SLOT_CAP),
            "frontier": opts.get("frontier", wgl.DEFAULT_FRONTIER),
            "max_closure": opts.get("max_closure"),
            "max_dispatch": opts.get(
                "max_dispatch", wgl.DEFAULT_MAX_DISPATCH),
        }
        esc = opts.get("escalation")
        exec_opts = {
            "escalation": (
                wgl.ESCALATION_FACTORS if esc is None else tuple(esc)
            ),
            "sufficient_rung": bool(opts.get("sufficient_rung", True)),
            "max_dispatch": plan_opts["max_dispatch"],
        }
        # compatible-group key: requests coalesce into shared dispatch
        # chunks only when the model AND every planning/execution
        # option agree (the wire model dict is canonical-enough: same
        # construction → same dict)
        group_key = (
            json.dumps(wire_model, sort_keys=True, default=repr),
            json.dumps(plan_opts, sort_keys=True),
            json.dumps(
                {**exec_opts, "escalation": list(exec_opts["escalation"])},
                sort_keys=True,
            ),
        )
        return plan_opts, exec_opts, group_key

    def _check_flow(self, payload, model, histories, opts,
                    trace_id: Optional[str]) -> Tuple[int, dict]:
        #: the client's idempotency key (serve.protocol request ids) —
        #: doubles as the verdict-WAL run id, so a retry after a
        #: daemon crash finds its settled partitions under the same id
        req_id = payload.get("req")
        cached = self._dedup_hit(req_id)
        if cached is not None:
            return cached
        if not self.precheck_admit(len(histories)):
            # overload sheds BEFORE the planning half: no encode, no
            # oracle-pool submissions for a request we will refuse
            with self._wake:
                depth = len(self._queue)
                self.stats["rejected"] += 1
            obs.count("jepsen_serve_rejected_total")
            return 503, {
                "error": "backlogged",
                "queue_depth": depth,
                "stopping": self._stopping.is_set(),
            }
        plan_opts, exec_opts, group_key = self._check_opts(
            payload["model"], opts)
        # the decomposition front-end runs handler-side (pure host
        # work): partitionable histories split into per-partition
        # sub-histories whose buckets then coalesce across runs like
        # any others (see _process_group's per-tag merge)
        run = decompose.DecomposedRun(
            model, histories,
            oracle_fallback=bool(opts.get("oracle_fallback", True)),
        )
        # crash-safe resumption: every NEW verdict appends to the WAL
        # under this request's id, and a RETRIED id replays the
        # verdicts a previous daemon life already settled — replay
        # runs BEFORE encode, so settled rows never re-encode and only
        # unsettled partitions re-dispatch (the planner's settled-row
        # skip)
        replayed = 0
        if self._wal is not None:
            run.attach_wal(
                self._wal.sink_for(req_id or protocol.request_id()))
            prior = self._wal_replay.get(req_id) if req_id else None
            if prior:
                replayed = run.replay(prior)
                if replayed:
                    with self._wake:
                        self.stats["replayed"] += replayed
                    obs.count("jepsen_serve_wal_replayed_total", replayed)
        streams = []
        with obs.span("serve/plan", cat="serve", histories=len(histories)):
            for tag, sctx in run.streams():
                planner = planning.Planner(
                    sctx.model, spec=sctx.spec, bucketed=True, **plan_opts
                )
                buckets, order = planner.encode_buckets(sctx)
                streams.append(
                    _Stream(tag, sctx.model, sctx.spec, buckets, order)
                )
        req = _Request(run, streams, group_key, model, plan_opts,
                       exec_opts, len(histories), trace_id=trace_id)
        req.replayed = replayed
        if not self.admit(req):
            # planning already submitted this run's unencodable rows
            # to the oracle pool; cancel what has not started — the
            # 503'd client re-runs everything in-process anyway
            req.abandoned = True
            run.abandon_oracles()
            with self._wake:
                depth = len(self._queue)
            return 503, {
                "error": "backlogged",
                "queue_depth": depth,
                "stopping": self._stopping.is_set(),
            }
        if not req.device_done.wait(
            _env_float("JEPSEN_TPU_SERVE_REQUEST_TIMEOUT",
                       DEFAULT_REQUEST_TIMEOUT_S)
        ):
            # nobody will read this request's results.  Only the flag
            # is set here: the DEVICE thread owns ctx once the request
            # is queued (it may be settling rows right now), so it —
            # not this handler — cancels the orphaned oracle futures
            # when it sees the flag (skip path and post-group check);
            # a handler-side abandon would race route_oracle's dict
            # inserts mid-settle
            req.abandoned = True
            return 500, {"error": "device thread timed out"}
        if req.error is not None:
            return 500, {"error": req.error}
        run.drain_oracles()
        diag = dict(req.diag)
        # the resumption evidence the chaos harness keys on: how many
        # slots came pre-settled from the WAL vs settled in total
        diag["replayed"] = req.replayed
        diag["settled"] = run.settled_count()
        body = {
            "results": protocol.sanitize_results(run.results()),
            "diag": diag,
        }
        self._dedup_store(req_id, 200, body)
        return 200, body

    # -- the /feed entry (handler threads) -----------------------------------

    def handle_feed(self, body: bytes) -> Tuple[int, dict]:
        """Streaming ingest (doc/checker-service.md "Online
        checking"): one endpoint, three ops — ``open`` a session,
        ``append`` deltas (whole histories and/or completed-op dicts),
        ``close`` for the authoritative merged results.  Every delta
        encodes, buckets, and dispatches THROUGH THE DEVICE THREAD the
        moment it arrives, so a violation at op 40k settles (and hits
        the WAL, and every ``/watch`` subscriber) near op 40k instead
        of at run end."""
        if self._fatal is not None:
            return 500, {"error": f"device thread failed: {self._fatal}"}
        try:
            payload = protocol.decode_body(body)
            fop = payload.get("op")
        except Exception as e:  # noqa: BLE001 — malformed client input
            return 400, {"error": f"bad request: {e!r}"}
        with obs.span("serve/feed", cat="serve", op=str(fop)):
            if fop == "open":
                return self._feed_open(payload)
            if fop == "append":
                return self._feed_append(payload)
            if fop == "close":
                return self._feed_close(payload)
            return 400, {"error": f"unknown feed op {fop!r}"}

    def _feed_open(self, payload) -> Tuple[int, dict]:
        try:
            model = protocol.model_from_wire(payload["model"])
            opts = payload.get("opts") or {}
            plan_opts, exec_opts, group_key = self._check_opts(
                payload["model"], opts)
        except Exception as e:  # noqa: BLE001 — malformed client input
            return 400, {"error": f"bad request: {e!r}"}
        ctx = propagate.parse_ctx(payload.get("trace_ctx"))
        #: the session id doubles as the verdict-WAL run id, so a
        #: session re-opened after a daemon crash (same client req id)
        #: replays its settled partitions into resumed deltas
        sid = payload.get("req") or protocol.request_id()
        run = decompose.DecomposedRun(
            model, [],
            oracle_fallback=bool(opts.get("oracle_fallback", True)),
            lazy=True,
        )
        prior: dict = {}
        if self._wal is not None:
            run.attach_wal(self._wal.sink_for(sid))
            prior = self._wal_replay.get(sid) or {}
        s = _FeedSession(sid, run, model, plan_opts, exec_opts,
                         group_key, ctx["trace_id"] if ctx else None,
                         dict(prior))
        with self._wake:
            if self._stopping.is_set():
                return 503, {"error": "stopping", "stopping": True}
            if sid in self._feeds:
                # idempotent re-open (retry whose response was lost):
                # the existing session keeps its state
                return 200, {"session": sid, "resumed": True}
            self._feeds[sid] = s
            self.stats["feed_sessions"] += 1
            n_open = len(self._feeds)
        obs.count("jepsen_feed_sessions_total")
        obs.gauge_set("jepsen_feed_open_sessions", n_open)
        return 200, {"session": sid, "resumed": False}

    def _feed_session(self, payload):
        sid = payload.get("session")
        with self._wake:
            s = self._feeds.get(sid)
        if s is None:
            return None, (404, {"error": f"unknown feed session {sid!r}"})
        return s, None

    def _feed_append(self, payload) -> Tuple[int, dict]:
        s, err = self._feed_session(payload)
        if s is None:
            return err
        try:
            seq = int(payload.get("seq"))
        except (TypeError, ValueError):
            return 400, {"error": "bad seq"}
        with s.lock:
            if seq <= s.last_seq:
                # retried delta (response lost on the wire): already
                # ingested — ack without re-dispatching anything
                return 200, {"session": s.sid, "seq": seq,
                             "duplicate": True, "accepted": 0,
                             "settled": s.run.settled_count()}
            try:
                histories = protocol.histories_from_wire(
                    payload.get("histories") or [])
            except Exception as e:  # noqa: BLE001 — malformed input
                return 400, {"error": f"bad request: {e!r}"}
            n_client = len(histories)
            ops = payload.get("ops") or []
            all_ops = s.ops
            if ops:
                # op-mode: accumulate the shipped events (real-time
                # order) and probe the assembled prefix history — the
                # P-compositionality bet: grown partitions recheck
                # cheaply in isolation, so the probe prices like its
                # changed keys, not like the whole run.  The buffer
                # commits only on dispatch success: a 503'd delta the
                # client retries must not double-ingest its ops.
                from ..history import History

                try:
                    all_ops = s.ops + [dict(o) for o in ops]
                    probe = History.from_dicts(all_ops)
                except Exception as e:  # noqa: BLE001 — malformed input
                    return 400, {"error": f"bad ops: {e!r}"}
                histories = list(histories) + [probe]
            base = s.run.n
            code, resp = self._feed_dispatch(s, histories,
                                             payload.get("t_inv"))
            if code != 200:
                return code, resp
            s.history_idx.extend(range(base, base + n_client))
            if ops:
                s.ops = all_ops
                s.probe_idx = base + n_client
                s.probed_n = len(s.ops)
            s.last_seq = seq
            resp["seq"] = seq
            return code, resp

    def _feed_dispatch(self, s: _FeedSession, histories,
                       t_inv) -> Tuple[int, dict]:
        """Ingest one delta: extend the session run, replay any WAL
        rows a previous daemon life settled for the fresh slots,
        encode ONLY the new rows, and push them through the device
        thread like any admitted request (coalescing with concurrent
        traffic under the session's group key)."""
        if not histories:
            return 200, {"session": s.sid, "accepted": 0, "rows": 0,
                         "replayed": 0,
                         "settled": s.run.settled_count()}
        if not self.precheck_admit(len(histories)):
            with self._wake:
                depth = len(self._queue)
                self.stats["rejected"] += 1
            obs.count("jepsen_serve_rejected_total")
            return 503, {
                "error": "backlogged",
                "queue_depth": depth,
                "stopping": self._stopping.is_set(),
            }
        rows = s.run.extend(histories)
        replayed = 0
        if s.prior:
            replayed = s.run.replay(s.prior)
            if replayed:
                with self._wake:
                    self.stats["replayed"] += replayed
                obs.count("jepsen_serve_wal_replayed_total", replayed)
        streams = []
        with obs.span("serve/feed-plan", cat="serve",
                      histories=len(histories)):
            for tag, sctx in s.run.streams():
                idxs = [i for c, i in rows if c is sctx]
                if not idxs:
                    continue
                planner = planning.Planner(
                    sctx.model, spec=sctx.spec, bucketed=True,
                    **s.plan_opts,
                )
                buckets, order = planner.encode_rows(sctx, idxs)
                streams.append(
                    _Stream(tag, sctx.model, sctx.spec, buckets, order))
        req = _FeedDelta(s.run, streams, s.group_key, s.model,
                         s.plan_opts, s.exec_opts, len(histories),
                         trace_id=s.trace_id)
        # the row budget must see THIS delta's queued footprint, not
        # the whole session run _Request.rows would count
        req.rows = len(rows)
        if not self.admit(req):
            req.abandoned = True
            s.run.abandon_oracles()
            with self._wake:
                depth = len(self._queue)
            return 503, {
                "error": "backlogged",
                "queue_depth": depth,
                "stopping": self._stopping.is_set(),
            }
        if not req.device_done.wait(
            _env_float("JEPSEN_TPU_SERVE_REQUEST_TIMEOUT",
                       DEFAULT_REQUEST_TIMEOUT_S)
        ):
            req.abandoned = True
            return 500, {"error": "device thread timed out"}
        if req.error is not None:
            return 500, {"error": req.error}
        s.run.drain_oracles()
        if t_inv is not None:
            try:
                # detect-time minus invoke-time: the monitor's core
                # promise, as a histogram
                obs.observe("jepsen_feed_ingest_lag_seconds",
                            max(0.0, time.time() - float(t_inv)))
            except (TypeError, ValueError):
                pass
        with self._wake:
            self.stats["feed_deltas"] += 1
            self.stats["feed_histories"] += len(histories)
        obs.count("jepsen_feed_deltas_total")
        obs.count("jepsen_feed_histories_total", len(histories))
        return 200, {"session": s.sid, "accepted": len(histories),
                     "rows": len(rows), "replayed": replayed,
                     "settled": s.run.settled_count(),
                     "diag": dict(req.diag)}

    def _feed_close(self, payload) -> Tuple[int, dict]:
        req_id = payload.get("req")
        cached = self._dedup_hit(req_id)
        if cached is not None:
            return cached
        s, err = self._feed_session(payload)
        if s is None:
            return err
        with s.lock:
            final_idx = s.probe_idx
            if s.ops and s.probed_n < len(s.ops):
                # ops arrived since the last probe: run the
                # authoritative final check over the complete history
                from ..history import History

                try:
                    final = History.from_dicts(s.ops)
                except Exception as e:  # noqa: BLE001 — malformed input
                    return 400, {"error": f"bad ops: {e!r}"}
                final_idx = s.run.n
                code, resp = self._feed_dispatch(s, [final], None)
                if code != 200:
                    return code, resp
            s.run.drain_oracles()
            results = s.run.results()
            out = [results[i] for i in s.history_idx]
            if final_idx is not None:
                out.append(results[final_idx])
            body = {
                "results": protocol.sanitize_results(out),
                "diag": {
                    "session": s.sid,
                    "deltas": s.last_seq + 1,
                    "histories": len(s.history_idx),
                    "ops": len(s.ops),
                    "settled": s.run.settled_count(),
                    "partitions": s.run.n_partitions,
                },
            }
        with self._wake:
            self._feeds.pop(s.sid, None)
            n_open = len(self._feeds)
        obs.gauge_set("jepsen_feed_open_sessions", n_open)
        self._dedup_store(req_id, 200, body)
        return 200, body

    # -- the /watch channel (handler threads) --------------------------------

    def _watch_enter(self) -> None:
        with self._wake:
            self._watchers += 1
            n = self._watchers
        obs.gauge_set("jepsen_watch_subscribers", n)

    def _watch_exit(self) -> None:
        with self._wake:
            self._watchers -= 1
            n = self._watchers
        obs.gauge_set("jepsen_watch_subscribers", n)

    # -- the /elle entry (handler threads) -----------------------------------

    def handle_elle(self, body: bytes) -> Tuple[int, dict]:
        """Screen a batch of encoded dependency graphs (the Elle
        transactional screens) on the resident executor; concurrent
        /elle batches coalesce same-(bucket, profile) graphs into
        shared dispatches (see :meth:`_process_elle`)."""
        if self._fatal is not None:
            return 500, {"error": f"device thread failed: {self._fatal}"}
        try:
            payload = protocol.decode_body(body)
            graphs = protocol.elle_graphs_from_wire(payload["graphs"])
        except Exception as e:  # noqa: BLE001 — malformed client input
            return 400, {"error": f"bad request: {e!r}"}
        ctx = propagate.parse_ctx(payload.get("trace_ctx"))
        attrs = {"graphs": len(graphs)}
        if ctx:
            attrs[propagate.ATTR_TRACE_ID] = ctx["trace_id"]
            attrs[propagate.ATTR_ROLE] = "daemon"
            attrs["parent_sid"] = ctx["parent_sid"]
        with obs.span("serve/elle", cat="serve", **attrs):
            return self._elle_flow(graphs,
                                   ctx["trace_id"] if ctx else None,
                                   req_id=payload.get("req"))

    def _elle_flow(self, graphs, trace_id: Optional[str],
                   req_id: Optional[str] = None) -> Tuple[int, dict]:
        cached = self._dedup_hit(req_id)
        if cached is not None:
            return cached
        req = _ElleRequest(graphs, trace_id=trace_id)
        if not self.admit(req):
            with self._wake:
                depth = len(self._queue)
            return 503, {
                "error": "backlogged",
                "queue_depth": depth,
                "stopping": self._stopping.is_set(),
            }
        if not req.device_done.wait(
            _env_float("JEPSEN_TPU_SERVE_REQUEST_TIMEOUT",
                       DEFAULT_REQUEST_TIMEOUT_S)
        ):
            req.abandoned = True
            return 500, {"error": "device thread timed out"}
        if req.error is not None:
            return 500, {"error": req.error}
        body = {
            "results": protocol.elle_results_to_wire(req.results or []),
            "diag": req.diag,
        }
        self._dedup_store(req_id, 200, body)
        return 200, body


def _make_handler(daemon: CheckerDaemon):
    class Handler(BaseHTTPRequestHandler):
        # one daemon per handler class: bound at server build time
        protocol_version = "HTTP/1.1"

        def _reply(self, code: int, body: bytes,
                   ctype: str = "application/json"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, code: int, payload: dict):
            self._reply(code, protocol.encode_body(payload))

        def do_GET(self):  # noqa: N802 — http.server API, jt: thread-entry
            try:
                if self.path == "/healthz":
                    ok = daemon._fatal is None
                    self._reply_json(200 if ok else 500, {
                        "ok": ok,
                        "error": daemon._fatal,
                        "platform": daemon._platform,
                        "uptime_s": round(time.time() - daemon.t_start, 1),
                    })
                elif self.path == "/status":
                    self._reply_json(200, daemon.status())
                elif self.path == "/metrics":
                    # live scrape — the SAME formatter as the at-exit
                    # metrics.prom dump (obs.render_prom)
                    self._reply(200, obs.render_prom().encode(),
                                "text/plain; version=0.0.4")
                elif self.path.startswith("/trace"):
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    ctx = (q.get("ctx") or [""])[0]
                    if not ctx:
                        self._reply_json(400, {"error": "missing ctx"})
                    else:
                        self._reply_json(200, daemon.trace_dump(ctx))
                elif self.path.startswith("/watch"):
                    self._serve_watch()
                else:
                    self._reply_json(404, {"error": "not found"})
            except BrokenPipeError:
                pass

        def _serve_watch(self):
            """The verdict watch channel: settled verdicts as
            server-sent events tailing the verdict WAL.  Each event's
            ``id:`` is the WAL's logical valid-row offset (damaged
            lines consume no offset), so a reconnecting subscriber
            sends ``Last-Event-ID`` and resumes exactly after the last
            row it saw — nothing replays twice.  The stream is
            unframed, so the response closes the connection when it
            ends (``Connection: close`` under this handler's
            HTTP/1.1)."""
            if daemon._wal is None:
                self._reply_json(404, {"error": "no verdict WAL"})
                return
            try:
                start = int(self.headers.get("Last-Event-ID")) + 1
            except (TypeError, ValueError):
                start = 0
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-store")
            self.send_header("Connection", "close")
            self.end_headers()
            daemon._watch_enter()
            tail = obs_journal.WalTail(daemon._wal.path, start=start)
            first = True
            quiet_s = 0.0
            try:
                while not daemon._stopping.is_set():
                    events = tail.poll()
                    if events:
                        if first:
                            # the catch-up burst: rows that settled
                            # before this subscriber connected/resumed
                            obs.count("jepsen_watch_replay_rows_total",
                                      len(events))
                        chunk = "".join(
                            f"id: {off}\ndata: "
                            f"{json.dumps(row, sort_keys=True)}\n\n"
                            for off, row in events
                        )
                        self.wfile.write(chunk.encode())
                        self.wfile.flush()
                        obs.count("jepsen_watch_events_total",
                                  len(events))
                        with daemon._wake:
                            daemon.stats["watch_events"] += len(events)
                        quiet_s = 0.0
                    else:
                        time.sleep(0.1)
                        quiet_s += 0.1
                        if quiet_s >= 5.0:
                            # a dead subscriber only surfaces on write:
                            # ping through quiet stretches so stale
                            # watcher threads reap promptly
                            self.wfile.write(b": keep-alive\n\n")
                            self.wfile.flush()
                            quiet_s = 0.0
                    first = False
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass  # subscriber went away — normal lifecycle
            finally:
                daemon._watch_exit()

        def do_POST(self):  # noqa: N802 — http.server API, jt: thread-entry
            try:
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                if self.path == "/check":
                    code, payload = daemon.handle_check(body)
                    self._reply_json(code, payload)
                elif self.path == "/elle":
                    code, payload = daemon.handle_elle(body)
                    self._reply_json(code, payload)
                elif self.path == "/feed":
                    code, payload = daemon.handle_feed(body)
                    self._reply_json(code, payload)
                elif self.path == "/profile":
                    code, payload = daemon.handle_profile(body)
                    self._reply_json(code, payload)
                elif self.path == "/shutdown":
                    self._reply_json(200, daemon.request_shutdown())
                else:
                    self._reply_json(404, {"error": "not found"})
            except BrokenPipeError:
                pass

        def log_message(self, fmt, *args):
            pass  # the daemon's obs metrics are the log of record

    return Handler


def serve(host: str = protocol.DEFAULT_HOST,
          port: Optional[int] = None,
          *,
          window: Optional[int] = None,
          block: bool = True,
          **kw) -> CheckerDaemon:
    """Build and start a checker daemon (the ``cli serve --checker``
    / ``python -m jepsen_tpu.serve`` entry)."""
    if port is None:
        port = int(os.environ.get("JEPSEN_TPU_SERVE_PORT",
                                  protocol.DEFAULT_PORT))
    if "journal_path" not in kw:
        # the production entry journals by default (the constructor
        # default stays off for in-process/test daemons): path from
        # JEPSEN_TPU_JOURNAL, falsy values disable
        jp = os.environ.get("JEPSEN_TPU_JOURNAL",
                            obs_journal.DEFAULT_FILENAME)
        if jp.lower() in ("0", "false", "off", "no", ""):
            jp = None
        kw["journal_path"] = jp
    if "wal_path" not in kw:
        # crash-safe by default at the production entry, like the
        # dispatch journal: verdicts append to JEPSEN_TPU_WAL and a
        # restarted daemon replays them into retried request ids
        # (doc/checker-service.md "Failure modes & recovery")
        wp = os.environ.get("JEPSEN_TPU_WAL",
                            obs_journal.DEFAULT_WAL_FILENAME)
        if wp.lower() in ("0", "false", "off", "no", ""):
            wp = None
        kw["wal_path"] = wp
    if "drift" not in kw:
        # drift sentinel on by default at the production entry (it
        # rides the journal, so a disabled journal disables it too);
        # falsy JEPSEN_TPU_DRIFT opts out explicitly
        dr = os.environ.get("JEPSEN_TPU_DRIFT", "1")
        kw["drift"] = dr.lower() not in ("0", "false", "off", "no", "")
    if "aot_cache_dir" not in kw:
        # the fleet tier's shared AOT executable cache
        # (doc/checker-service.md "Fleet tier"): record every cold
        # compile, pre-warm them all at startup.  Off unless the env
        # names a directory; falsy values disable.
        ad = os.environ.get("JEPSEN_TPU_SERVE_AOT_CACHE", "")
        if ad.lower() in ("0", "false", "off", "no", ""):
            ad = None
        kw["aot_cache_dir"] = ad
    # a persistent jit cache survives daemon crashes: the supervised
    # restart re-warms compiled kernels from disk instead of paying
    # every cold compile again.  Best-effort — an older jax without
    # the knob just runs cold.  The AOT cache grows this seam: when
    # only JEPSEN_TPU_SERVE_AOT_CACHE is set, its xla/ subdir becomes
    # the compilation cache, so the manifest replay at startup loads
    # executables from disk instead of re-jitting them.
    cache_dir = os.environ.get("JEPSEN_TPU_SERVE_JIT_CACHE", "")
    if not (cache_dir and cache_dir.lower() not in
            ("0", "false", "off", "no")) and kw["aot_cache_dir"]:
        from . import aotcache

        cache_dir = aotcache.xla_cache_dir(kw["aot_cache_dir"])
    if cache_dir and cache_dir.lower() not in ("0", "false", "off", "no"):
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", cache_dir)
        except Exception:  # noqa: BLE001 — cache warming is optional
            pass
    d = CheckerDaemon(host, port, window=window, **kw)
    return d.start(block=block)


def supervise(child_args, *, max_restarts: int = 16,
              backoff_s: float = 1.0, max_backoff_s: float = 30.0,
              env: Optional[dict] = None,
              _state: Optional[dict] = None,
              _signals: bool = True) -> int:
    """``serve --supervise``: run the daemon as a child process and
    restart it whenever it dies abnormally (kill -9, device wedge, OOM
    — the faults the self-chaos harness injects).  The restarted child
    inherits this process's environment (or ``env`` when given — the
    fleet supervisor's per-member WAL/journal overrides), so it
    re-warms from the same dispatch journal, verdict WAL, and
    jit/AOT cache paths: clients that retry their request ids replay
    settled verdicts instead of recomputing them.  Returns the child's
    final exit code — 0 on a clean exit (/shutdown) or supervisor
    signal, the last crash code once the restart budget is exhausted.

    ``_state``/``_signals`` are :func:`supervise_fleet` seams: the
    fleet runs one ``supervise`` per member on worker threads, where
    ``signal.signal`` is illegal — it installs ONE handler on the main
    thread and terminates every member through its shared state box."""
    import signal
    import subprocess
    import sys

    cmd = [sys.executable, "-m", "jepsen_tpu.serve", *child_args]
    state = _state if _state is not None else {"sig": None, "proc": None}

    def _forward(signum, frame):  # jt: thread-entry
        state["sig"] = signum
        p = state["proc"]
        if p is not None and p.poll() is None:
            p.terminate()

    if _signals:
        for s in (signal.SIGTERM, signal.SIGINT):
            signal.signal(s, _forward)
    restarts = 0
    delay = backoff_s
    while True:
        proc = subprocess.Popen(cmd, env=env)
        state["proc"] = proc
        rc = proc.wait()  # jt: allow[net-timeout] — the supervisor's whole job is blocking on the child's lifetime
        if state["sig"] is not None:
            return 0
        if rc == 0:
            return 0
        restarts += 1
        if restarts > max_restarts:
            print(f"jepsen-tpu serve: restart budget exhausted "
                  f"(rc={rc})", file=sys.stderr)
            return rc
        print(f"jepsen-tpu serve: child exited rc={rc}; restart "
              f"{restarts}/{max_restarts} in {delay:.1f}s",
              file=sys.stderr)
        time.sleep(delay)
        delay = min(delay * 2, max_backoff_s)


def fleet_member_env(i: int, base_env: Optional[dict] = None) -> dict:
    """One fleet member's environment: the dispatch journal and
    verdict WAL get a ``-<i>`` suffix (two daemons appending to one
    WAL would interleave torn rows), while the AOT cache dir is left
    UNTOUCHED — sharing compiled executables across members is the
    fleet cache's whole point (the manifest is multi-writer-safe)."""
    env = dict(os.environ if base_env is None else base_env)
    for var, default in (
        ("JEPSEN_TPU_JOURNAL", obs_journal.DEFAULT_FILENAME),
        ("JEPSEN_TPU_WAL", obs_journal.DEFAULT_WAL_FILENAME),
    ):
        cur = env.get(var, default)
        if cur.lower() in ("0", "false", "off", "no", ""):
            continue
        root, ext = os.path.splitext(cur)
        env[var] = f"{root}-{i}{ext}"
    return env


def supervise_fleet(n: int, child_args, *,
                    base_port: Optional[int] = None,
                    max_restarts: int = 16, backoff_s: float = 1.0,
                    max_backoff_s: float = 30.0) -> int:
    """``serve --supervise --fleet N``: N supervised daemons on one
    host — ports ``base_port..base_port+N-1``, per-member WAL/journal
    paths (:func:`fleet_member_env`), one shared AOT executable cache.
    The admission/device split (:class:`AdmissionState`) is what makes
    this just config: each member owns its own queue + executor pair,
    and the router (serve.router) spreads keys across them.  Returns
    the worst member exit code (0 when every member exited clean)."""
    import signal
    import sys

    if base_port is None:
        base_port = int(os.environ.get("JEPSEN_TPU_SERVE_PORT",
                                       protocol.DEFAULT_PORT))
    # each member gets its own --port; strip any caller-supplied one
    args = []
    skip = False
    for a in child_args:
        if skip:
            skip = False
            continue
        if a == "--port":
            skip = True
            continue
        args.append(a)
    boxes = [{"sig": None, "proc": None} for _ in range(n)]

    def _forward(signum, frame):  # jt: thread-entry
        for b in boxes:
            b["sig"] = signum
            p = b["proc"]
            if p is not None and p.poll() is None:
                p.terminate()

    # ONE handler on the main thread (signal.signal is main-thread
    # only); member supervisors run with _signals=False underneath it
    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _forward)
    rcs = [0] * n

    def _member(i: int) -> None:  # jt: thread-entry
        rcs[i] = supervise(
            [*args, "--port", str(base_port + i)],
            max_restarts=max_restarts, backoff_s=backoff_s,
            max_backoff_s=max_backoff_s, env=fleet_member_env(i),
            _state=boxes[i], _signals=False,
        )

    threads = [
        threading.Thread(target=_member, args=(i,),
                         name=f"jepsen-fleet-{i}", daemon=True)
        for i in range(n)
    ]
    for t in threads:
        t.start()
    members = ", ".join(str(base_port + i) for i in range(n))
    print(f"jepsen-tpu serve: supervising fleet of {n} "
          f"(ports {members})", file=sys.stderr)
    for t in threads:
        t.join()  # jt: allow[net-timeout] — the fleet supervisor's whole job is blocking on member lifetimes
    return max(rcs)
