"""Resident checker service: the device, the compiled-kernel cache,
and the oracle worker pool stay warm in one long-lived daemon; client
runs ship encoded histories over a local HTTP seam and share
coalesced device dispatches.

Why: every ``cli test`` run pays backend init and per-shape re-jit —
the bench's r01–r05 rows show init alone can eat the accelerator win.
For the ROADMAP's millions-of-users traffic the device must be
resident; the paper's ``check(self, test, history, opts)`` seam was
designed exactly so the execution substrate could swap without
touching tests, and this daemon is the next substrate.

The split that makes it possible lives in :mod:`jepsen_tpu.engine`:
the pure per-run **planning** layer runs on the daemon's request
handler threads (and unchanged in every in-process run), while ONE
resident device-owning **executor** serves every client — same-shape
buckets from concurrent runs merge into shared dispatch chunks, with
per-row ``(ctx, idx)`` tokens routing each verdict home.

Layout:

- :mod:`~jepsen_tpu.serve.protocol` — wire forms (models, histories,
  opts), endpoint contract, ``UnsupportedModel`` fallback rule.
- :mod:`~jepsen_tpu.serve.daemon` — :class:`CheckerDaemon`: admission
  control, cross-run coalescing, the device thread, live
  ``/metrics``+``/healthz``+``/status``.
- :mod:`~jepsen_tpu.serve.client` — :class:`ServiceClient`,
  :func:`~jepsen_tpu.serve.client.check_batch` (transparent
  fallback), :func:`ServiceChecker` (the ``check(...)`` seam).
- :mod:`~jepsen_tpu.serve.smoke` — ``make serve-smoke``: verdict
  equality vs the in-process engine, warm-cache proof, metrics
  validity, drain-on-shutdown.
- :mod:`~jepsen_tpu.serve.router` — :class:`Router`, the fleet tier's
  routing front: rendezvous-hashes shape keys over ``--member``
  daemons so same-shape traffic coalesces on one resident executor,
  with breaker-driven spillover and probe-driven re-routing.
- :mod:`~jepsen_tpu.serve.aotcache` — the shared on-disk AOT
  executable cache: a restarted member warms from the fleet manifest
  and answers its first request with zero cold dispatches.
- :mod:`~jepsen_tpu.serve.fleet_smoke` — ``make fleet-smoke``: routed
  verdict byte-equality, coalescing proof, kill/spill/rejoin drill,
  warm-restart zero-rejit assertion.

Start one with ``jepsen-tpu serve --checker`` (or ``python -m
jepsen_tpu.serve``); ``jepsen-tpu status`` / ``jepsen-tpu shutdown``
manage it.  ``JEPSEN_TPU_SERVICE=1`` routes checkers through a
reachable daemon, ``=auto`` spawns one on demand.  See
doc/checker-service.md.
"""

from .client import (  # noqa: F401
    ServiceChecker,
    ServiceClient,
    ServiceError,
    ServiceUnavailable,
    analysis,
    check_batch,
    probe_healthz,
    resolve_client,
    service_mode,
    spawn_daemon,
)
from .daemon import CheckerDaemon, serve  # noqa: F401
from .protocol import DEFAULT_HOST, DEFAULT_PORT, UnsupportedModel  # noqa: F401
from .router import Router  # noqa: F401
