"""Client side of the resident checker service.

:class:`ServiceClient` is the thin HTTP wrapper; :func:`check_batch`
is the transparent seam — try the daemon, fall back to the in-process
engine on ANY service problem (no daemon listening, backlogged 503,
unsupported model, mid-request failure).  The fallback is the same
``wgl.check_batch`` the daemon itself runs, so verdicts cannot depend
on which side did the work.

:class:`ServiceChecker` puts the service behind the unchanged
``check(self, test, history, opts)`` protocol: it IS the
linearizable checker with ``algorithm="service"`` — the whole
post-processing tail (failure witness rendering, field truncation) is
inherited, only the analysis hop changes.  ``checker.linearizable``
resolves ``algorithm="auto"`` to the service when
``JEPSEN_TPU_SERVICE`` opts in, so a fleet can flip every run to the
warm daemon with one environment variable and zero test edits.

Resilience (doc/checker-service.md "Failure modes & recovery"): every
``/check``/``/elle`` POST carries an idempotent request id and runs
through bounded exponential backoff with jitter under an overall
per-request deadline budget, behind a per-address circuit breaker
(N consecutive connection failures trip it open; after a cooldown a
single half-open ``/healthz`` probe decides).  An open breaker
fast-fails to :class:`ServiceUnavailable`, which the transparent seam
turns into the in-process engine — a dead daemon costs one probe per
cooldown, not a connect timeout per batch.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..obs import propagate
from . import protocol
from .protocol import UnsupportedModel  # noqa: F401 (re-export)


#: default socket timeout for client requests: a bit above the
#: daemon's own device-thread request timeout (600 s), so a healthy
#: daemon's timeout answer arrives first and a FROZEN daemon (stopped
#: process, dead keep-alive socket) still bounds the checker run —
#: the fallback contract covers hangs, not just refusals
DEFAULT_CLIENT_TIMEOUT_S = 630.0

#: retry/breaker defaults (env-overridable; doc/configuration.md)
DEFAULT_CLIENT_RETRIES = 2
DEFAULT_CLIENT_BACKOFF_S = 0.1
DEFAULT_BREAKER_FAILURES = 3
DEFAULT_BREAKER_COOLDOWN_S = 5.0


def _env_pos_float(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default
    return v if v > 0 else default


def _env_nonneg_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default
    return v if v >= 0 else default


class ServiceError(Exception):
    """The daemon was reachable but could not serve the request."""


class ServiceUnavailable(ServiceError):
    """No healthy daemon at the configured address."""


class CircuitBreaker:
    """Per-address breaker: closed → open after ``failures``
    consecutive connection failures → half-open after ``cooldown_s``
    (one probe decides: success closes, failure re-opens).

    Shared by every :class:`ServiceClient` pointed at one address (the
    transparent seam constructs a fresh client per call, so per-client
    state would never accumulate failures) — see :func:`breaker_for`.
    """

    def __init__(self, failures: int = DEFAULT_BREAKER_FAILURES,
                 cooldown_s: float = DEFAULT_BREAKER_COOLDOWN_S):
        self.failures = max(1, failures)
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._consecutive = 0  # jt: guarded-by(_lock)
        self._opened_at: Optional[float] = None  # jt: guarded-by(_lock)
        self.trips = 0  #: times the breaker tripped open
        self.probes = 0  #: half-open probes attempted

    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if time.monotonic() - self._opened_at >= self.cooldown_s:
                return "half-open"
            return "open"

    def allow(self, probe=None) -> bool:
        """True when a request may proceed.  While open within the
        cooldown: False (fast-fail).  After the cooldown: half-open —
        run ``probe()`` (a cheap liveness check); its verdict closes or
        re-opens the breaker."""
        with self._lock:
            if self._opened_at is None:
                return True
            if time.monotonic() - self._opened_at < self.cooldown_s:
                return False
        # half-open: probe outside the lock (it does network I/O)
        ok = bool(probe()) if probe is not None else False
        with self._lock:
            self.probes += 1
            if ok:
                self._opened_at = None
                self._consecutive = 0
                return True
            self._opened_at = time.monotonic()
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._opened_at = None

    def record_failure(self) -> bool:
        """Count one connection failure; True when this one TRIPPED
        the breaker open."""
        with self._lock:
            self._consecutive += 1
            if (self._opened_at is None
                    and self._consecutive >= self.failures):
                self._opened_at = time.monotonic()
                self.trips += 1
                return True
            return False


#: one breaker per daemon address, process-wide — resolve_client()
#: builds a fresh ServiceClient per seam call, so breaker state must
#: outlive any single client instance
_BREAKERS: Dict[Tuple[str, Optional[int]], CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker_for(host: str, port: Optional[int]) -> CircuitBreaker:
    key = (host, port)
    with _breakers_lock:
        br = _BREAKERS.get(key)
        if br is None:
            br = _BREAKERS[key] = CircuitBreaker(
                failures=_env_nonneg_int("JEPSEN_TPU_BREAKER_FAILURES",
                                         DEFAULT_BREAKER_FAILURES)
                or DEFAULT_BREAKER_FAILURES,
                cooldown_s=_env_pos_float("JEPSEN_TPU_BREAKER_COOLDOWN",
                                          DEFAULT_BREAKER_COOLDOWN_S),
            )
        return br


def reset_breakers() -> None:
    """Forget all breaker state (tests, and a fresh daemon spawn)."""
    with _breakers_lock:
        _BREAKERS.clear()


def probe_healthz(addr: str, timeout: float = 0.5) -> bool:
    """THE ``/healthz`` probe — the one implementation behind both the
    client breaker's half-open cooldown probe and the router's member
    health sweeps, so the two share a single timeout/exception taxonomy
    (connection-level failures AND malformed bodies are both "down")
    instead of drifting apart as hand-rolled urlopen paths.  ``addr``
    is ``HOST:PORT``.  Counted in ``jepsen_probe_healthz_total`` by
    outcome; never raises."""
    req = urllib.request.Request(f"http://{addr}/healthz", method="GET")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            ok = (resp.status == 200
                  and bool(protocol.decode_body(resp.read()).get("ok")))
    except (urllib.error.URLError, ConnectionError, OSError, ValueError):
        ok = False
    obs.count("jepsen_probe_healthz_total", outcome="up" if ok else "down")
    return ok


def service_mode() -> str:
    """``JEPSEN_TPU_SERVICE``: ``""``/``0`` off (default), ``1``/any
    truthy = use a reachable daemon, ``auto`` = additionally spawn one
    when none is listening."""
    v = os.environ.get("JEPSEN_TPU_SERVICE", "").strip().lower()
    if v in ("", "0", "false", "off", "no"):
        return "off"
    if v == "auto":
        return "auto"
    return "on"


class ServiceClient:
    """HTTP client for one daemon address (default: localhost
    ``JEPSEN_TPU_SERVE_PORT`` / :data:`protocol.DEFAULT_PORT`)."""

    def __init__(
        self,
        host: Optional[str] = None,
        port: Optional[int] = None,
        timeout: Optional[float] = None,
    ):
        self.host = host or os.environ.get(
            "JEPSEN_TPU_SERVE_HOST", protocol.DEFAULT_HOST)
        try:
            self.port: Optional[int] = int(
                port
                if port is not None
                else os.environ.get("JEPSEN_TPU_SERVE_PORT",
                                    protocol.DEFAULT_PORT)
            )
        except (TypeError, ValueError):
            # a mis-set JEPSEN_TPU_SERVE_PORT must degrade like an
            # absent daemon (the seam promises in-process fallback for
            # ANY service problem), never crash the checker run —
            # and silently retargeting the default port could hit a
            # daemon the user didn't intend
            self.port = None
        self.timeout = timeout
        self.last_diag: dict = {}
        self.spawned_pid: Optional[int] = None

    def _url(self, path: str) -> str:
        return f"http://{self.host}:{self.port}{path}"

    def _request(self, path: str, body: Optional[bytes] = None,
                 timeout: Optional[float] = None):
        if self.port is None:
            raise ServiceUnavailable(
                "invalid JEPSEN_TPU_SERVE_PORT "
                f"({os.environ.get('JEPSEN_TPU_SERVE_PORT')!r})")
        req = urllib.request.Request(
            self._url(path),
            data=body,
            method="POST" if body is not None else "GET",
            headers={"Content-Type": "application/json"}
            if body is not None else {},
        )
        try:
            with urllib.request.urlopen(
                req,
                timeout=timeout or self.timeout or DEFAULT_CLIENT_TIMEOUT_S,
            ) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise ServiceUnavailable(f"no daemon at {self._url('')}: {e}")

    def _resilient_post(self, path: str, body: bytes):
        """POST with retry/backoff/deadline through the address's
        circuit breaker (the body — and its idempotent request id —
        is byte-identical across attempts, so the daemon can dedupe).

        - **deadline budget**: the whole call (attempts + backoff
          sleeps) is bounded by ``JEPSEN_TPU_CLIENT_DEADLINE`` (or the
          client's own timeout when smaller) — a stalled daemon can
          never hang the checker past it.
        - **retries**: connection-level failures retry up to
          ``JEPSEN_TPU_CLIENT_RETRIES`` times with exponential backoff
          + full jitter from ``JEPSEN_TPU_CLIENT_BACKOFF``.  HTTP-level
          errors (503 backlog, daemon-side 500) do NOT retry: the
          daemon answered; retrying would fight its load shedding.
        - **breaker**: open → immediate :class:`ServiceUnavailable`
          (the seam falls back in-process); half-open → one
          ``/healthz`` probe decides.
        """
        br = breaker_for(self.host, self.port)
        if not br.allow(lambda: self._probe(br)):
            raise ServiceUnavailable(
                f"circuit open for {self.host}:{self.port} "
                f"(state {br.state()})")
        attempt_timeout = self.timeout or DEFAULT_CLIENT_TIMEOUT_S
        budget = min(
            _env_pos_float("JEPSEN_TPU_CLIENT_DEADLINE",
                           DEFAULT_CLIENT_TIMEOUT_S),
            attempt_timeout if self.timeout else float("inf"),
        )
        deadline = time.monotonic() + budget
        retries = _env_nonneg_int("JEPSEN_TPU_CLIENT_RETRIES",
                                  DEFAULT_CLIENT_RETRIES)
        backoff = _env_pos_float("JEPSEN_TPU_CLIENT_BACKOFF",
                                 DEFAULT_CLIENT_BACKOFF_S)
        attempt = 0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                obs.count("jepsen_client_deadline_exhausted_total")
                raise ServiceUnavailable(
                    f"deadline budget ({budget:.1f}s) exhausted for "
                    f"{self._url(path)}")
            try:
                code, resp = self._request(
                    path, body=body,
                    timeout=min(attempt_timeout, remaining))
            except ServiceUnavailable:
                if br.record_failure():
                    obs.count("jepsen_client_breaker_trips_total")
                attempt += 1
                remaining = deadline - time.monotonic()
                delay = min(backoff * (2 ** (attempt - 1)), remaining)
                delay *= 0.5 + random.random() / 2  # full jitter
                if attempt > retries or remaining <= delay:
                    raise
                obs.count("jepsen_client_retries_total")
                time.sleep(delay)
                continue
            br.record_success()
            return code, resp

    def _probe(self, br: CircuitBreaker) -> bool:
        """The half-open liveness probe (cheap, hard-bounded)."""
        obs.count("jepsen_client_breaker_probes_total")
        return self.healthy(timeout=0.5)

    def healthy(self, timeout: float = 0.5) -> bool:
        if self.port is None:
            return False
        return probe_healthz(f"{self.host}:{self.port}", timeout=timeout)

    def status(self) -> dict:
        code, body = self._request("/status", timeout=self.timeout or 5)
        if code != 200:
            raise ServiceError(f"status returned {code}")
        return protocol.decode_body(body)

    def metrics_text(self) -> str:
        code, body = self._request("/metrics", timeout=self.timeout or 5)
        if code != 200:
            raise ServiceError(f"metrics returned {code}")
        return body.decode()

    def shutdown(self) -> dict:
        code, body = self._request("/shutdown", body=b"{}",
                                   timeout=self.timeout or 5)
        if code != 200:
            raise ServiceError(f"shutdown returned {code}")
        return protocol.decode_body(body)

    def profile(self, seconds: float = 1.0, label: str = "",
                out_dir: Optional[str] = None) -> dict:
        """``POST /profile``: one bounded on-demand profiling window on
        the daemon (obs.profiling) — returns ``{dir, manifest}``.  The
        timeout covers the capture window itself, plus headroom."""
        req: dict = {"seconds": float(seconds)}
        if label:
            req["label"] = str(label)
        if out_dir:
            req["dir"] = out_dir
        code, body = self._request(
            "/profile", body=protocol.encode_body(req),
            timeout=max(self.timeout or 0.0, float(seconds) + 30.0))
        if code != 200:
            raise ServiceError(f"profile returned {code}")
        return protocol.decode_body(body)

    def _trace_ctx(self, span) -> Optional[dict]:
        """Wire ``trace_ctx`` for the current client ``span`` — None
        when tracing is off (NULL_SPAN has no sid), so untraced runs
        send exactly the pre-telemetry body."""
        sid = getattr(span, "sid", None)  # NULL_SPAN has no sid
        if not obs.enabled() or sid is None:
            return None
        ctx = propagate.make_ctx(parent_sid=sid)
        span.set(propagate.ATTR_TRACE_ID, ctx["trace_id"])
        span.set(propagate.ATTR_ROLE, "client")
        return ctx

    def fetch_trace(self, trace_id: str) -> int:
        """Pull the daemon's span dump for ``trace_id`` (``GET
        /trace?ctx=``) and adopt it into the local tracer so
        ``obs.export_all`` stitches one merged Chrome trace.  Degrades
        silently — telemetry must never fail a checker run."""
        try:
            code, body = self._request(
                f"/trace?ctx={trace_id}", timeout=self.timeout or 5)
            if code != 200:
                return 0
            payload = protocol.decode_body(body)
            return propagate.adopt(
                payload.get("spans") or [],
                pid=payload.get("pid"),
                wall_origin=payload.get("wall_origin"),
                origin_ns=payload.get("origin_ns"),
            )
        except (ServiceError, ServiceUnavailable, ValueError, KeyError,
                TypeError):
            return 0

    def screen_graphs(self, encs) -> list:
        """Screen encoded dependency graphs on the daemon (``POST
        /elle``); same ScreenResult shapes the in-process
        ``ops.cycles.screen_graphs`` returns.  Raises like
        :meth:`check_batch` — the caller decides whether to fall
        back."""
        with obs.span("client/elle", cat="serve", graphs=len(encs)) as sp:
            ctx = self._trace_ctx(sp)
            body = protocol.elle_request(encs, trace_ctx=ctx,
                                         req=protocol.request_id())
            code, resp = self._resilient_post("/elle", body)
            payload = protocol.decode_body(resp)
            if code == 503:
                raise ServiceError(
                    f"daemon backlogged: {payload.get('error')}")
            if code != 200:
                raise ServiceError(
                    f"/elle returned {code}: {payload.get('error')}")
            results = payload["results"]
            if len(results) != len(encs):
                raise ServiceError(
                    f"result count {len(results)} != batch {len(encs)}")
            self.last_diag = payload.get("diag") or {}
            out = protocol.elle_results_from_wire(results, encs)
        if ctx:
            self.fetch_trace(ctx["trace_id"])
        return out

    def check_batch(self, model, histories, **opts) -> List[dict]:
        """Check a batch on the daemon; raises
        :class:`~jepsen_tpu.serve.protocol.UnsupportedModel` (no wire
        form / unserviceable opt), :class:`ServiceUnavailable`, or
        :class:`ServiceError` (backlogged, daemon-side failure) — the
        caller decides whether to fall back."""
        with obs.span(
            "client/check", cat="serve", histories=len(histories),
        ) as sp:
            ctx = self._trace_ctx(sp)
            body = protocol.check_request(model, histories, opts,
                                          trace_ctx=ctx,
                                          req=protocol.request_id())
            code, resp = self._resilient_post("/check", body)
            payload = protocol.decode_body(resp)
            if code == 503:
                raise ServiceError(
                    f"daemon backlogged: {payload.get('error')}")
            if code != 200:
                raise ServiceError(
                    f"/check returned {code}: {payload.get('error')}")
            results = payload["results"]
            if len(results) != len(histories):
                raise ServiceError(
                    f"result count {len(results)} != batch"
                    f" {len(histories)}")
            self.last_diag = payload.get("diag") or {}
        if ctx:
            self.fetch_trace(ctx["trace_id"])
        return results

    def open_feed(self, model, opts: Optional[dict] = None,
                  req: Optional[str] = None) -> "FeedSession":
        """Open a streaming-ingest session (``POST /feed`` op=open) and
        return its :class:`FeedSession`.  ``req`` doubles as the
        session id and the verdict-WAL run id, so passing the same id
        after a daemon crash resumes against the replayed WAL rows."""
        return FeedSession(self, model, opts=opts, req=req).open()

    def watch(self, last_id: int = -1, timeout: Optional[float] = None):
        """Subscribe to the daemon's verdict channel (``GET /watch``)
        and yield ``(offset, row)`` tuples as verdicts settle.

        One generator == one HTTP connection.  ``last_id`` >= 0 is sent
        as ``Last-Event-ID`` so replay resumes *after* that WAL row.
        The generator ends (rather than raising) when the connection
        drops or the read ``timeout`` expires with the daemon quiet —
        callers reconnect with the last offset they saw.  Raises
        :class:`ServiceUnavailable` only when the initial connection
        fails.
        """
        headers = {}
        if last_id >= 0:
            headers["Last-Event-ID"] = str(last_id)
        request = urllib.request.Request(self._url("/watch"),
                                         headers=headers)
        try:
            resp = urllib.request.urlopen(
                request, timeout=timeout or self.timeout or 30.0)
        except urllib.error.HTTPError as e:
            raise ServiceError(f"/watch returned {e.code}")
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise ServiceUnavailable(
                f"no daemon at {self._url('/watch')}: {e}")
        try:
            event_id = None
            data: Optional[str] = None
            for raw in resp:
                line = raw.decode("utf-8", "replace").rstrip("\r\n")
                if not line:  # blank line terminates one SSE event
                    if data is not None:
                        try:
                            row = json.loads(data)
                        except ValueError:
                            row = None
                        if isinstance(row, dict):
                            try:
                                off = int(event_id)
                            except (TypeError, ValueError):
                                off = -1
                            yield off, row
                    event_id, data = None, None
                elif line.startswith(":"):
                    pass  # keep-alive comment
                elif line.startswith("id:"):
                    event_id = line[3:].strip()
                elif line.startswith("data:"):
                    chunk = line[5:].strip()
                    data = chunk if data is None else data + chunk
        except (ConnectionError, OSError):
            return  # subscriber-side disconnect: end of stream
        finally:
            resp.close()


class FeedSession:
    """Client half of one streaming-ingest session.

    Appends carry a monotonically increasing ``seq``; the daemon acks
    ``seq <= last_seq`` as a duplicate without re-dispatching, so a
    retried append (connection reset after the daemon ingested it) is
    safe.  ``append`` only advances ``seq`` after a 200, which makes
    the retry loop in the caller trivially idempotent.
    """

    def __init__(self, client: ServiceClient, model,
                 opts: Optional[dict] = None,
                 req: Optional[str] = None):
        self.client = client
        self.model = model
        self.opts = dict(opts or {})
        self.req = req or protocol.request_id()
        self.sid: Optional[str] = None
        self.seq = 0
        self.resumed = False
        self.closed = False
        self.last_diag: dict = {}

    def open(self) -> "FeedSession":
        body = protocol.feed_open_request(self.model, self.opts,
                                          req=self.req)
        code, resp = self.client._resilient_post("/feed", body)
        payload = protocol.decode_body(resp)
        if code == 503:
            raise ServiceError(
                f"daemon backlogged: {payload.get('error')}")
        if code != 200:
            raise ServiceError(
                f"/feed open returned {code}: {payload.get('error')}")
        self.sid = payload["session"]
        self.resumed = bool(payload.get("resumed"))
        return self

    def append(self, histories=None, ops=None,
               t_inv: Optional[float] = None) -> dict:
        """Ship one delta — whole histories and/or raw op events (both
        invocations and completions, in history-append order).  Returns
        the daemon's ack (``accepted``/``rows``/``settled``/``diag``)."""
        if self.sid is None:
            raise ServiceError("feed session not open")
        body = protocol.feed_append_request(
            self.sid, self.seq, histories=histories, ops=ops,
            t_inv=t_inv)
        code, resp = self.client._resilient_post("/feed", body)
        payload = protocol.decode_body(resp)
        if code == 503:
            raise ServiceError(
                f"daemon backlogged: {payload.get('error')}")
        if code != 200:
            raise ServiceError(
                f"/feed append returned {code}: {payload.get('error')}")
        self.seq += 1
        self.last_diag = payload.get("diag") or {}
        return payload

    def close(self) -> List[dict]:
        """Finalize the session; returns the settled results (client
        histories in feed order, assembled op-history last when ops
        were fed) — byte-identical to a one-shot ``/check`` of the same
        histories."""
        if self.sid is None:
            raise ServiceError("feed session not open")
        body = protocol.feed_close_request(self.sid, self.seq,
                                           req=self.req + ":close")
        code, resp = self.client._resilient_post("/feed", body)
        payload = protocol.decode_body(resp)
        if code != 200:
            raise ServiceError(
                f"/feed close returned {code}: {payload.get('error')}")
        self.closed = True
        self.last_diag = payload.get("diag") or {}
        return payload["results"]


def _reap(proc, grace_s: float = 10.0) -> None:
    """Terminate a child without ever leaking it: SIGTERM → bounded
    wait → SIGKILL → bounded wait.  The second wait can still time out
    (a child stuck in uninterruptible sleep survives SIGKILL until the
    kernel releases it); that is swallowed — the caller's error path
    must not be replaced by ``TimeoutExpired``, and the kernel will
    reap the KILLed child without us."""
    proc.terminate()
    try:
        proc.wait(timeout=grace_s)
        return
    except subprocess.TimeoutExpired:
        pass
    proc.kill()
    try:
        proc.wait(timeout=grace_s)
    except subprocess.TimeoutExpired:
        pass


def spawn_daemon(port: Optional[int] = None,
                 wait_s: float = 60.0) -> ServiceClient:
    """Start a daemon subprocess (``python -m jepsen_tpu.serve``) and
    wait until it answers /healthz.  Used by ``JEPSEN_TPU_SERVICE=auto``
    and ``bench.py --against-service``."""
    client = ServiceClient(port=port)
    if client.port is None:
        raise ServiceUnavailable("invalid JEPSEN_TPU_SERVE_PORT")
    if client.healthy():
        return client
    argv = [sys.executable, "-m", "jepsen_tpu.serve",
            "--port", str(client.port)]
    proc = subprocess.Popen(
        argv, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        if client.healthy():
            client.spawned_pid = proc.pid
            return client
        if proc.poll() is not None:
            raise ServiceUnavailable(
                f"spawned daemon exited with {proc.returncode}")
        time.sleep(0.25)
    # reap it: an unwaited child is a zombie for our lifetime, and a
    # half-initialized daemon surviving SIGTERM would squat the port
    # in an unknown state for the next auto-start
    _reap(proc)
    raise ServiceUnavailable(f"daemon not healthy within {wait_s}s")


def resolve_client(auto_start: Optional[bool] = None
                   ) -> Optional[ServiceClient]:
    """A healthy client per the environment policy, or None (caller
    runs in-process).  ``auto_start`` overrides the ``auto`` half of
    :func:`service_mode`."""
    mode = service_mode()
    if auto_start is None:
        auto_start = mode == "auto"
    client = ServiceClient()
    if client.healthy():
        return client
    if auto_start:
        try:
            return spawn_daemon()
        except ServiceUnavailable:
            return None
    return None


def mesh_matches_daemon(client: ServiceClient, mesh) -> bool:
    """True when the daemon's resident mesh has the same SHAPE as the
    caller's requested ``mesh`` (the full device grid, compared
    against ``/status`` ``mesh_shape`` — a same-size mesh with a
    different axis layout is NOT a match; the daemon would partition
    differently than the caller asked).  A Mesh object cannot cross
    the wire, but it doesn't need to: when the shapes agree the
    daemon's own resident mesh partitions the batch exactly as the
    client's in-process engine would — so the explicit-mesh opt is
    droppable, not unserviceable (the PR-6 restriction, lifted
    shape-wise)."""
    try:
        shape = list(mesh.devices.shape)
    except (AttributeError, TypeError):
        return False
    try:
        st = client.status()
    except (ServiceError, ServiceUnavailable):
        return False
    return st.get("mesh_shape") == shape and st.get("n_devices") == int(
        mesh.devices.size
    )


def check_batch(model, histories, *, client: Optional[ServiceClient] = None,
                auto_start: Optional[bool] = None,
                require_opt_in: bool = False, **opts) -> List[dict]:
    """The transparent seam: daemon when reachable, in-process
    otherwise — same verdicts either way (serve-smoke pins it).
    ``oracle_budget_s``, ``window``, and an explicit ``decomposed``
    override are engine-side only and force the in-process path (the
    daemon owns its own window and decomposition policy; budget
    semantics need the run's serial drain — see protocol.py).  An
    explicit ``mesh`` is serviceable when its shape MATCHES the
    daemon's resident mesh (``/status`` ``n_devices``): the daemon
    shards identically through its own mesh, so the opt is dropped
    from the wire rather than forcing the batch in-process; a
    mismatched shape still runs in-process — the caller asked for a
    partitioning the daemon cannot honor.

    ``require_opt_in=True`` is for default-path callers (the batched
    linearizable seam): the daemon is only consulted when
    ``JEPSEN_TPU_SERVICE`` opts the run in, so a stray listener can
    never silently take another run's traffic.  Explicit service users
    (``ServiceChecker``, ``algorithm="service"``, a passed ``client``)
    leave it False."""
    from ..ops import wgl

    mesh = opts.get("mesh")
    serviceable = (
        opts.get("oracle_budget_s") is None
        and opts.get("window") is None
        and opts.get("bucketed") is not False
        # an explicit decomposed= override is engine-side only (the
        # daemon decomposes per ITS environment): honoring it means
        # running in-process, not silently dropping the opt on the wire
        and opts.get("decomposed") is None
        and not (require_opt_in and client is None
                 and service_mode() == "off")
    )
    if serviceable:
        if client is None:
            client = resolve_client(auto_start)
        if (client is not None and mesh is not None
                and not mesh_matches_daemon(client, mesh)):
            client = None  # shape mismatch: honor the mesh in-process
        if client is not None:
            wire_opts = {
                k: v for k, v in opts.items()
                if k in protocol.CHECK_OPTS and v is not None
            }
            try:
                return client.check_batch(model, histories, **wire_opts)
            except (UnsupportedModel, ServiceError):
                pass  # transparent fallback below
    return wgl.check_batch(model, histories, **opts)


def analysis(model, history, **kw) -> dict:
    """Single-history :func:`check_batch` (the checker-seam shape)."""
    return check_batch(model, [history], **kw)[0]


def screen_graphs(encs, *, client: Optional[ServiceClient] = None,
                  auto_start: Optional[bool] = None) -> Optional[list]:
    """The Elle screens' transparent service seam: screen on a
    reachable daemon (coalescing with concurrent runs' graphs on its
    resident executor), or return ``None`` so the caller runs the
    in-process engine path.  Like the batched-linearizable seam this
    is opt-in by default: with ``JEPSEN_TPU_SERVICE`` off and no
    explicit client, a stray listener never takes the traffic."""
    if client is None:
        if service_mode() == "off":
            return None
        client = resolve_client(auto_start)
    if client is None:
        return None
    try:
        return client.screen_graphs(encs)
    except (ServiceError, ServiceUnavailable):
        return None  # transparent in-process fallback


def ServiceChecker(model, pure_fs=("read",), oracle_budget_s=None):
    """The resident-service linearizability checker, behind the
    unchanged ``check(self, test, history, opts)`` seam: connects to
    (or, under ``JEPSEN_TPU_SERVICE=auto``, starts) the local daemon
    and falls back transparently to the in-process engine when none is
    reachable.  This is ``checker.linearizable(algorithm="service")``
    — witness rendering and result truncation are shared with every
    other algorithm."""
    from ..checker import linearizable

    return linearizable(
        model, algorithm="service", pure_fs=pure_fs,
        oracle_budget_s=oracle_budget_s,
    )


def format_status(st: dict) -> str:
    """Render a /status dict as the CLI `status` table."""
    mesh_shape = st.get("mesh_shape")
    devices = (
        f"{st.get('n_devices')} devices (mesh {mesh_shape})"
        if mesh_shape else f"{st.get('n_devices') or 1} device"
    )
    lines = [
        "── checker service " + "─" * 29,
        f"  pid {st.get('pid')} on platform {st.get('platform')}"
        f" · {devices}"
        f" · up {st.get('uptime_s', 0):.0f}s"
        + (" · DRAINING" if st.get("stopping") else ""),
        f"  requests: {st.get('requests', 0)}"
        f" ({st.get('histories', 0)} histories,"
        f" {st.get('rejected', 0)} rejected,"
        f" {st.get('errors', 0)} errors)",
        f"  queue: {st.get('queue_depth', 0)}/{st.get('max_queue_runs')}"
        f" · coalesced: {st.get('coalesced', 0)}"
        f" · window: {st.get('window')}"
        f" · calibration: {st.get('calibration') or 'defaults'}",
    ]
    ratio = st.get("warm_hit_ratio")
    warm = (f"{ratio:.0%}" if isinstance(ratio, (int, float)) else "n/a")
    lines.append(
        f"  dispatches: {st.get('cold_dispatches', 0)} cold"
        f" + {st.get('warm_dispatches', 0)} warm"
        f" (warm-hit ratio {warm})"
    )
    if (st.get("feed_open") or st.get("feed_sessions")
            or st.get("watch_subscribers")):
        lines.append(
            f"  online: {st.get('feed_open', 0)} open feed(s)"
            f" ({st.get('feed_sessions', 0)} sessions,"
            f" {st.get('feed_deltas', 0)} deltas,"
            f" {st.get('feed_histories', 0)} histories)"
            f" · watchers {st.get('watch_subscribers', 0)}"
            f" ({st.get('watch_events', 0)} events)"
            f" · compactions {st.get('wal_compactions', 0)}"
        )
    quarantine = st.get("quarantine") or []
    if quarantine:
        lines.append(
            "  quarantine: "
            + ", ".join(f"{q.get('route')} → oracle ({q.get('error')})"
                        for q in quarantine)
        )
    live = st.get("live")
    if live:
        lines.append("  " + format_live(live))
    jp = st.get("journal_path")
    if jp:
        lines.append(
            f"  journal: {st.get('journal_rows', 0)} rows → {jp}")
    drift = st.get("drift")
    if drift:
        lines.append("  " + format_drift(drift))
    return "\n".join(lines)


def format_fleet_status(rows) -> str:
    """The fleet table for ``jepsen_tpu status --daemon … --daemon …``:
    one row per member with the operator-facing columns (devices,
    mesh, calibration identity, drift score, quarantined routes, live
    busy ratio, and the routing weight the router's prober would
    derive from that busy ratio — ``router.weight_from_busy``, so the
    table shows the same number ``jepsen_route_weight`` exports).
    ``rows`` is a sequence of ``(addr, status_or_None)`` — ``None``
    marks a member that did not answer ``/status``."""
    from . import router as router_mod  # client ← router is the cycle

    cols = ["member", "devices", "mesh", "calibration", "drift",
            "quarantined", "busy", "weight"]
    table = [cols]
    for addr, st in rows:
        if st is None:
            table.append([addr, "-", "-", "unreachable",
                          "-", "-", "-", "-"])
            continue
        drift = st.get("drift") or {}
        score = drift.get("score")
        busy = (st.get("live") or {}).get("device_busy_ratio")
        weight = router_mod.weight_from_busy(
            busy if isinstance(busy, (int, float)) else None)
        table.append([
            addr,
            str(st.get("n_devices") or 1),
            str(st.get("mesh_shape") or "-"),
            str(st.get("calibration") or "defaults"),
            (f"{score:.2f}×" + ("!" if drift.get("retune_recommended")
                                else "")
             if isinstance(score, (int, float)) else "n/a"),
            str(len(st.get("quarantine") or [])),
            f"{busy:.0%}" if isinstance(busy, (int, float)) else "n/a",
            f"{weight:.2f}",
        ])
    widths = [max(len(r[i]) for r in table) for i in range(len(cols))]
    lines = ["── fleet " + "─" * 39]
    for i, r in enumerate(table):
        lines.append("  " + "  ".join(
            c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  " + "  ".join("─" * w for w in widths))
    return "\n".join(lines)


def format_drift(drift: dict) -> str:
    """One-line drift-sentinel view of a /status ``drift`` block
    (obs.drift): aggregate score vs threshold, shape census, and —
    when the sentinel recommends one — the retune call-out naming the
    stale shapes."""
    score = drift.get("score")
    score_s = (f"{score:.2f}×" if isinstance(score, (int, float))
               else "n/a")
    line = (
        f"drift: score {score_s}"
        f" (threshold {drift.get('threshold')}×)"
        f" · {drift.get('shapes', 0)} shape(s)"
        f" · {drift.get('rows_scored', 0)} rows scored"
    )
    stale = drift.get("stale") or []
    if drift.get("retune_recommended"):
        shapes = ", ".join(
            f"{s.get('kernel')}(E={s.get('E')},C={s.get('C')},"
            f"F={s.get('F')})@{s.get('ratio')}×"
            for s in stale
        )
        line += f" · RETUNE RECOMMENDED: {shapes or 'aggregate'}"
    return line


def _rate(live: dict, key: str) -> str:
    v = live.get(key)
    return f"{v:.2f}/s" if isinstance(v, (int, float)) else "n/a"


def format_live(live: dict) -> str:
    """One-line last-60 s view of a /status ``live`` dict (the
    sliding-window rates; doc/observability.md 'Fleet telemetry')."""
    qw = live.get("queue_wait_mean_s")
    busy = live.get("device_busy_ratio")
    return (
        f"last 60s: req {_rate(live, 'requests_per_s')}"
        f" · hist {_rate(live, 'histories_per_s')}"
        f" · elle {_rate(live, 'elle_graphs_per_s')}"
        f" · disp {_rate(live, 'dispatches_per_s')}"
        f" · feed {_rate(live, 'feed_deltas_per_s')}"
        f" · watch {_rate(live, 'watch_events_per_s')}"
        f" · wait "
        + (f"{qw * 1e3:.1f}ms" if isinstance(qw, (int, float)) else "n/a")
        + " · busy "
        + (f"{busy:.0%}" if isinstance(busy, (int, float)) else "n/a")
    )


def format_top(host: str, port, st: dict) -> str:
    """One daemon's fleet-view block for ``jepsen_tpu top``: identity
    line, last-60 s rates, queue/journal line."""
    mesh = st.get("mesh_shape")
    live = st.get("live") or {}
    head = (
        f"● {host}:{port}  pid {st.get('pid')}"
        f" · {st.get('n_devices') or 1} device(s)"
        + (f" · mesh {mesh}" if mesh else "")
        + f" · up {st.get('uptime_s', 0):.0f}s"
        + (" · DRAINING" if st.get("stopping") else "")
    )
    jp = st.get("journal_path")
    # quarantined routes + drift score ride the same summary line:
    # the two "this daemon needs an operator" signals the fleet view
    # previously never showed
    quarantined = len(st.get("quarantine") or [])
    drift = st.get("drift") or {}
    score = drift.get("score")
    tail = (
        f"  queue {st.get('queue_depth', 0)}/{st.get('max_queue_runs')}"
        f" · in-flight {st.get('in_flight', 0)}"
        f" · coalesced {st.get('coalesced', 0)}"
        + (f" · feeds {st.get('feed_open', 0)}"
           if st.get("feed_open") else "")
        + (f" · watchers {st.get('watch_subscribers', 0)}"
           if st.get("watch_subscribers") else "")
        + (f" · journal {st.get('journal_rows', 0)} rows" if jp else "")
        + f" · quarantined {quarantined}"
        + (f" · drift {score:.2f}×"
           + ("!" if drift.get("retune_recommended") else "")
           if isinstance(score, (int, float)) else "")
    )
    return "\n".join([head, "  " + format_live(live), tail])


def format_verdicts(events, limit: int = 8) -> str:
    """Render the newest settled verdicts as ``jepsen_tpu top``'s
    verdicts pane.  ``events`` is a sequence of ``(addr, offset, row)``
    tuples collected off one or more ``/watch`` channels (newest
    last); only the trailing ``limit`` rows are shown."""
    lines = ["── verdicts " + "─" * 36]
    rows = list(events)[-limit:]
    if not rows:
        lines.append("  (no settled verdicts yet)")
        return "\n".join(lines)
    now = time.time()
    for addr, off, row in rows:
        res = row.get("result") or {}
        valid = res.get("valid?")
        mark = "✗" if valid is False else ("✓" if valid is True else "?")
        ts = row.get("ts")
        age = (f"{max(0.0, now - float(ts)):.0f}s ago"
               if isinstance(ts, (int, float)) else "t?")
        extra = ""
        if valid is False:
            anom = res.get("anomaly-types") or res.get("anomalies")
            if anom:
                extra = f" · {anom}"
        lines.append(
            f"  {mark} {addr} #{off}"
            f" req {str(row.get('req'))[:8]}"
            f" {row.get('stream')}[{row.get('idx')}]"
            f" · {age}{extra}"
        )
    return "\n".join(lines)
