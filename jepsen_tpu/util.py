"""Kitchen-sink utilities (reference: jepsen/src/jepsen/util.clj).

Hot pieces: the test-relative monotonic clock (util.clj:337-353), bounded
parallel map (util.clj:65-83), retry/timeout helpers (util.clj:370-466),
interval-set rendering (util.clj:629-668), latency extraction
(util.clj:700-760).
"""

from __future__ import annotations

import concurrent.futures
import random
import threading
import time as _time
from contextlib import contextmanager
from typing import Any, Callable, Iterable, List, Optional, Sequence, TypeVar

T = TypeVar("T")
U = TypeVar("U")

# ---------------------------------------------------------------------------
# Relative time
# ---------------------------------------------------------------------------

_global_origin: Optional[int] = None


def monotonic_nanos() -> int:
    return _time.monotonic_ns()


@contextmanager
def with_relative_time():
    """Establish t=0 for this test run; relative_time_nanos() measures from
    here.  (reference: util.clj:337-353)"""
    global _global_origin
    prev = _global_origin
    _global_origin = _time.monotonic_ns()
    try:
        yield
    finally:
        _global_origin = prev


def relative_time_nanos() -> int:
    origin = _global_origin
    if origin is None:
        raise RuntimeError("relative_time_nanos called outside with_relative_time")
    return _time.monotonic_ns() - origin


# ---------------------------------------------------------------------------
# Parallelism
# ---------------------------------------------------------------------------


def real_pmap(fn: Callable[[T], U], coll: Sequence[T]) -> List[U]:
    """Map fn over coll, one thread per element, re-raising the first
    exception.  (reference: util.clj:65-83 real-pmap)"""
    coll = list(coll)
    if not coll:
        return []
    if len(coll) == 1:
        return [fn(coll[0])]
    with concurrent.futures.ThreadPoolExecutor(max_workers=len(coll)) as ex:
        futures = [ex.submit(fn, x) for x in coll]
        return [f.result() for f in futures]


def bounded_pmap(fn: Callable[[T], U], coll: Sequence[T], limit: int = 16) -> List[U]:
    """Parallel map with at most `limit` concurrent workers.
    (reference: util.clj bounded-pmap)"""
    coll = list(coll)
    if not coll:
        return []
    with concurrent.futures.ThreadPoolExecutor(max_workers=max(1, min(limit, len(coll)))) as ex:
        return list(ex.map(fn, coll))


# ---------------------------------------------------------------------------
# Retry / timeout
# ---------------------------------------------------------------------------


class TimeoutError_(Exception):
    pass


def timeout(ms: float, fn: Callable[[], T], default: Any = TimeoutError_) -> Any:
    """Run fn in a thread; if it doesn't finish in `ms` milliseconds return
    `default` (or raise if default is the TimeoutError_ class).  The thread
    is abandoned, not killed — like the reference's future-based timeout
    (util.clj:370-390)."""
    ex = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    fut = ex.submit(fn)
    try:
        return fut.result(timeout=ms / 1000.0)
    except concurrent.futures.TimeoutError:
        fut.cancel()
        if default is TimeoutError_:
            raise TimeoutError_(f"timed out after {ms} ms")
        return default
    finally:
        ex.shutdown(wait=False)


def retry(delay_seconds: float, fn: Callable[[], T], tries: Optional[int] = None) -> T:
    """Retry fn until it succeeds, sleeping delay_seconds between attempts.
    (reference: util.clj:425-440)"""
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except Exception:
            if tries is not None and attempt >= tries:
                raise
            _time.sleep(delay_seconds)


# ---------------------------------------------------------------------------
# Collections / math
# ---------------------------------------------------------------------------


def free_port() -> int:
    """An ephemeral localhost TCP port (bind to 0, read, release).
    The one shared copy — the localkv suite, the checker-service
    bench, and the tests all allocate scratch ports this way."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def majority(n: int) -> int:
    """Smallest integer strictly greater than half of n; majority(0) = 1.
    (reference: util.clj:84-90)"""
    return n // 2 + 1


def minority(n: int) -> int:
    """Largest number of nodes that is NOT a majority."""
    return (n - 1) // 2


def random_nonempty_subset(coll: Sequence[T], rng: Optional[random.Random] = None) -> List[T]:
    """A random nonempty subset of coll, in shuffled order.
    (reference: util.clj:45-52)"""
    rng = rng or random
    coll = list(coll)
    if not coll:
        return []
    n = rng.randint(1, len(coll))
    return rng.sample(coll, n)


def integer_interval_set_str(xs: Iterable[int]) -> str:
    """Compact string for a set of integers as intervals:
    ``#{1 3..5 7}``.  (reference: util.clj:629-668)"""
    xs = sorted(set(xs))
    parts = []
    i = 0
    while i < len(xs):
        j = i
        while j + 1 < len(xs) and xs[j + 1] == xs[j] + 1:
            j += 1
        if j == i:
            parts.append(str(xs[i]))
        elif j == i + 1:
            parts.append(str(xs[i]))
            parts.append(str(xs[j]))
        else:
            parts.append(f"{xs[i]}..{xs[j]}")
        i = j + 1
    return "#{" + " ".join(parts) + "}"


def chunked(seq: Sequence[T], n: int) -> List[List[T]]:
    return [list(seq[i : i + n]) for i in range(0, len(seq), n)]


# ---------------------------------------------------------------------------
# History-derived metrics
# ---------------------------------------------------------------------------


def history_latencies(history) -> list:
    """Attach :latency (completion.time - invoke.time, ns) to each invoke;
    returns the invokes.  (reference: util.clj:700-735)"""
    out = []
    for inv, comp in history.pairs():
        if comp is not None:
            inv = inv.copy(latency=comp.time - inv.time, completion_type=comp.type)
        out.append(inv)
    return out


def nemesis_intervals(history, fs_start=("start",), fs_stop=("stop",)) -> list:
    """[(start-op, stop-op-or-None)] intervals of nemesis activity, pairing
    ops whose :f starts/stops a fault.  Overlapping faults of different
    kinds are matched by fault name (the :f with its start/stop prefix
    removed), so ``stop-clock-skew`` closes ``start-clock-skew`` even if a
    partition opened in between.  (reference: util.clj:736-760)"""

    def fault_key(name: str, prefixes) -> Optional[str]:
        for p in prefixes:
            p = str(p)
            if name == p or name.startswith(p):
                return name[len(p) :]
        return None

    intervals = []
    open_by_fault: dict = {}
    for op in history:
        if isinstance(op.process, int):
            continue
        if op.type != "info":
            continue
        name = str(op.f)
        start_key = fault_key(name, fs_start)
        stop_key = fault_key(name, fs_stop)
        if start_key is not None:
            open_by_fault.setdefault(start_key, []).append(op)
        elif stop_key is not None:
            opened = open_by_fault.get(stop_key)
            if opened:
                intervals.append((opened.pop(0), op))
    for opened in open_by_fault.values():
        for op in opened:
            intervals.append((op, None))
    intervals.sort(key=lambda pair: pair[0].time)
    return intervals


# ---------------------------------------------------------------------------
# Misc
# ---------------------------------------------------------------------------


class NamedLocks:
    """A family of locks addressed by arbitrary keys.
    (reference: util.clj:860-880)"""

    def __init__(self):
        self._guard = threading.Lock()
        self._locks: dict = {}

    def get(self, name: Any) -> threading.Lock:
        with self._guard:
            lock = self._locks.get(name)
            if lock is None:
                lock = threading.Lock()
                self._locks[name] = lock
            return lock

    @contextmanager
    def hold(self, name: Any):
        lock = self.get(name)
        with lock:
            yield


def coll_str(x: Any, limit: int = 8) -> str:
    """Abbreviated collection printing for log lines."""
    if isinstance(x, (list, tuple, set, frozenset)):
        xs = list(x)
        if len(xs) > limit:
            return f"[{', '.join(map(str, xs[:limit]))}, … ({len(xs)} total)]"
    return str(x)


def log_op(op) -> str:
    """One-line rendering of an op for logs.  (reference: util.clj:239-243)"""
    err = op.extra.get("error")
    err_s = f"\t{err}" if err else ""
    return f"{op.process}\t{op.type}\t{op.f}\t{coll_str(op.value)}{err_s}"


def fraction(a: float, b: float) -> float:
    """a/b, but 0 when b is 0."""
    return a / b if b else 0.0


def drop_common_proper_prefix(colls):
    """Drop the longest common *proper* prefix from each collection: at
    least one element of every collection is always kept.
    (reference: util.clj drop-common-proper-prefix, used by snarf-logs!)"""
    colls = [list(c) for c in colls]
    if not colls:
        return []
    limit = min(len(c) for c in colls) - 1
    k = 0
    while k < limit and all(c[k] == colls[0][k] for c in colls):
        k += 1
    return [c[k:] for c in colls]
