"""Command-line runner: ``test`` / ``analyze`` / ``serve`` / ``test-all``.

(reference: jepsen/src/jepsen/cli.clj — run! dispatcher:258, standard
test opt spec:64-111 incl. the "3n" concurrency convention:150-168,
single-test-cmd:355 providing both `test` and `analyze`:389-431,
serve-cmd:336, test-all-cmd:491, exit codes:129-138)

Exit codes: 0 valid, 1 invalid, 2 unknown/errors, 254 usage error,
255 crash.

A DB suite builds its CLI by passing its test-constructor to
:func:`single_test_cmd` and calling :func:`main` with the merged
command map — same shape as the reference's `(cli/run! (merge
(cli/single-test-cmd …) (cli/serve-cmd)))`.
"""

from __future__ import annotations

import argparse
import logging
import sys
import traceback
from typing import Any, Callable, Dict, List, Optional

EXIT_VALID = 0
EXIT_INVALID = 1
EXIT_UNKNOWN = 2
EXIT_USAGE = 254
EXIT_CRASH = 255

DEFAULT_NODES = ["n1", "n2", "n3", "n4", "n5"]


def parse_concurrency(s: str, node_count: int) -> int:
    """"30" → 30; "3n" → 3 × node count.  (reference: cli.clj:150-168)"""
    s = str(s).strip()
    if s.endswith("n"):
        return int(s[:-1] or 1) * node_count
    return int(s)


def _engine_window_arg(s: str) -> int:
    """--engine-window validator: ≥ 1 (1 IS the serial mode; a 0
    "disable" would otherwise be silently dropped by truthiness and
    run the default window instead — worse than an error)."""
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError(
            "must be >= 1 (1 = strictly serial; pipelining has no "
            "setting below serial)"
        )
    return v


def parse_nodes(args: argparse.Namespace) -> List[str]:
    """--nodes a,b,c / repeated --node / --nodes-file, last wins per
    source precedence (file > node > nodes).  (reference: cli.clj:68-84)"""
    nodes: List[str] = list(DEFAULT_NODES)
    if getattr(args, "nodes", None):
        nodes = [n.strip() for n in args.nodes.split(",") if n.strip()]
    if getattr(args, "node", None):
        nodes = list(args.node)
    if getattr(args, "nodes_file", None):
        with open(args.nodes_file) as f:
            nodes = [line.strip() for line in f if line.strip()]
    return nodes


def add_test_opts(p: argparse.ArgumentParser) -> None:
    """The standard test option spec.  (reference: cli.clj:64-111)"""
    p.add_argument("--nodes", help="comma-separated node hostnames")
    p.add_argument("--node", action="append", help="node hostname (repeatable)")
    p.add_argument("--nodes-file", help="file with one node per line")
    p.add_argument(
        "--concurrency",
        default=None,
        help='number of workers, or "<k>n" for k × node count '
        "(default 1n, unless the workload needs more)",
    )
    p.add_argument(
        "--time-limit",
        type=float,
        default=60,
        help="run the workload this many seconds (default 60)",
    )
    p.add_argument(
        "--test-count",
        type=int,
        default=1,
        help="run the whole test suite this many times",
    )
    p.add_argument("--username", default="root", help="ssh username")
    p.add_argument("--password", help="ssh password")
    p.add_argument("--ssh-private-key", help="path to an ssh identity file")
    p.add_argument(
        "--ssh-transport",
        choices=("ssh", "agent-ssh"),
        help="use a real SSH transport: 'ssh' (key-only, ControlMaster"
        " multiplexed) or 'agent-ssh' (sshj-style auth ladder: key,"
        " agent, default identities, password)",
    )
    p.add_argument(
        "--dummy",
        action="store_true",
        help="use the no-IO dummy remote (in-process runs)",
    )
    p.add_argument(
        "--leave-db-running",
        action="store_true",
        help="don't tear the DB down after the test",
    )
    p.add_argument(
        "--logging-json", action="store_true", help="JSON-structured logs"
    )
    p.add_argument("--store-base", default="store", help="artifact directory")
    p.add_argument(
        "--tracing",
        help="enable span tracing and export finished spans to this "
        "JSONL file (suites wrap client/nemesis calls in spans; "
        "reference: dgraph --tracing URL)",
    )
    p.add_argument(
        "--no-obs",
        action="store_true",
        help="disable the run-wide observability layer (phase/op/engine "
        "spans + metrics, trace.json/metrics.prom store artifacts, the "
        "post-run breakdown table; doc/observability.md).  Default on; "
        "JEPSEN_TPU_OBS=0 disables it globally.",
    )
    p.add_argument(
        "--mesh",
        dest="mesh_sharding",  # "mesh" is the test-map key for the
        action="store_true",   # built Mesh object itself
        help="explicitly shard the analysis batch over every visible "
        "accelerator device (jax.sharding.Mesh on the history axis); "
        "single-device runs are unaffected.  Mostly redundant now: the "
        "engine auto-resolves a mesh whenever >1 accelerator device is "
        "attached (resolution order: --mesh > test['mesh'] > auto; "
        "JEPSEN_TPU_ENGINE_MESH=0 disables auto — doc/"
        "checker-engines.md 'Slice-native dispatch')",
    )
    p.add_argument(
        "--profile",
        action="store_true",
        help="wrap the whole run in a jax.profiler capture window "
        "plus device memory high-water sampling; the artifact lands "
        "in the store dir beside trace.json (profile/profile.json; "
        "doc/observability.md 'Device profiling')",
    )
    p.add_argument(
        "--engine-window",
        type=_engine_window_arg,
        help="max in-flight device dispatches in the pipelined checker "
        "engine (jepsen_tpu.engine; doc/checker-engines.md).  1 = "
        "strictly serial dispatch-sync-dispatch (there is no value "
        "below serial, so 0 is rejected, not a disable switch); "
        "default 4 (JEPSEN_TPU_ENGINE_WINDOW).  Verdicts never depend "
        "on it.",
    )


def test_opts_to_map(args: argparse.Namespace) -> dict:
    """Build the base test map from parsed standard options.
    (reference: cli.clj:245-254 test-opt-fn)"""
    nodes = parse_nodes(args)
    test = {
        "nodes": nodes,
        "time-limit": args.time_limit,
        "store-base": args.store_base,
        # CLI runs always persist (the reference's `lein run test`
        # writes store/<name>/<time>/ unconditionally); suite modules
        # default store? off only for library/in-process use
        "store?": True,
        "leave-db-running?": args.leave_db_running,
        "logging-json?": args.logging_json,
        "ssh": {
            "username": args.username,
            "password": args.password,
            "private-key-path": args.ssh_private_key,
        },
    }
    if args.concurrency is not None:
        test["concurrency"] = parse_concurrency(args.concurrency, len(nodes))
    if getattr(args, "tracing", None):
        test["tracing"] = args.tracing
    if getattr(args, "no_obs", False):
        test["obs?"] = False
    if getattr(args, "profile", False):
        test["profile?"] = True
    if getattr(args, "engine_window", None) is not None:
        # consumed by the linearizability checkers (checker.linearizable,
        # independent.batched_linearizable) on their way into
        # wgl.check_batch(window=...); run_test additionally exports it
        # for the run's duration so DispatchWindows with no test-map
        # access (the Elle cycle screen) honor the same bound
        test["engine-window"] = args.engine_window
    if getattr(args, "mesh_sharding", False):
        # build lazily at analyze time: probing the backend here would
        # hang a wedged tunnel before the test even starts, and the
        # checker seam (batched_linearizable → check_batch(mesh=...))
        # only reads test["mesh"] once histories exist
        from .platform import ensure_usable_backend

        def _mesh():
            ensure_usable_backend()
            import jax

            from .parallel import mesh as mesh_mod

            devs = jax.devices()
            return mesh_mod.default_mesh(devs) if len(devs) > 1 else None

        test["mesh-fn"] = _mesh
    if args.dummy:
        from .control.core import DummyRemote

        test["remote"] = DummyRemote()
    elif getattr(args, "ssh_transport", None) == "agent-ssh":
        from .control.agent_ssh import AgentSSHRemote

        test["remote"] = AgentSSHRemote.from_test(test)
    elif getattr(args, "ssh_transport", None) == "ssh":
        from .control.ssh import SSHRemote

        test["remote"] = SSHRemote.from_test(test)
    return test


def _exit_code(results: dict) -> int:
    v = (results or {}).get("valid?")
    if v is True:
        return EXIT_VALID
    if v is False:
        return EXIT_INVALID
    return EXIT_UNKNOWN


def given_opts(args: argparse.Namespace) -> dict:
    """vars(args) minus the not-given options: argparse leaves those as
    None, and merging them verbatim into the test map would shadow the
    downstream setdefaults (e.g. core.run's concurrency = 1×nodes)."""
    return {k: v for k, v in vars(args).items() if v is not None}


def _run_profiled(test: dict) -> dict:
    """``--profile``: run the test inside one obs.profiling capture
    window (jax.profiler trace + device memory high-water), then move
    the artifact into the store dir beside trace.json.  The store dir
    only exists once the run has a start-time, so the capture lands in
    a temp dir first."""
    import shutil
    import tempfile

    from . import core
    from .obs import profiling as obs_profiling

    box: dict = {}

    def _work():
        box["result"] = core.run(test)

    tmp = tempfile.mkdtemp(prefix="jepsen-tpu-profile-")
    try:
        obs_profiling.capture(tmp, label=str(test.get("name", "")),
                              work=_work)
        result = box["result"]
        if result.get("store?", True) and result.get("start-time"):
            from . import store as store_mod

            dest = store_mod.path(result, "profile")
            shutil.rmtree(dest, ignore_errors=True)
            shutil.move(tmp, dest)
            tmp = None
            print(f"device profile → {dest}")
        return result
    finally:
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def run_test(test: dict) -> int:
    """Run one prepared test map; returns its exit code."""
    import os

    from . import core
    from .platform import ensure_usable_backend

    # pin the platform ONCE, before checker worker threads exist: a
    # wedged accelerator tunnel hangs the first in-process backend use,
    # and racing threads could reach a dispatch before any of them
    # finishes probing
    ensure_usable_backend()
    # scope the engine window to THIS run: dispatch windows without
    # test-map access (the Elle cycle screen) resolve the env default,
    # and --engine-window 1 must mean nothing in the run pipelines —
    # but a later run in the same process must not inherit it
    window = test.get("engine-window")
    prior = os.environ.get("JEPSEN_TPU_ENGINE_WINDOW")
    if window is not None:
        os.environ["JEPSEN_TPU_ENGINE_WINDOW"] = str(window)
    try:
        if test.get("profile?"):
            result = _run_profiled(test)
        else:
            result = core.run(test)
    finally:
        if window is not None:
            if prior is None:
                os.environ.pop("JEPSEN_TPU_ENGINE_WINDOW", None)
            else:
                os.environ["JEPSEN_TPU_ENGINE_WINDOW"] = prior
    summary = result.get("obs-summary")
    if summary:
        # phase/engine breakdown (doc/observability.md); the same dict
        # is durable under results.json → "obs"
        from . import obs

        print(obs.format_summary(summary))
    return _exit_code(result.get("results", {}))


def single_test_cmd(
    test_fn: Callable[[dict], dict],
    opt_fn: Optional[Callable[[argparse.ArgumentParser], None]] = None,
) -> Dict[str, dict]:
    """Commands for running/re-analyzing one test family:

    - ``test``: build the test from CLI opts via test_fn and run it
    - ``analyze``: re-run checkers over a stored history without
      re-running the test (analysis resume)

    (reference: cli.clj:355-431)"""

    def add_opts(p):
        add_test_opts(p)
        if opt_fn is not None:
            opt_fn(p)

    def run(args) -> int:
        worst = EXIT_VALID
        for _ in range(args.test_count):
            test = test_fn({**given_opts(args), **test_opts_to_map(args)})
            code = run_test(test)
            worst = max(worst, code)
            if code != EXIT_VALID:
                return code
        return worst

    def analyze(args) -> int:
        from . import checker as checker_mod
        from . import store as store_mod

        if args.test_name:
            # --test-name without --test-time means the test's most
            # recent run (reference: `lein run analyze` defaults to
            # the latest run the same way)
            start = args.test_time or store_mod.latest_time(
                args.store_base, args.test_name
            )
            stored = (
                store_mod.load(
                    {
                        "name": args.test_name,
                        "start-time": start,
                        "store-base": args.store_base,
                    }
                )
                if start is not None
                else None
            )
        else:
            stored = store_mod.latest(args.store_base)
        if stored is None:
            print("no stored test found", file=sys.stderr)
            return EXIT_USAGE
        if stored.get("recovered"):
            print(
                "note: test.jtpu was torn; analyzing the recovered "
                "valid prefix (the newest durable save phase)",
                file=sys.stderr,
            )
        test = test_fn({**given_opts(args), **test_opts_to_map(args), **stored})
        history = stored.get("history")
        results = checker_mod.check_safe(test["checker"], test, history, {})
        print_results = {
            k: v for k, v in results.items() if k != "history"
        }
        print(logging_safe_repr(print_results))
        return _exit_code(results)

    def add_analyze_opts(p):
        add_opts(p)
        p.add_argument("--test-name", help="stored test name")
        p.add_argument("--test-time", help="stored test start-time")

    return {
        "test": {
            "help": "run a test",
            "add_opts": add_opts,
            "run": run,
        },
        "analyze": {
            "help": "re-run the checker over a stored history",
            "add_opts": add_analyze_opts,
            "run": analyze,
        },
    }


def serve_cmd() -> Dict[str, dict]:
    """``serve`` (web UI, or the resident checker daemon with
    ``--checker``), plus ``status``/``shutdown`` for the daemon.
    (reference: cli.clj:336-354; the checker daemon is
    doc/checker-service.md)"""

    def add_opts(p):
        p.add_argument("--host", default=None)
        p.add_argument("--port", "-b", type=int, default=None)
        p.add_argument("--store-base", default="store")
        p.add_argument(
            "--checker",
            action="store_true",
            help="serve the resident checker daemon (device + jit "
            "cache + oracle pool stay warm across runs; "
            "doc/checker-service.md) instead of the store web UI",
        )
        p.add_argument(
            "--engine-window",
            type=_engine_window_arg,
            help="(--checker) the resident dispatch-window bound",
        )
        p.add_argument(
            "--max-queue",
            type=int,
            help="(--checker) queued client runs before /check "
            "answers 503 backlogged (default 8)",
        )
        p.add_argument(
            "--supervise",
            action="store_true",
            help="(--checker) run the daemon as a supervised child "
            "and restart it on abnormal exit; the restart re-warms "
            "from the journal, verdict WAL, and jit cache",
        )
        p.add_argument(
            "--fleet",
            type=int,
            default=1,
            metavar="N",
            help="(--checker --supervise) run N daemons on ports "
            "PORT..PORT+N-1 with per-member WAL/journal paths and one "
            "shared AOT executable cache; front them with "
            "`jepsen-tpu route` (doc/checker-service.md 'Fleet tier')",
        )

    def run(args) -> int:
        if args.checker:
            from . import serve as serve_mod
            from .serve import daemon as daemon_mod

            if args.fleet > 1 and not args.supervise:
                print("--fleet requires --supervise", file=sys.stderr)
                return EXIT_USAGE
            if args.supervise:
                child = []
                if args.host:
                    child += ["--host", args.host]
                if args.engine_window is not None:
                    child += ["--window", str(args.engine_window)]
                if args.max_queue is not None:
                    child += ["--max-queue", str(args.max_queue)]
                if args.fleet > 1:
                    return daemon_mod.supervise_fleet(
                        args.fleet, child, base_port=args.port)
                if args.port is not None:
                    child += ["--port", str(args.port)]
                return daemon_mod.supervise(child)
            serve_mod.serve(
                host=args.host or serve_mod.DEFAULT_HOST,
                port=args.port,
                window=args.engine_window,
                max_queue_runs=args.max_queue,
                block=True,
            )
            return EXIT_VALID
        from . import web

        web.serve(
            host=args.host or "0.0.0.0",
            port=args.port if args.port is not None else 8080,
            base=args.store_base,
        )
        return EXIT_VALID

    def add_daemon_opts(p):
        p.add_argument("--host", default=None,
                       help="daemon host (default 127.0.0.1)")
        p.add_argument("--port", "-b", type=int, default=None,
                       help="daemon port (default JEPSEN_TPU_SERVE_PORT "
                       "or 8519)")

    def add_fleet_daemon_opts(p):
        add_daemon_opts(p)
        p.add_argument(
            "--daemon",
            action="append",
            default=[],
            metavar="HOST:PORT",
            help="additional daemon address (repeatable) — address "
            "the whole fleet in one command, like `top`",
        )

    def fleet_clients(args, timeout=None):
        """The primary ``--host``/``--port`` client plus one per
        repeatable ``--daemon HOST:PORT``; ``None`` on a malformed
        address (after printing the usage error)."""
        from .serve import ServiceClient

        kw = {} if timeout is None else {"timeout": timeout}
        clients = [ServiceClient(host=args.host, port=args.port, **kw)]
        for addr in getattr(args, "daemon", []):
            host, _, port = str(addr).rpartition(":")
            try:
                clients.append(
                    ServiceClient(host=host or None, port=int(port),
                                  **kw))
            except ValueError:
                print(f"bad --daemon address {addr!r} (want HOST:PORT)",
                      file=sys.stderr)
                return None
        return clients

    def status(args) -> int:
        from .serve import ServiceError, ServiceUnavailable, client

        clients = fleet_clients(args)
        if clients is None:
            return EXIT_USAGE
        if len(clients) == 1:
            c = clients[0]
            try:
                print(client.format_status(c.status()))
            except ServiceUnavailable:
                print(
                    f"no checker service at http://{c.host}:{c.port}/ "
                    "(start one: jepsen-tpu serve --checker)",
                    file=sys.stderr,
                )
                return EXIT_UNKNOWN
            return EXIT_VALID
        rows, unreachable = [], 0
        for c in clients:
            try:
                rows.append((f"{c.host}:{c.port}", c.status()))
            except (ServiceError, ServiceUnavailable):
                rows.append((f"{c.host}:{c.port}", None))
                unreachable += 1
        print(client.format_fleet_status(rows))
        return EXIT_UNKNOWN if unreachable == len(clients) else EXIT_VALID

    def shutdown(args) -> int:
        from .serve import ServiceUnavailable

        clients = fleet_clients(args)
        if clients is None:
            return EXIT_USAGE
        unreachable = 0
        for c in clients:
            try:
                out = c.shutdown()
            except ServiceUnavailable:
                print(
                    f"no checker service at http://{c.host}:{c.port}/",
                    file=sys.stderr,
                )
                unreachable += 1
                continue
            print(
                f"checker service at {c.host}:{c.port} draining "
                f"({out.get('draining', 0)} queued runs), then stopping"
            )
        return EXIT_UNKNOWN if unreachable == len(clients) else EXIT_VALID

    def add_profile_opts(p):
        add_daemon_opts(p)
        p.add_argument(
            "--seconds", type=float, default=1.0,
            help="capture window length in seconds (clamped to 30)",
        )
        p.add_argument(
            "--label", default="",
            help="label recorded in the capture manifest (and the "
            "capture directory name)",
        )
        p.add_argument(
            "--dir", dest="out_dir", default=None,
            help="capture directory (default: a timestamped subdir of "
            "the daemon's profiles/ dir)",
        )

    def profile(args) -> int:
        from .serve import ServiceClient, ServiceError, ServiceUnavailable

        c = ServiceClient(host=args.host, port=args.port)
        try:
            out = c.profile(seconds=args.seconds, label=args.label,
                            out_dir=args.out_dir)
        except ServiceUnavailable:
            print(
                f"no checker service at http://{c.host}:{c.port}/ "
                "(start one: jepsen-tpu serve --checker)",
                file=sys.stderr,
            )
            return EXIT_UNKNOWN
        except ServiceError as e:
            print(f"profile failed: {e}", file=sys.stderr)
            return EXIT_UNKNOWN
        man = out.get("manifest") or {}
        peaks = ", ".join(
            f"{d.get('device')} "
            + (f"{d['peak_bytes_in_use'] / 1e6:.1f}MB"
               if isinstance(d.get("peak_bytes_in_use"), (int, float))
               else "n/a")
            for d in (man.get("memory") or [])
        ) or "no devices"
        print(
            f"profile capture → {out.get('dir')}"
            f" ({man.get('wall_seconds', 0)}s, "
            + ("trace collected" if man.get("trace") else "no trace")
            + ")"
        )
        print(f"  hbm peak: {peaks}")
        return EXIT_VALID

    def add_route_opts(p):
        p.add_argument(
            "--member",
            action="append",
            required=True,
            metavar="HOST:PORT",
            help="fleet member daemon address (repeatable)",
        )
        p.add_argument("--host", default=None,
                       help="router bind host (default 127.0.0.1)")
        p.add_argument(
            "--port", "-b", type=int, default=None,
            help="router bind port (default JEPSEN_TPU_SERVE_PORT or "
            "8519 — clients point at the router unchanged)",
        )

    def route(args) -> int:
        import os

        from .serve import protocol, router

        for m in args.member:
            host, _, port = str(m).rpartition(":")
            try:
                int(port)
            except ValueError:
                print(f"bad --member address {m!r} (want HOST:PORT)",
                      file=sys.stderr)
                return EXIT_USAGE
        router.Router(
            args.member,
            host=args.host or protocol.DEFAULT_HOST,
            port=(args.port if args.port is not None
                  else int(os.environ.get("JEPSEN_TPU_SERVE_PORT", 0)
                           or protocol.DEFAULT_PORT)),
        ).start(block=True)
        return EXIT_VALID

    def add_top_opts(p):
        add_daemon_opts(p)
        p.add_argument(
            "--daemon",
            action="append",
            default=[],
            metavar="HOST:PORT",
            help="additional daemon address (repeatable) — one block "
            "per daemon in the fleet view",
        )
        p.add_argument(
            "--once",
            action="store_true",
            help="render a single frame and exit (scripts/CI)",
        )
        p.add_argument(
            "--interval",
            type=float,
            default=2.0,
            help="refresh period in seconds (default 2)",
        )

    def top(args) -> int:
        import time as time_mod

        from .serve import ServiceClient, ServiceError, \
            ServiceUnavailable, client as client_mod

        clients = [ServiceClient(host=args.host, port=args.port,
                                 timeout=2.0)]
        for addr in args.daemon:
            host, _, port = str(addr).rpartition(":")
            try:
                clients.append(
                    ServiceClient(host=host or None, port=int(port),
                                  timeout=2.0))
            except ValueError:
                print(f"bad --daemon address {addr!r} (want HOST:PORT)",
                      file=sys.stderr)
                return EXIT_USAGE

        def tail_verdicts(c, st, limit: int = 8) -> list:
            """Bounded tail of one daemon's verdict channel: replay
            only the last ``limit`` WAL rows via ``Last-Event-ID``,
            stop as soon as they've arrived (or the read times out)."""
            rows: list = []
            wal_rows = st.get("wal_rows") or 0
            if not wal_rows:
                return rows
            try:
                for off, row in c.watch(
                        last_id=max(-1, wal_rows - limit - 1),
                        timeout=1.0):
                    rows.append((f"{c.host}:{c.port}", off, row))
                    if off >= wal_rows - 1 or len(rows) >= limit:
                        break
            except (ServiceError, ServiceUnavailable, OSError):
                pass
            return rows

        def frame():
            """One rendered fleet frame + the per-address errors (an
            entry per daemon that did not answer /status)."""
            blocks, verdicts, errors = [], [], []
            for c in clients:
                try:
                    st = c.status()
                except (ServiceError, ServiceUnavailable) as e:
                    blocks.append(f"○ {c.host}:{c.port}  (unreachable)")
                    errors.append((f"{c.host}:{c.port}", str(e)))
                    continue
                blocks.append(client_mod.format_top(c.host, c.port, st))
                verdicts.extend(tail_verdicts(c, st))
            verdicts.sort(key=lambda e: (e[2].get("ts") or 0, e[1]))
            blocks.append(client_mod.format_verdicts(verdicts))
            return "\n".join(blocks), errors

        if args.once:
            text, errors = frame()
            print(text)
            if len(errors) == len(clients):
                # every daemon unreachable: a monitoring script must
                # see a nonzero exit, with the reason per address
                for addr, err in errors:
                    print(f"top: {addr}: {err}", file=sys.stderr)
                return EXIT_UNKNOWN
            return EXIT_VALID
        try:
            while True:
                # clear + home, then the frame: a refreshing view
                # without curses (stdlib-only, like the web UI)
                print("\x1b[2J\x1b[H" + frame()[0], flush=True)
                time_mod.sleep(max(0.1, args.interval))
        except KeyboardInterrupt:
            return EXIT_VALID

    return {
        "serve": {
            "help": "serve the store web UI (--checker: the resident "
            "checker daemon)",
            "add_opts": add_opts,
            "run": run,
        },
        "status": {
            "help": "show the resident checker service's status "
            "(repeatable --daemon: one fleet table row per member)",
            "add_opts": add_fleet_daemon_opts,
            "run": status,
        },
        "shutdown": {
            "help": "drain and stop the resident checker service "
            "(repeatable --daemon: every addressed member)",
            "add_opts": add_fleet_daemon_opts,
            "run": shutdown,
        },
        "route": {
            "help": "run the fleet routing front: rendezvous-hash "
            "request shapes over --member daemons so same-shape "
            "traffic coalesces on one resident executor, with "
            "breaker-driven spillover (doc/checker-service.md "
            "'Fleet tier')",
            "add_opts": add_route_opts,
            "run": route,
        },
        "top": {
            "help": "live fleet view of one or more checker daemons "
            "(last-60s rates, queue wait, journal, quarantine, drift, "
            "settled verdicts; --once for one frame, nonzero exit "
            "when no daemon answers)",
            "add_opts": add_top_opts,
            "run": top,
        },
        "profile": {
            "help": "capture a bounded jax.profiler window + device "
            "memory high-water on the resident checker daemon "
            "(POST /profile; doc/observability.md 'Device profiling')",
            "add_opts": add_profile_opts,
            "run": profile,
        },
    }


def tune_cmd() -> Dict[str, dict]:
    """``tune``: the offline autotune pass (doc/tuning.md) — measure
    the attached device, persist a calibration artifact, and the
    engine's window / flush-rows / row-bucket / dense-union constants
    become measured per-chip picks on every later run that loads it."""

    def add_opts(p):
        p.add_argument(
            "--out",
            default=None,
            help="artifact path (default calibration.json in the "
            "working directory — the path the engine auto-loads; "
            "JEPSEN_TPU_CALIBRATION overrides)",
        )
        p.add_argument(
            "--profile",
            default="default",
            help="sweep profile: 'default' (the ~2-minute full sweep) "
            "or 'smoke' (the tiny CI gate)",
        )
        p.add_argument(
            "--budget-s",
            type=float,
            default=None,
            help="wall-clock budget for the sweep; a truncated sweep "
            "still persists every config it measured",
        )

    def run(args) -> int:
        from .tune import __main__ as tune_main

        argv = []
        if args.out:
            argv += ["--out", args.out]
        argv += ["--profile", args.profile]
        if args.budget_s is not None:
            argv += ["--budget-s", str(args.budget_s)]
        return tune_main.main(argv)

    return {
        "tune": {
            "help": "measure the attached device and persist a "
            "calibration artifact (auto-tuned dispatch; doc/tuning.md)",
            "add_opts": add_opts,
            "run": run,
        }
    }


def test_all_cmd(
    tests_fn: Callable[[dict], List[Callable[[], dict]]],
    opt_fn: Optional[Callable[[argparse.ArgumentParser], None]] = None,
) -> Dict[str, dict]:
    """Run every test a suite defines; worst exit code wins.
    ``tests_fn`` returns zero-arg BUILDERS, one per test, so a single
    test's construction error (like its run-time crash) folds into the
    worst-wins aggregate instead of aborting the whole sweep.
    (reference: cli.clj:491-519)"""

    def add_opts(p):
        add_test_opts(p)
        if opt_fn is not None:
            opt_fn(p)

    def run(args) -> int:
        worst = EXIT_VALID
        for _ in range(getattr(args, "test_count", 1) or 1):
            for build in tests_fn(
                {**given_opts(args), **test_opts_to_map(args)}
            ):
                try:
                    code = run_test(build())
                except Exception:  # noqa: BLE001 — one crash (building
                    # OR running) must not swallow the remaining tests'
                    # results; it folds into the worst-wins aggregate
                    # (reference: cli.clj test-all catches per-test
                    # throwables and continues)
                    traceback.print_exc()
                    code = EXIT_CRASH
                worst = max(worst, code)
        return worst

    return {"test-all": {"help": "run every defined test",
                         "add_opts": add_opts, "run": run}}


def logging_safe_repr(obj: Any) -> str:
    import json

    return json.dumps(obj, indent=2, default=repr)


def run_cli(commands: Dict[str, dict], argv: Optional[List[str]] = None) -> int:
    """Parse argv against the command map and dispatch.
    (reference: cli.clj:258-334 run!)"""
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s",
    )
    parser = argparse.ArgumentParser(
        prog="jepsen-tpu", description="TPU-native distributed-systems tester"
    )
    sub = parser.add_subparsers(dest="command")
    for name, spec in commands.items():
        p = sub.add_parser(name, help=spec.get("help"))
        spec.get("add_opts", lambda _p: None)(p)
    args = parser.parse_args(argv)
    if not args.command:
        parser.print_help()
        return EXIT_USAGE
    try:
        return commands[args.command]["run"](args)
    except SystemExit as e:
        return int(e.code or 0)
    except KeyboardInterrupt:
        return EXIT_CRASH
    except Exception:
        traceback.print_exc()
        return EXIT_CRASH


def default_commands() -> Dict[str, dict]:
    """The built-in command set: run any registered workload against the
    in-memory fake client (dummy remote), plus serve/analyze."""

    def add_workload_opt(p):
        p.add_argument(
            "--suite",
            help="DB suite to run against real nodes (e.g. etcd, "
            "cockroachdb; see jepsen_tpu.suites.SUITES).  Without "
            "--suite, the workload runs in-process against the "
            "in-memory fake client.",
        )
        p.add_argument(
            "--workload",
            default=None,
            help="workload name (suite-specific with --suite; see "
            "jepsen_tpu.workloads.workload otherwise)",
        )
        p.add_argument(
            "--faults",
            help="comma-separated nemesis faults for --suite runs "
            "(partition,kill,pause,clock,disk)",
        )
        p.add_argument(
            "--rate",
            type=float,
            help="target ops/sec for --suite runs",
        )
        p.add_argument(
            "--per-key-limit",
            type=int,
            default=32,
            help="ops per independent key before rotating to a fresh one",
        )
        p.add_argument(
            "-o",
            "--opt",
            action="append",
            default=[],
            metavar="KEY=VALUE",
            help="extra suite option (repeatable), e.g. -o version=v3.1.5 "
            "-o port=2379; ints parse as ints",
        )

    def make_test(opts: dict) -> dict:
        from . import generator as gen
        from . import workloads
        from .fake import (
            BankAtomClient,
            CausalAtomClient,
            InsertOnceAtomClient,
            KeyedAtomClient,
            KeyedAtomSetClient,
            TxnAtomClient,
        )

        opts = dict(opts)
        if "per_key_limit" in opts:
            opts.setdefault("per-key-limit", opts.pop("per_key_limit"))

        for kv in opts.pop("opt", []) or []:
            k, _, v = kv.partition("=")
            try:
                opts[k] = int(v)
            except ValueError:
                opts[k] = v

        if opts.get("suite"):
            from . import suites

            if opts.get("faults"):
                opts["faults"] = [
                    f for f in str(opts["faults"]).split(",") if f
                ]
            else:
                opts["faults"] = []
            if not opts.get("workload"):
                opts.pop("workload", None)  # let the suite pick its default
            return suites.suite(opts["suite"]).test(opts)

        opts.setdefault("workload", "linearizable-register")
        wl = workloads.workload(opts["workload"], opts)
        g = wl.get("generator")
        if opts.get("time-limit"):
            g = gen.time_limit(opts["time-limit"], g)
        # per-workload fake client: the CAS-register fake fits the
        # keyed register/txn probes, but bank needs transfer/balance
        # semantics and the causal/sequential probes need reads that
        # return the SET of observed writes
        fake_client = {
            "bank": BankAtomClient,
            "causal": CausalAtomClient,
            "causal-reverse": KeyedAtomSetClient,
            "long-fork": TxnAtomClient,
            "list-append": TxnAtomClient,
            "rw-register": TxnAtomClient,
            "adya-g2": InsertOnceAtomClient,
        }.get(opts["workload"], KeyedAtomClient)()
        test = {
            # strip stray callables from opts — except the lazy mesh
            # builder, which the checker seam resolves at analyze time
            **{k: v for k, v in opts.items()
               if not callable(v) or k == "mesh-fn"},
            # workload defaults (e.g. bank's accounts/total-amount)
            # flow into the test map — generators and checkers read
            # them from there; explicit opts still win
            **{k: v for k, v in wl.items()
               if k not in ("generator", "final-generator", "checker",
                            "concurrency") and k not in opts},
            "name": opts["workload"],
            "client": fake_client,
            "generator": g,
            "checker": wl.get("checker"),
        }
        # a workload that needs more workers than the 1n default says so
        # (e.g. linearizable-register's 2n-thread key groups); an
        # explicit --concurrency still wins
        if "concurrency" in wl and "concurrency" not in opts:
            test["concurrency"] = wl["concurrency"]
        from . import trace

        return trace.wire(test, opts.get("tracing"))

    def make_tests(opts: dict) -> List[Callable[[], dict]]:
        """One test BUILDER per workload: every workload of --suite,
        or every in-process workload without one.  (reference:
        cli.clj:491-519 test-all-cmd)"""
        if opts.get("suite"):
            from . import suites

            # one eager workloads() build just for the name list (each
            # make_test→suite.test rebuilds its own) — construction
            # cost only, accepted for the 10-20 workloads suites carry
            names = sorted(
                suites.suite(opts["suite"]).workloads(
                    {k: v for k, v in opts.items() if k != "workload"}
                )
            )
        else:
            from . import workloads as workloads_mod

            names = workloads_mod.names()
        return [
            (lambda w=w: make_test({**opts, "workload": w}))
            for w in names
        ]

    cmds: Dict[str, dict] = {}
    cmds.update(single_test_cmd(make_test, add_workload_opt))
    cmds.update(test_all_cmd(make_tests, add_workload_opt))
    cmds.update(serve_cmd())
    cmds.update(tune_cmd())
    return cmds


def main(argv: Optional[List[str]] = None) -> None:
    sys.exit(run_cli(default_commands(), argv))


if __name__ == "__main__":
    main()
