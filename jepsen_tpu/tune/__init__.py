"""Auto-tuned dispatch (ROADMAP item 4): an offline ``jepsen_tpu
tune`` pass measures the attached device and persists a calibration
artifact; the engine's hand-pinned constants become calibration-aware
lookups with the pinned values as the untuned fallback.

Two halves:

- :mod:`jepsen_tpu.tune.artifact` — the versioned ``calibration.json``
  schema (keyed by device kind + device count + code fingerprint),
  load/validate/fallback, and the process-wide :func:`active`
  calibration every engine lookup consults.
- :mod:`jepsen_tpu.tune.calibrate` — the sweep itself: coordinate
  descent over (union-mode, window, flush-rows, row-bucket) plus the
  measured per-(kernel, E, C, F) cost table, guarded so no proposal
  ever exceeds the crash-calibrated per-chip ``fn.safe_dispatch``
  budget.

See doc/tuning.md.
"""

from .artifact import (  # noqa: F401
    Calibration,
    DEFAULT_PATH,
    SCHEMA_VERSION,
    active,
    build_artifact,
    code_fingerprint,
    load_calibration,
    reset_active,
    resolved_path,
    save,
    set_active,
    validate,
)
from .calibrate import (  # noqa: F401
    PROFILES,
    proposal_within_budget,
    run_tune,
)


def retune_recommended() -> bool:
    """True when the cost-model drift sentinel (obs.drift) currently
    recommends re-running the tune pass: some journalled dispatch
    shape's measured cost has drifted past ``JEPSEN_TPU_DRIFT_THRESHOLD``
    from what the active calibration (or the analytic proxy) predicts.
    Observation only — nothing acts on it automatically; the operator
    runs ``jepsen_tpu tune`` (doc/tuning.md "Drift sentinel")."""
    from ..obs import drift as obs_drift

    sentinel = obs_drift.active()
    if sentinel is None:
        return False
    return bool(sentinel.snapshot().get("retune_recommended"))
