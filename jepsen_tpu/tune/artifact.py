"""The calibration artifact: schema, persistence, and the process-wide
active-calibration singleton the engine's lookups consult.

A calibration is the durable output of one ``jepsen_tpu tune`` sweep
(:mod:`jepsen_tpu.tune.calibrate`): the measured-best engine knobs
(window, flush rows, row-bucket floor, dense union lowering, closure
mode for the Elle cycle screens) plus a
per-(kernel, E, C, F) cost table, keyed by **device kind + device
count + code fingerprint** so an artifact tuned on one chip (or one
engine revision) can never silently steer another.  The engine loads
it lazily at first lookup (:func:`active`) and falls back to the
pinned defaults — with a warning and a
``jepsen_engine_calibration_fallback_total`` count — whenever the file
is missing, corrupt, version-mismatched, or stale.  Verdicts never
depend on any of this: every calibrated knob only moves wall time
(``make tune-smoke`` pins byte-equality tuned vs untuned).

Resolution of the artifact path:

- ``JEPSEN_TPU_CALIBRATION=0`` (or ``off``) — calibration disabled.
- ``JEPSEN_TPU_CALIBRATION=<path>`` — that file.
- unset — ``calibration.json`` in the working directory (the
  ``jepsen_tpu tune`` default output), loaded only when it exists.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger("jepsen_tpu.tune")

#: artifact schema version — loads refuse any other value (schema
#: stability is pinned by the tests' round-trip check)
SCHEMA_VERSION = 1

#: default artifact filename (cwd-relative, like the store dir)
DEFAULT_PATH = "calibration.json"

#: the engine files whose constants a calibration replaces — the code
#: fingerprint hashes exactly these, so editing any of them stales
#: every existing artifact (the knobs' meaning may have moved)
_FINGERPRINT_FILES = (
    "engine/execution.py",
    "engine/planning.py",
    "elle/encode.py",
    "ops/cycles.py",
    "ops/dense.py",
    "ops/wgl.py",
)

#: params every artifact carries; used by the round-trip/schema tests
PARAM_KEYS = ("window", "flush_rows", "row_bucket", "union_mode",
              "closure_mode", "closure_impl")

_VALID_UNIONS = ("unroll", "gather", "matmul")

_VALID_CLOSURES = ("fixed", "earlyexit")

_VALID_IMPLS = ("uint8", "packed32", "bf16")


def code_fingerprint() -> str:
    """SHA-1 over the engine sources whose hand-pinned constants the
    calibration replaces — a tuned artifact is only trusted against
    the exact code it was measured on."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha1()
    for rel in _FINGERPRINT_FILES:
        p = os.path.join(root, rel.replace("/", os.sep))
        try:
            with open(p, "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"?")
        h.update(b"\x1f")
    return h.hexdigest()


def aot_fingerprint() -> str:
    """The cache id keying the serve tier's shared on-disk AOT
    executable cache (doc/checker-service.md "Fleet tier"): the engine
    :func:`code_fingerprint` joined with the active calibration id.
    Both halves change what gets compiled — the sources define the
    kernels, the calibration steers union/closure variants and row
    buckets — so a manifest entry recorded under one pair must never
    pre-warm a daemon running another."""
    cal = active()
    return (f"{code_fingerprint()[:16]}"
            f"-{cal.calibration_id if cal is not None else 'untuned'}")


def device_key() -> Tuple[str, int]:
    """(device kind, local device count) of the attached backend —
    the hardware half of the artifact key.  Initializes the backend;
    callers only reach this when a calibration file actually exists
    (the common no-artifact case never pays it)."""
    import jax

    devs = jax.local_devices()
    kind = getattr(devs[0], "device_kind", None) or devs[0].platform
    return str(kind), len(devs)


class Calibration:
    """One validated calibration artifact.

    Constructed from the raw artifact dict (already schema-checked by
    :func:`load_calibration`); exposes the engine-facing lookups —
    :meth:`window`, :meth:`flush_rows`, :meth:`row_bucket`,
    :meth:`union_mode`, :meth:`closure_mode`, :meth:`closure_impl`,
    and the interpolating :meth:`cost` table."""

    def __init__(self, data: Dict[str, Any]):
        self.data = data
        self.calibration_id: str = data["calibration_id"]
        self.device_kind: str = data["device_kind"]
        self.n_devices: int = int(data["n_devices"])
        self.code_fingerprint: str = data["code_fingerprint"]
        p = data["params"]
        self.params: Dict[str, Any] = {k: p[k] for k in PARAM_KEYS}
        #: (kernel, E, C, F) -> sorted [(rows, seconds), ...]
        self._table: Dict[Tuple[str, int, int, int],
                          List[Tuple[int, float]]] = {}
        for e in data.get("cost_table", ()):
            k = (str(e["kernel"]), int(e["E"]), int(e["C"]), int(e["F"]))
            self._table.setdefault(k, []).append(
                (int(e["rows"]), float(e["seconds"]))
            )
        for pts in self._table.values():
            pts.sort()

    # -- engine-facing lookups --------------------------------------------

    def window(self) -> int:
        return int(self.params["window"])

    def flush_rows(self) -> int:
        return int(self.params["flush_rows"])

    def row_bucket(self) -> int:
        return int(self.params["row_bucket"])

    def union_mode(self) -> str:
        return str(self.params["union_mode"])

    def closure_mode(self) -> str:
        return str(self.params["closure_mode"])

    def closure_impl(self) -> str:
        return str(self.params["closure_impl"])

    def has_cost_table(self) -> bool:
        return bool(self._table)

    def cost(self, kernel: str, E: int, C: int, F: int,
             rows: int) -> Optional[float]:
        """Predicted device seconds for one ``rows``-row dispatch of
        ``kernel`` at shape (E, C, F) — the measured replacement for
        ``planning.estimated_cost``'s analytic proxy.  Exact shapes
        interpolate (piecewise-linearly in rows, through the origin
        below the first sample); unmeasured shapes scale the nearest
        measured shape (log-space distance) by the analytic footprint
        ratio — including ACROSS kernels when the table never measured
        this kernel at all, so every bucket a sort compares is in the
        same unit (seconds): a half-covered table must degrade to a
        cruder estimate, never to proxy-vs-seconds apples-and-oranges
        ordering.  Returns None only when the table is empty."""
        key = (kernel, int(E), int(C), int(F))
        pts = self._table.get(key)
        if pts is not None:
            return _interp_rows(pts, rows)
        pts, ref_key = self._nearest(kernel, E, C, F)
        if pts is None:  # no same-kernel entry: nearest ANY kernel
            pts, ref_key = self._nearest(None, E, C, F)
            if pts is None:
                return None
        scale = _proxy(kernel, E, C, F) / max(
            _proxy(ref_key[0], *ref_key[1:]), 1e-12
        )
        return scale * _interp_rows(pts, rows)

    def _nearest(self, kernel: Optional[str], E: int, C: int, F: int):
        """Closest measured shape by log-space distance; ``kernel=None``
        searches every kernel's entries."""
        best = None
        best_d = None
        for key in self._table:
            if kernel is not None and key[0] != kernel:
                continue
            d = sum(
                (math.log2(max(a, 1)) - math.log2(max(b, 1))) ** 2
                for a, b in zip(key[1:], (E, C, F))
            )
            if best_d is None or d < best_d:
                best, best_d = key, d
        if best is None:
            return None, None
        return self._table[best], best

    # -- matching ----------------------------------------------------------

    def stale_reason(self) -> Optional[str]:
        """None when this artifact matches the attached device and the
        current engine code; else a short human reason."""
        if self.code_fingerprint != code_fingerprint():
            return "code-fingerprint mismatch (engine sources changed)"
        kind, n = device_key()
        if self.device_kind != kind or self.n_devices != n:
            return (
                f"device mismatch (tuned on {self.device_kind}"
                f"×{self.n_devices}, attached {kind}×{n})"
            )
        return None


def _proxy(kernel: str, E: int, C: int, F: int) -> float:
    """The analytic per-row footprint proxy (same form as
    ``planning.estimated_cost``'s fallback), used only to scale a
    measured neighbor onto an unmeasured shape."""
    if kernel == "dense":
        return float(max(E, 1))
    if kernel == "cycles":
        # the Elle screens' boolean matrix closure: E is the vertex
        # bucket, F the packed plane weight (filter masks + lifted
        # walk queries folded into the batch axis; under the packed32
        # closure impl the callers pass it pre-discounted by W/n —
        # elle.encode.plane_weight), per-row work scales with F
        # planes of E×E matmul squaring
        return float(max(E, 1)) * max(E, 1) * max(F, 1)
    words = max(1, -(-max(E, 1) // 32))
    return float(max(F, 1) * (max(C, 0) + 1) * words)


def _interp_rows(pts: List[Tuple[int, float]], rows: int) -> float:
    """Piecewise-linear seconds(rows) through measured points; linear
    through the origin below the first sample, last-segment slope
    extrapolation above the last."""
    if rows <= 0:
        return 0.0
    if len(pts) == 1 or rows <= pts[0][0]:
        r0, s0 = pts[0]
        return s0 * rows / max(r0, 1)
    for (r0, s0), (r1, s1) in zip(pts, pts[1:]):
        if rows <= r1:
            t = (rows - r0) / max(r1 - r0, 1)
            return s0 + t * (s1 - s0)
    (r0, s0), (r1, s1) = pts[-2], pts[-1]
    slope = (s1 - s0) / max(r1 - r0, 1)
    return max(0.0, s1 + slope * (rows - r1))


# -- schema validation / persistence ----------------------------------------


def validate(data: Any) -> Dict[str, Any]:
    """Structural check of a raw artifact dict; raises ValueError with
    a reason on any problem (the load path turns that into a warned
    fallback, never a crash)."""
    if not isinstance(data, dict):
        raise ValueError("artifact is not a JSON object")
    if data.get("version") != SCHEMA_VERSION:
        raise ValueError(
            f"schema version {data.get('version')!r} != {SCHEMA_VERSION}"
        )
    for k in ("calibration_id", "device_kind", "n_devices",
              "code_fingerprint", "params"):
        if k not in data:
            raise ValueError(f"missing field {k!r}")
    p = data["params"]
    if not isinstance(p, dict):
        raise ValueError("params is not an object")
    for k in PARAM_KEYS:
        if k not in p:
            raise ValueError(f"missing param {k!r}")
    if int(p["window"]) < 1:
        raise ValueError("window must be >= 1")
    if int(p["flush_rows"]) < 1:
        raise ValueError("flush_rows must be >= 1")
    rb = int(p["row_bucket"])
    if rb < 1 or rb & (rb - 1):
        raise ValueError("row_bucket must be a power of two")
    if p["union_mode"] not in _VALID_UNIONS:
        raise ValueError(f"unknown union_mode {p['union_mode']!r}")
    if p["closure_mode"] not in _VALID_CLOSURES:
        raise ValueError(f"unknown closure_mode {p['closure_mode']!r}")
    if p["closure_impl"] not in _VALID_IMPLS:
        raise ValueError(f"unknown closure_impl {p['closure_impl']!r}")
    for e in data.get("cost_table", ()):
        for k in ("kernel", "E", "C", "F", "rows", "seconds"):
            if k not in e:
                raise ValueError(f"cost_table entry missing {k!r}")
        if float(e["seconds"]) < 0:
            raise ValueError("negative cost_table seconds")
    return data


def build_artifact(params: Dict[str, Any], cost_table: List[dict],
                   device_kind: str, n_devices: int,
                   created_at: str, sweep: Optional[dict] = None) -> dict:
    """Assemble a schema-valid artifact dict (the tuner's output)."""
    fp = code_fingerprint()
    data = {
        "version": SCHEMA_VERSION,
        "calibration_id": (
            f"{device_kind.replace(' ', '-').lower()}x{n_devices}"
            f"-{fp[:10]}"
        ),
        "created_at": created_at,
        "device_kind": device_kind,
        "n_devices": int(n_devices),
        "code_fingerprint": fp,
        "params": {k: params[k] for k in PARAM_KEYS},
        "cost_table": list(cost_table),
    }
    if sweep is not None:
        data["sweep"] = sweep
    return validate(data)


def save(data: dict, path: str) -> str:
    validate(data)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def resolved_path() -> Optional[str]:
    """The artifact path per the environment policy, or None when
    calibration is disabled / no default file exists."""
    v = os.environ.get("JEPSEN_TPU_CALIBRATION")
    if v is not None:
        v = v.strip()
        if v.lower() in ("", "0", "false", "off", "no"):
            return None
        return v
    return DEFAULT_PATH if os.path.exists(DEFAULT_PATH) else None


def load_calibration(path: str,
                     check_stale: bool = True) -> Optional[Calibration]:
    """Load + validate one artifact file; None (with a logged warning
    and a ``jepsen_engine_calibration_fallback_total`` count) on ANY
    problem — a bad artifact must degrade to the pinned defaults, never
    crash or skew a checker run."""
    from .. import obs

    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        log.warning(
            "calibration %s unreadable (%s); using pinned engine "
            "defaults", path, e,
        )
        obs.count("jepsen_engine_calibration_fallback_total",
                  reason="unreadable")
        return None
    try:
        cal = Calibration(validate(data))
    except (ValueError, KeyError, TypeError) as e:
        log.warning(
            "calibration %s invalid (%s); using pinned engine defaults",
            path, e,
        )
        obs.count("jepsen_engine_calibration_fallback_total",
                  reason="invalid")
        return None
    if check_stale:
        try:
            reason = cal.stale_reason()
        except Exception as e:  # noqa: BLE001 — a backend probe failure
            # must not take the engine down just to vet a calibration
            reason = f"device probe failed ({e!r})"
        if reason is not None:
            log.warning(
                "calibration %s stale: %s; using pinned engine defaults",
                path, reason,
            )
            obs.count("jepsen_engine_calibration_fallback_total",
                      reason="stale")
            return None
    return cal


# -- the process-wide active calibration -------------------------------------

_lock = threading.Lock()
_UNRESOLVED = object()
_active: Any = _UNRESOLVED


def active() -> Optional[Calibration]:
    """The process's active calibration, resolved lazily ONCE from the
    environment policy (:func:`resolved_path`); None when disabled,
    absent, or rejected.  This is what every engine lookup consults —
    the no-artifact fast path is a single ``os.path.exists``."""
    global _active
    got = _active  # jt: allow[concurrency-guard-drift] — double-checked fast path; resolved once under _lock
    if got is not _UNRESOLVED:
        return got
    with _lock:
        if _active is _UNRESOLVED:
            path = resolved_path()
            cal = load_calibration(path) if path else None
            if cal is not None:
                from .. import obs

                log.info("calibration %s active (from %s)",
                         cal.calibration_id, path)
                obs.gauge_set("jepsen_engine_calibration_loaded", 1)
            _active = cal
        return _active


def resolve_knob(env_var: str, parse, cal_get, default):
    """The ONE env > calibration > pinned-default ladder every
    calibrated engine knob resolves through (window, flush rows,
    row-bucket floor, dense union mode).  ``parse`` maps the raw env
    string to a usable value or ``None``; an unparseable/empty env
    value is noise, not a choice — it falls through to the
    calibration tier, exactly like an unset variable, instead of
    silently masking a tuned artifact.  ``cal_get`` reads the knob
    off an active :class:`Calibration`."""
    v = os.environ.get(env_var)
    if v is not None:
        try:
            parsed = parse(v)
        except (ValueError, TypeError):
            parsed = None
        if parsed is not None:
            return parsed
    cal = active()
    if cal is not None:
        return cal_get(cal)
    return default


def set_active(cal: Optional[Calibration]) -> None:
    """Pin the active calibration (tests; the ``tune`` CLI after a
    fresh write).  ``None`` means "resolved: no calibration"."""
    global _active
    with _lock:
        _active = cal


def reset_active() -> None:
    """Forget the resolution so the next :func:`active` re-reads the
    environment (tests, and the CLI between runs)."""
    global _active
    with _lock:
        _active = _UNRESOLVED
