"""``python -m jepsen_tpu.tune`` — the offline autotune pass (same
entry as ``jepsen_tpu tune``; see doc/tuning.md)."""

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.tune",
        description="Measure the attached device and persist a "
        "calibration artifact the engine loads at startup "
        "(doc/tuning.md).",
    )
    ap.add_argument(
        "--out", default=None,
        help="artifact path (default calibration.json in the working "
        "directory — the path the engine auto-loads)",
    )
    ap.add_argument(
        "--profile", choices=sorted(__profiles()), default="default",
        help="sweep profile: candidate sets + corpus sizes "
        "(default 'default'; 'smoke' is the tiny CI gate)",
    )
    ap.add_argument(
        "--budget-s", type=float, default=None,
        help="wall-clock budget for the sweep (a truncated sweep still "
        "persists every config it measured)",
    )
    args = ap.parse_args(argv)
    from . import artifact, calibrate

    out = args.out or artifact.DEFAULT_PATH
    path, data = calibrate.run_tune(
        out_path=out, profile=args.profile, budget_s=args.budget_s,
    )
    sweep = data.get("sweep", {})
    print(json.dumps({
        "calibration": data["calibration_id"],
        "path": path,
        "device_kind": data["device_kind"],
        "n_devices": data["n_devices"],
        "params": data["params"],
        "cost_table_entries": len(data.get("cost_table", ())),
        "measured_configs": sweep.get("measured_configs"),
        "wall_s": sweep.get("wall_s"),
        "truncated": sweep.get("truncated"),
    }))
    return 0


def __profiles():
    from .calibrate import PROFILES

    return PROFILES


if __name__ == "__main__":
    sys.exit(main())
