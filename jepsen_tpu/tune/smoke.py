"""Auto-tune smoke check: ``python -m jepsen_tpu.tune.smoke``.

The ``make tune-smoke`` gate (wired into ``make check``): a tiny
bounded sweep on the CPU fallback, then the four contracts the
calibration layer must never break —

1. **Artifact round-trip**: the sweep's ``calibration.json`` loads,
   validates, and re-saves byte-identically (schema stability).
2. **Budget guardrail**: the sweep recorded per-chip budget evidence
   with zero breaches, and :func:`~jepsen_tpu.tune.calibrate.
   proposal_within_budget` rejects an over-cap proposal outright.
3. **Fallback**: a corrupt artifact and a version-mismatched artifact
   both load as None (pinned defaults) — no crash.
4. **Verdict byte-equality tuned vs untuned** across the dense,
   frontier, escalation, decomposed, and service routes: a calibration
   may move wall time only, never a result dict.

Exit codes: 0 ok, 1 any contract broken.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def _corpora():
    import random

    from jepsen_tpu import models as m
    from jepsen_tpu.synth import generate_history, generate_mr_history

    rng = random.Random(45100)
    cas = [
        generate_history(rng, n_procs=3, n_ops=14, crash_p=0.02,
                         corrupt=(i % 3 == 0))
        for i in range(8)
    ]
    mr = [
        generate_mr_history(rng, n_procs=4, n_ops=30, n_keys=6,
                            n_values=4, crash_p=0.02, corrupt=(i % 3 == 0))
        for i in range(6)
    ]
    return m.cas_register(0), cas, m.multi_register(
        {k: 0 for k in range(6)}), mr


def main(argv=None) -> int:
    from jepsen_tpu import tune
    from jepsen_tpu.ops import wgl
    from jepsen_tpu.serve import client as serve_client
    from jepsen_tpu.serve import daemon as serve_daemon

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    # pin "no calibration" for the sweep itself: a stray artifact in
    # the invoking cwd must not steer the gate's measurements
    tune.set_active(None)

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "calibration.json")
        # 1. the bounded sweep (the artifact is NOT activated yet: the
        # verdict-equality checks below must control activation)
        path, data = tune.run_tune(out_path=path, profile="smoke",
                                   activate=False)
        sweep = data.get("sweep", {})
        check(os.path.exists(path), "sweep wrote no artifact")
        check(sweep.get("budget_breaches") == 0
              and sweep.get("budget_checks", 0) > 0,
              f"missing budget evidence: {sweep}")
        check(len(data.get("cost_table", ())) > 0, "empty cost table")

        # round-trip: load → validate → re-save → identical JSON
        cal = tune.load_calibration(path)
        check(cal is not None, "fresh artifact failed to load")
        path2 = os.path.join(td, "resaved.json")
        tune.save(data, path2)
        with open(path) as f1, open(path2) as f2:
            check(f1.read() == f2.read(),
                  "artifact did not round-trip byte-identically")
        reloaded = json.load(open(path2))
        check(tune.validate(reloaded) is reloaded,
              "re-saved artifact failed validation")

        # 2. the guardrail rejects over-budget proposals outright
        from jepsen_tpu.engine import planning

        model, cas, mr_model, mr = _corpora()
        ctx = planning.RunContext(model, cas, oracle_fallback=False)
        planner = planning.Planner(model, spec=ctx.spec, slot_cap=32,
                                   frontier=64, max_closure=9)
        buckets, order = planner.encode_buckets(ctx)
        pb = planner.plan_rows(order[0], *buckets[order[0]])
        check(pb is not None and pb.plan.disp > 0, "no frontier plan")
        if pb is not None and pb.plan.disp > 0:
            over = pb.plan.disp * 4 + 1
            check(not tune.proposal_within_budget(pb.plan, over, window=4),
                  "guardrail admitted an over-budget frontier proposal")
            check(tune.proposal_within_budget(pb.plan, 1, window=1),
                  "guardrail rejected a trivially-safe proposal")

        # 3. corrupt / version-mismatch artifacts fall back to None
        corrupt = os.path.join(td, "corrupt.json")
        with open(corrupt, "w") as f:
            f.write("{not json")
        check(tune.load_calibration(corrupt) is None,
              "corrupt artifact did not fall back")
        vbad = dict(data)
        vbad["version"] = 999
        vpath = os.path.join(td, "vbad.json")
        with open(vpath, "w") as f:
            json.dump(vbad, f)
        check(tune.load_calibration(vpath) is None,
              "version-mismatched artifact did not fall back")

        # 4. verdict byte-equality tuned vs untuned, per route
        def run_routes(label):
            out = {
                # dense automaton route
                "dense": wgl.check_batch(model, cas, slot_cap=32),
                # generic frontier kernel (explicit closure cap)
                "frontier": wgl.check_batch(model, cas, slot_cap=32,
                                            max_closure=9),
                # escalation ladder: a starved base frontier overflows
                # and must rerun at the escalated capacity
                "escalation": wgl.check_batch(model, cas, slot_cap=32,
                                              frontier=2, max_closure=9),
                # decomposition front-end (multi-register per key)
                "decomposed": wgl.check_batch(mr_model, mr, slot_cap=32),
            }
            return out

        tune.set_active(None)  # pinned defaults
        untuned = run_routes("untuned")
        tune.set_active(cal)
        try:
            tuned = run_routes("tuned")
            for route in untuned:
                check(
                    tuned[route] == untuned[route],
                    f"{route}: tuned results differ from untuned",
                )

            # service route: an in-process daemon with the calibration
            # active must answer byte-identically to the untuned
            # in-process engine and advertise the calibration id
            d = serve_daemon.CheckerDaemon("127.0.0.1", 0)
            d.start(block=False)
            try:
                cl = serve_client.ServiceClient(port=d.port)
                res_service = cl.check_batch(model, cas, slot_cap=32)
                check(res_service == untuned["dense"],
                      "service: tuned daemon results differ from untuned "
                      "in-process")
                st = cl.status()
                check(st.get("calibration") == cal.calibration_id,
                      f"/status calibration {st.get('calibration')!r} != "
                      f"{cal.calibration_id!r}")
            finally:
                d.stop()
        finally:
            tune.reset_active()

    if failures:
        for f_ in failures:
            print(f"tune-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "tune-smoke: ok (bounded sweep "
        f"{sweep.get('wall_s')}s, {sweep.get('measured_configs')} configs, "
        f"{len(data.get('cost_table', ()))} cost points, "
        "round-trip + budget guardrail + fallback + tuned≡untuned on "
        "dense/frontier/escalation/decomposed/service routes)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
