"""The offline autotuner behind ``jepsen_tpu tune``.

Replaces the engine's hand-pinned dispatch constants with measured
picks for the *attached* device (ROADMAP item 4): a coordinate-descent
search (the schedule-fine-tuning shape of arXiv:2406.20037, sized for
our six-knob space) from the current defaults over

- ``union_mode`` — the dense subset-union lowering (the stable ~1.6×
  unroll/gather gap in BENCH_tpu_windows.jsonl is exactly what this
  coordinate re-measures per chip; ``matmul`` recasts the subset maps
  as one-hot MXU matmuls),
- ``closure_mode`` — fixed-round vs convergence-early-exit boolean
  closure in the Elle cycle screens (the sync cost of the early-exit
  ``while_loop`` only pays off at large vertex buckets),
- ``closure_impl`` — the closure squaring arithmetic (historical
  saturated-bf16 ``uint8`` planes, boolean-carry ``bf16`` MXU matmul,
  or the word-packed ``packed32`` boolean semiring whose budget caps
  price rows at W/n ≈ 1/32 of the uint8 footprint); crossed with
  ``closure_mode`` over the sweep's screen timings,
- ``window`` — the engine's in-flight dispatch bound,
- ``flush_rows`` — the streaming bucket flush threshold,
- ``row_bucket`` — the power-of-two dispatch-row floor,

each candidate timed as a full pipelined run (encode → bucket → window
→ drain, the production ``Planner``/``Executor`` composition) on
synthetic corpora covering both kernel routes.  Compile and execute
phases are read separately from the existing obs dispatch timings
(``jepsen_kernel_compile_seconds`` / ``_execute_seconds``), and the
objective is steady-state (execute-phase) wall time, so a candidate is
never penalized for the one-off jit of its first visit.

A second pass measures the **cost table**: per-(kernel, E, C, F)
dispatch seconds at several row counts — the measured stand-in for the
analytic proxy in ``planning.estimated_cost`` (the learned-TPU-cost
direction of arXiv:2008.01040, as a direct lookup table rather than a
trained predictor: the config space per shape bucket is small enough
to measure outright).

**Budget guardrail**: no proposal — sweep candidate or cost-table row
count — may put more per-chip rows in flight than the crash-calibrated
``fn.safe_dispatch`` cap.  :func:`proposal_within_budget` is the
single gate; rejected proposals are counted
(``jepsen_tune_budget_rejections_total``) and recorded in the sweep
diag, and every measured run's ``Executor.chip_row_accounting`` peaks
are re-checked after the fact (``budget_evidence``), so the artifact
carries proof, not a promise.

Results persist via :mod:`jepsen_tpu.tune.artifact`.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from .. import obs
from ..obs import journal as obs_journal
from . import artifact

#: sweep profiles: bounded candidate sets + corpus sizes.  "default"
#: fits the ~2-minute CPU-fallback budget; "smoke" is the tiny
#: make-check gate (seconds, not minutes).
PROFILES: Dict[str, Dict[str, Any]] = {
    # corpus shape matters: the sweep optimizes wall time ON ITS OWN
    # corpora, so these must look like production traffic (hundreds of
    # ops per history — the flagship bench runs 1000-op histories), or
    # a pick that wins at toy shapes loses at real ones (measured: an
    # L=40 sweep corpus picked a union mode 2× slower at L=200)
    "default": dict(
        n_hists=32, n_ops=160, n_procs=3, reps=2, passes=2,
        windows=(1, 2, 4, 8), unions=("unroll", "gather", "matmul"),
        closures=("fixed", "earlyexit"),
        impls=("uint8", "packed32", "bf16"),
        flush_rows=(4096, 16384, 65536), row_buckets=(32, 64, 128),
        cost_rows=(32, 128), screen_ns=(16, 64), n_graphs=24,
        budget_s=100.0,
    ),
    "smoke": dict(
        n_hists=10, n_ops=12, n_procs=3, reps=1, passes=1,
        windows=(1, 4), unions=("unroll", "gather", "matmul"),
        closures=("fixed", "earlyexit"),
        impls=("uint8", "packed32", "bf16"),
        flush_rows=(16384,), row_buckets=(64,),
        cost_rows=(8,), screen_ns=(16,), n_graphs=6, budget_s=30.0,
    ),
}

#: shared shape knobs for the synthetic corpora (small on purpose: the
#: tuner ranks configs, it does not need flagship batch sizes)
SLOT_CAP = 32
FRONTIER = 64


def proposal_within_budget(plan, rows: int, window: int,
                           n_devices: int = 1) -> bool:
    """True iff dispatching ``rows`` rows of ``plan`` under an
    in-flight ``window`` keeps per-chip concurrent rows within the
    crash-calibrated ``fn.safe_dispatch`` cap (``plan.disp``).  Dense
    kernels allow the full cap per dispatch at any depth (small
    per-row footprint — the measured flagship pattern); frontier
    kernels hold at most ``disp`` rows across the whole window (the
    executor splits chunks to ``disp//window``, or serializes when
    even that floors out).  A plan with no dispatchable kernel admits
    nothing.  ``plan.disp`` already carries the closure-impl pricing
    (``ops.cycles.cycles_max_dispatch``): a ``packed32`` screen plan's
    cap is ~32× the uint8 cap for the same shape, so word-packed
    candidates legally admit ~32× more rows per chunk under the same
    per-chip word budget."""
    if plan.fn is None or plan.disp == 0:
        return rows == 0
    cap = plan.disp * max(1, n_devices)
    if plan.kernel == "dense":
        return rows <= cap
    w = max(1, window)
    if plan.disp >= w:
        # window-deep frontier dispatch: w chunks of disp//w rows each
        # — total in flight ≤ disp per chip by construction
        return rows <= (plan.disp // w) * w * max(1, n_devices)
    return rows <= cap  # serialized: one full-cap dispatch at a time


@contextmanager
def _env(**kv):
    """Scoped environment overrides for the knobs the engine reads
    from the environment (union lowering, row-bucket floor)."""
    saved = {k: os.environ.get(k) for k in kv}
    try:
        for k, v in kv.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _corpora(profile: Dict[str, Any]):
    """Synthetic measurement corpora: one dense-routed and one
    frontier-routed CAS-register batch (every history encodable, so
    timings are pure device+host pipeline, no oracle noise), plus a
    decomposable multi-register batch for the decomposed route's cost
    evidence, plus an ``"elle"`` list of encoded dependency graphs so
    the ``closure_mode`` coordinate has screen traffic to rank (NOT a
    ``(model, hists)`` pair — the history loops skip this key)."""
    import random

    from .. import models as m
    from ..synth import generate_history, generate_mr_history

    rng = random.Random(45100)
    n, L, P = profile["n_hists"], profile["n_ops"], profile["n_procs"]
    cas = [
        generate_history(rng, n_procs=P, n_ops=L, crash_p=0.0,
                         corrupt=(i % 4 == 0))
        for i in range(n)
    ]
    mr = [
        generate_mr_history(rng, n_procs=P, n_ops=L, n_keys=4,
                            n_values=4, crash_p=0.0, corrupt=(i % 4 == 0))
        for i in range(max(2, n // 4))
    ]
    return {
        "cas": (m.cas_register(0), cas),
        "multi-register": (m.multi_register({k: 0 for k in range(4)}), mr),
        "elle": _screen_corpus(profile.get("n_graphs", 8)),
    }


def _screen_corpus(n_graphs: int):
    """Deterministic encoded graphs for the screen timings: ring and
    chain relation matrices at the canonical no-suffix filter profile
    (the same shapes the cost-table cycles arm measures), spread over
    two vertex buckets so packed plane stacks of both shapes warm."""
    import numpy as np

    from ..elle import encode as encode_mod

    masks, nonadj = (1, 3, 7), ((4, 3),)
    encs = []
    for g in range(max(1, n_graphs)):
        n = 16 if g % 2 == 0 else 32
        rel = np.zeros((n, n), np.uint8)
        for i in range(n - 1):
            rel[i, i + 1] = (1, 2, 4)[(g + i) % 3]
        if g % 2 == 0:
            rel[n - 1, 0] = 1  # close into a ring
        encs.append(encode_mod.EncodedGraph(
            list(range(n)), rel, 7, masks, nonadj
        ))
    return encs


def _phase_seconds(reg) -> Tuple[float, float]:
    """(compile_s, execute_s) sums from the obs dispatch histograms —
    the existing per-dispatch timing seam, read instead of re-timed."""
    compile_s = execute_s = 0.0
    for d in reg.snapshot():
        if d["name"] == "jepsen_kernel_compile_seconds":
            compile_s += d.get("sum", 0.0)
        elif d["name"] == "jepsen_kernel_execute_seconds":
            execute_s += d.get("sum", 0.0)
    return compile_s, execute_s


def journal_rows(path: Optional[str] = None,
                 kernel: Optional[str] = None) -> List[dict]:
    """Production dispatch-journal rows
    (:mod:`jepsen_tpu.obs.journal`) read back in the cost-table entry
    shape — real-traffic evidence beside the synthetic
    :func:`measure_cost_table` points.  ``seconds`` is the warm
    execute time when the dispatch was a compile-cache hit, else the
    compile time; ``corpus`` is ``"journal"`` so consumers can tell
    measured-offline from observed-in-production rows.  Reads the
    process's configured journal by default (falling back to
    ``dispatch-journal.jsonl`` in the cwd); bad lines are skipped, a
    missing file is just an empty list."""
    p = path or obs_journal.path() or obs_journal.DEFAULT_FILENAME
    out: List[dict] = []
    for row in obs_journal.read_rows(p):
        if kernel is not None and row.get("kernel") != kernel:
            continue
        secs = (row["execute_s"] if row["cache"] == "hit"
                else row["compile_s"])
        out.append({
            "kernel": row["kernel"], "E": row["E"], "C": row["C"],
            "F": row["F"], "rows": row["rows"],
            "seconds": round(float(secs), 6),
            "corpus": "journal",
            "cache": row["cache"],
            "coalesced": row["coalesced"],
        })
    return out


class _Runner:
    """Measurement harness: one timed pipelined run per call, through
    the production planning/execution composition, with per-run budget
    evidence collected from the executor's chip-row accounting."""

    def __init__(self):
        self.budget_evidence: List[dict] = []
        self.budget_breaches: List[dict] = []

    def timed_run(self, model, hists, *, window: int, flush_rows: int,
                  max_closure: Optional[int] = None,
                  max_dispatch: Optional[int] = None) -> float:
        """Wall seconds of one full pipelined pass (encode → buckets →
        window → drain).  Oracle fallback is off: the corpora are fully
        encodable, and a worker pool would only add noise."""
        from ..engine import execution, planning

        ctx = planning.RunContext(model, hists, oracle_fallback=False)
        planner = planning.Planner(
            model, spec=ctx.spec, slot_cap=SLOT_CAP, frontier=FRONTIER,
            max_closure=max_closure, max_dispatch=max_dispatch,
            bucketed=True, flush_rows=flush_rows,
        )
        ex = execution.Executor(window, max_dispatch=max_dispatch)
        t0 = time.perf_counter()
        for pb in planner.stream(ctx):
            ex.submit(pb)
        ex.drain()
        wall = time.perf_counter() - t0
        self._collect_budget(ex)
        return wall

    def timed_screens(self, encs, *, window: int, reps: int) -> float:
        """Wall seconds of one screen pass over encoded dependency
        graphs (best of ``reps`` after one un-timed warmup) — the
        traffic the ``closure_mode`` and ``closure_impl`` coordinates
        rank on (each candidate's screens run under its own
        mode × impl pair, so the sweep crosses the two axes).  Same
        production Executor, same budget evidence."""
        from ..engine import execution
        from ..ops import cycles as ops_cycles

        def one() -> float:
            ex = execution.Executor(window)
            t0 = time.perf_counter()
            ops_cycles.screen_graphs(encs, executor=ex)
            wall = time.perf_counter() - t0
            self._collect_budget(ex)
            return wall

        one()  # warmup: compiles
        return min(one() for _ in range(reps))

    def _collect_budget(self, ex) -> None:
        for acct in ex.chip_row_accounting.values():
            cap = acct["chip_cap"]
            if acct["kernel"] == "dense":
                cap = cap * ex.window_size
            ev = {
                "kernel": acct["kernel"],
                "peak_chip_rows": acct["peak_chip_rows"],
                "chip_cap": acct["chip_cap"],
                "window": ex.window_size,
                "within_budget": acct["peak_chip_rows"] <= cap,
            }
            self.budget_evidence.append(ev)
            if not ev["within_budget"]:  # engine invariant — loudly
                self.budget_breaches.append(ev)


def measure_config(runner: _Runner, corpora, cfg: Dict[str, Any],
                   reps: int) -> float:
    """Objective for one candidate config: steady-state wall seconds
    (best of ``reps`` after one un-timed warmup that absorbs compiles)
    across the dense- and frontier-routed corpora."""
    model, cas = corpora["cas"]
    total = 0.0
    with _env(JEPSEN_TPU_DENSE_UNION=cfg["union_mode"],
              JEPSEN_TPU_ENGINE_ROW_BUCKET=cfg["row_bucket"],
              JEPSEN_TPU_CYCLES_CLOSURE=cfg["closure_mode"],
              JEPSEN_TPU_CYCLES_IMPL=cfg["closure_impl"]):
        for max_closure in (None, 9):  # dense route, then frontier
            kw = dict(window=cfg["window"], flush_rows=cfg["flush_rows"],
                      max_closure=max_closure)
            runner.timed_run(model, cas, **kw)  # warmup: compiles
            total += min(
                runner.timed_run(model, cas, **kw) for _ in range(reps)
            )
        total += runner.timed_screens(
            corpora["elle"], window=cfg["window"], reps=reps
        )
    obs.count("jepsen_tune_measurements_total", phase="sweep")
    return total


def coordinate_descent(runner: _Runner, corpora, profile: Dict[str, Any],
                       deadline: float) -> Tuple[Dict[str, Any], dict]:
    """Start from the pinned defaults and improve one coordinate at a
    time, re-visiting until a full pass changes nothing (or the time
    budget runs out — the partial result is still valid: every visited
    config was really measured)."""
    from ..engine import execution, planning
    from ..ops import cycles as ops_cycles
    from ..ops import dense

    space = {
        "union_mode": tuple(profile["unions"]),
        "closure_mode": tuple(profile["closures"]),
        "closure_impl": tuple(profile["impls"]),
        "window": tuple(profile["windows"]),
        "flush_rows": tuple(profile["flush_rows"]),
        "row_bucket": tuple(profile["row_buckets"]),
    }
    current = {
        "union_mode": dense.DEFAULT_UNION,
        "closure_mode": ops_cycles.DEFAULT_CLOSURE_MODE,
        "closure_impl": ops_cycles.DEFAULT_CLOSURE_IMPL,
        "window": execution.DEFAULT_WINDOW,
        "flush_rows": planning.DEFAULT_FLUSH_ROWS,
        "row_bucket": execution.ROW_BUCKET,
    }
    reps = profile["reps"]
    scores: Dict[str, float] = {}
    trail: List[dict] = []
    truncated = False

    def key_of(cfg):
        return "|".join(f"{k}={cfg[k]}" for k in sorted(cfg))

    def score(cfg) -> float:
        k = key_of(cfg)
        if k not in scores:
            scores[k] = measure_config(runner, corpora, cfg, reps)
        return scores[k]

    best_s = score(current)
    for _pass in range(profile["passes"]):
        moved = False
        for coord, cands in space.items():
            for cand in cands:
                if time.perf_counter() > deadline:
                    truncated = True
                    break
                if cand == current[coord]:
                    continue
                trial = {**current, coord: cand}
                s = score(trial)
                trail.append({"coord": coord, "value": cand,
                              "seconds": round(s, 5)})
                if s < best_s:
                    current, best_s = trial, s
                    moved = True
            if truncated:
                break
        if truncated or not moved:
            break
    diag = {
        "best_seconds": round(best_s, 5),
        "measured_configs": len(scores),
        "trail": trail,
        "truncated": truncated,
    }
    return current, diag


# jt: timing — intentional dispatch-and-sync measurement loop
def measure_cost_table(runner: _Runner, corpora, profile: Dict[str, Any],
                       params: Dict[str, Any]) -> List[dict]:
    """Per-(kernel, E, C, F) dispatch seconds at bounded row counts —
    the interpolation points ``planning.estimated_cost`` serves.  Row
    proposals are clamped through :func:`proposal_within_budget`
    BEFORE any dispatch; an over-budget proposal is counted and
    dropped, never measured.  The inline ``block_until_ready`` syncs
    are the point — this IS a timing loop, not a dispatch path
    (annotated ``# jt: timing`` for the trace-safety pass)."""
    import jax.numpy as jnp
    import numpy as np

    from ..engine import planning

    entries: List[dict] = []
    with _env(JEPSEN_TPU_DENSE_UNION=params["union_mode"],
              JEPSEN_TPU_CYCLES_CLOSURE=params["closure_mode"],
              JEPSEN_TPU_CYCLES_IMPL=params["closure_impl"]):
        for name, pair in corpora.items():
            if name == "elle":
                continue  # encoded graphs, not (model, hists) — the
                # screen shapes get their own arm below
            model, hists = pair
            for max_closure in (None, 9):
                ctx = planning.RunContext(model, hists,
                                          oracle_fallback=False)
                planner = planning.Planner(
                    model, spec=ctx.spec, slot_cap=SLOT_CAP,
                    frontier=FRONTIER, max_closure=max_closure,
                    bucketed=True,
                )
                if planner.spec is None:
                    continue
                buckets, order = planner.encode_buckets(ctx)
                for key in order:
                    encs, tokens = buckets[key]
                    pb = planner.plan_rows(key, encs, tokens)
                    if pb is None or pb.plan.fn is None or pb.plan.disp == 0:
                        continue
                    plan = pb.plan
                    for rows in profile["cost_rows"]:
                        rows = min(rows, len(pb.rows))
                        if not proposal_within_budget(
                            plan, rows, params["window"]
                        ):
                            obs.count("jepsen_tune_budget_rejections_total")
                            continue
                        args = tuple(
                            jnp.asarray(np.asarray(a)[:rows])
                            for a in pb.arrays
                        )
                        out = plan.fn(*args)  # warmup: trace + compile
                        out[0].block_until_ready()
                        t0 = time.perf_counter()
                        out = plan.fn(*args)
                        out[0].block_until_ready()
                        secs = time.perf_counter() - t0
                        obs.count("jepsen_tune_measurements_total",
                                  phase="cost")
                        entries.append({
                            "kernel": plan.kernel, "E": plan.E,
                            "C": plan.C, "F": plan.frontier,
                            "rows": rows,
                            "seconds": round(secs, 6),
                            "corpus": name,
                        })
    # the Elle transactional screens: (kernel="cycles", E=n, C=0,
    # F=plane weight) rows — F counts the packed closure planes the
    # profile expands into on the batch axis — so the measured table
    # ranks screen buckets in the same seconds unit as history buckets
    # (the daemon's largest-cost-first ordering compares them
    # directly).  Deterministic ring/chain relation matrices at the
    # canonical no-suffix filter profile, under the swept closure mode.
    from ..ops import cycles as ops_cycles

    masks, nonadj = (1, 3, 7), ((4, 3),)
    with _env(JEPSEN_TPU_CYCLES_CLOSURE=params["closure_mode"],
              JEPSEN_TPU_CYCLES_IMPL=params["closure_impl"]):
        for n in profile.get("screen_ns", ()):
            plan = ops_cycles.ScreenPlan(n, masks, nonadj)
            if plan.disp == 0:
                continue
            for rows in profile["cost_rows"]:
                if not proposal_within_budget(plan, rows, params["window"]):
                    obs.count("jepsen_tune_budget_rejections_total")
                    continue
                rel = np.zeros((rows, n, n), np.uint8)
                for b in range(rows):
                    for i in range(n - 1):
                        rel[b, i, i + 1] = (1, 2, 4)[(b + i) % 3]
                    if b % 2 == 0:
                        rel[b, n - 1, 0] = 1  # close into a ring
                args = jnp.asarray(rel)
                out = plan.fn(args)  # warmup: trace + compile
                out[0].block_until_ready()
                t0 = time.perf_counter()
                out = plan.fn(args)
                out[0].block_until_ready()
                secs = time.perf_counter() - t0
                obs.count("jepsen_tune_measurements_total", phase="cost")
                entries.append({
                    "kernel": "cycles", "E": n, "C": 0,
                    "F": plan.frontier,
                    "rows": rows, "seconds": round(secs, 6),
                    "corpus": "elle-screen",
                })
    # one point per (kernel, E, C, F, rows): keep the fastest (least
    # noisy) observation when corpora overlap in shape
    best: Dict[tuple, dict] = {}
    for e in entries:
        k = (e["kernel"], e["E"], e["C"], e["F"], e["rows"])
        if k not in best or e["seconds"] < best[k]["seconds"]:
            best[k] = e
    return [best[k] for k in sorted(best)]


def run_tune(out_path: str = artifact.DEFAULT_PATH,
             profile: str = "default",
             budget_s: Optional[float] = None,
             activate: bool = True) -> Tuple[str, dict]:
    """The whole offline pass: sweep → cost table → persisted
    artifact.  Returns ``(path, artifact_dict)``; with ``activate``
    the fresh artifact becomes this process's active calibration."""
    from ..platform import ensure_usable_backend

    ensure_usable_backend()
    prof = dict(PROFILES[profile])
    if budget_s is not None:
        prof["budget_s"] = float(budget_s)
    t_start = time.perf_counter()
    deadline = t_start + prof["budget_s"]
    device_kind, n_devices = artifact.device_key()
    corpora = _corpora(prof)
    runner = _Runner()

    params, sweep_diag = coordinate_descent(runner, corpora, prof, deadline)
    cost_table = measure_cost_table(runner, corpora, prof, params)
    if runner.budget_breaches:
        raise RuntimeError(
            "tuner measured a per-chip budget breach (engine invariant "
            f"violated): {runner.budget_breaches[:3]}"
        )
    sweep_diag.update({
        "profile": profile,
        "device_kind": device_kind,
        "n_devices": n_devices,
        "budget_checks": len(runner.budget_evidence),
        "budget_breaches": 0,
        "wall_s": round(time.perf_counter() - t_start, 3),
    })
    obs.gauge_set("jepsen_tune_sweep_seconds",
                  time.perf_counter() - t_start)
    import datetime

    data = artifact.build_artifact(
        params, cost_table, device_kind, n_devices,
        created_at=datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        sweep=sweep_diag,
    )
    artifact.save(data, out_path)
    if activate:
        artifact.set_active(artifact.Calibration(data))
    return out_path, data
