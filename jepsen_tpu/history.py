"""Op and history data model.

The history is the interchange format of the whole framework: the
interpreter produces one, the store persists one, and every checker consumes
one.  A history is a flat, time-ordered list of :class:`Op` events; each
logical operation appears as an ``invoke`` event followed (usually) by a
completion event (``ok``, ``fail``, or ``info``).

Semantics mirror the reference's knossos.op / jepsen history conventions
(reference: jepsen/src/jepsen/core.clj:228 assigns indices via
knossos.history/index; jepsen/src/jepsen/generator/interpreter.clj:142-157
turns worker crashes into ``info`` ops):

- ``invoke``: a process began an operation.
- ``ok``:     it completed successfully (reads carry the observed value
              on the *completion* event).
- ``fail``:   it definitely did NOT take effect.
- ``info``:   indeterminate — it may or may not have taken effect, at any
              later time ("open forever" for linearizability checking).

Processes are logically single-threaded: a process has at most one
outstanding operation, and a crashed process id is never reused.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional, Union

INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

TYPES = (INVOKE, OK, FAIL, INFO)

#: Integer codes for the device encoding (see jepsen_tpu.ops.encode).
TYPE_CODES = {INVOKE: 0, OK: 1, FAIL: 2, INFO: 3}

NEMESIS = "nemesis"

Process = Union[int, str]


class Op:
    """One history event.

    Cheap, mutable-by-convention record with a small fixed set of hot
    fields plus an ``extra`` dict for workload-specific keys (e.g.
    ``:error``, ``:link``, ``:clock-offsets``).
    """

    __slots__ = ("index", "type", "process", "f", "value", "time", "extra")

    def __init__(
        self,
        type: str,
        process: Process,
        f: Any,
        value: Any = None,
        time: int = 0,
        index: int = -1,
        **extra: Any,
    ):
        self.type = type
        self.process = process
        self.f = f
        self.value = value
        self.time = time
        self.index = index
        self.extra = extra or {}

    # -- dict-ish access so workloads can stash arbitrary keys -------------

    def get(self, key: str, default: Any = None) -> Any:
        if key in Op.__slots__ and key != "extra":
            return getattr(self, key)
        return self.extra.get(key, default)

    def __getitem__(self, key: str) -> Any:
        if key in Op.__slots__ and key != "extra":
            return getattr(self, key)
        return self.extra[key]

    def __setitem__(self, key: str, value: Any) -> None:
        if key in Op.__slots__ and key != "extra":
            setattr(self, key, value)
        else:
            self.extra[key] = value

    def __contains__(self, key: str) -> bool:
        if key in ("index", "type", "process", "f", "value", "time"):
            return True
        return key in self.extra

    @property
    def error(self) -> Any:
        return self.extra.get("error")

    # -- predicates --------------------------------------------------------

    @property
    def is_invoke(self) -> bool:
        return self.type == INVOKE

    @property
    def is_ok(self) -> bool:
        return self.type == OK

    @property
    def is_fail(self) -> bool:
        return self.type == FAIL

    @property
    def is_info(self) -> bool:
        return self.type == INFO

    def copy(self, **updates: Any) -> "Op":
        op = Op(
            self.type,
            self.process,
            self.f,
            self.value,
            self.time,
            self.index,
            **dict(self.extra),
        )
        for k, v in updates.items():
            op[k] = v
        return op

    def to_dict(self) -> dict:
        d = {
            "index": self.index,
            "type": self.type,
            "process": self.process,
            "f": self.f,
            "value": self.value,
            "time": self.time,
        }
        d.update(self.extra)
        return d

    @staticmethod
    def from_dict(d: dict) -> "Op":
        d = dict(d)
        return Op(
            d.pop("type"),
            d.pop("process"),
            d.pop("f", None),
            d.pop("value", None),
            d.pop("time", 0),
            d.pop("index", -1),
            **d,
        )

    def __repr__(self) -> str:
        extra = f" {self.extra}" if self.extra else ""
        return (
            f"Op({self.index} {self.type} p={self.process} f={self.f!r}"
            f" v={self.value!r} t={self.time}{extra})"
        )

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Op):
            return NotImplemented
        return (
            self.type == other.type
            and self.process == other.process
            and self.f == other.f
            and self.value == other.value
            and self.time == other.time
            and self.index == other.index
            and self.extra == other.extra
        )

    def __hash__(self) -> int:
        return hash((self.type, self.process, self.f, self.index))


def invoke_op(process: Process, f: Any, value: Any = None, **kw: Any) -> Op:
    return Op(INVOKE, process, f, value, **kw)


def ok_op(process: Process, f: Any, value: Any = None, **kw: Any) -> Op:
    return Op(OK, process, f, value, **kw)


def fail_op(process: Process, f: Any, value: Any = None, **kw: Any) -> Op:
    return Op(FAIL, process, f, value, **kw)


def info_op(process: Process, f: Any, value: Any = None, **kw: Any) -> Op:
    return Op(INFO, process, f, value, **kw)


class History(list):
    """A list of Ops with indexing and pairing helpers.

    Subclasses list so all the single-pass checkers can iterate it
    directly; adds the pairing structure (invoke ↔ completion) every
    analysis needs.
    """

    def __init__(self, ops: Iterable[Op] = ()):
        super().__init__(ops)

    # -- index assignment (knossos.history/index equivalent) ---------------

    def index_ops(self) -> "History":
        """Assign a monotone :index to every op, in place. Returns self."""
        for i, op in enumerate(self):
            op.index = i
        return self

    # -- views -------------------------------------------------------------

    def invocations(self) -> Iterator[Op]:
        return (op for op in self if op.type == INVOKE)

    def completions(self) -> Iterator[Op]:
        return (op for op in self if op.type != INVOKE)

    def oks(self) -> Iterator[Op]:
        return (op for op in self if op.type == OK)

    def client_ops(self) -> "History":
        return History(op for op in self if isinstance(op.process, int))

    def nemesis_ops(self) -> "History":
        return History(op for op in self if not isinstance(op.process, int))

    def filter_f(self, f: Any) -> "History":
        return History(op for op in self if op.f == f)

    # -- pairing -----------------------------------------------------------

    def pair_index(self) -> list:
        """For each position i, the position of the other half of the
        operation (invoke↔completion), or -1 if unpaired.

        Processes are logically single-threaded, so the completion of an
        invoke is the next event from the same process.
        """
        pairs = [-1] * len(self)
        open_by_process: dict = {}
        for i, op in enumerate(self):
            if op.type == INVOKE:
                open_by_process[op.process] = i
            else:
                j = open_by_process.pop(op.process, None)
                if j is not None:
                    pairs[i] = j
                    pairs[j] = i
        return pairs

    def pairs(self) -> Iterator[tuple]:
        """Yield (invoke, completion-or-None) tuples in invocation order."""
        pair = self.pair_index()
        for i, op in enumerate(self):
            if op.type == INVOKE:
                j = pair[i]
                yield (op, self[j] if j >= 0 else None)

    def completion_of(self, invoke: Op) -> Optional[Op]:
        """The next event from invoke's process after invoke's position in
        THIS history (located by identity, so it works on unindexed or
        filtered histories whose :index fields are stale)."""
        seen_invoke = False
        for op in self:
            if op is invoke:
                seen_invoke = True
                continue
            if seen_invoke and op.process == invoke.process:
                return op
        return None

    # -- transformations ---------------------------------------------------

    def complete(self) -> "History":
        """Propagate completion values back onto invocations (and invoke
        values forward onto completions that lack one).  Knossos-style
        'complete': an ok read's observed value appears on both events.
        """
        h = History(op.copy() for op in self)
        pair = self.pair_index()
        for i, op in enumerate(h):
            if op.type != INVOKE:
                continue
            j = pair[i]
            if j < 0:
                continue
            comp = h[j]
            if comp.type == OK:
                if comp.value is None:
                    comp.value = op.value
                else:
                    op.value = comp.value
        return h

    def map(self, fn: Callable[[Op], Op]) -> "History":
        return History(fn(op) for op in self)

    def without_failures(self) -> "History":
        """Drop fail completions and their invocations (a failed op never
        took effect — reference semantics)."""
        pair = self.pair_index()
        dropped = set()
        for i, op in enumerate(self):
            if op.type == FAIL:
                dropped.add(i)
                if pair[i] >= 0:
                    dropped.add(pair[i])
        return History(op for i, op in enumerate(self) if i not in dropped)

    def to_dicts(self) -> list:
        return [op.to_dict() for op in self]

    @staticmethod
    def from_dicts(dicts: Iterable[dict]) -> "History":
        return History(Op.from_dict(d) for d in dicts)


def strip_indeterminate_reads(history: History, pure_fs: Iterable[Any]) -> History:
    """Drop ``info`` (indeterminate) ops whose :f is a pure read — a crashed
    read can always linearize (it observed *some* value) and never changes
    state, so removing it shrinks the search space without changing the
    verdict.  Standard Knossos-style preprocessing optimization.
    """
    pure = set(pure_fs)
    pair = history.pair_index()
    dropped = set()
    for i, op in enumerate(history):
        if op.type == INFO and op.f in pure:
            dropped.add(i)
            if pair[i] >= 0:
                dropped.add(pair[i])
    return History(op for i, op in enumerate(history) if i not in dropped)
