"""REPL conveniences.  (reference: jepsen/src/jepsen/repl.clj)"""

from __future__ import annotations

from typing import Optional

from . import store


def latest_test(base: str = store.BASE) -> Optional[dict]:
    """The most recently run test, loaded from the store.
    (reference: repl.clj:6-15)"""
    return store.latest(base)
