"""Write/read-register txn workload: unique writes, point reads.
(reference: jepsen/src/jepsen/tests/cycle/wr.clj — its docstring
enumerates the anomaly vocabulary this checker reports)
"""

from __future__ import annotations

from typing import Optional

from . import TxnGenerator, checker as elle_checker
from ...checker import Checker


def gen(opts: Optional[dict] = None):
    """(reference: wr.clj:10-13)"""
    return TxnGenerator("wr", opts or {})


def checker(opts: Optional[dict] = None) -> Checker:
    """Default anomalies [G2 G1a G1b internal] — catches everything —
    when the opts carry no anomaly/model selection.
    (reference: wr.clj:15-52)"""
    opts = dict(opts or {})
    if "anomalies" not in opts and "consistency-models" not in opts:
        opts["anomalies"] = ["G2", "G1a", "G1b", "internal"]
    return elle_checker("rw-register", opts)


def test(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    return {"generator": gen(opts), "checker": checker(opts)}
