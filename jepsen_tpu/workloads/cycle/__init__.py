"""Transactional-cycle workloads: generators + checkers over the
Elle-equivalent analysis plane (jepsen_tpu.elle).

(reference: jepsen/src/jepsen/tests/cycle.clj — the generic adapter —
plus cycle/append.clj and cycle/wr.clj)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ... import generator as gen
from ...checker import Checker


def _fmt_anomaly_item(item: Any) -> str:
    """One anomaly instance as readable text: witness cycles render as
    step chains, everything else as indented JSON."""
    import json

    if isinstance(item, dict) and "steps" in item:
        lines = ["Cycle:"]
        for s in item["steps"]:
            rels = ",".join(s.get("rels", []))
            lines.append(f"  {s.get('from')} -[{rels}]-> {s.get('to')}")
        return "\n".join(lines)
    return json.dumps(item, indent=2, default=repr)


def write_anomaly_artifacts(test, result: dict, opts=None) -> None:
    """Persist one explanation file per anomaly type under
    ``<store>/<test>/<time>/elle/`` so the web UI's directory browser
    surfaces them next to results.json — the artifact the reference
    gets from Elle's :directory option (consumed at
    jepsen/src/jepsen/tests/cycle.clj:10-16).  Only runs when the test
    has a real store identity; adds the written paths to the result as
    "anomaly-files"."""
    if not (test and test.get("name") and test.get("start-time")):
        return
    anomalies = {
        **(result.get("anomalies") or {}),
        **(result.get("also-anomalies") or {}),
    }
    if not anomalies:
        return
    from ... import store as store_mod

    paths: List[str] = []
    try:
        for name, items in sorted(anomalies.items()):
            p = store_mod.path_(
                test,
                *(opts or {}).get("subdirectory", []),
                "elle",
                f"{name}.txt",
            )
            with open(p, "w") as f:
                f.write(f"{name}: {len(items)} instance(s)\n\n")
                for i, item in enumerate(items):
                    f.write(f"--- instance {i} ---\n")
                    f.write(_fmt_anomaly_item(item))
                    f.write("\n\n")
            paths.append(p)
        result["anomaly-files"] = paths
    except Exception as e:  # noqa: BLE001 — never mask the verdict
        result["anomaly-files-error"] = repr(e)


class _ElleChecker(Checker):
    def __init__(self, workload: str, opts: Optional[dict]):
        self.workload = workload
        self.opts = dict(opts or {})

    def check(self, test, history, opts=None):
        from ... import elle

        out = elle.check(
            {**self.opts, "workload": self.workload}, history
        )
        write_anomaly_artifacts(test, out, opts)
        return out


def checker(workload: str, opts: Optional[dict] = None) -> Checker:
    """A checker running the elle analysis for a txn workload.
    (reference: cycle.clj:9-16)"""
    return _ElleChecker(workload, opts)


class TxnGenerator(gen.Generator):
    """Random micro-op transactions over a rotating pool of keys.

    Mirrors elle's wr-txns/append-txns behavior: ``key-count`` keys are
    active at once; each key takes at most ``max-writes-per-key`` writes
    before being retired for a fresh one; txns have min..max mops, each
    a read or write/append of an active key with globally-unique written
    values per key.
    """

    def __init__(self, mode: str, opts: dict, state: Optional[dict] = None):
        self.mode = mode  # "append" | "wr"
        self.opts = opts
        if state is None:
            kc = opts.get("key-count", 2)
            state = {
                "next_key": kc,
                "active": list(range(kc)),
                "writes": {k: 0 for k in range(kc)},
                "counter": 0,
            }
        self.state = state

    def op(self, test, ctx):
        o = self.opts
        min_len = o.get("min-txn-length", 1)
        max_len = o.get("max-txn-length", 4)
        max_wpk = o.get("max-writes-per-key", 32)
        st = {
            "next_key": self.state["next_key"],
            "active": list(self.state["active"]),
            "writes": dict(self.state["writes"]),
            "counter": self.state["counter"],
        }
        n = min_len + gen.rng.randrange(max_len - min_len + 1)
        value: List[list] = []
        for _ in range(n):
            k = st["active"][gen.rng.randrange(len(st["active"]))]
            if gen.rng.random() < 0.5:
                value.append(["r", k, None])
            else:
                st["counter"] += 1
                f = "append" if self.mode == "append" else "w"
                value.append([f, k, st["counter"]])
                st["writes"][k] += 1
                if st["writes"][k] >= max_wpk:
                    idx = st["active"].index(k)
                    fresh = st["next_key"]
                    st["next_key"] += 1
                    st["active"][idx] = fresh
                    st["writes"][fresh] = 0
        filled = gen.fill_in_op(
            {"f": "txn", "value": value}, ctx
        )
        if filled == gen.PENDING:
            return (gen.PENDING, self)
        return (filled, TxnGenerator(self.mode, self.opts, st))

    def update(self, test, ctx, event):
        return self
