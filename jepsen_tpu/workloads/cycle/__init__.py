"""Transactional-cycle workloads: generators + checkers over the
Elle-equivalent analysis plane (jepsen_tpu.elle).

(reference: jepsen/src/jepsen/tests/cycle.clj — the generic adapter —
plus cycle/append.clj and cycle/wr.clj)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ... import generator as gen
from ...checker import Checker


def _fmt_anomaly_item(item: Any) -> str:
    """One anomaly instance as readable text: witness cycles render as
    step chains, everything else as indented JSON."""
    import json

    if isinstance(item, dict) and "steps" in item:
        lines = ["Cycle:"]
        for s in item["steps"]:
            rels = ",".join(s.get("rels", []))
            lines.append(f"  {s.get('from')} -[{rels}]-> {s.get('to')}")
        return "\n".join(lines)
    return json.dumps(item, indent=2, default=repr)


def _esc(s: Any) -> str:
    import html

    return html.escape(str(s), quote=True)


#: edge colors per dependency type (write-write, write-read, read-write,
#: process, realtime) — matching the conventional elle rendering
_REL_COLORS = {
    "ww": "#1f6feb", "wr": "#2da44e", "rw": "#cf222e",
    "process": "#8250df", "realtime": "#bf8700",
}


def cycle_svg(item: dict) -> Optional[str]:
    """One witness cycle as a standalone SVG: transactions on a circle,
    directed edges labeled and colored by dependency type — the
    graphviz-style anomaly rendering the reference ecosystem gets from
    Elle's plot-analysis, self-rendered like the rest of this
    framework's graphics (checker/svg.py replaces gnuplot the same
    way)."""
    import math

    steps = item.get("steps") or []
    if not steps:
        return None
    nodes = [s.get("from") for s in steps]
    n = len(nodes)
    R, pad = 150, 120
    cx = cy = R + pad
    size = 2 * (R + pad)
    pos = {}
    for i, node in enumerate(nodes):
        ang = -math.pi / 2 + 2 * math.pi * i / n
        pos[i] = (cx + R * math.cos(ang), cy + R * math.sin(ang))
    # one arrowhead marker per edge color (context-stroke would be
    # neater but isn't supported by Chromium-family viewers)
    colors_used = sorted(
        {
            _REL_COLORS.get((s.get("rels") or [""])[0], "#57606a")
            for s in steps
        }
    )
    markers = "".join(
        f'<marker id="arr{c.lstrip("#")}" viewBox="0 0 10 10" refX="9" '
        'refY="5" markerWidth="7" markerHeight="7" '
        f'orient="auto-start-reverse">'
        f'<path d="M 0 0 L 10 5 L 0 10 z" fill="{c}"/></marker>'
        for c in colors_used
    )
    out = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{size}" '
        f'height="{size}" viewBox="0 0 {size} {size}" '
        'font-family="monospace" font-size="11">',
        f"<defs>{markers}</defs>",
        f'<rect width="{size}" height="{size}" fill="white"/>',
    ]
    node_r = 26
    for i, s in enumerate(steps):
        j = (i + 1) % n
        (x1, y1), (x2, y2) = pos[i], pos[j]
        # shorten the segment so the arrowhead lands on the node rim
        dx, dy = x2 - x1, y2 - y1
        d = math.hypot(dx, dy) or 1.0
        x1s, y1s = x1 + dx / d * node_r, y1 + dy / d * node_r
        x2s, y2s = x2 - dx / d * (node_r + 4), y2 - dy / d * (node_r + 4)
        rels = s.get("rels") or []
        color = _REL_COLORS.get(rels[0] if rels else "", "#57606a")
        out.append(
            f'<line x1="{x1s:.1f}" y1="{y1s:.1f}" x2="{x2s:.1f}" '
            f'y2="{y2s:.1f}" stroke="{color}" stroke-width="1.6" '
            f'marker-end="url(#arr{color.lstrip("#")})"/>'
        )
        mx, my = (x1s + x2s) / 2, (y1s + y2s) / 2
        out.append(
            f'<text x="{mx:.1f}" y="{my - 4:.1f}" fill="{color}" '
            f'text-anchor="middle">{_esc(",".join(rels))}</text>'
        )
    for i, node in enumerate(nodes):
        x, y = pos[i]
        label = str(node)
        short = label if len(label) <= 24 else label[:21] + "…"
        out.append(
            f'<g><circle cx="{x:.1f}" cy="{y:.1f}" r="{node_r}" '
            'fill="#f6f8fa" stroke="#57606a"/>'
            f"<title>{_esc(label)}</title>"
            f'<text x="{x:.1f}" y="{y + 4:.1f}" text-anchor="middle">'
            f"{_esc(short)}</text></g>"
        )
    out.append("</svg>")
    return "\n".join(out)


def write_anomaly_artifacts(test, result: dict, opts=None) -> None:
    """Persist one explanation file per anomaly type under
    ``<store>/<test>/<time>/elle/`` so the web UI's directory browser
    surfaces them next to results.json — the artifact the reference
    gets from Elle's :directory option (consumed at
    jepsen/src/jepsen/tests/cycle.clj:10-16).  Only runs when the test
    has a real store identity; adds the written paths to the result as
    "anomaly-files"."""
    if not (test and test.get("name") and test.get("start-time")):
        return
    anomalies = {
        **(result.get("anomalies") or {}),
        **(result.get("also-anomalies") or {}),
    }
    if not anomalies:
        return
    from ... import store as store_mod

    paths: List[str] = []
    try:
        for name, items in sorted(anomalies.items()):
            p = store_mod.path_(
                test,
                *(opts or {}).get("subdirectory", []),
                "elle",
                f"{name}.txt",
            )
            with open(p, "w") as f:
                f.write(f"{name}: {len(items)} instance(s)\n\n")
                for i, item in enumerate(items):
                    f.write(f"--- instance {i} ---\n")
                    f.write(_fmt_anomaly_item(item))
                    f.write("\n\n")
            paths.append(p)
            # first witness cycle per type also renders as an SVG next
            # to the text file (reference ecosystem: elle plot-analysis)
            for item in items:
                svg = cycle_svg(item) if isinstance(item, dict) else None
                if svg:
                    sp = store_mod.path_(
                        test,
                        *(opts or {}).get("subdirectory", []),
                        "elle",
                        f"{name}.svg",
                    )
                    with open(sp, "w") as f:
                        f.write(svg)
                    paths.append(sp)
                    break
        result["anomaly-files"] = paths
    except Exception as e:  # noqa: BLE001 — never mask the verdict
        result["anomaly-files-error"] = repr(e)


class _ElleChecker(Checker):
    def __init__(self, workload: str, opts: Optional[dict]):
        self.workload = workload
        self.opts = dict(opts or {})

    def check(self, test, history, opts=None):
        from ... import elle

        out = elle.check(
            {**self.opts, "workload": self.workload}, history
        )
        write_anomaly_artifacts(test, out, opts)
        return out


def checker(workload: str, opts: Optional[dict] = None) -> Checker:
    """A checker running the elle analysis for a txn workload.
    (reference: cycle.clj:9-16)"""
    return _ElleChecker(workload, opts)


class TxnGenerator(gen.Generator):
    """Random micro-op transactions over a rotating pool of keys.

    Mirrors elle's wr-txns/append-txns behavior: ``key-count`` keys are
    active at once; each key takes at most ``max-writes-per-key`` writes
    before being retired for a fresh one; txns have min..max mops, each
    a read or write/append of an active key with globally-unique written
    values per key.
    """

    def __init__(self, mode: str, opts: dict, state: Optional[dict] = None):
        self.mode = mode  # "append" | "wr"
        self.opts = opts
        if state is None:
            kc = opts.get("key-count", 2)
            state = {
                "next_key": kc,
                "active": list(range(kc)),
                "writes": {k: 0 for k in range(kc)},
                "counter": 0,
            }
        self.state = state

    def op(self, test, ctx):
        o = self.opts
        min_len = o.get("min-txn-length", 1)
        max_len = o.get("max-txn-length", 4)
        max_wpk = o.get("max-writes-per-key", 32)
        st = {
            "next_key": self.state["next_key"],
            "active": list(self.state["active"]),
            "writes": dict(self.state["writes"]),
            "counter": self.state["counter"],
        }
        n = min_len + gen.rng.randrange(max_len - min_len + 1)
        value: List[list] = []
        for _ in range(n):
            k = st["active"][gen.rng.randrange(len(st["active"]))]
            if gen.rng.random() < 0.5:
                value.append(["r", k, None])
            else:
                st["counter"] += 1
                f = "append" if self.mode == "append" else "w"
                value.append([f, k, st["counter"]])
                st["writes"][k] += 1
                if st["writes"][k] >= max_wpk:
                    idx = st["active"].index(k)
                    fresh = st["next_key"]
                    st["next_key"] += 1
                    st["active"][idx] = fresh
                    st["writes"][fresh] = 0
        filled = gen.fill_in_op(
            {"f": "txn", "value": value}, ctx
        )
        if filled == gen.PENDING:
            return (gen.PENDING, self)
        return (filled, TxnGenerator(self.mode, self.opts, st))

    def update(self, test, ctx, event):
        return self
