"""Transactional-cycle workloads: generators + checkers over the
Elle-equivalent analysis plane (jepsen_tpu.elle).

(reference: jepsen/src/jepsen/tests/cycle.clj — the generic adapter —
plus cycle/append.clj and cycle/wr.clj)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ... import generator as gen
from ...checker import Checker


class _ElleChecker(Checker):
    def __init__(self, workload: str, opts: Optional[dict]):
        self.workload = workload
        self.opts = dict(opts or {})

    def check(self, test, history, opts=None):
        from ... import elle

        return elle.check(
            {**self.opts, "workload": self.workload}, history
        )


def checker(workload: str, opts: Optional[dict] = None) -> Checker:
    """A checker running the elle analysis for a txn workload.
    (reference: cycle.clj:9-16)"""
    return _ElleChecker(workload, opts)


class TxnGenerator(gen.Generator):
    """Random micro-op transactions over a rotating pool of keys.

    Mirrors elle's wr-txns/append-txns behavior: ``key-count`` keys are
    active at once; each key takes at most ``max-writes-per-key`` writes
    before being retired for a fresh one; txns have min..max mops, each
    a read or write/append of an active key with globally-unique written
    values per key.
    """

    def __init__(self, mode: str, opts: dict, state: Optional[dict] = None):
        self.mode = mode  # "append" | "wr"
        self.opts = opts
        if state is None:
            kc = opts.get("key-count", 2)
            state = {
                "next_key": kc,
                "active": list(range(kc)),
                "writes": {k: 0 for k in range(kc)},
                "counter": 0,
            }
        self.state = state

    def op(self, test, ctx):
        o = self.opts
        min_len = o.get("min-txn-length", 1)
        max_len = o.get("max-txn-length", 4)
        max_wpk = o.get("max-writes-per-key", 32)
        st = {
            "next_key": self.state["next_key"],
            "active": list(self.state["active"]),
            "writes": dict(self.state["writes"]),
            "counter": self.state["counter"],
        }
        n = min_len + gen.rng.randrange(max_len - min_len + 1)
        value: List[list] = []
        for _ in range(n):
            k = st["active"][gen.rng.randrange(len(st["active"]))]
            if gen.rng.random() < 0.5:
                value.append(["r", k, None])
            else:
                st["counter"] += 1
                f = "append" if self.mode == "append" else "w"
                value.append([f, k, st["counter"]])
                st["writes"][k] += 1
                if st["writes"][k] >= max_wpk:
                    idx = st["active"].index(k)
                    fresh = st["next_key"]
                    st["next_key"] += 1
                    st["active"][idx] = fresh
                    st["writes"][fresh] = 0
        filled = gen.fill_in_op(
            {"f": "txn", "value": value}, ctx
        )
        if filled == gen.PENDING:
            return (gen.PENDING, self)
        return (filled, TxnGenerator(self.mode, self.opts, st))

    def update(self, test, ctx, event):
        return self
