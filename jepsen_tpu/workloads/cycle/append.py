"""List-append txn workload: clients take ops like

    {"type": "invoke", "f": "txn",
     "value": [["r", 3, None], ["append", 3, 2], ["r", 3, None]]}

and complete them with observed lists filled in.
(reference: jepsen/src/jepsen/tests/cycle/append.clj)
"""

from __future__ import annotations

from typing import Optional

from . import TxnGenerator, checker as elle_checker
from ...checker import Checker


def gen(opts: Optional[dict] = None):
    """(reference: append.clj:23-26)"""
    return TxnGenerator("append", opts or {})


def checker(opts: Optional[dict] = None) -> Checker:
    """Defaults to the reference's {:anomalies [:G1 :G2]} when the opts
    carry no anomaly/model selection.  (reference: append.clj:11-21)"""
    opts = dict(opts or {})
    if "anomalies" not in opts and "consistency-models" not in opts:
        opts["anomalies"] = ["G1", "G2"]
    return elle_checker("list-append", opts)


def test(opts: Optional[dict] = None) -> dict:
    """Partial test: generator + checker; bring a client.  Options:
    key-count, min-txn-length, max-txn-length, max-writes-per-key,
    anomalies, consistency-models.  (reference: append.clj:28-55)"""
    opts = opts or {}
    return {"generator": gen(opts), "checker": checker(opts)}
