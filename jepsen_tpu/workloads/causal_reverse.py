"""Strict-serializability anomaly: T1 < T2, but T2 visible without T1.

Concurrent blind inserts over keys plus multi-key reads; replaying the
history tracks which writes completed before each write began, so any
read observing w_i but missing some w_j < w_i is a violation.
(reference: jepsen/src/jepsen/tests/causal_reverse.clj)
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Set

from .. import checker as checker_mod
from .. import generator as gen
from .. import independent
from ..checker import Checker
from ..history import INVOKE, OK


def graph(history) -> Dict[Any, Set[Any]]:
    """First-order write-precedence: value -> set of writes completed
    before its invocation.  (reference: causal_reverse.clj:21-47)"""
    completed: Set[Any] = set()
    expected: Dict[Any, Set[Any]] = {}
    for op in history:
        if op.f != "write":
            continue
        if op.type == INVOKE:
            expected[op.value] = set(completed)
        elif op.type == OK:
            completed.add(op.value)
    return expected


def errors(history, expected: Dict[Any, Set[Any]]) -> list:
    """Reads that observe a write but miss an earlier acknowledged one.
    (reference: causal_reverse.clj:49-72)"""
    errs = []
    for op in history:
        if op.type != OK or op.f != "read":
            continue
        seen = set(op.value or [])
        our_expected: Set[Any] = set()
        for v in seen:
            our_expected |= expected.get(v, set())
        missing = our_expected - seen
        if missing:
            err = op.copy(value=None)
            errs.append(
                {
                    "op": err.to_dict(),
                    "missing": sorted(missing, key=str),
                    "expected-count": len(our_expected),
                }
            )
    return errs


class _CausalReverseChecker(Checker):
    def check(self, test, history, opts=None):
        expected = graph(history)
        errs = errors(history, expected)
        return {"valid?": not errs, "errors": errs}


def checker() -> Checker:
    """(reference: causal_reverse.clj:74-84)"""
    return _CausalReverseChecker()


def workload(opts: Optional[dict] = None) -> dict:
    """Options: ``nodes`` (only the count matters), ``per-key-limit``
    (default 500).  (reference: causal_reverse.clj:89-114)"""
    opts = opts or {}
    n = len(opts.get("nodes", ["n1"]))
    reads = {"f": "read"}

    def fgen(k):
        counter = iter(range(10**12))

        def writes():
            return {"f": "write", "value": next(counter)}

        return gen.limit(
            opts.get("per-key-limit", 500),
            gen.stagger(1 / 100, gen.mix([reads, writes])),
        )

    # no perf checker here: build_test composes one into every suite
    # run; a second instance would race the same SVG paths
    return {
        "checker": checker_mod.compose(
            {
                "sequential": independent.checker(checker()),
            }
        ),
        "generator": independent.concurrent_generator(
            n, list(range(10_000)), fgen
        ),
    }
