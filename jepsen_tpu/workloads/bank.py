"""Bank-transfer workload: transfers between accounts must preserve the
total balance (a snapshot-isolation probe).

Test map options: ``accounts`` (ids), ``total-amount``, ``max-transfer``.
(reference: jepsen/src/jepsen/tests/bank.clj)
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .. import checker as checker_mod
from .. import generator as gen
from ..checker import Checker
from ..history import History, OK


def read(test, ctx) -> dict:
    """(reference: bank.clj:20-23)"""
    return {"type": "invoke", "f": "read"}


def transfer(test, ctx) -> dict:
    """A random amount between two random accounts.
    (reference: bank.clj:25-33)"""
    accounts = test["accounts"]
    return {
        "type": "invoke",
        "f": "transfer",
        "value": {
            "from": accounts[gen.rng.randrange(len(accounts))],
            "to": accounts[gen.rng.randrange(len(accounts))],
            "amount": 1 + gen.rng.randrange(test["max-transfer"]),
        },
    }


#: Transfers only between different accounts.  (reference: bank.clj:35-39)
diff_transfer = gen.filter(
    lambda op: op["value"]["from"] != op["value"]["to"], transfer
)


def generator():
    """A mixture of reads and transfers.  (reference: bank.clj:41-44)"""
    return gen.mix([diff_transfer, read])


def err_badness(test: dict, err: dict) -> float:
    """How egregious is a bank error?  (reference: bank.clj:46-55)"""
    t = err["type"]
    if t == "unexpected-key":
        return len(err["unexpected"])
    if t == "nil-balance":
        return len(err["nils"])
    if t == "wrong-total":
        return abs((err["total"] - test["total-amount"]) / test["total-amount"])
    if t == "negative-value":
        return -sum(err["negative"])
    return 0


def check_op(accts: set, total: int, negative_balances: bool, op) -> Optional[dict]:
    """Errors in one read's balance map.  (reference: bank.clj:57-82)"""
    value = op.value or {}
    ks = list(value.keys())
    balances = list(value.values())
    unexpected = [k for k in ks if k not in accts]
    if unexpected:
        return {"type": "unexpected-key", "unexpected": unexpected, "op": op}
    nils = {k: v for k, v in value.items() if v is None}
    if nils:
        return {"type": "nil-balance", "nils": nils, "op": op}
    s = sum(balances)
    if s != total:
        return {"type": "wrong-total", "total": s, "op": op}
    negative = [b for b in balances if b < 0]
    if not negative_balances and negative:
        return {"type": "negative-value", "negative": negative, "op": op}
    return None


class _BankChecker(Checker):
    def __init__(self, checker_opts: dict):
        self.negative_balances = bool(checker_opts.get("negative-balances?"))

    def check(self, test, history, opts=None):
        accts = set(test["accounts"])
        total = test["total-amount"]
        reads = [op for op in history if op.type == OK and op.f == "read"]
        errors: Dict[str, list] = {}
        for op in reads:
            err = check_op(accts, total, self.negative_balances, op)
            if err is not None:
                errors.setdefault(err["type"], []).append(err)
        all_errs = [e for errs in errors.values() for e in errs]
        first_error = (
            min(all_errs, key=lambda e: e["op"].index) if all_errs else None
        )
        summary = {}
        for etype, errs in errors.items():
            entry = {
                "count": len(errs),
                "first": errs[0],
                "worst": max(errs, key=lambda e: err_badness(test, e)),
                "last": errs[-1],
            }
            if etype == "wrong-total":
                entry["lowest"] = min(errs, key=lambda e: e["total"])
                entry["highest"] = max(errs, key=lambda e: e["total"])
            summary[etype] = entry
        return {
            "valid?": not all_errs,
            "read-count": len(reads),
            "error-count": len(all_errs),
            "first-error": first_error,
            "errors": summary,
        }


def checker(checker_opts: Optional[dict] = None) -> Checker:
    """All reads sum to total-amount; balances non-negative unless
    negative-balances?.  (reference: bank.clj:84-121)"""
    return _BankChecker(checker_opts or {})


class _BankPlotter(Checker):
    def check(self, test, history, opts=None):
        from ..checker import perf

        reads = [op for op in history if op.type == OK and op.f == "read"]
        if not reads:
            return {"valid?": True}
        nodes = test.get("nodes", [])
        series: Dict[Any, list] = {}
        for op in reads:
            node = (
                nodes[op.process % len(nodes)]
                if nodes and isinstance(op.process, int)
                else op.process
            )
            totals = [v for v in (op.value or {}).values() if v is not None]
            series.setdefault(node, []).append(
                (op.time / 1e9, sum(totals))
            )
        perf.scatter_plot(
            test,
            series,
            path_components=list((opts or {}).get("subdirectory", []))
            + ["bank.svg"],
            title=f"{test.get('name', 'test')} bank",
            ylabel="Total of all accounts",
            history=history,
        )
        return {"valid?": True}


def plotter() -> Checker:
    """Balances-over-time scatter plot, one series per node.
    (reference: bank.clj:151-177; SVG instead of gnuplot)"""
    return _BankPlotter()


def test(opts: Optional[dict] = None) -> dict:
    """A partial test: default accounts/amounts + generator + checker;
    ``accounts``/``total-amount``/``max-transfer`` opts override the
    defaults.  (reference: bank.clj:179-192)"""
    opts = opts or {}
    return {
        "max-transfer": opts.get("max-transfer", 5),
        "total-amount": opts.get("total-amount", 100),
        "accounts": list(opts.get("accounts", range(8))),
        "checker": checker_mod.compose(
            {"SI": checker(opts), "plot": plotter()}
        ),
        "generator": generator(),
    }
