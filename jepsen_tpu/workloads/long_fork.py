"""Long-fork anomaly detection (parallel snapshot isolation).

Concurrent write transactions observed in conflicting orders:

    T1: (write x 1)      T3: (read x nil) (read y 1)
    T2: (write y 1)      T4: (read x 1)   (read y nil)

T3 implies T2 < T1 but T4 implies T1 < T2.  Each key is written exactly
once, so reads of a key group must admit a total order where identical
values are contiguous; mutually incomparable reads are a fork.
(reference: jepsen/src/jepsen/tests/long_fork.clj:1-90 — the algorithm
documentation there derives the dominance-comparison approach used here.)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .. import generator as gen
from ..checker import Checker, UNKNOWN
from ..history import History, INVOKE, OK
from ..txn import R, W


class IllegalHistory(Exception):
    def __init__(self, info: dict):
        super().__init__(str(info))
        self.info = info


def group_for(n: int, k: int) -> range:
    """The n keys in k's group (lower inclusive, upper exclusive).
    (reference: long_fork.clj:98-105)"""
    lower = k - (k % n)
    return range(lower, lower + n)


def read_txn_for(n: int, k: int) -> List[list]:
    """A txn reading k's whole group, in shuffled order.
    (reference: long_fork.clj:107-113)"""
    ks = list(group_for(n, k))
    gen.rng.shuffle(ks)
    return [[R, k2, None] for k2 in ks]


class _LongForkGen(gen.Generator):
    """Single inserts followed by group reads, mixed with reads of other
    in-flight groups.  (reference: long_fork.clj:115-160)"""

    def __init__(self, n: int, next_key: int, workers: Dict[Any, Any]):
        self.n = n
        self.next_key = next_key
        self.workers = workers  # worker thread -> last written key | None

    def op(self, test, ctx):
        process = gen.some_free_process(ctx)
        worker = gen.process_to_thread(ctx, process)
        if worker is None:
            return (gen.PENDING, self)
        k = self.workers.get(worker)
        if k is not None:
            # We wrote a key: follow with a read of its group.
            o = gen.fill_in_op(
                {"process": process, "f": "read", "value": read_txn_for(self.n, k)},
                ctx,
            )
            return (o, _LongForkGen(self.n, self.next_key, {**self.workers, worker: None}))
        active = [v for v in self.workers.values() if v is not None]
        if active and gen.rng.random() < 0.5:
            # Read some other active group.
            k2 = active[gen.rng.randrange(len(active))]
            o = gen.fill_in_op(
                {"process": process, "f": "read", "value": read_txn_for(self.n, k2)},
                ctx,
            )
            return (o, self)
        # Write a fresh key.
        o = gen.fill_in_op(
            {"process": process, "f": "write", "value": [[W, self.next_key, 1]]},
            ctx,
        )
        return (
            o,
            _LongForkGen(
                self.n, self.next_key + 1, {**self.workers, worker: self.next_key}
            ),
        )

    def update(self, test, ctx, event):
        return self


def generator(n: int) -> gen.Generator:
    """(reference: long_fork.clj:162-166)"""
    return _LongForkGen(n, 0, {})


def read_compare(a: Dict[Any, Any], b: Dict[Any, Any]) -> Optional[int]:
    """-1 if a dominates, 0 if equal, 1 if b dominates, None if
    incomparable.  (reference: long_fork.clj:168-206)"""
    if len(a) != len(b):
        raise IllegalHistory(
            {"reads": [a, b], "msg": "reads queried different keys"}
        )
    res = 0
    for k, va in a.items():
        if k not in b:
            raise IllegalHistory(
                {"reads": [a, b], "key": k, "msg": "reads queried different keys"}
            )
        vb = b[k]
        if va == vb:
            continue
        if vb is None:
            if res > 0:
                return None
            res = -1
        elif va is None:
            if res < 0:
                return None
            res = 1
        else:
            raise IllegalHistory(
                {
                    "key": k,
                    "reads": [a, b],
                    "msg": "distinct non-nil values for one key; "
                    "this checker assumes one write per key",
                }
            )
    return res


def read_op_value_map(op) -> Dict[Any, Any]:
    """A read op's txn as {key: value}.  (reference: long_fork.clj:208-217)"""
    return {k: v for _, k, v in (op.value or [])}


def find_forks(ops: List[Any]) -> List[Tuple[Any, Any]]:
    """Pairs of mutually incomparable reads.
    (reference: long_fork.clj:219-234)"""
    forks = []
    for i in range(len(ops)):
        ma = read_op_value_map(ops[i])
        for j in range(i + 1, len(ops)):
            if read_compare(ma, read_op_value_map(ops[j])) is None:
                forks.append((ops[i], ops[j]))
    return forks


def is_read_txn(txn) -> bool:
    return all(m[0] == R for m in (txn or []))


def is_write_txn(txn) -> bool:
    return bool(txn) and len(txn) == 1 and txn[0][0] != R


def op_read_keys(op) -> frozenset:
    return frozenset(m[1] for m in (op.value or []))


def groups(n: int, read_ops: List[Any]) -> List[List[Any]]:
    """Partition reads by key-group; throw if a group is mis-sized.
    (reference: long_fork.clj:240-255)"""
    by_group: Dict[frozenset, List[Any]] = {}
    for op in read_ops:
        by_group.setdefault(op_read_keys(op), []).append(op)
    out = []
    for group, ops in by_group.items():
        if len(group) != n:
            raise IllegalHistory(
                {
                    "op": ops[0],
                    "msg": f"every read should observe exactly {n} keys, "
                    f"but this read observed {len(group)}: {sorted(group)}",
                }
            )
        out.append(ops)
    return out


class _LongForkChecker(Checker):
    def __init__(self, n: int):
        self.n = n

    def check(self, test, history, opts=None):
        reads = [
            op
            for op in history
            if op.type == OK and is_read_txn(op.value)
        ]
        early = [
            op.value
            for op in reads
            if not any(m[2] is not None for m in op.value)
        ]
        late = [
            op.value
            for op in reads
            if all(m[2] is not None for m in op.value)
        ]
        out = {
            "reads-count": len(reads),
            "early-read-count": len(early),
            "late-read-count": len(late),
        }
        try:
            # Multiple writes to one key make the analysis unsound.
            seen = set()
            for op in history:
                if op.type == INVOKE and is_write_txn(op.value):
                    k = op.value[0][1]
                    if k in seen:
                        out.update(
                            {"valid?": UNKNOWN, "error": ["multiple-writes", k]}
                        )
                        return out
                    seen.add(k)
            forks = []
            for group_ops in groups(self.n, reads):
                forks.extend(find_forks(group_ops))
            if forks:
                out.update(
                    {
                        "valid?": False,
                        "forks": [
                            [a.to_dict(), b.to_dict()] for a, b in forks
                        ],
                    }
                )
            else:
                out["valid?"] = True
        except IllegalHistory as e:
            out.update({"valid?": UNKNOWN, "error": e.info})
        return out


def checker(n: int) -> Checker:
    """No key written twice; no mutually incomparable group reads.
    (reference: long_fork.clj:283-300)"""
    return _LongForkChecker(n)


def workload(n: int = 2) -> dict:
    """(reference: long_fork.clj:302-308)"""
    return {"checker": checker(n), "generator": generator(n)}
