"""Linearizability over a set of independent CAS registers — the flagship
workload of the TPU analysis plane.

Clients understand three functions over ``[k, v]`` tuple values:

    {"type": "invoke", "f": "write", "value": [k, v]}
    {"type": "invoke", "f": "read",  "value": [k, None]}
    {"type": "invoke", "f": "cas",   "value": [k, [v, v2]]}

(reference: jepsen/src/jepsen/tests/linearizable_register.clj)

Two checker lifts are offered: the classic per-key lift
(independent.checker over checker.linearizable, which itself dispatches to
the TPU kernel per history) and — by default — the batched lift
(independent.batched_linearizable), which checks the entire keyspace in
one vmapped device dispatch.
"""

from __future__ import annotations

from typing import Optional

from .. import checker as checker_mod
from .. import generator as gen
from .. import independent
from .. import models
from ..checker import timeline


def w(test, ctx):
    return {"type": "invoke", "f": "write", "value": gen.rng.randrange(5)}


def r(test, ctx):
    return {"type": "invoke", "f": "read"}


def cas(test, ctx):
    return {
        "type": "invoke",
        "f": "cas",
        "value": [gen.rng.randrange(5), gen.rng.randrange(5)],
    }


def _steer_group_size(threads: int, nodes: int, max_c: int):
    """(group, threads): the largest per-key thread-group size ≤
    min(2·nodes, the dense kernel's slot envelope) that divides the
    worker count — shrinking the worker count itself when no
    non-trivial divisor fits (prime concurrency), because degrading to
    1-thread groups would make every per-key history sequential and the
    linearizability check vacuous.

    This is the dense-envelope steering: the reference keeps per-key
    histories tractable for knossos by bounding threads-per-key and the
    per-key process budget (linearizable_register.clj:40-52); here the
    same levers keep per-key peak open-op slots ≤ dense.MAX_C so the
    whole keyspace rides the overflow-free dense subset-automaton
    kernel instead of drifting onto the capacity-bound frontier
    kernel."""
    cap = max(1, min(2 * nodes, max_c))
    for g in range(min(cap, threads), 1, -1):
        if threads % g == 0:
            return g, threads
    g = min(cap, threads)
    return g, g * max(1, threads // g)


def test(opts: Optional[dict] = None) -> dict:
    """A partial test (generator, model, checker); bring a client.
    Options: ``nodes``, ``model``, ``per-key-limit``, ``process-limit``,
    ``concurrency`` (int or "3n"-style), ``batched?`` (default True —
    one device dispatch for all keys), ``steer?`` (default True).

    With ``steer?`` the workload sizes its per-key thread groups and
    the default process budget to the dense kernel's envelope
    (ops.dense.MAX_C): every retired (crashed) process can leave one
    permanently-open op, and group size bounds concurrently-live ops,
    so process-limit ≤ MAX_C guarantees per-key open-op slots ≤ MAX_C —
    the batch then reports kernel=dense in wgl.batch_stats regardless
    of "3n"-scale total concurrency.  The TPU-native analogue of the
    reference's per-key tractability design
    (linearizable_register.clj:40-52)."""
    opts = opts or {}
    n = len(opts.get("nodes", ["n1"]))
    model = opts.get("model", models.cas_register())

    if opts.get("batched?", True):
        lin = independent.batched_linearizable(model)
    else:
        lin = independent.checker(checker_mod.linearizable(model))

    conc = opts.get("concurrency")
    if conc is None:
        threads = 2 * n
    else:
        from ..cli import parse_concurrency

        threads = parse_concurrency(str(conc), n)
    if opts.get("steer?", True):
        from ..ops import dense as dense_mod

        group, threads = _steer_group_size(threads, n, dense_mod.MAX_C)
        default_process_limit = dense_mod.MAX_C
    else:
        group = min(threads, 2 * n)
        if threads % group:
            raise ValueError(
                f"concurrency {threads} is not a multiple of the "
                f"{group}-thread key groups; pass a multiple of {group} "
                "or leave steer? on"
            )
        default_process_limit = 20

    def fgen(k):
        # cas? False for systems exposing only get/set (e.g. raftis)
        mixed = [w, cas, cas] if opts.get("cas?", True) else [w]
        # half the group reads, half mutates (the reference reserves n
        # of its 2n-thread groups for reads); a 1-thread group mixes
        # reads in instead of starving mutations
        readers = group // 2
        if readers:
            g = gen.reserve(readers, r, gen.mix(mixed))
        else:
            g = gen.mix(mixed + [r])
        pkl = opts.get("per-key-limit")
        if pkl:
            # Jitter the limit so keys drift off Significant Event
            # Boundaries over time.  (reference: :45-49)
            g = gen.limit(int((0.9 + gen.rng.random() * 0.1) * pkl) or 1, g)
        return gen.process_limit(
            opts.get("process-limit", default_process_limit), g
        )

    return {
        "checker": checker_mod.compose(
            {"linearizable": lin, "timeline": timeline.html()}
        ),
        "generator": independent.concurrent_generator(
            group, list(range(100_000)), fgen
        ),
        # concurrent-generator runs each key on a `group`-thread group;
        # the test needs at least that many workers (reference:
        # linearizable_register.clj:40-43 via independent.clj:103-121)
        "concurrency": threads,
        "steered-group-size": group,
    }
