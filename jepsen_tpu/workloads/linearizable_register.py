"""Linearizability over a set of independent CAS registers — the flagship
workload of the TPU analysis plane.

Clients understand three functions over ``[k, v]`` tuple values:

    {"type": "invoke", "f": "write", "value": [k, v]}
    {"type": "invoke", "f": "read",  "value": [k, None]}
    {"type": "invoke", "f": "cas",   "value": [k, [v, v2]]}

(reference: jepsen/src/jepsen/tests/linearizable_register.clj)

Two checker lifts are offered: the classic per-key lift
(independent.checker over checker.linearizable, which itself dispatches to
the TPU kernel per history) and — by default — the batched lift
(independent.batched_linearizable), which checks the entire keyspace in
one vmapped device dispatch.
"""

from __future__ import annotations

from typing import Optional

from .. import checker as checker_mod
from .. import generator as gen
from .. import independent
from .. import models
from ..checker import timeline


def w(test, ctx):
    return {"type": "invoke", "f": "write", "value": gen.rng.randrange(5)}


def r(test, ctx):
    return {"type": "invoke", "f": "read"}


def cas(test, ctx):
    return {
        "type": "invoke",
        "f": "cas",
        "value": [gen.rng.randrange(5), gen.rng.randrange(5)],
    }


def test(opts: Optional[dict] = None) -> dict:
    """A partial test (generator, model, checker); bring a client.
    Options: ``nodes``, ``model``, ``per-key-limit``, ``process-limit``
    (default 20), ``batched?`` (default True — one device dispatch for
    all keys).  (reference: linearizable_register.clj:22-53)"""
    opts = opts or {}
    n = len(opts.get("nodes", ["n1"]))
    model = opts.get("model", models.cas_register())

    if opts.get("batched?", True):
        lin = independent.batched_linearizable(model)
    else:
        lin = independent.checker(checker_mod.linearizable(model))

    def fgen(k):
        # cas? False for systems exposing only get/set (e.g. raftis)
        mixed = [w, cas, cas] if opts.get("cas?", True) else [w]
        g = gen.reserve(n, r, gen.mix(mixed))
        pkl = opts.get("per-key-limit")
        if pkl:
            # Jitter the limit so keys drift off Significant Event
            # Boundaries over time.  (reference: :45-49)
            g = gen.limit(int((0.9 + gen.rng.random() * 0.1) * pkl) or 1, g)
        return gen.process_limit(opts.get("process-limit", 20), g)

    return {
        "checker": checker_mod.compose(
            {"linearizable": lin, "timeline": timeline.html()}
        ),
        "generator": independent.concurrent_generator(
            2 * n, list(range(100_000)), fgen
        ),
        # concurrent-generator runs each key on a 2n-thread group, so
        # the test needs at least that many workers (reference:
        # linearizable_register.clj:40-43 via independent.clj:103-121)
        "concurrency": 2 * n,
    }
