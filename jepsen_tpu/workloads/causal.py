"""Causal-consistency register workload.

A causal order of five ops (read-init, write 1, read, write 2, read) is
issued per key; all must appear to execute in issue order, linked by
``position``/``link`` markers the client fills in.
(reference: jepsen/src/jepsen/tests/causal.clj)
"""

from __future__ import annotations

from typing import Optional

from .. import generator as gen
from .. import independent
from ..checker import Checker
from ..history import OK
from ..models import Model, inconsistent, Inconsistent


class CausalRegister(Model):
    """Register whose ops carry :position/:link causal markers.
    (reference: causal.clj:33-82)"""

    def __init__(self, value=0, counter=0, last_pos=None):
        self.value = value
        self.counter = counter
        self.last_pos = last_pos

    def step(self, op):
        c = self.counter + 1
        v = op.value
        pos = op.get("position")
        link = op.get("link")
        if link != "init" and link != self.last_pos:
            return inconsistent(
                f"Cannot link {link!r} to last-seen position {self.last_pos!r}"
            )
        if op.f == "write":
            if v == c:
                return CausalRegister(v, c, pos)
            return inconsistent(
                f"expected value {c} attempting to write {v} instead"
            )
        if op.f == "read-init":
            if self.counter == 0 and v not in (0, None):
                return inconsistent(f"expected init value 0, read {v}")
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return inconsistent(f"can't read {v} from register {self.value}")
        if op.f == "read":
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return inconsistent(f"can't read {v} from register {self.value}")
        return inconsistent(f"unknown f {op.f!r}")

    def __repr__(self):
        return repr(self.value)


def causal_register() -> CausalRegister:
    return CausalRegister(0, 0, None)


class _CausalChecker(Checker):
    def __init__(self, model: Model):
        self.model = model

    def check(self, test, history, opts=None):
        state = self.model
        for op in history:
            if op.type != OK:
                continue
            state = state.step(op)
            if isinstance(state, Inconsistent):
                return {"valid?": False, "error": state.msg}
        return {"valid?": True, "model": repr(state)}


def check(model: Model) -> Checker:
    """Fold the causal model over ok ops.  (reference: causal.clj:88-110)"""
    return _CausalChecker(model)


def r(test, ctx):
    return {"type": "invoke", "f": "read"}


def ri(test, ctx):
    return {"type": "invoke", "f": "read-init"}


def cw1(test, ctx):
    return {"type": "invoke", "f": "write", "value": 1}


def cw2(test, ctx):
    return {"type": "invoke", "f": "write", "value": 2}


def test(opts: Optional[dict] = None) -> dict:
    """(reference: causal.clj:113-126)"""
    opts = opts or {}
    return {
        "checker": independent.checker(check(causal_register())),
        "generator": gen.time_limit(
            opts.get("time-limit", 60),
            gen.nemesis(
                gen.cycle(
                    [
                        gen.sleep(10),
                        {"type": "info", "f": "start"},
                        gen.sleep(10),
                        {"type": "info", "f": "stop"},
                    ]
                ),
                gen.stagger(
                    1,
                    independent.concurrent_generator(
                        1,
                        _keys(),
                        lambda k: [ri, cw1, r, cw2, r],
                    ),
                ),
            ),
        ),
    }


def _keys():
    """An unbounded key sequence (materialized lazily by the generator)."""
    return list(range(10_000))
