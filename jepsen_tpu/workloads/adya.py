"""Adya's proscribed weak-consistency phenomena: the G2 (anti-dependency
cycle) probe via paired predicate inserts.

Clients take ``{"f": "insert", "value": [k, [a_id, b_id]]}`` ops (one id
nil per op), read both tables under a predicate, and insert only if both
reads are empty — so at most one of each pair may commit under
serializability.  (reference: jepsen/src/jepsen/tests/adya.clj)
"""

from __future__ import annotations

import itertools
from typing import Any, Dict

from .. import generator as gen
from .. import independent
from ..checker import Checker
from ..history import OK


def g2_gen():
    """Pairs of :insert ops per key: one with a-id, one with b-id;
    ids globally unique.  (reference: adya.clj:12-58)"""
    ids = itertools.count(1)

    def fgen(k):
        return [
            gen.once(
                lambda test, ctx: {
                    "type": "invoke",
                    "f": "insert",
                    "value": [None, next(ids)],
                }
            ),
            gen.once(
                lambda test, ctx: {
                    "type": "invoke",
                    "f": "insert",
                    "value": [next(ids), None],
                }
            ),
        ]

    return independent.concurrent_generator(2, list(range(100_000)), fgen)


class _G2Checker(Checker):
    def check(self, test, history, opts=None):
        # At most one successful insert per key.  Values here are the
        # independent-keyed tuples [k, [a_id, b_id]].
        keys: Dict[Any, int] = {}
        for op in history:
            if op.f != "insert":
                continue
            v = op.value
            if not independent.is_tuple(v):
                continue
            k = v.key
            if op.type == OK:
                keys[k] = keys.get(k, 0) + 1
            else:
                keys.setdefault(k, 0)
        inserted = [k for k, c in keys.items() if c > 0]
        illegal = {k: c for k, c in sorted(keys.items(), key=lambda kv: str(kv[0])) if c > 1}
        return {
            "valid?": not illegal,
            "key-count": len(keys),
            "legal-count": len(inserted) - len(illegal),
            "illegal-count": len(illegal),
            "illegal": illegal,
        }


def g2_checker() -> Checker:
    """(reference: adya.clj:60-87)"""
    return _G2Checker()


def workload(opts=None) -> dict:
    """The paired-insert G2 workload package, shared by every suite that
    wires a predicate-insert client (faunadb g2, cockroach adya).
    (reference: jepsen/src/jepsen/tests/adya.clj:12-87)"""
    return {
        "generator": g2_gen(),
        "checker": g2_checker(),
        "concurrency": 2,
    }
