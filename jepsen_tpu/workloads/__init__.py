"""Reusable workloads: partial test maps bundling a generator + checker
(and sometimes defaults) that DB suites mix into their tests.

Mirrors the reference's jepsen.tests namespace family
(jepsen/src/jepsen/tests.clj and jepsen/src/jepsen/tests/*.clj):
``noop_test`` and the atom fakes live here; each workload gets its own
module (bank, long_fork, causal, causal_reverse, adya,
linearizable_register, cycle/append, cycle/wr).
"""

from __future__ import annotations

from .. import checker as checker_mod
from .. import client as client_mod
from .. import db as db_mod
from .. import nemesis as nemesis_mod


def noop_test() -> dict:
    """Boring test stub; a basis for more complex tests.
    (reference: tests.clj:12-25)"""
    return {
        "name": "noop",
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "db": db_mod.noop(),
        "client": client_mod.noop(),
        "nemesis": nemesis_mod.noop(),
        "generator": None,
        "checker": checker_mod.unbridled_optimism(),
        "store?": False,
    }


def workload(name: str, opts: dict | None = None) -> dict:
    """Look up a workload package by name."""
    opts = opts or {}
    table = _table(opts)
    if name not in table:
        raise KeyError(f"unknown workload {name!r}; known: {sorted(table)}")
    return table[name]()


def names() -> list:
    """Every in-process workload name (for test-all and --help)."""
    return sorted(_table({}))


def _table(opts: dict) -> dict:
    from . import (  # local imports keep startup light
        adya,
        bank,
        causal,
        causal_reverse,
        linearizable_register,
        long_fork,
    )
    from .cycle import append as cycle_append
    from .cycle import wr as cycle_wr

    table = {
        "bank": lambda: bank.test(opts),
        "long-fork": lambda: long_fork.workload(opts.get("group-size", 2)),
        "causal": lambda: causal.test(opts),
        "causal-reverse": lambda: causal_reverse.workload(opts),
        # the paired-insert generator runs 2 threads per key, so the
        # worker count must divide evenly (the reference's
        # concurrent-generator asserts the same, independent.clj);
        # default 1n x 5 nodes = 5 workers would crash
        "adya-g2": lambda: {
            **adya.workload(opts),
            "concurrency": 2 * len(
                opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
            ),
        },
        "linearizable-register": lambda: linearizable_register.test(opts),
        "list-append": lambda: cycle_append.test(opts),
        "rw-register": lambda: cycle_wr.test(opts),
    }
    return table
