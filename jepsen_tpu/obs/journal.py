"""Dispatch journal: one JSONL line per Executor dispatch, size-rotated.

ROADMAP item 3's learned cost model needs a durable per-dispatch
telemetry stream; the in-memory registry histograms die with the
process.  This journal is that stream: the daemon configures a path at
startup, the Executor emits one row per settled chunk, and
``tune.calibrate.journal_rows()`` reads the rows back as cost-table
evidence.

Schema v1 (pinned — ``validate_row`` rejects drift so readers can trust
old files):

    v            schema version (1)
    ts           wall-clock seconds (time.time) at settle
    kernel       engine kernel name ("dense", "elle_screen", ...)
    E, C, F      bucket shape: events, concurrency, frontier cap
    rows         histories in the chunk
    n_devices    mesh size at dispatch
    mesh_shape   mesh axis sizes, list
    window       dispatch-window depth
    compile_s    seconds when this dispatch compiled (cache miss), else 0
    execute_s    seconds when it ran warm (cache hit), else 0
    coalesced    number of runs sharing the dispatch (1 = unshared)
    cache        "hit" | "miss"
    closure_mode closure kernel variant in effect ("" when n/a)
    union        union lowering in effect ("" when n/a)
    calibration  active calibration id ("" when untuned)
    trace_id     comma-joined trace ids of participating runs ("" when untraced)

Rotation: when the current file exceeds ``max_bytes`` the writer
renames it to ``<path>.1`` (replacing any previous ``.1``) and starts
fresh — bounded disk, and readers see at most two files.  The rename
is followed by a directory fsync so a crash right after rotation
cannot lose the directory entry.

The module-level singleton (``configure``/``emit``/``path``) is a
no-op until configured, so library use (tests, in-process engines)
never writes to cwd by accident.

Verdict WAL (``VerdictWAL``, schema below): the service layer's
crash-safe verdict record.  Where the dispatch journal records *cost
evidence*, the WAL records *settled verdicts* — one append-only row
per (request, stream, history index) the engine settles, so a daemon
killed mid-batch can replay everything already decided and re-dispatch
only the unsettled remainder (doc/checker-service.md "Failure modes &
recovery").
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

SCHEMA_VERSION = 1
DEFAULT_MAX_BYTES = 8 * 1024 * 1024
DEFAULT_FILENAME = "dispatch-journal.jsonl"

#: required fields -> acceptable types (schema pin)
_SCHEMA: Dict[str, tuple] = {
    "v": (int,),
    "ts": (int, float),
    "kernel": (str,),
    "E": (int,),
    "C": (int,),
    "F": (int,),
    "rows": (int,),
    "n_devices": (int,),
    "mesh_shape": (list,),
    "window": (int,),
    "compile_s": (int, float),
    "execute_s": (int, float),
    "coalesced": (int,),
    "cache": (str,),
    "closure_mode": (str,),
    "union": (str,),
    "calibration": (str,),
    "trace_id": (str,),
}


def _fsync_dir(path: str) -> None:
    """fsync the directory containing ``path`` so a rename survives a
    crash; best-effort (some filesystems refuse directory fds)."""
    dirname = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def validate_row(row: Any) -> bool:
    """True iff ``row`` matches the pinned v1 schema exactly."""
    if not isinstance(row, dict):
        return False
    if row.get("v") != SCHEMA_VERSION:
        return False
    if set(row) != set(_SCHEMA):
        # extras are drift too: a reader of old files must be able to
        # trust that v1 means exactly these fields
        return False
    for key, types in _SCHEMA.items():
        if not isinstance(row[key], types):
            return False
        if types == (int,) and isinstance(row[key], bool):
            # bool is an int subclass; reject it for int fields
            return False
    if row["cache"] not in ("hit", "miss"):
        return False
    return True


class DispatchJournal:
    """Thread-safe append-only JSONL writer with single-step rotation."""

    def __init__(self, path: str, max_bytes: int = DEFAULT_MAX_BYTES):
        self.path = path
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self.written = 0  #: rows appended by this writer
        self.dropped = 0  #: rows lost to write errors (disk full etc.)

    def emit(self, **fields: Any) -> Optional[Dict[str, Any]]:
        """Append one row; fills ``v``/``ts``, validates, rotates.

        Returns the row dict on success, None when dropped — journal
        failures must never fail a dispatch.
        """
        row = dict(fields)
        row.setdefault("v", SCHEMA_VERSION)
        row.setdefault("ts", time.time())
        if not validate_row(row):
            # the counter is shared with every emitting thread; the
            # write path below already takes the lock, so the reject
            # path must too or increments can be lost
            with self._lock:
                self.dropped += 1
            return None
        line = json.dumps(row, sort_keys=True) + "\n"
        with self._lock:
            try:
                self._rotate_locked()
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line)
                self.written += 1
            except OSError:
                self.dropped += 1
                return None
        return row

    def _rotate_locked(self) -> None:
        try:
            if os.path.getsize(self.path) < self.max_bytes:
                return
        except OSError:
            return  # no file yet
        os.replace(self.path, self.path + ".1")
        # crash consistency: persist the directory entry for the
        # rename before any new-file write can depend on it
        _fsync_dir(self.path)

    def files(self) -> List[str]:
        """Rotated-then-current paths that exist, oldest first."""
        return [p for p in (self.path + ".1", self.path)
                if os.path.exists(p)]


# -- tail-follow (the ONE journal row reader) ------------------------------


def _decode_line(line, validate) -> tuple:
    """One JSONL line → ``(row, why)``: the validated dict or None, and
    ``"ok" | "blank" | "json" | "schema"``.  The single damage-skip
    decision every journal reader shares — :func:`read_rows` (dispatch
    journal → ``tune.calibrate.journal_rows``),
    :func:`read_verdict_rows` (WAL replay), and the live ``/watch``
    tailer (:class:`WalTail`) — so a half-written tail line from a
    crashed daemon is skipped identically everywhere."""
    if isinstance(line, bytes):
        try:
            line = line.decode("utf-8")
        except UnicodeDecodeError:
            return None, "json"
    line = line.strip()
    if not line:
        return None, "blank"
    try:
        row = json.loads(line)
    except ValueError:
        return None, "json"
    if not validate(row):
        return None, "schema"
    return row, "ok"


def follow_rows(paths, validate, *, start: int = 0,
                strict: bool = False) -> Iterator[tuple]:
    """THE journal tail-follow reader: yield ``(offset, row)`` for every
    valid row across ``paths`` in order.  ``offset`` numbers valid rows
    from 0 — damaged lines are skipped and consume no offset, so an
    offset is a stable resume cursor even over a file with torn lines.
    ``start`` skips rows below that offset (the replay half of the
    ``/watch`` ``Last-Event-ID`` contract); ``strict`` raises on the
    first damaged line instead of skipping."""
    offset = 0
    for p in paths:
        if not os.path.exists(p):
            continue
        with open(p, "r", encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                row, why = _decode_line(line, validate)
                if row is None:
                    if strict and why == "json":
                        raise ValueError(f"{p}:{lineno}: bad JSON")
                    if strict and why == "schema":
                        raise ValueError(f"{p}:{lineno}: schema violation")
                    continue
                if offset >= start:
                    yield offset, row
                offset += 1


def read_rows(path: str, *, strict: bool = False) -> Iterator[Dict[str, Any]]:
    """Yield valid rows from a journal path (rotated ``.1`` first).

    Invalid lines are skipped (or raise ValueError under ``strict``):
    a half-written tail line from a crashed daemon must not poison the
    whole corpus.
    """
    for _offset, row in follow_rows((path + ".1", path), validate_row,
                                    strict=strict):
        yield row


# -- verdict write-ahead log ----------------------------------------------

WAL_SCHEMA_VERSION = 1
DEFAULT_WAL_FILENAME = "verdict-wal.jsonl"

#: required fields -> acceptable types (verdict-WAL schema pin).
#: ``req`` is the client request id (idempotency key), ``stream`` the
#: decomposition stream tag ("main"/"sub"), ``idx`` the history index
#: within that stream, ``result`` the settled verdict dict.
_WAL_SCHEMA: Dict[str, tuple] = {
    "v": (int,),
    "ts": (int, float),
    "req": (str,),
    "stream": (str,),
    "idx": (int,),
    "result": (dict,),
}


def validate_verdict_row(row: Any) -> bool:
    """True iff ``row`` matches the pinned verdict-WAL v1 schema."""
    if not isinstance(row, dict):
        return False
    if row.get("v") != WAL_SCHEMA_VERSION:
        return False
    if set(row) != set(_WAL_SCHEMA):
        return False
    for key, types in _WAL_SCHEMA.items():
        if not isinstance(row[key], types):
            return False
        if types == (int,) and isinstance(row[key], bool):
            return False
    return True


class VerdictWAL:
    """Append-only per-verdict write-ahead log, one JSONL row per
    settled (request, stream, history) slot.

    Verdict accumulation is monotone — a slot settles exactly once and
    never changes — so the log needs no update-in-place and replay is
    a pure union.  Durability model: appends ride the page cache (a
    kill -9 of the *process* loses nothing already written(2)); only
    ``compact()`` — which rewrites the file — pays write-temp + atomic
    rename + directory fsync for crash consistency.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self.written = 0  #: rows appended by this writer
        self.dropped = 0  #: rows lost to write errors (disk full etc.)
        self._repair_tail()

    def _repair_tail(self) -> None:
        """Seal a torn tail left by a crash mid-append: without a
        trailing newline, the FIRST row this writer appends would
        concatenate onto the torn fragment and both would be lost on
        read-back — one damaged line must never cascade into two."""
        try:
            with open(self.path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() == 0:
                    return
                f.seek(-1, os.SEEK_END)
                if f.read(1) != b"\n":
                    f.write(b"\n")
        except OSError:
            pass  # absent file (fresh WAL) or unreadable — append as-is

    def append(self, req: str, stream: str, idx: int,
               result: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Append one settled verdict; fills ``v``/``ts``, validates.

        Returns the row dict on success, None when dropped — WAL
        failures must never fail a check.
        """
        row = {
            "v": WAL_SCHEMA_VERSION,
            "ts": time.time(),
            "req": req,
            "stream": stream,
            "idx": idx,
            "result": result,
        }
        if not validate_verdict_row(row):
            with self._lock:
                self.dropped += 1
            return None
        line = json.dumps(row, sort_keys=True, default=str) + "\n"
        with self._lock:
            try:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line)
                self.written += 1
            except OSError:
                self.dropped += 1
                return None
        return row

    def sink_for(self, req: str):
        """A ``(stream, idx, result) -> None`` settle sink bound to one
        request id — the shape ``DecomposedRun.attach_wal`` expects."""
        def _sink(stream: str, idx: int, result: Dict[str, Any]) -> None:
            self.append(req, stream, idx, result)
        return _sink

    def compact(self, keep_reqs=None) -> int:
        """Rewrite the log keeping only rows whose ``req`` is in
        ``keep_reqs`` (None keeps everything — pure rewrite).

        Crash-consistent: live rows stream into ``<path>.tmp``, which
        is fsynced, atomically renamed over the log, and sealed with a
        directory fsync — a crash at any point leaves either the old
        or the new file, never a torn one.  Returns rows kept.
        """
        with self._lock:
            rows = [r for r in read_verdict_rows(self.path)
                    if keep_reqs is None or r["req"] in keep_reqs]
            tmp = self.path + ".tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    for r in rows:
                        f.write(json.dumps(r, sort_keys=True) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
                _fsync_dir(self.path)
            except OSError:
                self.dropped += 1
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            return len(rows)


def read_verdict_rows(path: str) -> List[Dict[str, Any]]:
    """All valid verdict rows from a WAL path, file order.

    Damaged lines — the half-written tail of a killed daemon — are
    skipped: prior rows must survive a torn final append.
    """
    return [row for _offset, row
            in follow_rows((path,), validate_verdict_row)]


def replay_index(path: str) -> Dict[str, Dict[tuple, Dict[str, Any]]]:
    """WAL rows grouped for replay: ``{req: {(stream, idx): result}}``.

    Later rows win, though monotone settle means duplicates only arise
    from a retried request re-settling identically.
    """
    index: Dict[str, Dict[tuple, Dict[str, Any]]] = {}
    for row in read_verdict_rows(path):
        index.setdefault(row["req"], {})[(row["stream"], row["idx"])] = (
            row["result"])
    return index


class WalTail:
    """Incremental follower over a verdict WAL — the live half of the
    tail-follow contract behind the daemon's ``/watch`` channel.

    ``poll()`` returns the ``(offset, row)`` pairs appended since the
    last poll, with the same valid-row offsets :func:`follow_rows`
    assigns (damaged lines consume no offset) and the same damage-skip
    decision (:func:`_decode_line`).  Differences forced by liveness:

    - an in-progress tail line without its newline is left *pending*
      (the writer appends line+newline in one write, so a complete row
      always arrives with its terminator; a torn line never completes
      and is sealed + skipped after the writer's ``_repair_tail``);
    - a rewrite of the file (``compact()``'s atomic rename, detected by
      inode change or shrink) restarts the follower at offset 0 of the
      new file — retained rows are re-delivered, which is safe because
      verdict settlement is monotone and rows carry their full
      ``(req, stream, idx)`` identity.

    ``start`` resumes past already-consumed offsets (``Last-Event-ID``
    + 1): rows below it are read but not returned.
    """

    def __init__(self, path: str, *, start: int = 0):
        self.path = path
        self._skip = max(0, int(start))
        self._pos = 0     # byte offset after the last complete line read
        self._count = 0   # valid rows consumed so far (= next offset)
        self._sig = None  # (st_dev, st_ino) identity of the followed file

    def poll(self) -> List[tuple]:
        """Newly appended ``(offset, row)`` pairs since the last poll
        (empty when nothing new, the file is absent, or only a torn
        in-progress tail arrived)."""
        try:
            st = os.stat(self.path)
        except OSError:
            return []
        sig = (st.st_dev, st.st_ino)
        if self._sig is not None and (sig != self._sig
                                      or st.st_size < self._pos):
            # compacted (atomic-rename rewrite) or truncated: restart
            # from the top of the replacement file
            self._pos = 0
            self._count = 0
            self._skip = 0
        self._sig = sig
        if st.st_size <= self._pos:
            return []
        out: List[tuple] = []
        try:
            with open(self.path, "rb") as f:
                f.seek(self._pos)
                while True:
                    line = f.readline()
                    if not line or not line.endswith(b"\n"):
                        break  # torn in-progress tail: wait for newline
                    self._pos = f.tell()
                    row, _why = _decode_line(line, validate_verdict_row)
                    if row is None:
                        continue  # damage skipped, consumes no offset
                    offset = self._count
                    self._count += 1
                    if offset >= self._skip:
                        out.append((offset, row))
        except OSError:
            return out
        return out


# -- module singleton (no-op until configured) ----------------------------

_active: Optional[DispatchJournal] = None
_lock = threading.Lock()


def configure(path: Optional[str],
              max_bytes: int = DEFAULT_MAX_BYTES) -> Optional[DispatchJournal]:
    """Install (or with ``path=None`` remove) the process journal."""
    global _active
    with _lock:
        _active = DispatchJournal(path, max_bytes) if path else None
        return _active


def active() -> Optional[DispatchJournal]:
    # lock-free snapshot of an atomic reference; readers tolerate
    # either side of a configure() swap
    return _active  # jt: allow[concurrency-guard-drift] — atomic-ref snapshot (see above)


def path() -> Optional[str]:
    j = _active  # jt: allow[concurrency-guard-drift] — atomic-ref snapshot
    return j.path if j else None


def emit(**fields: Any) -> Optional[Dict[str, Any]]:
    """Append to the process journal; silently a no-op when unconfigured."""
    j = _active  # jt: allow[concurrency-guard-drift] — atomic-ref snapshot
    if j is None:
        return None
    return j.emit(**fields)
