"""Dispatch journal: one JSONL line per Executor dispatch, size-rotated.

ROADMAP item 3's learned cost model needs a durable per-dispatch
telemetry stream; the in-memory registry histograms die with the
process.  This journal is that stream: the daemon configures a path at
startup, the Executor emits one row per settled chunk, and
``tune.calibrate.journal_rows()`` reads the rows back as cost-table
evidence.

Schema v1 (pinned — ``validate_row`` rejects drift so readers can trust
old files):

    v            schema version (1)
    ts           wall-clock seconds (time.time) at settle
    kernel       engine kernel name ("dense", "elle_screen", ...)
    E, C, F      bucket shape: events, concurrency, frontier cap
    rows         histories in the chunk
    n_devices    mesh size at dispatch
    mesh_shape   mesh axis sizes, list
    window       dispatch-window depth
    compile_s    seconds when this dispatch compiled (cache miss), else 0
    execute_s    seconds when it ran warm (cache hit), else 0
    coalesced    number of runs sharing the dispatch (1 = unshared)
    cache        "hit" | "miss"
    closure_mode closure kernel variant in effect ("" when n/a)
    union        union lowering in effect ("" when n/a)
    calibration  active calibration id ("" when untuned)
    trace_id     comma-joined trace ids of participating runs ("" when untraced)

Rotation: when the current file exceeds ``max_bytes`` the writer
renames it to ``<path>.1`` (replacing any previous ``.1``) and starts
fresh — bounded disk, and readers see at most two files.

The module-level singleton (``configure``/``emit``/``path``) is a
no-op until configured, so library use (tests, in-process engines)
never writes to cwd by accident.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

SCHEMA_VERSION = 1
DEFAULT_MAX_BYTES = 8 * 1024 * 1024
DEFAULT_FILENAME = "dispatch-journal.jsonl"

#: required fields -> acceptable types (schema pin)
_SCHEMA: Dict[str, tuple] = {
    "v": (int,),
    "ts": (int, float),
    "kernel": (str,),
    "E": (int,),
    "C": (int,),
    "F": (int,),
    "rows": (int,),
    "n_devices": (int,),
    "mesh_shape": (list,),
    "window": (int,),
    "compile_s": (int, float),
    "execute_s": (int, float),
    "coalesced": (int,),
    "cache": (str,),
    "closure_mode": (str,),
    "union": (str,),
    "calibration": (str,),
    "trace_id": (str,),
}


def validate_row(row: Any) -> bool:
    """True iff ``row`` matches the pinned v1 schema exactly."""
    if not isinstance(row, dict):
        return False
    if row.get("v") != SCHEMA_VERSION:
        return False
    if set(row) != set(_SCHEMA):
        # extras are drift too: a reader of old files must be able to
        # trust that v1 means exactly these fields
        return False
    for key, types in _SCHEMA.items():
        if not isinstance(row[key], types):
            return False
        if types == (int,) and isinstance(row[key], bool):
            # bool is an int subclass; reject it for int fields
            return False
    if row["cache"] not in ("hit", "miss"):
        return False
    return True


class DispatchJournal:
    """Thread-safe append-only JSONL writer with single-step rotation."""

    def __init__(self, path: str, max_bytes: int = DEFAULT_MAX_BYTES):
        self.path = path
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self.written = 0  #: rows appended by this writer
        self.dropped = 0  #: rows lost to write errors (disk full etc.)

    def emit(self, **fields: Any) -> Optional[Dict[str, Any]]:
        """Append one row; fills ``v``/``ts``, validates, rotates.

        Returns the row dict on success, None when dropped — journal
        failures must never fail a dispatch.
        """
        row = dict(fields)
        row.setdefault("v", SCHEMA_VERSION)
        row.setdefault("ts", time.time())
        if not validate_row(row):
            # the counter is shared with every emitting thread; the
            # write path below already takes the lock, so the reject
            # path must too or increments can be lost
            with self._lock:
                self.dropped += 1
            return None
        line = json.dumps(row, sort_keys=True) + "\n"
        with self._lock:
            try:
                self._rotate_locked()
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line)
                self.written += 1
            except OSError:
                self.dropped += 1
                return None
        return row

    def _rotate_locked(self) -> None:
        try:
            if os.path.getsize(self.path) < self.max_bytes:
                return
        except OSError:
            return  # no file yet
        os.replace(self.path, self.path + ".1")

    def files(self) -> List[str]:
        """Rotated-then-current paths that exist, oldest first."""
        return [p for p in (self.path + ".1", self.path)
                if os.path.exists(p)]


def read_rows(path: str, *, strict: bool = False) -> Iterator[Dict[str, Any]]:
    """Yield valid rows from a journal path (rotated ``.1`` first).

    Invalid lines are skipped (or raise ValueError under ``strict``):
    a half-written tail line from a crashed daemon must not poison the
    whole corpus.
    """
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        with open(p, "r", encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    if strict:
                        raise ValueError(f"{p}:{lineno}: bad JSON")
                    continue
                if validate_row(row):
                    yield row
                elif strict:
                    raise ValueError(f"{p}:{lineno}: schema violation")


# -- module singleton (no-op until configured) ----------------------------

_active: Optional[DispatchJournal] = None
_lock = threading.Lock()


def configure(path: Optional[str],
              max_bytes: int = DEFAULT_MAX_BYTES) -> Optional[DispatchJournal]:
    """Install (or with ``path=None`` remove) the process journal."""
    global _active
    with _lock:
        _active = DispatchJournal(path, max_bytes) if path else None
        return _active


def active() -> Optional[DispatchJournal]:
    # lock-free snapshot of an atomic reference; readers tolerate
    # either side of a configure() swap
    return _active  # jt: allow[concurrency-guard-drift] — atomic-ref snapshot (see above)


def path() -> Optional[str]:
    j = _active  # jt: allow[concurrency-guard-drift] — atomic-ref snapshot
    return j.path if j else None


def emit(**fields: Any) -> Optional[Dict[str, Any]]:
    """Append to the process journal; silently a no-op when unconfigured."""
    j = _active  # jt: allow[concurrency-guard-drift] — atomic-ref snapshot
    if j is None:
        return None
    return j.emit(**fields)
