"""Run-wide observability: spans + metrics threaded through every layer.

The answer to "where did this 10-minute run spend its time, and which
checker engine actually ran?".  One process-global :class:`Tracer`
(jepsen_tpu.obs.tracer) and :class:`MetricsRegistry`
(jepsen_tpu.obs.metrics) are fed by hooks at every seam:

- ``core.run`` phases (setup / db-start / generator / teardown /
  snarf-logs / analyze) — category ``phase``
- ``interpreter`` worker op invokes + ok/info/fail counters — ``op``
- ``nemesis`` fault invokes — ``nemesis``
- ``control`` command latency + transport retries — ``control``
- per-checker spans (``check_safe``) — ``checker``
- engine telemetry in ``ops/wgl.py`` / ``ops/dense.py`` /
  ``checker/linear.py``: routed engine, compile-vs-execute timings,
  batch shape, frontier high-water mark, dispatch budget — ``engine``

Exports (jepsen_tpu.obs.export) land in the store directory:
``trace.json`` (Chrome trace_event), ``trace-spans.jsonl``,
``metrics.prom`` (Prometheus text); a summary dict is embedded in
``results["obs"]`` and printed by the CLI as a breakdown table.

Everything is stdlib-only.  Default ON; disable with the
``JEPSEN_TPU_OBS=0`` environment variable, the ``--no-obs`` CLI flag,
or ``test["obs?"] = False``.  Disabled hooks cost one branch — span()
returns a shared null context with no allocation, and counters check
the flag before taking their lock (see ``tests/test_obs.py``'s
no-allocation guard).

Distinct from :mod:`jepsen_tpu.trace` (the reference-parity
per-client span exporter wired by ``--tracing``): obs is the
harness's own flight recorder; trace.py mirrors dgraph's opencensus
client tracing.  They compose — both can be on.
"""

from __future__ import annotations

import os
from typing import Optional

from . import export as export_mod
from . import propagate as propagate_mod
from .metrics import MetricsRegistry
from .tracer import NULL_SPAN, SpanRecord, Tracer  # noqa: F401 (re-export)


def default_enabled() -> bool:
    """The environment default: on unless JEPSEN_TPU_OBS is falsy."""
    return os.environ.get("JEPSEN_TPU_OBS", "1").lower() not in (
        "0", "false", "off", "no",
    )


_tracer = Tracer(enabled=default_enabled())
_registry = MetricsRegistry(enabled=default_enabled())


def tracer() -> Tracer:
    return _tracer


def registry() -> MetricsRegistry:
    return _registry


def enabled() -> bool:
    return _tracer.enabled


def enable(reset: bool = False) -> None:
    if reset:
        _tracer.reset()
        _registry.reset()
        propagate_mod.reset()
    _tracer.enabled = True
    _registry.enabled = True


def disable() -> None:
    _tracer.enabled = False
    _registry.enabled = False


def reset() -> None:
    _tracer.reset()
    _registry.reset()
    propagate_mod.reset()


# -- span + metric shorthands (the instrumentation surface) -----------------


def span(name: str, cat: str = "", **attrs):
    """Context manager for one span; shared null context when disabled
    (one branch, zero allocation — safe in hot loops)."""
    if not _tracer.enabled:
        return NULL_SPAN
    return _tracer.span(name, cat, attrs or None)


def count(name: str, n: int = 1, **labels) -> None:
    if not _registry.enabled:
        return
    _registry.counter(name, **labels).inc(n)


def gauge_set(name: str, v: float, **labels) -> None:
    if not _registry.enabled:
        return
    _registry.gauge(name, **labels).set(v)


def gauge_max(name: str, v: float, **labels) -> None:
    if not _registry.enabled:
        return
    _registry.gauge(name, **labels).set_max(v)


def observe(name: str, v: float, **labels) -> None:
    if not _registry.enabled:
        return
    _registry.histogram(name, **labels).observe(v)


def count_op(completion_type) -> None:
    """Interpreter hot-loop counter: one branch when disabled."""
    if not _registry.enabled:
        return
    _registry.counter(
        "jepsen_interpreter_ops_total", type=str(completion_type)
    ).inc()


# -- run anchoring ----------------------------------------------------------


def set_run_anchor() -> None:
    """Record the monotonic instant of the run's t=0 (call inside
    ``util.with_relative_time``) so exports can align span times with
    history op times."""
    if not _tracer.enabled:
        return
    import time as _t

    from ..util import relative_time_nanos

    try:
        _tracer.run_anchor_ns = _t.monotonic_ns() - relative_time_nanos()
    except RuntimeError:
        _tracer.run_anchor_ns = None


def run_anchor_ns() -> Optional[int]:
    return _tracer.run_anchor_ns


def phase_intervals() -> list:
    """Completed lifecycle phases as ``(name, start_s, end_s)`` relative
    to the run anchor (history time axis); empty when no anchor was set
    or tracing is off.  Used by checker.perf's phase overlay."""
    if not _tracer.enabled:
        # disable() doesn't clear the buffer/anchor — without this
        # check an obs-off run following an obs-on run in the same
        # process would overlay the PREVIOUS run's stale phases
        return []
    anchor = _tracer.run_anchor_ns
    if anchor is None:
        return []
    out = []
    for rec in _tracer.finished(cat="phase"):
        if rec.t1 is None:
            continue
        out.append(
            (rec.name, (rec.t0 - anchor) / 1e9, (rec.t1 - anchor) / 1e9)
        )
    out.sort(key=lambda t: t[1])
    return out


# -- exports ----------------------------------------------------------------


def export_all(directory: str) -> dict:
    return export_mod.export_all(_tracer, _registry, directory)


def render_prom() -> str:
    """Live Prometheus exposition text for the process registry — the
    same formatter the at-exit ``metrics.prom`` dump uses (served by
    the checker-service daemon's ``/metrics``)."""
    return export_mod.render_prom(_registry)


def summary() -> dict:
    return export_mod.summary(_tracer, _registry)


def format_summary(s: Optional[dict] = None) -> str:
    return export_mod.format_summary(s if s is not None else summary())
