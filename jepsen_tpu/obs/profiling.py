"""On-demand **device profiling** capture.

One bounded window: start a ``jax.profiler`` trace, run the supplied
work (or just sleep the window out), stop the trace, and sample every
local device's memory high-water mark — then write a small loadable
``profile.json`` manifest beside the raw trace directory so the web
UI, the CLI, and tests all consume one shape.

Everything degrades gracefully off-TPU: CPU devices usually answer
``memory_stats() -> None`` (recorded as ``null``), and environments
without a working ``jax.profiler`` backend still produce a manifest
with ``trace: null`` — the memory inventory and wall-clock are still
worth having.  Nothing here raises for a missing accelerator; only
the caller's ``work`` exceptions propagate (after the trace is
stopped).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from .. import obs

#: manifest filename inside every capture directory
MANIFEST = "profile.json"
#: hard cap on the idle capture window, seconds
MAX_SECONDS = 30.0


def capture_available() -> bool:
    """True when a ``jax.profiler`` trace can plausibly be collected
    (the module imports and exposes the start/stop pair).  Tests use
    this for their skip marks; :func:`capture` itself never needs it."""
    try:
        import jax
        return (hasattr(jax, "profiler")
                and hasattr(jax.profiler, "start_trace")
                and hasattr(jax.profiler, "stop_trace"))
    except Exception:
        return False


def _memory_inventory() -> List[Dict[str, Any]]:
    """Per-device memory stats, ``None``-tolerant (CPU backends)."""
    out: List[Dict[str, Any]] = []
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return out
    for d in devices:
        stats: Optional[Dict[str, Any]] = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        peak = None
        if isinstance(stats, dict):
            peak = stats.get("peak_bytes_in_use",
                             stats.get("bytes_in_use"))
        out.append({
            "device": str(d),
            "platform": getattr(d, "platform", ""),
            "peak_bytes_in_use": peak,
            "bytes_in_use":
                stats.get("bytes_in_use") if isinstance(stats, dict)
                else None,
        })
        if peak is not None:
            obs.gauge_max("jepsen_device_hbm_peak_bytes", float(peak),
                          device=str(d))
    return out


def capture(out_dir: str, seconds: float = 1.0, label: str = "",
            work: Optional[Callable[[], Any]] = None) -> Dict[str, Any]:
    """Run one bounded profiling window into ``out_dir``.

    With ``work`` the window lasts exactly as long as the work; idle
    captures sleep ``seconds`` (clamped to :data:`MAX_SECONDS`).
    Returns the manifest dict (also written to ``profile.json``).
    ``work`` exceptions propagate after the trace is stopped."""
    seconds = max(0.0, min(float(seconds), MAX_SECONDS))
    os.makedirs(out_dir, exist_ok=True)
    trace_dir = os.path.join(out_dir, "trace")
    started = False
    try:
        import jax
        jax.profiler.start_trace(trace_dir)
        started = True
    except Exception:
        started = False
    t0 = time.monotonic()
    try:
        if work is not None:
            work()
        else:
            time.sleep(seconds)
    finally:
        wall = time.monotonic() - t0
        if started:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                started = False
        memory = _memory_inventory()
        manifest = {
            "v": 1,
            "label": str(label or ""),
            "requested_seconds": seconds,
            "wall_seconds": round(wall, 6),
            "idle": work is None,
            "trace": ("trace" if started and os.path.isdir(trace_dir)
                      else None),
            "memory": memory,
        }
        tmp = os.path.join(out_dir, MANIFEST + ".tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, os.path.join(out_dir, MANIFEST))
        obs.count("jepsen_profile_captures_total")
    return manifest


def load_manifest(out_dir: str) -> Optional[Dict[str, Any]]:
    """Read a capture directory's manifest back, or None."""
    p = os.path.join(out_dir, MANIFEST)
    try:
        with open(p, "r", encoding="utf-8") as fh:
            loaded = json.load(fh)
    except (OSError, ValueError):
        return None
    return loaded if isinstance(loaded, dict) else None
