"""Cost-model **drift sentinel** — residual tracking over the
dispatch journal.

The telemetry→tuning loop has an input side (the dispatch journal,
:mod:`jepsen_tpu.obs.journal`) and a consumer
(:func:`jepsen_tpu.tune.calibrate.journal_rows`), but nothing ever
*compared* what the calibration predicted against what production
dispatches actually cost — a stale cost table silently degrades
scheduling until a human re-runs ``jepsen_tpu tune``.  This module
closes that gap as pure observation: every settled execute chunk that
lands in the journal is also scored here, per dispatch shape
``(kernel, E, C, F)``, as the ratio

    measured ``execute_s`` / predicted seconds

smoothed by a deterministic EWMA.  The prediction comes from the
active calibration artifact when one is loaded (so a ratio of 1.0
means "the table still tells the truth"); with no calibration it
falls back to the same analytic footprint proxy
:func:`jepsen_tpu.engine.planning.estimated_cost` uses, and the
per-shape ratios are normalised by their cross-shape **median** so
the unknown proxy scale cancels — a healthy fleet sits at ~1.0 either
way, and a shape whose real cost inflated 3× reads ~3.0.

Aggregates: the daemon-level **drift score** is the worst per-shape
deviation (``max(ratio, 1/ratio)``) across shapes with at least
``min_samples`` observations; shapes at or past the threshold
(``JEPSEN_TPU_DRIFT_THRESHOLD``, default 2.0) are **stale**.  When
the score first crosses the threshold the sentinel records a retune
recommendation — a marker row in the journal (kernel
``drift-retune``) plus a crossing counter — and latches, so one
sustained drift episode produces exactly one recommendation.  The
flag gauge tracks the *current* state and clears when drift recovers.

Median normalisation needs company: with only two proxy-scored
shapes the median sits between them and BOTH deviate.  The smoke
drill (:mod:`jepsen_tpu.obs.drift_smoke`) therefore feeds at least
three healthy shapes beside the inflated one; production journals
clear this bar trivially.

This PR observes only — no scheduling, admission, or routing decision
reads the drift score.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from .. import obs
from . import journal as obs_journal

#: per-shape deviation at/past this flags the shape stale (override
#: with ``JEPSEN_TPU_DRIFT_THRESHOLD``)
DEFAULT_THRESHOLD = 2.0
#: EWMA smoothing weight for the newest ratio
DEFAULT_ALPHA = 0.3
#: observations a shape needs before it can flag or drive the score
DEFAULT_MIN_SAMPLES = 3
#: journal kernel name of the retune-recommendation marker row
MARKER_KERNEL = "drift-retune"

#: every reason :meth:`DriftSentinel.observe_row` may skip a row for
SKIP_REASONS = (
    "not-dict",     # row is not a mapping at all (damaged line)
    "marker",       # our own drift-retune marker row
    "no-shape",     # kernel/E/C/F/rows missing or non-numeric (old schema)
    "not-hit",      # compile rows: elapsed is compile_s, not steady-state
    "not-timed",    # execute_s absent or <= 0
    "no-estimate",  # predictor returned None/<=0 for this shape
    "bad-ratio",    # ratio not finite or <= 0
)


def _env_threshold() -> Optional[float]:
    raw = os.environ.get("JEPSEN_TPU_DRIFT_THRESHOLD", "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if math.isfinite(v) and v > 1.0 else None


def analytic_proxy(kernel: str, E: int, C: int, F: int, rows: int) -> float:
    """The calibration-free footprint proxy — the same arithmetic
    :func:`jepsen_tpu.engine.planning.estimated_cost` falls back to,
    duplicated here so scoring never imports the engine (obs stays a
    leaf package).  Unitless; only ratios of it are meaningful."""
    if kernel == "dense":
        return float(rows) * float(max(1, E))
    if kernel == "cycles":
        return float(rows) * float(E) * float(E) * float(max(1, F))
    if kernel == "frontier":
        words = max(1, -(-int(E) // 32))
        return float(rows) * float(max(1, F)) * float(C + 1) * float(words)
    return float(rows) * float(max(1, E))


def predicted_seconds(kernel: str, E: int, C: int, F: int,
                      rows: int) -> Tuple[Optional[float], str]:
    """Predicted cost for one dispatch shape → ``(value, source)``.

    Source ``"calibration"`` means measured seconds interpolated from
    the active artifact (absolute — 1.0 is truth); ``"proxy"`` means
    the analytic footprint (relative — needs median normalisation)."""
    try:
        from ..tune import artifact as _artifact
        cal = _artifact.active()
        if cal is not None:
            est = cal.cost(kernel, E, C, F, rows)
            if est is not None and est > 0.0:
                return float(est), "calibration"
    except Exception:
        pass
    proxy = analytic_proxy(kernel, E, C, F, rows)
    if proxy <= 0.0 or not math.isfinite(proxy):
        return None, "proxy"
    return proxy, "proxy"


class _ShapeState:
    __slots__ = ("ewma", "n", "source")

    def __init__(self) -> None:
        self.ewma = 0.0
        self.n = 0
        self.source = "proxy"


class DriftSentinel:
    """Per-daemon residual tracker.  Thread-safe: journal emits come
    from the executor's owner thread while ``/status`` snapshots come
    from handler threads."""

    def __init__(self, threshold: Optional[float] = None,
                 alpha: float = DEFAULT_ALPHA,
                 min_samples: int = DEFAULT_MIN_SAMPLES) -> None:
        if threshold is None:
            threshold = _env_threshold() or DEFAULT_THRESHOLD
        self.threshold = float(threshold)
        self.alpha = float(alpha)
        self.min_samples = max(1, int(min_samples))
        self._lock = threading.Lock()
        # every field below: # jt: guarded-by(_lock)
        self._shapes: Dict[Tuple[str, int, int, int], _ShapeState] = {}
        self._scored = 0          # jt: guarded-by(_lock)
        self._skipped: Dict[str, int] = {}   # jt: guarded-by(_lock)
        self._score = 1.0         # jt: guarded-by(_lock)
        self._stale: List[Dict[str, Any]] = []   # jt: guarded-by(_lock)
        self._above = False       # crossing latch  # jt: guarded-by(_lock)
        self._crossings = 0       # jt: guarded-by(_lock)

    # ------------------------------------------------------------- score

    def observe_row(self, row: Any) -> Optional[str]:
        """Score one journal row.  Returns the skip reason, or None
        when the row was scored.  NEVER raises and NEVER produces a
        NaN/inf ratio — old-schema rows, damaged lines, and shapes the
        predictor cannot price all land in the skip counters."""
        reason = self._classify(row)
        if reason is not None:
            with self._lock:
                self._skipped[reason] = self._skipped.get(reason, 0) + 1
            obs.count("jepsen_drift_rows_skipped_total", reason=reason)
            return reason

        kernel = str(row["kernel"])
        E, C, F = int(row["E"]), int(row["C"]), int(row["F"])
        rows_n = int(row["rows"])
        measured = float(row["execute_s"])
        est, source = predicted_seconds(kernel, E, C, F, rows_n)
        if est is None or est <= 0.0:
            with self._lock:
                self._skipped["no-estimate"] = \
                    self._skipped.get("no-estimate", 0) + 1
            obs.count("jepsen_drift_rows_skipped_total", reason="no-estimate")
            return "no-estimate"
        ratio = measured / est
        if not math.isfinite(ratio) or ratio <= 0.0:
            with self._lock:
                self._skipped["bad-ratio"] = \
                    self._skipped.get("bad-ratio", 0) + 1
            obs.count("jepsen_drift_rows_skipped_total", reason="bad-ratio")
            return "bad-ratio"

        with self._lock:
            st = self._shapes.setdefault((kernel, E, C, F), _ShapeState())
            if st.n == 0:
                st.ewma = ratio
            else:
                st.ewma = self.alpha * ratio + (1.0 - self.alpha) * st.ewma
            st.n += 1
            st.source = source
            self._scored += 1
            crossed, published = self._recompute_locked()
        obs.count("jepsen_drift_rows_scored_total")
        self._publish(published, crossed)
        if crossed:
            self._record_recommendation()
        return None

    @staticmethod
    def _classify(row: Any) -> Optional[str]:
        if not isinstance(row, dict):
            return "not-dict"
        if row.get("kernel") == MARKER_KERNEL:
            return "marker"
        try:
            kernel = str(row["kernel"])
            E, C, F = int(row["E"]), int(row["C"]), int(row["F"])
            rows_n = int(row["rows"])
        except (KeyError, TypeError, ValueError):
            return "no-shape"
        if not kernel or rows_n <= 0 or E < 0 or C < 0 or F < 0:
            return "no-shape"
        if row.get("cache") != "hit":
            return "not-hit"
        try:
            measured = float(row.get("execute_s") or 0.0)
        except (TypeError, ValueError):
            return "not-timed"
        if measured <= 0.0 or not math.isfinite(measured):
            return "not-timed"
        return None

    # jt: holds(_lock)
    def _recompute_locked(self) -> Tuple[bool, Dict[str, Any]]:
        """Rebuild normalised deviations, the aggregate score, and the
        stale list.  Returns (crossed-now, gauge payload).  Caller
        holds ``_lock``."""
        proxy_ewmas = sorted(
            st.ewma for st in self._shapes.values() if st.source == "proxy")
        baseline = 1.0
        if proxy_ewmas:
            mid = len(proxy_ewmas) // 2
            if len(proxy_ewmas) % 2:
                baseline = proxy_ewmas[mid]
            else:
                baseline = 0.5 * (proxy_ewmas[mid - 1] + proxy_ewmas[mid])
            if baseline <= 0.0 or not math.isfinite(baseline):
                baseline = 1.0

        per_shape: List[Dict[str, Any]] = []
        score = 1.0
        stale: List[Dict[str, Any]] = []
        for (kernel, E, C, F), st in sorted(self._shapes.items()):
            nd = st.ewma if st.source == "calibration" else st.ewma / baseline
            if nd <= 0.0 or not math.isfinite(nd):
                nd = 1.0
            deviation = max(nd, 1.0 / nd)
            entry = {
                "kernel": kernel, "E": E, "C": C, "F": F,
                "ratio": round(nd, 4), "deviation": round(deviation, 4),
                "n": st.n, "source": st.source,
            }
            per_shape.append(entry)
            if st.n >= self.min_samples:
                score = max(score, deviation)
                if deviation >= self.threshold:
                    stale.append(entry)
        self._score = score
        self._stale = stale
        recommended = bool(stale)
        crossed = recommended and not self._above
        if crossed:
            self._crossings += 1
        self._above = recommended
        return crossed, {
            "per_shape": per_shape, "score": score,
            "stale": len(stale), "recommended": recommended,
        }

    def _publish(self, g: Dict[str, Any], crossed: bool) -> None:
        """Push the recomputed state to the metrics registry (outside
        ``_lock`` — the registry has its own lock)."""
        for s in g["per_shape"]:
            obs.gauge_set("jepsen_drift_ratio", s["ratio"],
                          kernel=s["kernel"], E=s["E"], C=s["C"], F=s["F"])
        obs.gauge_set("jepsen_drift_score", round(g["score"], 4))
        obs.gauge_set("jepsen_drift_stale_shapes", g["stale"])
        obs.gauge_set("jepsen_drift_retune_recommended",
                      1.0 if g["recommended"] else 0.0)
        if crossed:
            obs.count("jepsen_drift_retune_crossings_total")

    def _record_recommendation(self) -> None:
        """Drop the retune-recommendation marker into the journal —
        full v1-schema row so replay tooling never special-cases it;
        :meth:`observe_row` and ``tune.calibrate.journal_rows`` both
        skip it (rows=0, nothing timed)."""
        if obs_journal.active() is None:
            return
        cal_id = ""
        try:
            from ..tune import artifact as _artifact
            cal = _artifact.active()
            if cal is not None:
                cal_id = str(cal.calibration_id)
        except Exception:
            cal_id = ""
        with self._lock:
            score = self._score
        obs_journal.emit(
            kernel=MARKER_KERNEL, E=0, C=0, F=0, rows=0, n_devices=0,
            mesh_shape=[], window=0, compile_s=0.0, execute_s=0.0,
            coalesced=0, cache="hit", closure_mode="", union="",
            calibration=cal_id,
            trace_id="drift-score=%.3f" % score,
        )

    # --------------------------------------------------------- read side

    def scan(self, path: Optional[str] = None) -> int:
        """Feed every readable row of a journal file through
        :meth:`observe_row` — warm start for a restarted daemon.
        Returns the number of rows scored."""
        if path is None:
            path = obs_journal.path()
        if not path:
            return 0
        scored = 0
        try:
            rows = obs_journal.read_rows(path)
        except OSError:
            return 0
        for row in rows:
            if self.observe_row(row) is None:
                scored += 1
        return scored

    def snapshot(self) -> Dict[str, Any]:
        """The ``drift`` block for ``/status`` and ``top``."""
        with self._lock:
            _, g = self._recompute_locked() if self._shapes else (False, {
                "per_shape": [], "score": 1.0, "stale": 0,
                "recommended": False,
            })
            return {
                "score": round(g["score"], 4),
                "threshold": self.threshold,
                "shapes": len(self._shapes),
                "stale": [dict(s) for s in self._stale],
                "stale_shapes": g["stale"],
                "retune_recommended": g["recommended"],
                "crossings": self._crossings,
                "rows_scored": self._scored,
                "rows_skipped": dict(sorted(self._skipped.items())),
            }


# ----------------------------------------------------------- singleton

_active: Optional[DriftSentinel] = None
_lock = threading.Lock()


def configure(threshold: Optional[float] = None, *,
              alpha: float = DEFAULT_ALPHA,
              min_samples: int = DEFAULT_MIN_SAMPLES
              ) -> DriftSentinel:
    """Install a fresh module-level sentinel (the daemon calls this at
    start, beside ``obs_journal.configure``)."""
    global _active
    with _lock:
        _active = DriftSentinel(threshold=threshold, alpha=alpha,
                                min_samples=min_samples)
        return _active


def disable() -> None:
    global _active
    with _lock:
        _active = None


def active() -> Optional[DriftSentinel]:
    return _active  # jt: allow[concurrency-guard-drift] — atomic-ref snapshot
