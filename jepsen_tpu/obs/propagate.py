"""Cross-seam trace propagation: trace_ctx wire form + remote-span adoption.

A service-routed run crosses two processes: the client encodes and
POSTs, the daemon queues/coalesces/dispatches.  Each side has its own
process-global tracer, so without propagation the run's story shatters
into two unrelated trace files.  This module is the seam glue:

- ``make_ctx(span)`` mints a ``trace_ctx`` dict — a random 64-bit trace
  id plus the client-side parent span id — that the serve client stamps
  onto ``/check`` and ``/elle`` wire frames (serve/protocol.py).
- ``parse_ctx(obj)`` validates the wire form on the daemon side; the
  daemon tags its request/batch/dispatch spans with the trace id so a
  later ``GET /trace?ctx=`` can slice its span buffer per run.
- ``adopt(rows, ...)`` stores daemon-side span dicts fetched at settle
  so ``obs.export.chrome_trace`` can merge them into the client's
  Chrome trace, wall-clock aligned and stitched with flow events.

Everything here is plain dict/JSON plumbing — no sockets, no tracer
mutation — so both ends can unit-test the round trip without a daemon.
"""

from __future__ import annotations

import os
import secrets
import threading
from typing import Any, Dict, List, Optional

#: wire keys of a trace_ctx frame
CTX_KEYS = ("trace_id", "parent_sid")

#: span-attribute keys the tracer sides stamp (str-coerced by SpanRecord.set)
ATTR_TRACE_ID = "trace_id"
ATTR_TRACE_IDS = "trace_ids"  # comma-joined, on shared/coalesced spans
ATTR_ROLE = "ctx_role"  # "client" | "daemon"

_lock = threading.Lock()
#: adopted remote spans: span dicts + alignment metadata, per trace id
_remote: List[Dict[str, Any]] = []


def new_trace_id() -> str:
    """Random 64-bit hex trace id (Chrome flow-event ``id`` compatible)."""
    return secrets.token_hex(8)


def make_ctx(parent_sid: int = 0, trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Mint a trace_ctx for one service-routed request."""
    return {"trace_id": trace_id or new_trace_id(),
            "parent_sid": int(parent_sid)}


def parse_ctx(obj: Any) -> Optional[Dict[str, Any]]:
    """Validate a wire-side trace_ctx; None when absent or malformed.

    Malformed contexts degrade to untraced rather than erroring: trace
    propagation must never fail a check request.
    """
    if not isinstance(obj, dict):
        return None
    tid = obj.get("trace_id")
    if not isinstance(tid, str) or not (1 <= len(tid) <= 64):
        return None
    if not all(c in "0123456789abcdef" for c in tid):
        return None
    try:
        psid = int(obj.get("parent_sid", 0))
    except (TypeError, ValueError):
        return None
    return {"trace_id": tid, "parent_sid": psid}


def span_matches(span_dict: Dict[str, Any], trace_id: str) -> bool:
    """Does a finished-span dict belong to ``trace_id``?

    Matches either the direct ``trace_id`` attr or membership in the
    comma-joined ``trace_ids`` attr that coalesced daemon spans carry
    (a shared dispatch appears in every participating run's trace).
    """
    attrs = span_dict.get("attrs") or {}
    if attrs.get(ATTR_TRACE_ID) == trace_id:
        return True
    ids = attrs.get(ATTR_TRACE_IDS)
    if isinstance(ids, str) and trace_id in ids.split(","):
        return True
    return False


def adopt(rows: List[Dict[str, Any]], *, pid: Optional[int] = None,
          wall_origin: Optional[float] = None,
          origin_ns: Optional[int] = None) -> int:
    """Store remote span dicts for merging into this process's export.

    ``pid``/``wall_origin``/``origin_ns`` come from the daemon's
    ``/trace`` payload and let the exporter rebase the remote
    monotonic timestamps onto this process's clock.  Rows from our own
    pid are skipped: an in-process daemon shares the tracer, so its
    spans are already in the local buffer and adopting them would
    duplicate every event.

    Returns the number of rows actually adopted.
    """
    if pid is not None and pid == os.getpid():
        return 0
    kept = []
    for r in rows:
        if not isinstance(r, dict) or "name" not in r:
            continue
        rec = dict(r)
        rec["_remote_pid"] = pid
        rec["_remote_wall_origin"] = wall_origin
        rec["_remote_origin_ns"] = origin_ns
        kept.append(rec)
    with _lock:
        _remote.extend(kept)
    return len(kept)


def adopted() -> List[Dict[str, Any]]:
    """Snapshot of all adopted remote spans."""
    with _lock:
        return list(_remote)


def reset() -> None:
    """Drop adopted remote spans (wired into ``obs.reset``/``enable``)."""
    with _lock:
        _remote.clear()
