"""Trace smoke check: ``python -m jepsen_tpu.obs.smoke``.

Runs the in-process CLI path (the localkv-style dummy-remote run:
``test --workload linearizable-register --dummy``) with observability
on, then fails loudly unless the store directory holds a VALID Chrome
``trace_event`` JSON, span JSONL, and Prometheus dump, the trace
carries the expected phase + op spans, and the results embed the obs
summary with a linearizability engine.  Wired into ``make
trace-smoke`` / ``make check`` so a refactor that silently stops
exporting telemetry breaks CI, not a debugging session three rounds
later.

Exit codes: 0 ok, 1 artifact missing/malformed, 2 the run itself
failed.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def main(argv=None) -> int:
    from .. import cli, store
    from . import export as export_mod

    workload = "linearizable-register"
    base = os.path.join(
        tempfile.mkdtemp(prefix="jepsen-trace-smoke-"), "store"
    )
    code = cli.run_cli(
        cli.default_commands(),
        [
            "test",
            "--workload", workload,
            "--dummy",
            "--nodes", "n1",
            "--concurrency", "2n",
            "--time-limit", "1",
            "--store-base", base,
        ],
    )
    if code != cli.EXIT_VALID:
        print(f"trace-smoke: CLI run failed (exit {code})", file=sys.stderr)
        return 2

    runs = store.tests(base).get(workload, [])
    if not runs:
        print("trace-smoke: no stored run found", file=sys.stderr)
        return 1
    d = os.path.join(base, workload, runs[-1])

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    trace_path = os.path.join(d, export_mod.TRACE_JSON)
    prom_path = os.path.join(d, export_mod.METRICS_PROM)
    spans_path = os.path.join(d, export_mod.SPANS_JSONL)
    for p in (trace_path, prom_path, spans_path):
        check(os.path.exists(p), f"missing artifact {os.path.basename(p)}")

    if os.path.exists(trace_path):
        err = export_mod.validate_chrome_trace(trace_path)
        check(err is None, f"trace.json invalid: {err}")
        with open(trace_path) as f:
            events = json.load(f).get("traceEvents", [])
        cats = {e.get("cat") for e in events}
        names = {e.get("name") for e in events}
        check("phase" in cats, f"no phase spans in trace (cats={cats})")
        check("op" in cats, "no op spans in trace")
        check("generator" in names, "generator phase span missing")
        check("analyze" in names, "analyze phase span missing")

    if os.path.exists(prom_path):
        err = export_mod.validate_prometheus(prom_path)
        check(err is None, f"metrics.prom invalid: {err}")
        text = open(prom_path).read()
        check(
            "jepsen_interpreter_ops_total" in text,
            "op counters missing from metrics.prom",
        )
        check(
            "jepsen_engine_rows_total" in text,
            "engine telemetry missing from metrics.prom",
        )

    with open(os.path.join(d, "results.json")) as f:
        results = json.load(f)
    obs_summary = results.get("obs")
    check(isinstance(obs_summary, dict), "results.json lacks obs summary")
    if isinstance(obs_summary, dict):
        check(bool(obs_summary.get("phases")), "summary has no phases")
        check(
            bool(obs_summary.get("engines")),
            "summary names no checker engine",
        )

    if failures:
        for f_ in failures:
            print(f"trace-smoke: FAIL — {f_}", file=sys.stderr)
        print(f"trace-smoke: artifacts under {d}", file=sys.stderr)
        return 1
    print(f"trace-smoke: ok ({d})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
