"""Fleet-telemetry smoke check: ``python -m jepsen_tpu.obs.fleet_smoke``.

Brings a resident checker daemon up in-process with a dispatch
journal, pushes two concurrent service-routed runs through it, and
proves the fleet-telemetry acceptance gates (doc/observability.md
"Fleet telemetry"):

- **stitched traces**: each run's ``trace_ctx`` links the client-side
  span to the daemon-side spans — the exported Chrome trace carries
  flow events (``ph`` s/t/f, cat ``trace_ctx``) connecting both
  sides of every traced run, and ``GET /trace?ctx=`` serves the
  daemon's span dump for a given trace id;
- **dispatch journal**: every device dispatch appended one
  schema-valid row; a coalesced group's rows record ``coalesced >=
  2``; ``tune.calibrate.journal_rows`` reads them back as cost
  evidence;
- **live windowed metrics**: ``/metrics`` still passes the Prometheus
  validator and now exports ``*_rate1m`` gauges; ``/status`` carries
  the last-60 s ``live`` view including the queue-wait mean
  (``jepsen_serve_queue_wait_seconds``);
- **fleet view**: ``jepsen_tpu top --once`` renders the fleet block
  from a live daemon.

Wired into ``make obs-fleet-smoke`` / ``make check``.  Exit codes:
0 ok, 1 any gate failed.
"""

from __future__ import annotations

import contextlib
import io
import os
import random
import shutil
import sys
import tempfile
import threading


def _corpus(seed: int, n: int = 8):
    from jepsen_tpu.synth import generate_history

    rng = random.Random(seed)
    return [
        generate_history(rng, n_procs=3, n_ops=12, crash_p=0.02,
                         corrupt=(i % 2 == 0))
        for i in range(n)
    ]


def main(argv=None) -> int:
    from jepsen_tpu import cli, models as m, obs
    from jepsen_tpu.obs import export as obs_export
    from jepsen_tpu.obs import journal as obs_journal
    from jepsen_tpu.obs import propagate
    from jepsen_tpu.serve import CheckerDaemon, ServiceClient, protocol
    from jepsen_tpu.tune import calibrate

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    obs.enable(reset=True)
    tmp = tempfile.mkdtemp(prefix="jt-fleet-smoke-")
    jpath = os.path.join(tmp, obs_journal.DEFAULT_FILENAME)
    model = m.cas_register(0)
    batch_a = _corpus(45100)
    batch_b = _corpus(977)

    daemon = CheckerDaemon(port=0, coalesce_wait_s=0.75,
                           journal_path=jpath)
    daemon.start(block=False)
    try:
        client = ServiceClient(port=daemon.port)
        check(client.healthy(), "daemon did not come up healthy")

        # one solo run (compiles), then two concurrent runs that
        # coalesce into shared dispatches
        client.check_batch(model, batch_a, max_dispatch=4)
        barrier = threading.Barrier(2)
        out = {}

        def post(tag, hists):
            c = ServiceClient(port=daemon.port)
            barrier.wait()
            out[tag] = c.check_batch(model, hists, max_dispatch=4)

        threads = [
            threading.Thread(target=post, args=("a", batch_a)),
            threading.Thread(target=post, args=("b", batch_b)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        check(len(out.get("a") or []) == len(batch_a)
              and len(out.get("b") or []) == len(batch_b),
              "concurrent service runs did not return full batches")

        # -- stitched traces: client + daemon spans share trace ids,
        # and the export carries cross-seam flow events
        spans = obs.tracer().finished()
        client_ids = {
            s.attrs[propagate.ATTR_TRACE_ID]
            for s in spans
            if (s.attrs or {}).get(propagate.ATTR_ROLE) == "client"
        }
        daemon_ids = {
            s.attrs[propagate.ATTR_TRACE_ID]
            for s in spans
            if (s.attrs or {}).get(propagate.ATTR_ROLE) == "daemon"
        }
        check(len(client_ids) >= 3,
              f"expected >=3 traced client runs, saw {len(client_ids)}")
        check(client_ids <= daemon_ids or client_ids & daemon_ids,
              f"daemon spans not linked to client trace ids "
              f"(client {client_ids}, daemon {daemon_ids})")
        trace = obs_export.chrome_trace(obs.tracer())
        events = trace["traceEvents"]
        tpath = os.path.join(tmp, "trace.json")
        with open(tpath, "w") as f:
            import json

            json.dump(trace, f)
        reason = obs_export.validate_chrome_trace(tpath)
        check(reason is None, f"chrome trace failed validation: {reason}")
        flows = [e for e in events if e.get("cat") == "trace_ctx"]
        flow_ids = {e.get("id") for e in flows}
        check({e.get("ph") for e in flows} >= {"s", "f"},
              f"flow events missing start/finish phases: {flows[:4]}")
        check(client_ids & flow_ids,
              "no flow event stitched a traced client run")

        # -- the /trace endpoint serves a span dump per trace id
        tid = sorted(client_ids)[0]
        code, body = client._request(f"/trace?ctx={tid}")
        check(code == 200, f"/trace returned {code}")
        dump = protocol.decode_body(body)
        check(bool(dump.get("spans"))
              and all(propagate.span_matches(s, tid)
                      for s in dump["spans"])
              and dump.get("pid") == os.getpid()
              and "wall_origin" in dump and "origin_ns" in dump,
              f"/trace dump malformed for {tid}: "
              f"{str(dump)[:200]}")
        code, _ = client._request("/trace")
        check(code == 400, f"/trace without ctx should 400, got {code}")

        # -- dispatch journal: schema-valid rows, coalescing evidence,
        # read-back as cost evidence
        st = daemon.status()
        check(st.get("journal_path") == jpath,
              f"status journal_path {st.get('journal_path')!r}")
        check((st.get("journal_rows") or 0) >= 1,
              f"no journal rows written (status {st})")
        rows = list(obs_journal.read_rows(jpath, strict=True))
        check(len(rows) >= 1, "journal file empty")
        check(any(r["coalesced"] >= 2 for r in rows),
              f"no journal row from a coalesced group "
              f"(coalesced={[r['coalesced'] for r in rows]})")
        check(any(r["trace_id"] for r in rows),
              "no journal row carries a trace id")
        evidence = calibrate.journal_rows(jpath)
        check(len(evidence) == len(rows)
              and all(e["corpus"] == "journal" for e in evidence),
              "journal_rows() read-back diverged from the journal")

        # -- live windowed metrics: valid exposition + rate1m gauges,
        # and the /status live view
        mtext = client.metrics_text()
        reason = obs_export.validate_prometheus_text(mtext)
        check(reason is None, f"/metrics failed validation: {reason}")
        for rname in ("jepsen_serve_requests_rate1m",
                      "jepsen_serve_histories_rate1m"):
            check(f"# TYPE {rname} gauge" in mtext,
                  f"/metrics missing live {rname} gauge")
        check("jepsen_serve_queue_wait_seconds" in mtext,
              "/metrics missing the queue-wait histogram")
        live = st.get("live") or {}
        check(isinstance(live.get("requests_per_s"), (int, float))
              and live["requests_per_s"] > 0,
              f"live view missing request rate: {live}")
        check(live.get("queue_wait_mean_s") is not None,
              f"live view missing queue-wait mean: {live}")

        # -- the fleet view renders from a live daemon
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli.run_cli(cli.serve_cmd(), [
                "top", "--port", str(daemon.port), "--once"])
        top_out = buf.getvalue()
        check(rc == 0, f"top --once exited {rc}")
        check("last 60s" in top_out and "journal" in top_out,
              f"top --once frame incomplete: {top_out!r}")
    finally:
        daemon.stop()
        obs_journal.configure(None)
        shutil.rmtree(tmp, ignore_errors=True)

    if failures:
        for f_ in failures:
            print(f"obs-fleet-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "obs-fleet-smoke: ok (stitched cross-seam traces with flow "
        "events, /trace span dump, schema-valid dispatch journal with "
        "coalescing evidence + journal_rows read-back, live *_rate1m "
        "gauges + queue-wait, top --once)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
