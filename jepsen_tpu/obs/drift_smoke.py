"""Drift-sentinel smoke check: ``python -m jepsen_tpu.obs.drift_smoke``.

The end-to-end drift drill (doc/observability.md "Drift sentinel"):
a synthetic dispatch journal holds four shapes' worth of schema-valid
rows, one shape's measured ``execute_s`` inflated 3× over what the
cost model predicts.  A resident checker daemon warm-scans that
journal at start, and the gates assert:

- the sentinel flags the inflated shape and ONLY that shape (no false
  positives on the three healthy shapes), with the aggregate score
  ~3× and the retune recommendation latched exactly once;
- the recommendation is durable: a ``drift-retune`` marker row landed
  in the journal itself;
- the drift block is visible on every surface — ``/status``, the
  rendered ``status`` table (RETUNE RECOMMENDED call-out),
  ``jepsen_tpu top --once`` (drift + quarantined columns), and the
  ``jepsen_drift_*`` gauges on a Prometheus-valid ``/metrics``;
- ``POST /profile`` round-trips: the capture directory holds a
  loadable manifest with a per-device memory inventory (trace
  collection itself is best-effort off-TPU).

Wired into ``make drift-smoke`` / ``make check``.  Exit codes: 0 ok,
1 any gate failed.
"""

from __future__ import annotations

import contextlib
import io
import os
import shutil
import sys
import tempfile

#: the four synthetic dispatch shapes: (E, healthy-or-inflated scale)
_SHAPES = ((8, 1.0), (16, 1.0), (32, 1.0), (64, 3.0))
_INFLATED_E = 64
_ROWS_PER_SHAPE = 5
_SECONDS_PER_COST = 1e-6  # healthy seconds per analytic-proxy unit


def _write_journal(jpath: str) -> None:
    """Schema-valid rows through the real emit path: per shape,
    ``execute_s`` = analytic proxy × the shape's scale — so ratios are
    exactly 1.0 healthy, 3.0 inflated, with zero measurement noise."""
    from jepsen_tpu.obs import drift as obs_drift
    from jepsen_tpu.obs import journal as obs_journal

    obs_journal.configure(jpath)
    try:
        for E, scale in _SHAPES:
            cost = obs_drift.analytic_proxy("dense", E, 2, 0, 256)
            for _ in range(_ROWS_PER_SHAPE):
                row = obs_journal.emit(
                    kernel="dense", E=E, C=2, F=0, rows=256,
                    n_devices=1, mesh_shape=[1], window=4,
                    compile_s=0.0,
                    execute_s=round(cost * scale * _SECONDS_PER_COST, 6),
                    coalesced=1, cache="hit", closure_mode="",
                    union="", calibration="", trace_id="",
                )
                assert row is not None, "synthetic journal emit dropped"
    finally:
        obs_journal.configure(None)


def main(argv=None) -> int:
    from jepsen_tpu import cli, obs
    from jepsen_tpu.obs import drift as obs_drift
    from jepsen_tpu.obs import export as obs_export
    from jepsen_tpu.obs import journal as obs_journal
    from jepsen_tpu.obs import profiling as obs_profiling
    from jepsen_tpu.serve import CheckerDaemon, ServiceClient, client \
        as client_mod

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    obs.enable(reset=True)
    tmp = tempfile.mkdtemp(prefix="jt-drift-smoke-")
    jpath = os.path.join(tmp, obs_journal.DEFAULT_FILENAME)
    _write_journal(jpath)

    daemon = CheckerDaemon(port=0, journal_path=jpath,
                           profile_dir=os.path.join(tmp, "profiles"))
    daemon.start(block=False)
    try:
        client = ServiceClient(port=daemon.port)
        check(client.healthy(), "daemon did not come up healthy")

        # -- the sentinel flagged the inflated shape, and only it
        st = daemon.status()
        drift = st.get("drift")
        check(isinstance(drift, dict), f"/status has no drift block: {st}")
        drift = drift or {}
        stale = drift.get("stale") or []
        check(len(stale) == 1,
              f"expected exactly 1 stale shape, got {stale}")
        check(stale and stale[0].get("E") == _INFLATED_E,
              f"wrong shape flagged: {stale}")
        score = drift.get("score")
        check(isinstance(score, (int, float)) and 2.5 <= score <= 3.5,
              f"aggregate score should be ~3.0, got {score}")
        check(drift.get("retune_recommended") is True,
              f"retune flag not set: {drift}")
        check(drift.get("crossings") == 1,
              f"one sustained episode must latch one crossing: {drift}")
        check(drift.get("rows_scored")
              == len(_SHAPES) * _ROWS_PER_SHAPE,
              f"row accounting off: {drift}")

        # -- durable recommendation: the marker row is in the journal
        rows = list(obs_journal.read_rows(jpath))
        markers = [r for r in rows
                   if r.get("kernel") == obs_drift.MARKER_KERNEL]
        check(len(markers) == 1,
              f"expected 1 drift-retune marker row, got {len(markers)}")
        check(markers and "drift-score=" in markers[0].get("trace_id", ""),
              f"marker row carries no score: {markers}")

        # -- every operator surface shows it
        rendered = client_mod.format_status(st)
        check("RETUNE RECOMMENDED" in rendered,
              f"status table missing the retune call-out:\n{rendered}")
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli.run_cli(cli.serve_cmd(), [
                "top", "--port", str(daemon.port), "--once"])
        top_out = buf.getvalue()
        check(rc == 0, f"top --once exited {rc}")
        check("drift" in top_out and "quarantined" in top_out,
              f"top --once missing drift/quarantine columns: {top_out!r}")
        mtext = client.metrics_text()
        reason = obs_export.validate_prometheus_text(mtext)
        check(reason is None, f"/metrics failed validation: {reason}")
        for gname in ("jepsen_drift_score",
                      "jepsen_drift_stale_shapes",
                      "jepsen_drift_retune_recommended"):
            check(f"# TYPE {gname} gauge" in mtext,
                  f"/metrics missing {gname} gauge")
        check("jepsen_drift_ratio" in mtext,
              "/metrics missing the per-shape ratio gauge")

        # -- /profile round-trip: loadable manifest + memory inventory
        pdir = os.path.join(tmp, "capture")
        out = client.profile(seconds=0.1, label="smoke", out_dir=pdir)
        check(out.get("ok") is True and out.get("dir") == pdir,
              f"/profile answered {out}")
        man = obs_profiling.load_manifest(pdir)
        check(man is not None and man.get("label") == "smoke",
              f"capture manifest not loadable: {man}")
        check(isinstance((man or {}).get("memory"), list),
              f"manifest missing the device memory inventory: {man}")
    finally:
        daemon.stop()
        obs_journal.configure(None)
        obs_drift.disable()
        shutil.rmtree(tmp, ignore_errors=True)

    if failures:
        for f_ in failures:
            print(f"drift-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "drift-smoke: ok (3×-inflated shape flagged with no false "
        "positives, one latched crossing + journal marker, drift on "
        "/status + status table + top + Prometheus, /profile "
        "round-trip manifest)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
