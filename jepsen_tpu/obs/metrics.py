"""Dependency-free metrics: counters, gauges, fixed-bucket histograms.

The registry is the numeric half of the observability layer
(jepsen_tpu.obs): spans answer "where did the time go", these answer
"how many / how big".  Everything is plain Python + threading — no
prometheus_client, no opentelemetry — because the harness must run in
the bare jax_graft container.  The export format IS the Prometheus
text exposition format (rendered by :func:`MetricsRegistry.prometheus_text`),
so a real scrape endpoint or push gateway could consume the dump
unchanged.

Instruments are keyed by (name, sorted label items): the registry
interns one instrument per key, so hot paths can resolve once and call
``inc``/``observe`` repeatedly — but only WITHIN one run:
``MetricsRegistry.reset()`` (invoked via ``obs.enable(reset=True)`` at
every ``core.run`` start) discards the intern table, so a handle cached
across runs mutates an orphan no export will ever see.  Resolve per
run (or per worker loop), never at module import.  Every mutator takes
the instrument lock — increments are a few hundred ns, far below the
op latencies they count — and checks the shared enabled flag first, so
a disabled registry costs one attribute read + branch per call.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

#: Default histogram bucket upper bounds, in seconds — spans the range
#: from a sub-ms kernel execute to a multi-minute compile/SSH install.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    __slots__ = ("name", "labels", "_lock", "_registry")

    def __init__(self, registry: "MetricsRegistry", name: str, labels: LabelKey):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._registry = registry


class Counter(_Instrument):
    __slots__ = ("value",)

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self.value = 0  # jt: guarded-by(_lock)

    def inc(self, n: int = 1) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self.value += n


class Gauge(_Instrument):
    __slots__ = ("value",)

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self.value = 0.0  # jt: guarded-by(_lock)

    def set(self, v: float) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self.value = v

    def set_max(self, v: float) -> None:
        """Record a high-water mark: keep the larger of current/new."""
        if not self._registry.enabled:
            return
        with self._lock:
            if v > self.value:
                self.value = v


class Histogram(_Instrument):
    """Fixed-boundary histogram: per-bucket counts + sum + count.
    Buckets are cumulative at render time (Prometheus ``le`` semantics);
    internally each slot counts only its own interval so ``observe`` is
    one bisect + three increments."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, registry, name, labels,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, labels)
        self.buckets = tuple(buckets)  # immutable after init: no guard
        self.counts = [0] * (len(self.buckets) + 1)  # jt: guarded-by(_lock)
        self.sum = 0.0  # jt: guarded-by(_lock)
        self.count = 0  # jt: guarded-by(_lock)

    def observe(self, v: float) -> None:
        if not self._registry.enabled:
            return
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def cumulative(self) -> List[int]:
        """Per-``le`` cumulative counts (the Prometheus rendering)."""
        out, acc = [], 0
        with self._lock:
            for c in self.counts:
                acc += c
                out.append(acc)
        return out


class MetricsRegistry:
    """Process-wide instrument registry with Prometheus text export."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, str, LabelKey], _Instrument] = {}  # jt: guarded-by(_lock)

    def _get(self, kind: str, cls, name: str, labels: Dict[str, str],
             **kw) -> _Instrument:
        key = (kind, name, _label_key(labels))
        # lock-free fast path: a GIL-atomic dict read; double-checked
        # under the lock below before any insert
        inst = self._instruments.get(key)  # jt: allow[lock-discipline]
        if inst is None:
            with self._lock:
                inst = self._instruments.get(key)
                if inst is None:
                    inst = cls(self, name, key[2], **kw)
                    self._instruments[key] = inst
        return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        return self._get("histogram", Histogram, name, labels,
                         buckets=buckets)

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()

    # -- introspection ----------------------------------------------------

    def snapshot(self) -> List[dict]:
        """All instruments as plain dicts (stable name/label order) —
        the source for both the Prometheus dump and the run summary."""
        with self._lock:
            items = sorted(self._instruments.items())
        out = []
        for (kind, name, labels), inst in items:
            d = {"kind": kind, "name": name, "labels": dict(labels)}
            if kind == "histogram":
                # one lock acquisition for counts+sum+count: reading
                # them separately could interleave with a concurrent
                # observe and render a +Inf bucket SMALLER than the
                # last le bucket (invalid Prometheus exposition)
                with inst._lock:
                    counts = list(inst.counts)
                    d["sum"] = inst.sum
                    d["count"] = inst.count
                cum, acc = [], 0
                for c in counts:
                    acc += c
                    cum.append(acc)
                d["buckets"] = list(zip(inst.buckets, cum))
            else:
                d["value"] = inst.value
            out.append(d)
        return out

    def value(self, name: str, **labels) -> Optional[float]:
        """Read one counter/gauge value (None when never recorded)."""
        for kind in ("counter", "gauge"):
            # GIL-atomic dict read, same rationale as _get's fast path;
            # the value itself is read under the instrument's own lock
            # (the lock its guarded-by annotation names)
            inst = self._instruments.get(  # jt: allow[lock-discipline]
                (kind, name, _label_key(labels)))
            if inst is not None:
                with inst._lock:
                    return inst.value
        return None

    def prometheus_text(self) -> str:
        """The Prometheus text exposition format (one TYPE line per
        metric family, samples with sorted labels)."""
        lines: List[str] = []
        seen_type: set = set()
        for d in self.snapshot():
            name, kind = d["name"], d["kind"]
            if name not in seen_type:
                lines.append(f"# TYPE {name} {kind}")
                seen_type.add(name)
            base_labels = d["labels"]
            if kind == "histogram":
                cum = d["buckets"]
                for le, c in cum:
                    lines.append(
                        _sample(name + "_bucket",
                                {**base_labels, "le": _fmt_le(le)}, c)
                    )
                lines.append(
                    _sample(name + "_bucket",
                            {**base_labels, "le": "+Inf"}, d["count"])
                )
                lines.append(_sample(name + "_sum", base_labels, d["sum"]))
                lines.append(_sample(name + "_count", base_labels, d["count"]))
            else:
                lines.append(_sample(name, base_labels, d["value"]))
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_le(v: float) -> str:
    s = repr(float(v))
    return s[:-2] if s.endswith(".0") else s


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _sample(name: str, labels: Dict[str, str], value) -> str:
    if labels:
        body = ",".join(
            f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{body}}} {_fmt_num(value)}"
    return f"{name} {_fmt_num(value)}"


def _fmt_num(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return repr(f) if f != int(f) else str(int(f))
