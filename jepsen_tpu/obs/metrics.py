"""Dependency-free metrics: counters, gauges, fixed-bucket histograms.

The registry is the numeric half of the observability layer
(jepsen_tpu.obs): spans answer "where did the time go", these answer
"how many / how big".  Everything is plain Python + threading — no
prometheus_client, no opentelemetry — because the harness must run in
the bare jax_graft container.  The export format IS the Prometheus
text exposition format (rendered by :func:`MetricsRegistry.prometheus_text`),
so a real scrape endpoint or push gateway could consume the dump
unchanged.

Instruments are keyed by (name, sorted label items): the registry
interns one instrument per key, so hot paths can resolve once and call
``inc``/``observe`` repeatedly — but only WITHIN one run:
``MetricsRegistry.reset()`` (invoked via ``obs.enable(reset=True)`` at
every ``core.run`` start) discards the intern table, so a handle cached
across runs mutates an orphan no export will ever see.  Resolve per
run (or per worker loop), never at module import.  Every mutator takes
the instrument lock — increments are a few hundred ns, far below the
op latencies they count — and checks the shared enabled flag first, so
a disabled registry costs one attribute read + branch per call.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

#: Default histogram bucket upper bounds, in seconds — spans the range
#: from a sub-ms kernel execute to a multi-minute compile/SSH install.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

#: Sliding-window geometry: a ring of fixed slots per instrument lets a
#: long-lived daemon answer "last 60 s" without unbounded history.
SLOT_SECONDS = 10
WINDOW_SLOTS = 6  # 6 × 10 s = the 1-minute window behind *_rate1m

#: series-cardinality cap (per metric name): overflow label sets fold
#: into one {overflow="1"} series instead of growing the registry
DEFAULT_MAX_SERIES = 512
OVERFLOW_LABELS: "LabelKey" = (("overflow", "1"),)
SERIES_DROPPED = "jepsen_obs_series_dropped_total"

#: window clock — module-level so tests can monkeypatch slot rollover
_now = time.monotonic

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _env_max_series() -> int:
    try:
        return int(os.environ.get("JEPSEN_TPU_OBS_MAX_SERIES",
                                  str(DEFAULT_MAX_SERIES)))
    except ValueError:
        return DEFAULT_MAX_SERIES


def rate1m_name(name: str) -> str:
    """Synthesized 1-minute-rate gauge name for a counter/histogram
    family: strip the unit suffix (``_total``/``_seconds``), append
    ``_rate1m``."""
    for suf in ("_total", "_seconds"):
        if name.endswith(suf):
            name = name[: -len(suf)]
            break
    return name + "_rate1m"


class _Instrument:
    __slots__ = ("name", "labels", "_lock", "_registry")

    def __init__(self, registry: "MetricsRegistry", name: str, labels: LabelKey):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._registry = registry


class _SlotRing:
    """Fixed ring of time slots accumulating (count, sum) deltas.

    Not self-locking: the owning instrument mutates/reads it under its
    own ``_lock`` (the ring is part of that instrument's state)."""

    __slots__ = ("ids", "counts", "sums")

    def __init__(self):
        self.ids = [-1] * WINDOW_SLOTS
        self.counts = [0] * WINDOW_SLOTS
        self.sums = [0.0] * WINDOW_SLOTS

    def add(self, n: int, v: float) -> None:
        slot = int(_now() // SLOT_SECONDS)
        i = slot % WINDOW_SLOTS
        if self.ids[i] != slot:  # ring wrapped: this slot is stale
            self.ids[i] = slot
            self.counts[i] = 0
            self.sums[i] = 0.0
        self.counts[i] += n
        self.sums[i] += v

    def totals(self) -> Tuple[int, float]:
        """(count, sum) over the live window — current partial slot
        plus the WINDOW_SLOTS-1 full slots behind it."""
        lo = int(_now() // SLOT_SECONDS) - WINDOW_SLOTS + 1
        n, s = 0, 0.0
        for i in range(WINDOW_SLOTS):
            if self.ids[i] >= lo:
                n += self.counts[i]
                s += self.sums[i]
        return n, s


class Counter(_Instrument):
    __slots__ = ("value", "_win")

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self.value = 0  # jt: guarded-by(_lock)
        self._win = _SlotRing()  # jt: guarded-by(_lock)

    def inc(self, n: int = 1) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self.value += n
            self._win.add(n, float(n))

    def window_sum(self) -> int:
        """Increments landed in the last WINDOW_SLOTS×SLOT_SECONDS."""
        with self._lock:
            return self._win.totals()[0]


class Gauge(_Instrument):
    __slots__ = ("value",)

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self.value = 0.0  # jt: guarded-by(_lock)

    def set(self, v: float) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self.value = v

    def set_max(self, v: float) -> None:
        """Record a high-water mark: keep the larger of current/new."""
        if not self._registry.enabled:
            return
        with self._lock:
            if v > self.value:
                self.value = v


class Histogram(_Instrument):
    """Fixed-boundary histogram: per-bucket counts + sum + count.
    Buckets are cumulative at render time (Prometheus ``le`` semantics);
    internally each slot counts only its own interval so ``observe`` is
    one bisect + three increments."""

    __slots__ = ("buckets", "counts", "sum", "count", "_win")

    def __init__(self, registry, name, labels,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, labels)
        self.buckets = tuple(buckets)  # immutable after init: no guard
        self.counts = [0] * (len(self.buckets) + 1)  # jt: guarded-by(_lock)
        self.sum = 0.0  # jt: guarded-by(_lock)
        self.count = 0  # jt: guarded-by(_lock)
        self._win = _SlotRing()  # jt: guarded-by(_lock)

    def observe(self, v: float) -> None:
        if not self._registry.enabled:
            return
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            self._win.add(1, v)

    def window_totals(self) -> Tuple[int, float]:
        """(observations, summed value) over the live window."""
        with self._lock:
            return self._win.totals()

    def cumulative(self) -> List[int]:
        """Per-``le`` cumulative counts (the Prometheus rendering)."""
        out, acc = [], 0
        with self._lock:
            for c in self.counts:
                acc += c
                out.append(acc)
        return out


class MetricsRegistry:
    """Process-wide instrument registry with Prometheus text export."""

    def __init__(self, enabled: bool = True,
                 max_series: Optional[int] = None):
        self.enabled = enabled
        self.max_series = (_env_max_series() if max_series is None
                           else max_series)
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, str, LabelKey], _Instrument] = {}  # jt: guarded-by(_lock)
        self._series: Dict[Tuple[str, str], int] = {}  # jt: guarded-by(_lock)

    def _get(self, kind: str, cls, name: str, labels: Dict[str, str],
             **kw) -> _Instrument:
        key = (kind, name, _label_key(labels))
        # lock-free fast path: a GIL-atomic dict read; double-checked
        # under the lock below before any insert
        inst = self._instruments.get(key)  # jt: allow[lock-discipline]
        if inst is None:
            with self._lock:
                inst = self._instruments.get(key)
                if inst is None:
                    fam = (kind, name)
                    n_series = self._series.get(fam, 0)
                    if (n_series >= self.max_series
                            and key[2] != OVERFLOW_LABELS
                            and name != SERIES_DROPPED):
                        # cardinality cap: fold this (and every later)
                        # novel label set into the overflow series so
                        # a long-lived daemon's registry stays bounded
                        inst = self._overflow_locked(kind, cls, name, **kw)
                    else:
                        inst = cls(self, name, key[2], **kw)
                        self._instruments[key] = inst
                        self._series[fam] = n_series + 1
        return inst

    # jt: holds(_lock)
    def _overflow_locked(self, kind: str, cls, name: str,
                         **kw) -> _Instrument:
        """Intern the {overflow="1"} series + bump the drop counter.
        Caller holds self._lock; instrument locks are leaves (they
        never take the registry lock), so nesting is safe."""
        okey = (kind, name, OVERFLOW_LABELS)
        inst = self._instruments.get(okey)
        if inst is None:
            inst = cls(self, name, OVERFLOW_LABELS, **kw)
            self._instruments[okey] = inst
        dkey = ("counter", SERIES_DROPPED, ())
        dropped = self._instruments.get(dkey)
        if dropped is None:
            dropped = Counter(self, SERIES_DROPPED, ())
            self._instruments[dkey] = dropped
            self._series[("counter", SERIES_DROPPED)] = 1
        dropped.inc()
        return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        return self._get("histogram", Histogram, name, labels,
                         buckets=buckets)

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()
            self._series.clear()

    # -- introspection ----------------------------------------------------

    def snapshot(self) -> List[dict]:
        """All instruments as plain dicts (stable name/label order) —
        the source for both the Prometheus dump and the run summary."""
        with self._lock:
            items = sorted(self._instruments.items())
        out = []
        for (kind, name, labels), inst in items:
            d = {"kind": kind, "name": name, "labels": dict(labels)}
            if kind == "histogram":
                # one lock acquisition for counts+sum+count: reading
                # them separately could interleave with a concurrent
                # observe and render a +Inf bucket SMALLER than the
                # last le bucket (invalid Prometheus exposition)
                with inst._lock:
                    counts = list(inst.counts)
                    d["sum"] = inst.sum
                    d["count"] = inst.count
                    wn, ws = inst._win.totals()
                cum, acc = [], 0
                for c in counts:
                    acc += c
                    cum.append(acc)
                d["buckets"] = list(zip(inst.buckets, cum))
                d["win_count"], d["win_sum"] = wn, ws
            else:
                d["value"] = inst.value
                if kind == "counter":
                    d["win_count"] = inst.window_sum()
            out.append(d)
        return out

    # -- windowed aggregation (the /status "live" numbers) ----------------

    def window_rate(self, name: str, kind: Optional[str] = None) -> float:
        """Per-second rate over the last minute, summed across every
        label set of ``name``: counter increments, or histogram
        observation counts."""
        total = 0
        with self._lock:
            insts = [(k[0], inst) for k, inst in self._instruments.items()
                     if k[1] == name and (kind is None or k[0] == kind)]
        for k, inst in insts:
            if k == "counter":
                total += inst.window_sum()
            elif k == "histogram":
                total += inst.window_totals()[0]
        return total / float(WINDOW_SLOTS * SLOT_SECONDS)

    def window_mean(self, name: str) -> Optional[float]:
        """Mean observed value over the last minute across every label
        set of histogram ``name`` (None when the window is empty)."""
        n, s = 0, 0.0
        with self._lock:
            insts = [inst for k, inst in self._instruments.items()
                     if k[1] == name and k[0] == "histogram"]
        for inst in insts:
            wn, ws = inst.window_totals()
            n += wn
            s += ws
        return (s / n) if n else None

    def window_seconds_sum(self, name: str) -> float:
        """Summed observed seconds over the last minute across every
        label set of histogram ``name`` — busy-fraction numerator."""
        s = 0.0
        with self._lock:
            insts = [inst for k, inst in self._instruments.items()
                     if k[1] == name and k[0] == "histogram"]
        for inst in insts:
            s += inst.window_totals()[1]
        return s

    def value(self, name: str, **labels) -> Optional[float]:
        """Read one counter/gauge value (None when never recorded)."""
        for kind in ("counter", "gauge"):
            # GIL-atomic dict read, same rationale as _get's fast path;
            # the value itself is read under the instrument's own lock
            # (the lock its guarded-by annotation names)
            inst = self._instruments.get(  # jt: allow[lock-discipline]
                (kind, name, _label_key(labels)))
            if inst is not None:
                with inst._lock:
                    return inst.value
        return None

    def prometheus_text(self) -> str:
        """The Prometheus text exposition format (one TYPE line per
        metric family, samples with sorted labels)."""
        lines: List[str] = []
        seen_type: set = set()
        for d in self.snapshot():
            name, kind = d["name"], d["kind"]
            if name not in seen_type:
                lines.append(f"# TYPE {name} {kind}")
                seen_type.add(name)
            base_labels = d["labels"]
            if kind == "histogram":
                cum = d["buckets"]
                for le, c in cum:
                    lines.append(
                        _sample(name + "_bucket",
                                {**base_labels, "le": _fmt_le(le)}, c)
                    )
                lines.append(
                    _sample(name + "_bucket",
                            {**base_labels, "le": "+Inf"}, d["count"])
                )
                lines.append(_sample(name + "_sum", base_labels, d["sum"]))
                lines.append(_sample(name + "_count", base_labels, d["count"]))
            else:
                lines.append(_sample(name, base_labels, d["value"]))
        lines.extend(self._rate_lines())
        return "\n".join(lines) + ("\n" if lines else "")

    def _rate_lines(self) -> List[str]:
        """Synthesized ``*_rate1m`` gauge families: last-minute
        per-second rates for every counter (increments/s) and histogram
        (observations/s), one sample per underlying label set."""
        window = float(WINDOW_SLOTS * SLOT_SECONDS)
        lines: List[str] = []
        seen_type: set = set()
        seen_sample: set = set()
        for d in self.snapshot():
            if "win_count" not in d:
                continue
            rname = rate1m_name(d["name"])
            lkey = tuple(sorted(d["labels"].items()))
            if (rname, lkey) in seen_sample:
                continue  # counter+histogram families folding to one name
            seen_sample.add((rname, lkey))
            if rname not in seen_type:
                lines.append(f"# TYPE {rname} gauge")
                seen_type.add(rname)
            lines.append(
                _sample(rname, d["labels"],
                        round(d["win_count"] / window, 6)))
        return lines


def _fmt_le(v: float) -> str:
    s = repr(float(v))
    return s[:-2] if s.endswith(".0") else s


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _sample(name: str, labels: Dict[str, str], value) -> str:
    if labels:
        body = ",".join(
            f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{body}}} {_fmt_num(value)}"
    return f"{name} {_fmt_num(value)}"


def _fmt_num(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return repr(f) if f != int(f) else str(int(f))
