"""Exporters for the observability layer: Chrome trace, span JSONL,
Prometheus text dump, and the run-summary dict/table.

File inventory (written into the test's store directory by
``core.run`` alongside ``history.jsonl``/``results.json``):

- ``trace.json`` — Chrome ``trace_event`` format (the
  ``{"traceEvents": [...]}`` JSON object of complete-``"X"`` events).
  Open with ``chrome://tracing`` or https://ui.perfetto.dev.
- ``trace-spans.jsonl`` — one raw span record per line (monotonic-ns
  timestamps + attrs), for programmatic consumers.
- ``metrics.prom`` — Prometheus text exposition dump of every counter,
  gauge, and histogram recorded during the run.

``summary`` distills both into the dict embedded under
``results["obs"]`` and rendered by :func:`format_summary` as the CLI's
phase/engine breakdown table.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from .metrics import MetricsRegistry
from .tracer import SpanRecord, Tracer

TRACE_JSON = "trace.json"
SPANS_JSONL = "trace-spans.jsonl"
METRICS_PROM = "metrics.prom"


def chrome_trace(tracer: Tracer,
                 remote_spans: Optional[List[dict]] = None) -> dict:
    """Finished spans as a Chrome ``trace_event`` document.  Timestamps
    are microseconds from the tracer origin (complete events, ph="X").

    Remote spans adopted from a daemon (``obs.propagate``, fetched via
    ``GET /trace?ctx=`` at settle) are merged in wall-clock aligned,
    and trace_ctx-tagged request spans are stitched across the process
    boundary with Chrome flow events (ph "s"/"f") so the client→daemon
    hop renders as one connected arrow per run."""
    events: List[dict] = []
    origin = tracer.origin_ns
    for rec in tracer.finished():
        if rec.t1 is None:
            continue
        ev = {
            "name": rec.name,
            "cat": rec.cat or "span",
            "ph": "X",
            "ts": (rec.t0 - origin) / 1e3,
            "dur": (rec.t1 - rec.t0) / 1e3,
            "pid": rec.pid,
            "tid": rec.tid,
        }
        if rec.attrs:
            ev["args"] = dict(rec.attrs)
        events.append(ev)
    if remote_spans is None:
        from . import propagate

        remote_spans = propagate.adopted()
    for rec in remote_spans:
        ev = _remote_event(rec, tracer.wall_origin)
        if ev is not None:
            events.append(ev)
    events.extend(_flow_events(events))
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "jepsen_tpu.obs",
            "wall_origin": tracer.wall_origin,
            "dropped_spans": tracer.dropped,
        },
    }
    if tracer.run_anchor_ns is not None:
        doc["otherData"]["run_anchor_us"] = (
            (tracer.run_anchor_ns - origin) / 1e3
        )
    return doc


def _remote_event(rec: dict, wall_origin: float) -> Optional[dict]:
    """One adopted daemon span dict → a wall-clock-aligned local event.

    Local events sit at ``(t0 − origin_ns)/1e3`` µs, i.e. µs since this
    process's ``wall_origin``; a remote span's wall time is its own
    ``wall_origin + (t0 − origin_ns)/1e9``, so rebasing is one wall
    delta.  Spans missing alignment metadata are dropped, not guessed."""
    t0, t1 = rec.get("t0"), rec.get("t1")
    r_origin = rec.get("_remote_origin_ns")
    r_wall = rec.get("_remote_wall_origin")
    if None in (t0, t1, r_origin, r_wall):
        return None
    ev = {
        "name": rec.get("name", "?"),
        "cat": rec.get("cat") or "span",
        "ph": "X",
        "ts": (r_wall - wall_origin) * 1e6 + (t0 - r_origin) / 1e3,
        "dur": (t1 - t0) / 1e3,
        "pid": rec.get("pid", rec.get("_remote_pid", 0)),
        "tid": rec.get("tid", 0),
    }
    if rec.get("attrs"):
        ev["args"] = dict(rec["attrs"])
    return ev


def _flow_events(events: List[dict]) -> List[dict]:
    """Chrome flow events stitching trace_ctx-tagged request spans: one
    ph="s" at the client span, ph="t" steps through intermediate daemon
    spans, ph="f" (bp="e") at the last — all sharing the trace id."""
    starts: Dict[str, dict] = {}
    finishes: Dict[str, List[dict]] = {}
    for ev in events:
        args = ev.get("args") or {}
        trace_id, role = args.get("trace_id"), args.get("ctx_role")
        if not trace_id or not role:
            continue
        if role == "client":
            starts.setdefault(trace_id, ev)
        elif role == "daemon":
            finishes.setdefault(trace_id, []).append(ev)
    flows: List[dict] = []
    for trace_id in sorted(starts):
        sev = starts[trace_id]
        fevs = sorted(finishes.get(trace_id, []), key=lambda e: e["ts"])
        if not fevs:
            continue
        base = {"name": "trace_ctx", "cat": "trace_ctx", "id": trace_id}
        flows.append({**base, "ph": "s", "ts": sev["ts"],
                      "pid": sev["pid"], "tid": sev["tid"]})
        for fev in fevs[:-1]:
            flows.append({**base, "ph": "t", "ts": fev["ts"],
                          "pid": fev["pid"], "tid": fev["tid"]})
        last = fevs[-1]
        flows.append({**base, "ph": "f", "bp": "e", "ts": last["ts"],
                      "pid": last["pid"], "tid": last["tid"]})
    return flows


def write_chrome_trace(tracer: Tracer, path: str) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(tracer), f)
    return path


def write_spans_jsonl(tracer: Tracer, path: str) -> str:
    with open(path, "w") as f:
        for rec in tracer.finished():
            f.write(json.dumps(rec.to_dict()) + "\n")
    return path


def render_prom(registry: Optional[MetricsRegistry] = None) -> str:
    """Incremental registry → Prometheus text exposition, no file I/O.
    The ONE formatter behind both the at-exit ``metrics.prom`` store
    artifact and the checker-service daemon's live ``/metrics``
    endpoint (jepsen_tpu.serve), so a scrape and a dump can never
    disagree about the same registry.  Defaults to the process
    registry."""
    if registry is None:
        from . import registry as _live_registry

        registry = _live_registry()
    return registry.prometheus_text()


def write_prometheus(registry: MetricsRegistry, path: str) -> str:
    with open(path, "w") as f:
        f.write(render_prom(registry))
    return path


def export_all(tracer: Tracer, registry: MetricsRegistry,
               directory: str) -> Dict[str, str]:
    """Write all three artifacts into ``directory``; returns the paths."""
    os.makedirs(directory, exist_ok=True)
    return {
        "trace": write_chrome_trace(
            tracer, os.path.join(directory, TRACE_JSON)),
        "spans": write_spans_jsonl(
            tracer, os.path.join(directory, SPANS_JSONL)),
        "metrics": write_prometheus(
            registry, os.path.join(directory, METRICS_PROM)),
    }


# ---------------------------------------------------------------------------
# Summary
# ---------------------------------------------------------------------------


def _phase_rows(tracer: Tracer) -> List[dict]:
    rows = []
    for rec in tracer.finished(cat="phase"):
        if rec.t1 is None:
            continue
        rows.append({
            "name": rec.name,
            "wall_s": round(rec.duration_s(), 4),
            "start_ns": rec.t0,
            "end_ns": rec.t1,
        })
    rows.sort(key=lambda r: r["start_ns"])
    return rows


def _engine_rows(snapshot: List[dict]) -> Dict[str, dict]:
    """Fold the kernel/engine metric families into one row per engine:
    rows checked, compile (first-dispatch) and execute seconds, dispatch
    counts, oracle timings."""
    engines: Dict[str, dict] = {}

    def row(engine: str) -> dict:
        # escalation rungs ARE frontier work: fold their timings into
        # the frontier row (their histories are counted there too);
        # jepsen_engine_escalations_total keeps the rung detail
        if engine == "frontier-escalated":
            engine = "frontier"
        return engines.setdefault(engine, {"rows": 0})

    for d in snapshot:
        name, labels = d["name"], d["labels"]
        if name == "jepsen_engine_rows_total":
            row(labels.get("engine", "?"))["rows"] = (
                row(labels.get("engine", "?")).get("rows", 0) + d["value"]
            )
        elif name == "jepsen_kernel_compile_seconds":
            r = row(labels.get("engine", "?"))
            r["compile_s"] = round(r.get("compile_s", 0.0) + d["sum"], 4)
            r["compile_dispatches"] = (
                r.get("compile_dispatches", 0) + d["count"]
            )
        elif name == "jepsen_kernel_execute_seconds":
            r = row(labels.get("engine", "?"))
            r["execute_s"] = round(r.get("execute_s", 0.0) + d["sum"], 4)
            r["execute_dispatches"] = (
                r.get("execute_dispatches", 0) + d["count"]
            )
        elif name == "jepsen_oracle_seconds":
            r = row("oracle")
            r["execute_s"] = round(r.get("execute_s", 0.0) + d["sum"], 4)
            r["analyses"] = r.get("analyses", 0) + d["count"]
    return engines


def summary(tracer: Tracer, registry: MetricsRegistry) -> dict:
    """The run-summary dict embedded in ``results["obs"]``: phase wall
    times, per-engine rows + compile/execute seconds, op counters,
    frontier telemetry, and span accounting."""
    snapshot = registry.snapshot()
    ops: Dict[str, int] = {}
    nemesis_ops = 0
    retries = 0
    for d in snapshot:
        if d["name"] == "jepsen_interpreter_ops_total":
            t = d["labels"].get("type", "?")
            ops[t] = ops.get(t, 0) + d["value"]
        elif d["name"] == "jepsen_nemesis_ops_total":
            nemesis_ops += d["value"]
        elif d["name"] == "jepsen_remote_retries_total":
            retries += d["value"]
    out = {
        "phases": _phase_rows(tracer),
        "engines": _engine_rows(snapshot),
        "ops": ops,
        "nemesis-ops": nemesis_ops,
        "remote-retries": retries,
        "spans": len(tracer),
        "spans-dropped": tracer.dropped,
    }
    hw = registry.value("jepsen_frontier_high_water")
    if hw is not None:
        out["frontier-high-water"] = hw
    budget = registry.value("jepsen_frontier_dispatch_budget_used_ratio")
    if budget is not None:
        out["frontier-dispatch-budget-used"] = round(budget, 4)
    # pipelined-engine occupancy (jepsen_tpu.engine): peak in-flight
    # dispatch depth (>1 proves overlap happened), peak shape-bucket
    # count, and the last run's 1 − bubble/wall occupancy ratio
    depth = registry.value("jepsen_engine_inflight_depth")
    if depth is not None:
        out["engine-inflight-depth"] = int(depth)
    nb = registry.value("jepsen_engine_bucket_count")
    if nb is not None:
        out["engine-buckets"] = int(nb)
    occ = registry.value("jepsen_engine_occupancy_ratio")
    if occ is not None:
        out["engine-occupancy"] = round(occ, 4)
    # online-checking latency: seconds from the run's wall origin to
    # the first settled verdict / first violation verdict (the gauges
    # set once by engine.planning as partitions settle)
    ttfv = registry.value("jepsen_run_first_verdict_seconds")
    if ttfv is not None:
        out["time-to-first-verdict"] = round(ttfv, 4)
    ttv = registry.value("jepsen_run_first_violation_seconds")
    if ttv is not None:
        out["time-to-violation"] = round(ttv, 4)
    # cost-model drift sentinel (obs.drift): the aggregate residual
    # score and the retune recommendation become durable in
    # results.json["obs"], so a stored run records that its estimates
    # had gone stale — not just the live /status view
    ds = registry.value("jepsen_drift_score")
    if ds is not None:
        out["drift-score"] = round(ds, 4)
    stale = registry.value("jepsen_drift_stale_shapes")
    if stale is not None:
        out["drift-stale-shapes"] = int(stale)
    rec = registry.value("jepsen_drift_retune_recommended")
    if rec is not None:
        out["retune-recommended"] = bool(rec)
    return out


def format_summary(s: dict) -> str:
    """Render the summary as the CLI's breakdown table."""
    lines: List[str] = []
    phases = s.get("phases") or []
    if phases:
        lines.append("── run phases " + "─" * 34)
        for p in phases:
            lines.append(f"  {p['name']:<28} {p['wall_s']:>10.3f} s")
    engines = s.get("engines") or {}
    if engines:
        lines.append("── checker engines " + "─" * 29)
        lines.append(
            f"  {'engine':<18}{'rows':>8}{'compile s':>12}{'execute s':>12}"
        )
        for name in sorted(engines):
            e = engines[name]
            comp = e.get("compile_s")
            exe = e.get("execute_s")
            lines.append(
                f"  {name:<18}{int(e.get('rows', 0)):>8}"
                f"{comp if comp is not None else '—':>12}"
                f"{exe if exe is not None else '—':>12}"
            )
    ops = s.get("ops") or {}
    if ops:
        opline = ", ".join(f"{v} {k}" for k, v in sorted(ops.items()))
        lines.append(f"  ops: {opline}")
    extras = []
    if s.get("nemesis-ops"):
        extras.append(f"nemesis ops: {s['nemesis-ops']}")
    if s.get("remote-retries"):
        extras.append(f"remote retries: {s['remote-retries']}")
    if s.get("frontier-high-water") is not None:
        extras.append(f"frontier high-water: {int(s['frontier-high-water'])}")
    if s.get("engine-inflight-depth") is not None:
        pipe = f"pipeline depth: {s['engine-inflight-depth']}"
        if s.get("engine-occupancy") is not None:
            pipe += f", occupancy: {s['engine-occupancy']:.0%}"
        extras.append(pipe)
    if s.get("time-to-first-verdict") is not None:
        online = f"first verdict: {s['time-to-first-verdict']:.3f}s"
        if s.get("time-to-violation") is not None:
            online += f", first violation: {s['time-to-violation']:.3f}s"
        extras.append(online)
    if s.get("spans-dropped"):
        extras.append(f"spans dropped: {s['spans-dropped']}")
    if extras:
        lines.append("  " + "; ".join(extras))
    lines.append(f"  spans recorded: {s.get('spans', 0)}")
    return "\n".join(lines)


def validate_chrome_trace(path: str) -> Optional[str]:
    """Sanity-check a trace.json: returns None when valid, else a
    human-readable reason (used by the trace-smoke make target)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return f"unreadable trace file: {e!r}"
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        return "traceEvents missing or empty"
    for ev in events:
        for k in ("name", "ph", "ts", "pid", "tid"):
            if k not in ev:
                return f"event missing {k!r}: {ev!r}"
        if ev["ph"] == "X" and "dur" not in ev:
            return f"complete event missing dur: {ev!r}"
    return None


def validate_prometheus(path: str) -> Optional[str]:
    """Sanity-check a metrics.prom dump: None when valid, else reason."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return f"unreadable metrics file: {e!r}"
    return validate_prometheus_text(text)


def validate_prometheus_text(text: str) -> Optional[str]:
    """Sanity-check Prometheus exposition text (a ``render_prom``
    result or a live ``/metrics`` scrape body): None when valid, else
    a human-readable reason.  Shared by the trace-smoke file check and
    the serve-smoke endpoint check."""
    samples = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(" ", 1)
        if len(parts) != 2:
            return f"malformed sample line: {line!r}"
        try:
            float(parts[1])
        except ValueError:
            return f"non-numeric sample value: {line!r}"
        samples += 1
    if not samples:
        return "no metric samples recorded"
    return None
