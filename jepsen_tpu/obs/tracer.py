"""Span tracer: nestable, thread-aware, bounded, dependency-free.

A span is one timed region of the run — a lifecycle phase, a worker op
invoke, a nemesis fault, a checker, a kernel dispatch.  Spans nest per
thread (each thread keeps its own stack, so a worker's ``op`` span
parents any ``control/exec`` spans the client issues), carry a
category + string attributes, and record monotonic-nanosecond
timestamps so durations are immune to wall-clock steps (the clock
discipline :mod:`jepsen_tpu.util`'s relative clock already uses).

Finished spans land in one bounded, lock-protected buffer.  When the
buffer fills, further spans are *counted as dropped* rather than
grown without limit — a runaway generator can't OOM the harness
through its own telemetry.  Exports (Chrome ``trace_event`` JSON,
span JSONL) read the buffer snapshot; see :mod:`jepsen_tpu.obs.export`.

Cost contract: ``Tracer.span(...)`` when disabled returns a shared
null context — one branch, zero allocation — which is what lets the
interpreter hot loop keep the hook unconditionally.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

#: Span buffer capacity.  ~120 bytes/record ⇒ a full buffer is ~25 MB,
#: bounded regardless of run length.
DEFAULT_MAX_SPANS = 200_000


class SpanRecord:
    """One finished (or live) span.  ``t0``/``t1`` are raw
    ``time.monotonic_ns()`` stamps; exports rebase them on the tracer
    origin (trace-relative) or the run anchor (history-relative)."""

    __slots__ = ("name", "cat", "t0", "t1", "tid", "pid", "attrs", "sid",
                 "parent")

    def __init__(self, name: str, cat: str, tid: int, pid: int,
                 sid: int, parent: Optional[int], attrs: Optional[dict]):
        self.name = name
        self.cat = cat
        self.t0 = time.monotonic_ns()
        self.t1: Optional[int] = None
        self.tid = tid
        self.pid = pid
        self.sid = sid
        self.parent = parent
        self.attrs: Optional[Dict[str, str]] = attrs

    def set(self, k, v) -> None:
        """Attach/overwrite one attribute on the live span."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs[str(k)] = str(v)

    def duration_s(self) -> float:
        end = self.t1 if self.t1 is not None else time.monotonic_ns()
        return (end - self.t0) / 1e9

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "cat": self.cat,
            "t0": self.t0,
            "t1": self.t1,
            "tid": self.tid,
            "pid": self.pid,
            "sid": self.sid,
            "parent": self.parent,
            "attrs": self.attrs or {},
        }


class _NullSpan:
    """The shared disabled-mode span: supports the context-manager and
    ``set`` surface with zero allocation.  ``bool(null_span)`` is False
    so call sites can branch on the handle itself."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, k, v):
        pass

    def duration_s(self) -> float:
        return 0.0

    def __bool__(self):
        return False


NULL_SPAN = _NullSpan()


class _SpanCtx:
    """Context manager binding one SpanRecord to the thread's stack."""

    __slots__ = ("_tracer", "_rec")

    def __init__(self, tracer: "Tracer", rec: SpanRecord):
        self._tracer = tracer
        self._rec = rec

    def __enter__(self) -> SpanRecord:
        self._tracer._push(self._rec)
        # re-stamp t0 here so stack bookkeeping isn't inside the
        # measured region
        self._rec.t0 = time.monotonic_ns()
        return self._rec

    def __exit__(self, exc_type, exc, tb):
        rec = self._rec
        rec.t1 = time.monotonic_ns()
        if exc_type is not None:
            rec.set("error", exc_type.__name__)
        self._tracer._pop(rec)
        return False


class Tracer:
    def __init__(self, enabled: bool = True,
                 max_spans: int = DEFAULT_MAX_SPANS):
        self.enabled = enabled
        self.max_spans = max_spans
        self.origin_ns = time.monotonic_ns()
        self.wall_origin = time.time()
        #: monotonic ns of the run's t=0 (util.with_relative_time
        #: entry); lets exports align spans with history op times
        self.run_anchor_ns: Optional[int] = None
        self._lock = threading.Lock()
        self._spans: List[SpanRecord] = []  # jt: guarded-by(_lock)
        self._dropped = 0  # jt: guarded-by(_lock)
        self._next_sid = 0  # jt: guarded-by(_lock)
        self._local = threading.local()  # per-thread by construction

    # -- span lifecycle ----------------------------------------------------

    def span(self, name: str, cat: str = "", attrs: Optional[dict] = None):
        """A context manager recording one span; the shared null span
        when disabled (one branch, no allocation)."""
        if not self.enabled:
            return NULL_SPAN
        stack = self._stack()
        parent = stack[-1].sid if stack else None
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
        rec = SpanRecord(
            name, cat, threading.get_ident(), os.getpid(), sid, parent,
            # str-coerce like SpanRecord.set: attrs must stay
            # JSON-serializable for the exporters no matter what a
            # call site passes (numpy scalars, ops, …)
            {str(k): str(v) for k, v in attrs.items()} if attrs else None,
        )
        return _SpanCtx(self, rec)

    def current(self) -> Optional[SpanRecord]:
        stack = self._stack()
        return stack[-1] if stack else None

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, rec: SpanRecord) -> None:
        self._stack().append(rec)

    def _pop(self, rec: SpanRecord) -> None:
        stack = self._stack()
        if stack and stack[-1] is rec:
            stack.pop()
        elif rec in stack:  # tolerate mis-nested exits
            stack.remove(rec)
        with self._lock:
            if len(self._spans) < self.max_spans:
                self._spans.append(rec)
            else:
                self._dropped += 1

    # -- introspection -----------------------------------------------------

    def finished(self, cat: Optional[str] = None) -> List[SpanRecord]:
        """Snapshot of finished spans, in completion order."""
        with self._lock:
            spans = list(self._spans)
        if cat is not None:
            spans = [s for s in spans if s.cat == cat]
        return spans

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def reset(self) -> None:
        """Drop all recorded spans and re-anchor the trace origin.
        Thread-local stacks are untouched — live spans from other
        threads complete into the fresh buffer."""
        with self._lock:
            self._spans = []
            self._dropped = 0
            self._next_sid = 0
        self.origin_ns = time.monotonic_ns()
        self.wall_origin = time.time()
        self.run_anchor_ns = None
