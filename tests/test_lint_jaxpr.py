"""jaxpr-audit: the jtlint v3 certification pass (lint/jaxpr_audit.py).

Every ``jaxpr-*`` rule gets at least two positive fixtures (the rule
demonstrably catches a seeded violation) and one suppressed fixture,
plus the framework pins: determinism/fingerprint stability, the
incremental-cache round-trip (hit ≡ miss byte-identical, stale-hash
invalidation), the trace kill-switch, and the CLI contracts (rule
globbing, ``--changed``, subset-run baseline merging).

The traced rules run against *toy* registries injected through
``options["jaxpr_registry"]``: each entry anchors at a fixture file
written into tmp_path (the contract annotation and suppressions live
there) while the kernel itself is built in-process — exactly how the
default registry anchors at ops/cycles.py & co.
"""

import json
import os
import subprocess
import sys
import textwrap

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import pytest

from jepsen_tpu.lint import lint_paths
from jepsen_tpu.lint.jaxpr_audit import (KernelEntry, RULE_VERSION, Contract,
                                         eval_bound, parse_contract)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_lint(tmp_path, sources, rules=None, options=None):
    base = tmp_path
    for rel, code in sources.items():
        p = base / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(code))
    opts = {"metric_doc": None, "journal_doc": None, "env_doc": None}
    opts.update(options or {})
    return lint_paths([str(base)], rules=rules, options=opts)


def rules_of(result):
    return [f.rule for f in result.findings]


# ---------------------------------------------------------------------------
# toy kernels for the traced rules
# ---------------------------------------------------------------------------


def _args_f32(shape, batch):
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as SDS
    return (SDS((batch, 64), jnp.float32),)


def _scan_kernel(shape, knobs):
    """Scan carrying the (B, 64) float32 input: measured resident
    slope is exactly 256 bytes/row."""
    from jax import lax

    def f(x):
        def step(c, _):
            return c * 0.5, None

        c, _ = lax.scan(step, x, None, length=4)
        return c

    return f


def _dot_kernel(shape, knobs):
    import jax.numpy as jnp

    def f(x):
        return jnp.einsum("bi,bj->bij", x, x)

    return f


def _while_kernel_dtype(shape, knobs):
    """Carry dtype switches on the toy impl knob."""
    import jax.numpy as jnp
    from jax import lax

    as_int = knobs.get("impl") == "int"

    def f(x):
        c0 = x.astype(jnp.int32) if as_int else x

        def cond(c):
            return c[0, 0] < 100

        def body(c):
            return c + 1

        return lax.while_loop(cond, body, c0)

    return f


def _debug_print_kernel(shape, knobs):
    import jax

    def f(x):
        jax.debug.print("row {x}", x=x[0, 0])
        return x * 2

    return f


def _pure_callback_kernel(shape, knobs):
    import jax
    import numpy as np

    def f(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v).astype(np.float32),
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1

    return f


def _weak_capture_kernel(value):
    def build(shape, knobs):
        import jax.numpy as jnp

        c = jnp.asarray(value)  # weak-typed 0-d capture

        def f(x):
            return x * c

        return f

    return build


def toy_entry(name, scope, build, claimed=None, axes=None,
              path="kern/toy.py"):
    return KernelEntry(
        name, path, scope, build, _args_f32,
        axes=axes, shapes=({"n": 64},), claimed=claimed)


def anchor_src(*defs):
    """A fixture anchor module: one stub def per (name, directive)."""
    lines = ["def _noop(): ...", ""]
    for name, directive in defs:
        lines.append(f"def {name}(x):  {directive}")
        lines.append("    return x")
        lines.append("")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# jaxpr-budget
# ---------------------------------------------------------------------------


def test_budget_pricing_2x_under_fires(tmp_path):
    """The seeded mispricing: claimed per-row bytes 2x the measured
    resident slope, against a tight declared band."""
    entry = toy_entry("t", "kern_a", _scan_kernel,
                      claimed=lambda s, k: 512.0)
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: jaxpr(budget=0.8..1.2)")),
    }, options={"jaxpr_registry": [entry]})
    assert rules_of(res) == ["jaxpr-budget"]
    assert "0.50x" in res.findings[0].message


def test_budget_pricing_2x_over_fires(tmp_path):
    entry = toy_entry("t", "kern_a", _scan_kernel,
                      claimed=lambda s, k: 128.0)
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: jaxpr(budget=0.8..1.2)")),
    }, options={"jaxpr_registry": [entry]})
    assert rules_of(res) == ["jaxpr-budget"]
    assert "2.00x" in res.findings[0].message


def test_budget_correct_pricing_is_clean(tmp_path):
    entry = toy_entry("t", "kern_a", _scan_kernel,
                      claimed=lambda s, k: 256.0)
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: jaxpr(budget=0.8..1.2)")),
    }, options={"jaxpr_registry": [entry]})
    assert res.findings == []


def test_budget_suppressed(tmp_path):
    entry = toy_entry("t", "kern_a", _scan_kernel,
                      claimed=lambda s, k: 512.0)
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a",
             "# jt: allow[jaxpr-budget] jaxpr(budget=0.8..1.2)")),
    }, options={"jaxpr_registry": [entry]})
    assert res.findings == []


# ---------------------------------------------------------------------------
# jaxpr-shape-pin
# ---------------------------------------------------------------------------


def test_shape_pin_dot_count_fires(tmp_path):
    entry = toy_entry("t", "kern_a", _dot_kernel)
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: jaxpr(dot_generals<=0)")),
    }, options={"jaxpr_registry": [entry]})
    assert rules_of(res) == ["jaxpr-shape-pin"]
    assert "dot_generals<=0" in res.findings[0].message


def test_shape_pin_dot_bound_expression(tmp_path):
    """Bounds are expressions over the shape env (here n=64, so
    log2n=6 — 1 dot_general is within log2n-5 but not log2n-6)."""
    entry = toy_entry("t", "kern_a", _dot_kernel)
    ok = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: jaxpr(dot_generals<=log2n-5)")),
    }, options={"jaxpr_registry": [entry]})
    assert ok.findings == []
    bad = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: jaxpr(dot_generals<=log2n-6)")),
    }, options={"jaxpr_registry": [entry]})
    assert rules_of(bad) == ["jaxpr-shape-pin"]


def test_shape_pin_dtype_conditional_fires_per_combo(tmp_path):
    """dtype[KNOBVALUE]=DT checks only the matching combination."""
    entry = toy_entry("t", "kern_a", _while_kernel_dtype,
                      axes={"impl": ("float", "int")})
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a",
             "# jt: jaxpr(dtype[float]=float32, dtype[int]=uint8)")),
    }, options={"jaxpr_registry": [entry]})
    assert rules_of(res) == ["jaxpr-shape-pin"]
    assert "impl=int" in res.findings[0].message
    assert "int32" in res.findings[0].message


def test_shape_pin_suppressed(tmp_path):
    entry = toy_entry("t", "kern_a", _dot_kernel)
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a",
             "# jt: allow[jaxpr-shape-pin] jaxpr(dot_generals<=0)")),
    }, options={"jaxpr_registry": [entry]})
    assert res.findings == []


# ---------------------------------------------------------------------------
# jaxpr-host-sync
# ---------------------------------------------------------------------------


def test_host_sync_debug_print_fires(tmp_path):
    entry = toy_entry("t", "kern_a", _debug_print_kernel)
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(("kern_a", "# a plain comment")),
    }, options={"jaxpr_registry": [entry]})
    assert rules_of(res) == ["jaxpr-host-sync"]
    assert "callback" in res.findings[0].message


def test_host_sync_pure_callback_fires(tmp_path):
    entry = toy_entry("t", "kern_a", _pure_callback_kernel)
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(("kern_a", "# a plain comment")),
    }, options={"jaxpr_registry": [entry]})
    assert rules_of(res) == ["jaxpr-host-sync"]


def test_host_sync_suppressed(tmp_path):
    entry = toy_entry("t", "kern_a", _debug_print_kernel)
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: allow[jaxpr-host-sync] — debug build")),
    }, options={"jaxpr_registry": [entry]})
    assert res.findings == []


# ---------------------------------------------------------------------------
# jaxpr-retrace
# ---------------------------------------------------------------------------


def test_retrace_weak_float_capture_fires(tmp_path):
    entry = toy_entry("t", "kern_a", _weak_capture_kernel(3.0))
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(("kern_a", "# a plain comment")),
    }, options={"jaxpr_registry": [entry]})
    assert rules_of(res) == ["jaxpr-retrace"]
    assert "weak-typed" in res.findings[0].message


def test_retrace_weak_int_capture_fires(tmp_path):
    entry = toy_entry("t", "kern_a", _weak_capture_kernel(7))
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(("kern_a", "# a plain comment")),
    }, options={"jaxpr_registry": [entry]})
    assert rules_of(res) == ["jaxpr-retrace"]


def test_retrace_suppressed(tmp_path):
    entry = toy_entry("t", "kern_a", _weak_capture_kernel(3.0))
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: allow[jaxpr-retrace] — frozen constant")),
    }, options={"jaxpr_registry": [entry]})
    assert res.findings == []


# ---------------------------------------------------------------------------
# jaxpr-cache-key (pure AST — no tracing, no jax)
# ---------------------------------------------------------------------------


RESOLVER_IN_CACHED = """
    from functools import lru_cache
    import jax

    def my_mode():
        return resolve_knob("JEPSEN_TPU_X", str, lambda c: c.x(), "a")

    @lru_cache(maxsize=8)
    def factory(n):
        m = my_mode()
        return jax.jit(lambda x: x * (m == "a"))
"""


def test_cache_key_resolver_inside_cached_factory(tmp_path):
    res = run_lint(tmp_path, {"ops/k.py": RESOLVER_IN_CACHED},
                   rules=["jaxpr-cache-key"])
    assert rules_of(res) == ["jaxpr-cache-key"]
    assert "bypasses the cache key" in res.findings[0].message


RESOLVER_NOT_PASSED = """
    from functools import lru_cache
    import jax

    def my_mode():
        return resolve_knob("JEPSEN_TPU_X", str, lambda c: c.x(), "a")

    @lru_cache(maxsize=8)
    def _cached(n):
        return jax.jit(lambda x: x)

    def wrapper(n):
        m = my_mode()
        print(m)
        return _cached(n)
"""


def test_cache_key_resolved_knob_not_passed(tmp_path):
    res = run_lint(tmp_path, {"ops/k.py": RESOLVER_NOT_PASSED},
                   rules=["jaxpr-cache-key"])
    assert rules_of(res) == ["jaxpr-cache-key"]
    assert "not passed" in res.findings[0].message


KNOB_PARAM_UNSTAMPED = """
    from functools import lru_cache
    import jax

    @lru_cache(maxsize=8)
    def factory(n, impl):
        fn = jax.jit(lambda x: x)
        fn.safe_dispatch = 1024
        return fn
"""


def test_cache_key_knob_param_not_stamped(tmp_path):
    res = run_lint(tmp_path, {"ops/k.py": KNOB_PARAM_UNSTAMPED},
                   rules=["jaxpr-cache-key"])
    assert rules_of(res) == ["jaxpr-cache-key"]
    assert "closure_impl" in res.findings[0].message


SHARD_KEY_NARROW = """
    from functools import lru_cache
    import jax

    @lru_cache(maxsize=8)
    def factory(n, union):
        fn = jax.jit(lambda x: x)
        fn.union_mode = union
        return fn

    def shard_fn(check_fn, mesh):
        key = (mesh, getattr(check_fn, "closure_impl", ""))
        return key
"""


def test_cache_key_shard_key_narrower_than_lru_key(tmp_path):
    """The hardening target: a shard_fn call site keying on fewer
    fields than the kernel factories stamp."""
    res = run_lint(tmp_path, {"ops/k.py": SHARD_KEY_NARROW},
                   rules=["jaxpr-cache-key"])
    assert rules_of(res) == ["jaxpr-cache-key"]
    assert "union_mode" in res.findings[0].message
    assert "fewer fields" in res.findings[0].message


SANCTIONED = """
    from functools import lru_cache
    import jax

    def my_mode():
        return resolve_knob("JEPSEN_TPU_X", str, lambda c: c.x(), "a")

    @lru_cache(maxsize=8)
    def _cached(n, mode):
        fn = jax.jit(lambda x: x)
        fn.closure_mode = mode
        return fn

    def wrapper(n):
        mode = my_mode()
        return _cached(n, mode)

    def wrapper_direct(n):
        return _cached(n, my_mode())

    def shard_fn(check_fn, mesh):
        return (mesh, getattr(check_fn, "closure_mode", ""))
"""


def test_cache_key_sanctioned_pattern_is_clean(tmp_path):
    """Resolve-in-the-caller, pass-as-key-parameter, stamp, read back
    in shard_fn: the pattern ops/cycles.py & co. follow."""
    res = run_lint(tmp_path, {"ops/k.py": SANCTIONED},
                   rules=["jaxpr-cache-key"])
    assert res.findings == []


SUPPRESSED_CACHE_KEY = """
    from functools import lru_cache
    import jax

    def my_mode():
        return resolve_knob("JEPSEN_TPU_X", str, lambda c: c.x(), "a")

    @lru_cache(maxsize=8)
    def factory(n):
        m = my_mode()  # jt: allow[jaxpr-cache-key] — value only logged
        return jax.jit(lambda x: x)
"""


def test_cache_key_suppressed(tmp_path):
    res = run_lint(tmp_path, {"ops/k.py": SUPPRESSED_CACHE_KEY},
                   rules=["jaxpr-cache-key"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# jaxpr-coverage
# ---------------------------------------------------------------------------


def test_coverage_unregistered_traced_def_fires(tmp_path):
    entry = toy_entry("t", "known", _scan_kernel,
                      path="ops/step_kernels.py")
    res = run_lint(tmp_path, {
        "ops/step_kernels.py": (
            "def known(state, f, a, b):  # jt: traced\n"
            "    return state\n\n"
            "def rogue(state, f, a, b):  # jt: traced\n"
            "    return state\n"),
    }, rules=["jaxpr-coverage"], options={"jaxpr_registry": [entry]})
    assert rules_of(res) == ["jaxpr-coverage"]
    assert "`rogue`" in res.findings[0].message


def test_coverage_default_registry_module(tmp_path):
    """A traced def in a file shadowing a default-registry module path
    is judged against the default registry."""
    res = run_lint(tmp_path, {
        "ops/cycles.py": (
            "def new_screen(rel):  # jt: traced\n"
            "    return rel\n"),
    }, rules=["jaxpr-coverage"])
    assert rules_of(res) == ["jaxpr-coverage"]
    assert "`new_screen`" in res.findings[0].message


def test_coverage_suppressed(tmp_path):
    res = run_lint(tmp_path, {
        "ops/cycles.py": (
            "def new_screen(rel):  # jt: traced allow[jaxpr-coverage]\n"
            "    return rel\n"),
    }, rules=["jaxpr-coverage"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# contract grammar
# ---------------------------------------------------------------------------


def test_parse_contract_clauses():
    c = parse_contract([
        "jaxpr(dot_generals<=2*log2n+3, dtype[packed32]=uint32, "
        "dtype=bfloat16, budget=0.2..0.6)"])
    assert c.dot_generals == "2*log2n+3"
    assert c.dtypes == {"packed32": "uint32", None: "bfloat16"}
    assert c.budget == (0.2, 0.6)


def test_parse_contract_absent_and_unknown_clause():
    assert parse_contract(["allow[trace-sync]"]) is None
    c = parse_contract(["jaxpr(frobnicate=1, budget=1..2)"])
    assert isinstance(c, Contract) and c.budget == (1.0, 2.0)


def test_eval_bound():
    env = {"n": 64, "log2n": 6, "E": 16}
    assert eval_bound("2*log2n+3", env) == 15
    assert eval_bound("log2n-5", env) == 1
    assert eval_bound("2*E", env) == 32
    assert eval_bound("0", env) == 0
    assert eval_bound("q+1", env) is None          # unknown name
    assert eval_bound("__import__('os')", env) is None  # only +-*


# ---------------------------------------------------------------------------
# determinism + incremental cache
# ---------------------------------------------------------------------------


def _dump(result):
    return json.dumps([f.to_dict() for f in result.findings],
                      sort_keys=True)


def test_traced_findings_deterministic(tmp_path):
    entry = toy_entry("t", "kern_a", _dot_kernel,
                      axes={"impl": ("x", "y")})
    sources = {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: jaxpr(dot_generals<=0)")),
    }
    a = run_lint(tmp_path, sources, options={"jaxpr_registry": [entry]})
    b = run_lint(tmp_path, sources, options={"jaxpr_registry": [entry]})
    assert _dump(a) == _dump(b)
    assert len(a.findings) == 2  # one per knob combination
    assert ([f.fingerprint() for f in a.findings]
            == [f.fingerprint() for f in b.findings])


def test_cache_roundtrip_hit_equals_miss(tmp_path):
    cache = tmp_path / "jaxpr_cache.json"
    entry = toy_entry("t", "kern_a", _scan_kernel,
                      claimed=lambda s, k: 512.0)
    sources = {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: jaxpr(budget=0.8..1.2)")),
    }
    opts = {"jaxpr_registry": [entry], "jaxpr_cache": str(cache)}
    miss = run_lint(tmp_path, sources, options=opts)
    assert cache.exists()
    key1 = json.loads(cache.read_text())["key"]
    hit = run_lint(tmp_path, sources, options=opts)
    assert _dump(miss) == _dump(hit)
    assert rules_of(hit) == ["jaxpr-budget"]
    assert json.loads(cache.read_text())["key"] == key1


def test_cache_stale_hash_invalidation(tmp_path):
    cache = tmp_path / "jaxpr_cache.json"
    entry = toy_entry("t", "kern_a", _scan_kernel,
                      claimed=lambda s, k: 512.0)
    sources = {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: jaxpr(budget=0.8..1.2)")),
    }
    opts = {"jaxpr_registry": [entry], "jaxpr_cache": str(cache)}
    run_lint(tmp_path, sources, options=opts)
    key1 = json.loads(cache.read_text())["key"]
    # editing the anchor file invalidates the content hash; the edit
    # here suppresses the finding, and a stale cache would miss that
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a",
             "# jt: allow[jaxpr-budget] jaxpr(budget=0.8..1.2)")),
    }, options=opts)
    key2 = json.loads(cache.read_text())["key"]
    assert key2 != key1
    assert res.findings == []


def test_trace_kill_switch(tmp_path, monkeypatch):
    """JEPSEN_TPU_LINT_JAXPR=0 disables the traced rules; the AST
    rules still run."""
    monkeypatch.setenv("JEPSEN_TPU_LINT_JAXPR", "0")
    entry = toy_entry("t", "kern_a", _dot_kernel)
    res = run_lint(tmp_path, {
        "kern/toy.py": anchor_src(
            ("kern_a", "# jt: jaxpr(dot_generals<=0)")),
        "ops/k.py": RESOLVER_IN_CACHED,
    }, rules=["jaxpr-cache-key", "jaxpr-coverage", "jaxpr-budget",
              "jaxpr-shape-pin", "jaxpr-host-sync", "jaxpr-retrace"],
       options={"jaxpr_registry": [entry]})
    assert rules_of(res) == ["jaxpr-cache-key"]


def test_rule_version_in_cache_key(tmp_path):
    """The cache key binds the rule version (and this module's own
    source), so a lint upgrade re-traces."""
    assert RULE_VERSION  # bumping it is the documented invalidation
    cache = tmp_path / "jaxpr_cache.json"
    entry = toy_entry("t", "kern_a", _scan_kernel)
    run_lint(tmp_path, {"kern/toy.py": anchor_src(("kern_a", "# x"))},
             options={"jaxpr_registry": [entry],
                      "jaxpr_cache": str(cache)})
    data = json.loads(cache.read_text())
    assert data["version"] == 1 and len(data["key"]) == 40


# ---------------------------------------------------------------------------
# CLI: rule globbing, --changed, subset-run baseline merge
# ---------------------------------------------------------------------------


def _cli(*args, cwd=None, env_extra=None):
    return subprocess.run(
        [sys.executable, "-m", "jepsen_tpu.lint", *args],
        capture_output=True, text=True, cwd=cwd or REPO,
        env={**os.environ, "PYTHONPATH": REPO, **(env_extra or {})},
    )


def test_cli_rule_glob_expansion(tmp_path):
    (tmp_path / "k.py").write_text(textwrap.dedent(RESOLVER_IN_CACHED))
    proc = _cli(str(tmp_path), "--no-baseline", "--rules", "jaxpr-*")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "jaxpr-cache-key" in proc.stdout
    bad = _cli(str(tmp_path), "--no-baseline", "--rules", "jaxpr-zzz*")
    assert bad.returncode == 2
    assert "unknown rule" in bad.stderr


def test_cli_jaxpr_subset_merges_into_baseline(tmp_path):
    """--rules jaxpr-* subset runs merge with the committed baseline
    without clobbering other rules' entries (the PR-5 scoping
    contract): a full-run baseline stays green under a subset run and
    reports no stale entries for out-of-scope rules."""
    (tmp_path / "k.py").write_text(textwrap.dedent(RESOLVER_IN_CACHED))
    bl = tmp_path / "bl.json"
    full = _cli(str(tmp_path), "--baseline", str(bl), "--write-baseline")
    assert full.returncode == 0, full.stdout + full.stderr
    entries = json.loads(bl.read_text())["findings"]
    # the fixture trips a contracts-pass rule too (unregistered knob)
    assert {e["rule"] for e in entries} >= {"jaxpr-cache-key",
                                           "seam-env-read"}
    subset = _cli(str(tmp_path), "--baseline", str(bl),
                  "--rules", "jaxpr-*")
    assert subset.returncode == 0, subset.stdout + subset.stderr
    assert "stale" not in subset.stderr
    # the baseline file is untouched by a plain subset run
    assert json.loads(bl.read_text())["findings"] == entries


def test_cli_sarif_carries_jaxpr_rules(tmp_path):
    (tmp_path / "k.py").write_text(textwrap.dedent(RESOLVER_IN_CACHED))
    out = tmp_path / "out.sarif"
    proc = _cli(str(tmp_path), "--no-baseline", "--sarif", str(out))
    assert proc.returncode == 1
    sarif = json.loads(out.read_text())
    run = sarif["runs"][0]
    assert {"id": "jaxpr-cache-key"} in run["tool"]["driver"]["rules"]
    assert any(r["ruleId"] == "jaxpr-cache-key" for r in run["results"])


def test_cli_changed_limits_paths(tmp_path):
    """--changed lints only files that differ from HEAD (plus
    untracked); with nothing changed it exits 0 without scanning."""
    git = dict(cwd=str(tmp_path))
    for cmd in (["git", "init", "-q"],
                ["git", "config", "user.email", "t@t"],
                ["git", "config", "user.name", "t"]):
        subprocess.run(cmd, check=True, capture_output=True, **git)
    (tmp_path / "clean.py").write_text(
        textwrap.dedent(RESOLVER_IN_CACHED))  # committed: not re-linted
    subprocess.run(["git", "add", "-A"], check=True,
                   capture_output=True, **git)
    subprocess.run(["git", "commit", "-qm", "seed"], check=True,
                   capture_output=True, **git)
    all_clean = _cli(".", "--changed", "--no-baseline", cwd=str(tmp_path))
    assert all_clean.returncode == 0, all_clean.stdout + all_clean.stderr
    assert "no changed files" in all_clean.stdout
    (tmp_path / "dirty.py").write_text(
        textwrap.dedent(RESOLVER_IN_CACHED))
    changed = _cli(".", "--changed", "--no-baseline", cwd=str(tmp_path))
    assert changed.returncode == 1
    assert "dirty.py" in changed.stdout
    assert "clean.py" not in changed.stdout


def test_changed_subset_skips_whole_tree_env_check(tmp_path):
    """A --changed subset that includes the env registry must not fire
    the registered-but-never-read check — the readers are simply out
    of the scanned set.  The subset_scan option is the wiring."""
    sources = {"m.py": """
        import os

        def a():
            return os.environ.get("JEPSEN_TPU_A")
    """}
    doc = tmp_path / "conf.md"
    doc.write_text("| `JEPSEN_TPU_A` | | `JEPSEN_TPU_B` |\n")
    base = {"env_registry": ["JEPSEN_TPU_A", "JEPSEN_TPU_B"],
            "env_doc": str(doc)}
    full = run_lint(tmp_path, sources, rules=["seam-env-doc"],
                    options=base)
    assert [f.message for f in full.findings
            if "never read" in f.message]  # JEPSEN_TPU_B is unread
    subset = run_lint(tmp_path, sources, rules=["seam-env-doc"],
                      options={**base, "subset_scan": True})
    assert not [f.message for f in subset.findings
                if "never read" in f.message]


# ---------------------------------------------------------------------------
# the default registry against the real tree
# ---------------------------------------------------------------------------


def test_default_registry_anchors_every_entry():
    """Every default-registry entry anchors at a real def with the
    declared scope — a rename breaks the audit loudly, not silently."""
    from jepsen_tpu.lint.core import collect_files, Project
    from jepsen_tpu.lint.jaxpr_audit import JaxprAudit, default_registry

    files = collect_files([os.path.join(REPO, "jepsen_tpu")])
    project = Project(files, {})
    registry = default_registry()
    anchored = JaxprAudit()._anchor(project, registry)
    assert len(anchored) == len(registry)
    # the knob cross-product covers closure_impl x closure_mode x union
    axes = {k for e in registry for k in e.axes}
    assert axes == {"mode", "impl", "union", "compaction"}
    # every # jt: traced def in the registry modules is registered
    findings = []
    JaxprAudit()._check_coverage(project, registry, findings)
    assert findings == []
