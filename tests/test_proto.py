"""Wire-protocol client tests against in-process fake servers
(tests/fake_servers.py) speaking the real protocols over loopback."""

import pytest

from fake_servers import FakeMysql, FakePg, FakeRedis
from jepsen_tpu.suites.proto import IndeterminateError, ProtocolError
from jepsen_tpu.suites.proto.mysql import MysqlClient, MysqlError
from jepsen_tpu.suites.proto.pgwire import PgClient, PgError
from jepsen_tpu.suites.proto.resp import RespClient


# -- RESP -------------------------------------------------------------------


@pytest.fixture
def redis():
    srv = FakeRedis().start()
    yield srv
    srv.stop()


def test_resp_roundtrip(redis):
    c = RespClient("127.0.0.1", redis.port).connect()
    assert c.call("PING") == "PONG"
    assert c.call("SET", "x", "1") == "OK"
    assert c.call("GET", "x") == "1"
    assert c.call("GET", "missing") is None
    assert c.call("INCR", "x") == 2
    assert c.call("DEL", "x") == 1
    c.close()


def test_resp_sets_and_errors(redis):
    c = RespClient("127.0.0.1", redis.port).connect()
    assert c.call("SADD", "s", "a", "b") == 2
    assert c.call("SADD", "s", "b") == 0
    assert c.call("SMEMBERS", "s") == ["a", "b"]
    with pytest.raises(ProtocolError) as ei:
        c.call("NOPE")
    assert ei.value.code == "ERR"
    c.close()


def test_resp_disque_jobs(redis):
    c = RespClient("127.0.0.1", redis.port).connect()
    assert c.call("ADDJOB", "q1", "payload-1").startswith("DI-")
    got = c.call("GETJOB", "FROM", "q1")
    assert got[0][0] == "q1" and got[0][2] == "payload-1"
    assert c.call("GETJOB", "FROM", "q1") is None
    c.close()


def test_resp_dead_server_is_indeterminate(redis):
    c = RespClient("127.0.0.1", redis.port).connect()
    redis.stop()
    with pytest.raises((IndeterminateError, OSError)):
        for _ in range(3):  # first send may land in the OS buffer
            c.call("SET", "x", "1")


# -- Postgres wire ----------------------------------------------------------


@pytest.mark.parametrize("auth", ["trust", "cleartext", "md5", "scram"])
def test_pg_auth_modes(auth):
    srv = FakePg(auth_mode=auth, password="sekrit").start()
    try:
        c = PgClient(
            "127.0.0.1", srv.port, user="alice", password="sekrit"
        ).connect()
        res = c.query("SELECT 1")
        assert res.rows == [["1"]]
        c.close()
    finally:
        srv.stop()


def test_pg_bad_password_rejected():
    srv = FakePg(auth_mode="md5", password="right").start()
    try:
        with pytest.raises(PgError) as ei:
            PgClient("127.0.0.1", srv.port, password="wrong").connect()
        assert ei.value.code == "28P01"
    finally:
        srv.stop()


@pytest.fixture
def pg():
    srv = FakePg().start()
    c = PgClient("127.0.0.1", srv.port).connect()
    yield c
    c.close()
    srv.stop()


def test_pg_kv_roundtrip(pg):
    assert pg.query("INSERT INTO kv (key, val) VALUES ('a', '10')").command == "INSERT 0 1"
    r = pg.query("SELECT val FROM kv WHERE key='a'")
    assert r.columns == ["val"] and r.rows == [["10"]]
    assert pg.query("SELECT val FROM kv WHERE key='nope'").rows == []
    assert pg.query("UPDATE kv SET val='11' WHERE key='a'").command == "UPDATE 1"
    assert pg.query("SELECT val FROM kv WHERE key='a'").rows == [["11"]]


def test_pg_errors_carry_sqlstate(pg):
    pg.query("INSERT INTO kv (key, val) VALUES ('dup', '1')")
    with pytest.raises(PgError) as ei:
        pg.query("INSERT INTO kv (key, val) VALUES ('dup', '2')")
    assert ei.value.code == "23505"
    with pytest.raises(PgError) as ei:
        pg.query("SELECT boom")
    assert ei.value.serialization_failure
    # connection still usable after an error
    assert pg.query("SELECT 1").rows == [["1"]]


# -- MySQL ------------------------------------------------------------------


@pytest.fixture
def my():
    srv = FakeMysql(password="pw").start()
    c = MysqlClient("127.0.0.1", srv.port, user="root", password="pw").connect()
    yield c
    c.close()
    srv.stop()


def test_mysql_auth_and_select(my):
    r = my.query("SELECT 1")
    assert r.rows == [["1"]]


def test_mysql_bad_password():
    srv = FakeMysql(password="right").start()
    try:
        with pytest.raises(MysqlError) as ei:
            MysqlClient("127.0.0.1", srv.port, password="wrong").connect()
        assert ei.value.code == 1045
    finally:
        srv.stop()


def test_mysql_kv_roundtrip(my):
    r = my.query("INSERT INTO kv (key, val) VALUES ('a', '5')")
    assert r.affected_rows == 1
    r = my.query("SELECT val FROM kv WHERE key='a'")
    assert r.columns == ["val"] and r.rows == [["5"]]
    assert my.query("SELECT val FROM kv WHERE key='zzz'").rows == []
    assert my.query("UPDATE kv SET val='6' WHERE key='a'").affected_rows == 1


def test_mysql_errors_classified(my):
    with pytest.raises(MysqlError) as ei:
        my.query("SELECT boom")
    assert ei.value.code == 1213 and ei.value.retriable
    my.query("INSERT INTO kv (key, val) VALUES ('d', '1')")
    with pytest.raises(MysqlError) as ei:
        my.query("INSERT INTO kv (key, val) VALUES ('d', '2')")
    assert ei.value.code == 1062 and not ei.value.retriable
    # connection survives errors
    assert my.query("SELECT 1").rows == [["1"]]


# -- ZooKeeper --------------------------------------------------------------


@pytest.fixture
def zk():
    from fake_servers import FakeZk

    srv = FakeZk().start()
    from jepsen_tpu.suites.proto.zk import ZkClient

    c = ZkClient("127.0.0.1", srv.port).connect()
    yield c
    c.close()
    srv.stop()


def test_zk_session_and_crud(zk):
    from jepsen_tpu.suites.proto.zk import NO_NODE, NODE_EXISTS, ZkError

    assert zk.session_id != 0
    assert zk.create("/jepsen", b"0") == "/jepsen"
    with pytest.raises(ZkError) as ei:
        zk.create("/jepsen", b"1")
    assert ei.value.code == NODE_EXISTS
    data, stat = zk.get_data("/jepsen")
    assert data == b"0" and stat.version == 0
    stat2 = zk.set_data("/jepsen", b"5", version=0)
    assert stat2.version == 1
    assert zk.get_data("/jepsen")[0] == b"5"
    with pytest.raises(ZkError) as ei:
        zk.get_data("/none")
    assert ei.value.code == NO_NODE


def test_zk_cas_via_version(zk):
    from jepsen_tpu.suites.proto.zk import BAD_VERSION, ZkError

    zk.create("/r", b"a")
    zk.set_data("/r", b"b", version=0)
    # stale version CAS fails
    with pytest.raises(ZkError) as ei:
        zk.set_data("/r", b"c", version=0)
    assert ei.value.code == BAD_VERSION
    assert zk.get_data("/r")[0] == b"b"


def test_zk_children_and_delete(zk):
    zk.create("/q", b"")
    zk.create("/q/a", b"1")
    zk.create("/q/b", b"2")
    assert zk.get_children("/q") == ["a", "b"]
    zk.delete("/q/a")
    assert zk.get_children("/q") == ["b"]
    assert zk.exists("/q/a") is None
    assert zk.exists("/q/b") is not None


# -- BSON / MongoDB ---------------------------------------------------------


def test_bson_roundtrip():
    from jepsen_tpu.suites.proto.mongo import bson_decode, bson_encode

    doc = {
        "str": "hello",
        "int": 42,
        "big": 2**40,
        "float": 1.5,
        "bool": True,
        "none": None,
        "nested": {"a": 1},
        "arr": [1, "two", {"three": 3}],
    }
    assert bson_decode(bson_encode(doc)) == doc


@pytest.fixture
def mongo():
    from fake_servers import FakeMongo

    srv = FakeMongo().start()
    from jepsen_tpu.suites.proto.mongo import MongoClient

    c = MongoClient("127.0.0.1", srv.port).connect()
    yield c
    c.close()
    srv.stop()


def test_mongo_insert_find_update(mongo):
    mongo.insert("reg", [{"_id": 0, "value": 1}], write_concern={"w": "majority"})
    assert mongo.find("reg", {"_id": 0}) == [{"_id": 0, "value": 1}]
    mongo.update("reg", {"_id": 0}, {"$set": {"value": 9}})
    assert mongo.find("reg", {"_id": 0})[0]["value"] == 9
    assert mongo.find("reg", {"_id": 1}) == []


def test_mongo_duplicate_key_and_cas(mongo):
    from jepsen_tpu.suites.proto.mongo import MongoError

    mongo.insert("reg", [{"_id": 0, "value": 1}])
    with pytest.raises(MongoError) as ei:
        mongo.insert("reg", [{"_id": 0, "value": 2}])
    assert ei.value.code == 11000
    # CAS via findAndModify on (id, expected value)
    out = mongo.find_and_modify(
        "reg", {"_id": 0, "value": 1}, {"$set": {"value": 3}}, new=True
    )
    assert out["value"] == 3
    assert (
        mongo.find_and_modify("reg", {"_id": 0, "value": 99}, {"$set": {"value": 4}})
        is None
    )


# -- CQL --------------------------------------------------------------------


@pytest.fixture
def cql():
    from fake_servers import FakeCql

    srv = FakeCql().start()
    from jepsen_tpu.suites.proto.cql import CqlClient

    c = CqlClient("127.0.0.1", srv.port).connect()
    yield c
    c.close()
    srv.stop()


def test_cql_roundtrip(cql):
    from jepsen_tpu.suites.proto.cql import text_value

    r = cql.query("INSERT INTO kv (key, val) VALUES ('a', '7')")
    assert r.kind == "void"
    r = cql.query("SELECT val FROM kv WHERE key='a'")
    assert r.columns == ["val"] and text_value(r.rows[0][0]) == "7"
    assert cql.query("SELECT val FROM kv WHERE key='x'").rows == []


def test_cql_lwt_and_timeout(cql):
    from jepsen_tpu.suites.proto.cql import CqlError

    r = cql.query("INSERT INTO kv (key, val) VALUES ('k', '1') IF NOT EXISTS")
    assert r.rows[0][0] == b"true"
    r = cql.query("INSERT INTO kv (key, val) VALUES ('k', '2') IF NOT EXISTS")
    assert r.rows[0][0] == b"false"
    with pytest.raises(CqlError) as ei:
        cql.query("SELECT boom")
    assert ei.value.timeout  # write-timeout class → indeterminate


# -- IRC --------------------------------------------------------------------


def test_irc_join_and_message_delivery():
    from fake_servers import FakeIrc

    from jepsen_tpu.suites.proto.irc import IrcClient

    srv = FakeIrc().start()
    try:
        a = IrcClient("127.0.0.1", srv.port, nick="alice").connect()
        b = IrcClient("127.0.0.1", srv.port, nick="bob").connect()
        a.join("#jepsen")
        b.join("#jepsen")
        a.privmsg("#jepsen", "msg-1")
        a.privmsg("#jepsen", "msg-2")
        import time

        time.sleep(0.2)
        got = b.read_messages()
        assert [(n, t) for n, t, _ in got] == [("alice", "#jepsen")] * 2
        assert [m for _, _, m in got] == ["msg-1", "msg-2"]
        a.close()
        b.close()
    finally:
        srv.stop()


def test_cql_lwt_update_condition(cql):
    cql.query("INSERT INTO kv (key, val) VALUES ('r', '1')")
    r = cql.query("UPDATE kv SET val='2' WHERE key='r' IF val='1'")
    assert r.rows[0][0] == b"true"
    r = cql.query("UPDATE kv SET val='9' WHERE key='r' IF val='999'")
    assert r.rows[0][0] == b"false"
    from jepsen_tpu.suites.proto.cql import text_value

    assert text_value(cql.query("SELECT val FROM kv WHERE key='r'").rows[0][0]) == "2"


def test_irc_dead_connection_raises_not_empty():
    from fake_servers import FakeIrc
    from jepsen_tpu.suites.proto.irc import IrcClient

    srv = FakeIrc().start()
    a = IrcClient("127.0.0.1", srv.port, nick="alice").connect()
    a.join("#x")
    srv.stop()
    with pytest.raises(IndeterminateError):
        a.read_messages()
