"""Tests for jepsen_tpu.independent (reference: independent.clj +
test/jepsen/independent_test.clj behaviors)."""

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu import independent as ind
from jepsen_tpu import models
from jepsen_tpu.generator import sim
from jepsen_tpu.history import History, Op, invoke_op, ok_op
from jepsen_tpu.checker import Checker


def test_kv_tuple():
    t = ind.kv("k", 3)
    assert ind.is_tuple(t)
    assert t.key == "k"
    assert t.value == 3
    assert not ind.is_tuple(("k", 3))
    assert t == ("k", 3)  # still a tuple


def test_sequential_generator_wraps_values():
    g = ind.sequential_generator([0, 1], lambda k: [{"f": "read"}] * 2)
    h = sim.quick(g, ctx=sim.n_plus_nemesis_context(1))
    vals = [o["value"] for o in h]
    assert all(ind.is_tuple(v) for v in vals)
    assert [v.key for v in vals] == [0, 0, 1, 1]


def test_concurrent_generator_groups():
    # 4 client threads, 2 per key => 2 concurrent keys
    g = ind.concurrent_generator(
        2, list(range(4)), lambda k: [{"f": "read"}] * 4
    )
    h = sim.quick(g, ctx=sim.n_plus_nemesis_context(4))
    keys = [o["value"].key for o in h]
    assert len(h) == 16
    # first two keys run concurrently before later ones appear
    first_half = set(keys[:8])
    assert first_half == {0, 1}
    assert set(keys) == {0, 1, 2, 3}


def test_concurrent_generator_rejects_bad_concurrency():
    g = ind.concurrent_generator(3, [0], lambda k: [{"f": "read"}])
    with pytest.raises(Exception):
        sim.quick(g, ctx=sim.n_plus_nemesis_context(4))


def test_history_keys_and_subhistory():
    h = History(
        [
            invoke_op(0, "read", ind.kv(1, None), time=0, index=0),
            Op("info", "nemesis", "start", None, time=1, index=1),
            ok_op(0, "read", ind.kv(1, 5), time=2, index=2),
            invoke_op(1, "write", ind.kv(2, 7), time=3, index=3),
            ok_op(1, "write", ind.kv(2, 7), time=4, index=4),
        ]
    )
    assert ind.history_keys(h) == {1, 2}
    sub = ind.subhistory(1, h)
    assert [op.value for op in sub] == [None, None, 5]
    # nemesis op appears in every subhistory
    assert any(op.process == "nemesis" for op in ind.subhistory(2, h))


class _ValueChecker(Checker):
    """Valid iff every ok op's value is even."""

    def check(self, test, history, opts=None):
        bad = [op.value for op in history if op.is_ok and op.value % 2]
        return {"valid?": not bad, "bad": bad}


def test_independent_checker():
    h = History(
        [
            invoke_op(0, "w", ind.kv("a", 2), time=0),
            ok_op(0, "w", ind.kv("a", 2), time=1),
            invoke_op(0, "w", ind.kv("b", 3), time=2),
            ok_op(0, "w", ind.kv("b", 3), time=3),
        ]
    ).index_ops()
    chk = ind.checker(_ValueChecker())
    res = chk.check({"name": "t", "store?": False}, h, {})
    assert res["valid?"] is False
    assert res["failures"] == ["b"]
    assert res["results"]["a"]["valid?"] is True


def _register_history(k, values_ok=True):
    """A tiny per-key linearizable (or not) register history."""
    ops = [
        invoke_op(0, "write", ind.kv(k, 1), time=0),
        ok_op(0, "write", ind.kv(k, 1), time=1),
        invoke_op(1, "read", ind.kv(k, None), time=2),
        ok_op(1, "read", ind.kv(k, 1 if values_ok else 9), time=3),
    ]
    return ops


def test_batched_linearizable():
    ops = _register_history("good") + _register_history("bad", values_ok=False)
    # adjust times so ops interleave but remain per-key sane
    h = History(ops).index_ops()
    chk = ind.batched_linearizable(models.cas_register())
    res = chk.check({"name": "t", "store?": False}, h, {})
    assert res["results"]["good"]["valid?"] is True
    assert res["results"]["bad"]["valid?"] is False
    assert res["failures"] == ["bad"]
    assert res["valid?"] is False
    # the engine/kernel breakdown rides the result so keyspace routing
    # drift is visible in results.json
    stats = res["batch-stats"]
    assert stats["engines"].get("tpu") == 2, stats
    assert stats["device-rate"] == 1.0 and stats["oracle-rate"] == 0.0


def test_concurrent_generator_infinite_lazy_keys():
    """The reference's independent-deadlock-case
    (generator_test.clj:440): concurrent-generator over an INFINITE
    lazy key sequence must stream keys on demand — materializing the
    sequence hung forever before round 5.  The schedule matches the
    reference: each 2-thread group drains one key per round."""
    import itertools

    g = gen.limit(
        5,
        ind.concurrent_generator(
            2, itertools.count(), lambda k: gen.each_thread({"f": "meow"})
        ),
    )
    out = sim.perfect(g)
    got = [
        (o["time"], o["f"], o["value"].key)
        for o in out
        if o["type"] == "invoke"
    ]
    assert got == [
        (0, "meow", 0),
        (0, "meow", 0),
        (10, "meow", 1),
        (10, "meow", 1),
        (20, "meow", 2),
    ]
