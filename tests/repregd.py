#!/usr/bin/env python3
"""A replicated linearizable register daemon — the multi-process
integration-test service with REAL replication state
(tests/test_local_cluster.py runs three of these under
start-stop-daemon, with peer links routed through partitionable
proxies).

Replication is multi-writer ABD over majority quorums:

- every replica persists ``(ts, tiebreak, value)`` with fsync;
- a write queries a majority for the max timestamp, picks
  ``(max_ts+1, node_id)``, and stores to a majority before acking;
- a read queries a majority, takes the max-timestamped value, and
  WRITES IT BACK to a majority before returning (the read-repair phase
  that makes concurrent reads linearizable).

Quorum intersection makes this linearizable under crashes, SIGSTOP
pauses, and partitions — safety never depends on clocks or leases, so
a paused-then-resumed replica can never ack stale data (its quorum
replies carry whatever newer timestamps the majority moved to).

On top rides a REAL term-based election (persisted current/voted
terms, majority votes over the peer links): replicas heartbeat the
leader, campaign on silence, and step down on seeing a higher term.
The leader is a coordination hint only — any replica coordinates
quorum ops — so the election demonstrably runs (terms advance when the
leader is killed or partitioned away; ``STATUS`` exposes term/leader
for the test's assertions) without safety ever resting on it.

Line protocol (one port serves clients and peers):
  clients:  ``R`` → value|ERR…   ``W <v>`` → OK|ERR…   ``STATUS`` →
            ``<term> <leader>``
  peers:    ``GET`` → ``<ts> <tb> <v>``   ``SET <ts> <tb> <v>`` → OK
            ``VOTE <term> <cand>`` → YES|NO   ``COORD <term> <id>`` → OK

Write failures distinguish ``ERR-EARLY`` (no store was attempted —
definite failure) from ``ERR-MAYBE`` (stores were sent but a majority
never acked — indeterminate), so the harness can map them to
:fail/:info correctly.
"""

import os
import random
import socket
import socketserver
import sys
import threading
import time

PEER_TIMEOUT = 0.25
ELECTION_MIN_S = 0.4
ELECTION_JITTER_S = 0.4
HEARTBEAT_S = 0.15


class State:
    """fsync'd (ts, tiebreak, value, term, voted_term) cell."""

    def __init__(self, path):
        self.path = path
        self.lock = threading.Lock()
        self.ts = 0
        self.tb = 0
        self.value = 0
        self.term = 0
        self.voted = 0
        try:
            with open(path) as f:
                parts = f.read().split()
                self.ts, self.tb, self.value, self.term, self.voted = map(
                    int, parts
                )
        except (FileNotFoundError, ValueError):
            pass

    def _persist(self):
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(
                f"{self.ts} {self.tb} {self.value} {self.term} {self.voted}"
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def read_local(self):
        with self.lock:
            return self.ts, self.tb, self.value

    def store_if_newer(self, ts, tb, value):
        with self.lock:
            if (ts, tb) > (self.ts, self.tb):
                self.ts, self.tb, self.value = ts, tb, value
                self._persist()
            return True

    def grant_vote(self, term):
        with self.lock:
            if term > self.voted:
                self.voted = term
                self._persist()
                return True
            return False

    def see_term(self, term):
        with self.lock:
            if term > self.term:
                self.term = term
                self._persist()


class Replica:
    def __init__(self, node_id, peers, state):
        self.id = node_id
        self.peers = peers  # {peer_id: (host, port)} — proxied links
        self.state = state
        self.leader = None
        self.leader_seen = 0.0
        self.n = len(peers) + 1
        self.majority = self.n // 2 + 1
        # MWMR ABD needs a unique (ts, writer) per write; this
        # replica's id is the writer tiebreak, so concurrent writes
        # COORDINATED BY THE SAME REPLICA must serialize or two could
        # pick the same (max_ts+1, id) for different values — an acked
        # split the reads then disagree on
        self.write_lock = threading.Lock()

    # -- peer RPC ------------------------------------------------------

    def _call_peer(self, addr, line):
        try:
            with socket.create_connection(addr, timeout=PEER_TIMEOUT) as s:
                s.settimeout(PEER_TIMEOUT)
                f = s.makefile("rw")
                f.write(line + "\n")
                f.flush()
                return f.readline().strip() or None
        except OSError:
            return None

    def _broadcast(self, line):
        """Ask every peer; list of replies (None for unreachable).
        Pre-populated so a straggler thread outliving the join timeout
        updates an existing key instead of resizing the dict under a
        caller's iteration."""
        replies = {pid: None for pid in self.peers}
        threads = []

        def one(pid, addr):
            replies[pid] = self._call_peer(addr, line)

        for pid, addr in self.peers.items():
            t = threading.Thread(target=one, args=(pid, addr), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(PEER_TIMEOUT * 2)
        return replies

    # -- quorum ops (multi-writer ABD) ---------------------------------

    def _quorum_get(self):
        """(ts, tb, value) of the max-timestamped majority reply, or
        None without a quorum.  Counts self."""
        best = self.state.read_local()
        got = 1
        for rep in self._broadcast("GET").values():
            if rep is None:
                continue
            try:
                ts, tb, v = map(int, rep.split())
            except ValueError:
                continue
            got += 1
            if (ts, tb) > (best[0], best[1]):
                best = (ts, tb, v)
        return best if got >= self.majority else None

    def _quorum_set(self, ts, tb, value):
        self.state.store_if_newer(ts, tb, value)
        acks = 1
        for rep in self._broadcast(f"SET {ts} {tb} {value}").values():
            if rep == "OK":
                acks += 1
        return acks >= self.majority

    def client_read(self):
        best = self._quorum_get()
        if best is None:
            return "ERR-EARLY no-quorum"
        ts, tb, v = best
        # read repair: the observed value must reach a majority before
        # the read returns, else a later read could observe an older one
        if not self._quorum_set(ts, tb, v):
            return "ERR-EARLY no-quorum"
        return str(v)

    def client_write(self, v):
        with self.write_lock:
            best = self._quorum_get()
            if best is None:
                return "ERR-EARLY no-quorum"  # nothing stored anywhere
            ts = best[0] + 1
            if self._quorum_set(ts, self.id, v):
                return "OK"
            return "ERR-MAYBE no-quorum"  # stored somewhere, maybe visible

    # -- election (coordination hint; safety-free) ---------------------

    def election_loop(self):
        while True:
            time.sleep(HEARTBEAT_S)
            if self.leader == self.id:
                self._broadcast(f"COORD {self.state.term} {self.id}")
                continue
            fresh = time.monotonic() - self.leader_seen
            if self.leader is not None and fresh < ELECTION_MIN_S:
                continue
            time.sleep(random.random() * ELECTION_JITTER_S)
            if (
                self.leader is not None
                and time.monotonic() - self.leader_seen < ELECTION_MIN_S
            ):
                continue
            term = self.state.term + 1
            self.state.see_term(term)
            if not self.state.grant_vote(term):
                continue
            votes = 1
            for rep in self._broadcast(f"VOTE {term} {self.id}").values():
                if rep == "YES":
                    votes += 1
            if votes >= self.majority and term >= self.state.term:
                self.leader = self.id
                self.leader_seen = time.monotonic()
                self._broadcast(f"COORD {term} {self.id}")

    # -- request handling ----------------------------------------------

    def handle(self, parts):
        cmd = parts[0]
        if cmd == "R":
            return self.client_read()
        if cmd == "W":
            return self.client_write(int(parts[1]))
        if cmd == "STATUS":
            return f"{self.state.term} {self.leader if self.leader is not None else -1}"
        if cmd == "GET":
            ts, tb, v = self.state.read_local()
            return f"{ts} {tb} {v}"
        if cmd == "SET":
            self.state.store_if_newer(
                int(parts[1]), int(parts[2]), int(parts[3])
            )
            return "OK"
        if cmd == "VOTE":
            term = int(parts[1])
            self.state.see_term(term)
            return "YES" if self.state.grant_vote(term) else "NO"
        if cmd == "COORD":
            term, lid = int(parts[1]), int(parts[2])
            if term >= self.state.term:
                self.state.see_term(term)
                # adopting the announcer also steps a stale leader down
                self.leader = lid
                self.leader_seen = time.monotonic()
            return "OK"
        return "ERR"


def main(node_id, port, state_path, peer_spec):
    peers = {}
    if peer_spec:
        for item in peer_spec.split(","):
            pid, addr = item.split("=")
            host, p = addr.rsplit(":", 1)
            peers[int(pid)] = (host, int(p))
    replica = Replica(node_id, peers, State(state_path))

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            while True:
                line = self.rfile.readline()
                if not line:
                    return
                parts = line.decode().split()
                out = replica.handle(parts) if parts else "ERR"
                self.wfile.write((out + "\n").encode())

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    threading.Thread(target=replica.election_loop, daemon=True).start()
    with Server(("127.0.0.1", port), Handler) as srv:
        print(f"repregd {node_id} listening on {port}", flush=True)
        srv.serve_forever()


if __name__ == "__main__":
    main(int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
         if len(sys.argv) > 4 else "")
