"""In-process fake database servers speaking real wire protocols.

The reference gets integration coverage without a cluster via in-JVM
fakes (jepsen/src/jepsen/tests.clj:27-66 atom-db/atom-client); here the
fakes additionally speak each suite's actual wire protocol over
loopback TCP, so the from-scratch protocol clients in
jepsen_tpu.suites.proto get end-to-end exercise in unit tests.

Every fake serves a tiny linearizable KV (a dict under a lock) — enough
for register/set/bank workloads to run against them.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import re as _re
import socket
import socketserver
import struct
import threading
from typing import Dict, Optional, Tuple


class _Store:
    """Shared KV behind every fake server."""

    def __init__(self):
        self.lock = threading.Lock()
        self.kv: Dict[str, str] = {}



class _RecvExact:
    """Shared exact-n recv loop for the binary-protocol handlers."""

    def _recv_exact(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf


class FakeServer:
    """TCP server harness: start() binds an ephemeral loopback port."""

    handler_class: type = None

    def __init__(self):
        self.store = _Store()
        self.active = set()  # live per-connection sockets
        self._active_lock = threading.Lock()
        store = self.store
        outer = self

        class Handler(self.handler_class):
            fake_store = store
            server_ref = self

            def setup(inner):
                with outer._active_lock:
                    outer.active.add(inner.request)
                super().setup()

            def finish(inner):
                with outer._active_lock:
                    outer.active.discard(inner.request)
                super().finish()

        self.server = socketserver.ThreadingTCPServer(
            ("127.0.0.1", 0), Handler, bind_and_activate=True
        )
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )

    def start(self) -> "FakeServer":
        self.thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        with self._active_lock:
            conns = list(self.active)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# RESP (redis/disque/raftis)
# ---------------------------------------------------------------------------


class _RespHandler(socketserver.StreamRequestHandler):
    def _read_command(self) -> Optional[list]:
        line = self.rfile.readline()
        if not line:
            return None
        assert line[:1] == b"*", line
        n = int(line[1:].strip())
        args = []
        for _ in range(n):
            ln = int(self.rfile.readline()[1:].strip())
            args.append(self.rfile.read(ln).decode())
            self.rfile.read(2)
        return args

    def handle(self):
        while True:
            try:
                cmd = self._read_command()
            except Exception:
                return
            if cmd is None:
                return
            op, args = cmd[0].upper(), cmd[1:]
            kv, lock = self.fake_store.kv, self.fake_store.lock
            with lock:
                if op == "PING":
                    self.wfile.write(b"+PONG\r\n")
                elif op == "SET":
                    kv[args[0]] = args[1]
                    self.wfile.write(b"+OK\r\n")
                elif op == "GET":
                    v = kv.get(args[0])
                    if v is None:
                        self.wfile.write(b"$-1\r\n")
                    else:
                        b = v.encode()
                        self.wfile.write(b"$%d\r\n%s\r\n" % (len(b), b))
                elif op == "INCR":
                    v = int(kv.get(args[0], "0")) + 1
                    kv[args[0]] = str(v)
                    self.wfile.write(b":%d\r\n" % v)
                elif op == "DEL":
                    n = sum(1 for k in args if kv.pop(k, None) is not None)
                    self.wfile.write(b":%d\r\n" % n)
                elif op == "SADD":
                    s = set(json.loads(kv.get(args[0], "[]")))
                    added = sum(1 for m in args[1:] if m not in s)
                    s.update(args[1:])
                    kv[args[0]] = json.dumps(sorted(s))
                    self.wfile.write(b":%d\r\n" % added)
                elif op == "SMEMBERS":
                    s = sorted(set(json.loads(kv.get(args[0], "[]"))))
                    out = b"*%d\r\n" % len(s)
                    for m in s:
                        mb = str(m).encode()
                        out += b"$%d\r\n%s\r\n" % (len(mb), mb)
                    self.wfile.write(out)
                # disque-style queue commands
                elif op == "ADDJOB":
                    q = json.loads(kv.get("q:" + args[0], "[]"))
                    q.append(args[1])
                    kv["q:" + args[0]] = json.dumps(q)
                    self.wfile.write(b"+DI-fake-job\r\n")
                elif op == "GETJOB":
                    # GETJOB FROM q
                    qname = args[args.index("FROM") + 1] if "FROM" in args else args[-1]
                    q = json.loads(kv.get("q:" + qname, "[]"))
                    if not q:
                        self.wfile.write(b"*-1\r\n")
                    else:
                        body = q.pop(0)
                        kv["q:" + qname] = json.dumps(q)
                        bb = body.encode()
                        qb = qname.encode()
                        self.wfile.write(
                            b"*1\r\n*3\r\n$%d\r\n%s\r\n$10\r\nDI-fake-id\r\n$%d\r\n%s\r\n"
                            % (len(qb), qb, len(bb), bb)
                        )
                else:
                    self.wfile.write(b"-ERR unknown command '%s'\r\n" % op.encode())


class FakeRedis(FakeServer):
    handler_class = _RespHandler


# ---------------------------------------------------------------------------
# PostgreSQL wire v3
# ---------------------------------------------------------------------------


class _PgHandler(_RecvExact, socketserver.BaseRequestHandler):
    """Simple-query-protocol server with pluggable auth and a tiny SQL
    dialect: SELECT val FROM kv WHERE key='k' / INSERT ... / UPDATE ...,
    plus 'SELECT 1' and an error trigger."""

    auth_mode = "trust"  # overridden per-server: trust|cleartext|md5|scram
    password = "pw"

    def _send(self, t: bytes, payload: bytes = b""):
        self.request.sendall(t + struct.pack("!I", len(payload) + 4) + payload)

    def _read_msg(self) -> Tuple[bytes, bytes]:
        head = self._recv_exact(5)
        ln = struct.unpack("!I", head[1:])[0]
        return head[:1], self._recv_exact(ln - 4)

    def _error(self, sqlstate: str, msg: str):
        fields = b"SERROR\0" + b"C" + sqlstate.encode() + b"\0M" + msg.encode() + b"\0\0"
        self._send(b"E", fields)

    def _ready(self):
        self._send(b"Z", b"I")

    def _rows(self, cols, rows):
        desc = struct.pack("!H", len(cols))
        for c in cols:
            desc += c.encode() + b"\0" + struct.pack("!IHIHIH", 0, 0, 25, -1 & 0xFFFF, 0, 0)
        self._send(b"T", desc)
        for row in rows:
            data = struct.pack("!H", len(row))
            for v in row:
                if v is None:
                    data += struct.pack("!i", -1)
                else:
                    vb = str(v).encode()
                    data += struct.pack("!i", len(vb)) + vb
            self._send(b"D", data)
        self._send(b"C", b"SELECT %d\0" % len(rows))

    def handle(self):
        try:
            head = self._recv_exact(8)
            ln, code = struct.unpack("!II", head)
            body = self._recv_exact(ln - 8)
            if code == 80877103:  # SSLRequest → refuse
                self.request.sendall(b"N")
                head = self._recv_exact(8)
                ln, code = struct.unpack("!II", head)
                body = self._recv_exact(ln - 8)
            params = body.split(b"\0")
            user = ""
            for i in range(0, len(params) - 1, 2):
                if params[i] == b"user":
                    user = params[i + 1].decode()
            if not self._authenticate(user):
                return
            self._send(b"R", struct.pack("!I", 0))  # AuthenticationOk
            self._send(b"S", b"server_version\0fake-14.0\0")
            self._ready()
            while True:
                t, payload = self._read_msg()
                if t == b"X":
                    return
                if t != b"Q":
                    continue
                self._query(payload.rstrip(b"\0").decode())
        except ConnectionError:
            return
        except Exception:
            return

    def _authenticate(self, user: str) -> bool:
        if self.auth_mode == "trust":
            return True
        if self.auth_mode == "cleartext":
            self._send(b"R", struct.pack("!I", 3))
            t, payload = self._read_msg()
            ok = payload.rstrip(b"\0").decode() == self.password
        elif self.auth_mode == "md5":
            salt = b"salt"
            self._send(b"R", struct.pack("!I", 5) + salt)
            t, payload = self._read_msg()
            inner = hashlib.md5(
                self.password.encode() + user.encode()
            ).hexdigest()
            want = "md5" + hashlib.md5(inner.encode() + salt).hexdigest()
            ok = payload.rstrip(b"\0").decode() == want
        elif self.auth_mode == "scram":
            ok = self._scram_server(user)
        else:
            ok = False
        if not ok:
            self._error("28P01", f'password authentication failed for user "{user}"')
            return False
        return True

    def _scram_server(self, user: str) -> bool:
        self._send(b"R", struct.pack("!I", 10) + b"SCRAM-SHA-256\0\0")
        t, payload = self._read_msg()
        # SASLInitialResponse: mech \0 int32 len, client-first
        mech_end = payload.index(b"\0")
        ln = struct.unpack("!I", payload[mech_end + 1 : mech_end + 5])[0]
        client_first = payload[mech_end + 5 : mech_end + 5 + ln].decode()
        bare = client_first.split(",", 2)[2]
        cnonce = dict(f.split("=", 1) for f in bare.split(","))["r"]
        snonce = cnonce + base64.b64encode(os.urandom(9)).decode()
        salt, iters = b"saltsalt", 4096
        server_first = (
            f"r={snonce},s={base64.b64encode(salt).decode()},i={iters}"
        )
        self._send(b"R", struct.pack("!I", 11) + server_first.encode())
        t, payload = self._read_msg()
        client_final = payload.decode()
        parts = dict(f.split("=", 1) for f in client_final.split(","))
        salted = hashlib.pbkdf2_hmac("sha256", self.password.encode(), salt, iters)
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored = hashlib.sha256(client_key).digest()
        wo_proof = client_final.rsplit(",p=", 1)[0]
        auth_msg = f"{bare},{server_first},{wo_proof}".encode()
        sig = hmac.new(stored, auth_msg, hashlib.sha256).digest()
        want = base64.b64encode(
            bytes(a ^ b for a, b in zip(client_key, sig))
        ).decode()
        if parts.get("p") != want:
            return False
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        v = base64.b64encode(
            hmac.new(server_key, auth_msg, hashlib.sha256).digest()
        ).decode()
        self._send(b"R", struct.pack("!I", 12) + f"v={v}".encode())
        return True

    def _query(self, sql: str):
        kv, lock = self.fake_store.kv, self.fake_store.lock
        s = sql.strip().rstrip(";")
        low = s.lower()
        with lock:
            if low == "select 1":
                self._rows(["?column?"], [[1]])
            elif low == "select boom":
                self._error("40001", "restart transaction: forced serialization failure")
            elif low.startswith("select val from kv where key="):
                key = s.split("=", 1)[1].strip().strip("'")
                v = kv.get(key)
                self._rows(["val"], [[v]] if v is not None else [])
            elif low.startswith("insert into kv"):
                # INSERT INTO kv (key, val) VALUES ('k', 'v') [ON CONFLICT ...]
                vals = s[s.lower().index("values") + 6 :].strip()
                inner = vals[vals.index("(") + 1 : vals.index(")")]
                k, v = [x.strip().strip("'") for x in inner.split(",", 1)]
                if k in kv and "on conflict" not in low:
                    self._error("23505", "duplicate key value violates unique constraint")
                    self._ready()
                    return
                kv[k] = v
                self._send(b"C", b"INSERT 0 1\0")
            elif low.startswith("update kv set val="):
                rest = s[len("update kv set val=") :]
                v, where = _re.split(r"\s+where\s+", rest, 1, flags=_re.I)
                v = v.strip().strip("'")
                key = where.split("=", 1)[1].strip().strip("'")
                n = 1 if key in kv else 0
                if n:
                    kv[key] = v
                self._send(b"C", b"UPDATE %d\0" % n)
            elif low in ("begin", "commit", "rollback") or low.startswith(
                ("begin ", "drop", "set ")
            ):
                # "begin isolation level ..." → plain BEGIN for sqlite
                stmt = "BEGIN" if low.startswith("begin ") else s
                try:
                    self._backend().execute(stmt)
                except SqlBackendError:
                    pass
                self._send(b"C", s.split()[0].upper().encode() + b"\0")
            else:
                self._backend_query(s)
        self._ready()

    def _backend(self):
        if not hasattr(self, "_sql_be"):
            self._sql_be = _SqlBackend(self.fake_store)
        return self._sql_be

    def _backend_query(self, s: str):
        try:
            cols, rows, affected = self._backend().execute(s)
        except SqlBackendError as e:
            code = {"conflict": "40001", "duplicate": "23505"}.get(
                e.kind, "42601")
            self._error(code, str(e))
            return
        if cols:
            self._rows(cols, [[None if v is None else str(v) for v in r]
                              for r in rows])
        else:
            verb = s.split()[0].upper()
            tag = (f"INSERT 0 {affected}" if verb == "INSERT"
                   else f"{verb} {affected}")
            self._send(b"C", tag.encode() + b"\0")


class FakePg(FakeServer):
    handler_class = _PgHandler

    def __init__(self, auth_mode="trust", password="pw"):
        self.auth_mode = auth_mode
        self.password = password
        super().__init__()
        self.server.RequestHandlerClass.auth_mode = auth_mode
        self.server.RequestHandlerClass.password = password


# ---------------------------------------------------------------------------
# MySQL protocol
# ---------------------------------------------------------------------------


class _MysqlHandler(_RecvExact, socketserver.BaseRequestHandler):
    password = "pw"

    def _read_packet(self):
        head = self._recv_exact(4)
        ln = int.from_bytes(head[:3], "little")
        self.seq = (head[3] + 1) & 0xFF
        return self._recv_exact(ln)

    def _send_packet(self, payload: bytes):
        self.request.sendall(
            len(payload).to_bytes(3, "little") + bytes([self.seq]) + payload
        )
        self.seq = (self.seq + 1) & 0xFF

    def _ok(self, affected=0):
        self._send_packet(b"\x00" + bytes([affected]) + b"\x00" + b"\x02\x00\x00\x00")

    def _err(self, code, msg):
        self._send_packet(
            b"\xff"
            + struct.pack("<H", code)
            + b"#40001"
            + msg.encode()
        )

    @staticmethod
    def _lenenc_str(b: bytes) -> bytes:
        return bytes([len(b)]) + b

    def _resultset(self, cols, rows):
        self._send_packet(bytes([len(cols)]))
        for c in cols:
            cb = c.encode()
            coldef = (
                self._lenenc_str(b"def")
                + self._lenenc_str(b"")
                + self._lenenc_str(b"kv")
                + self._lenenc_str(b"kv")
                + self._lenenc_str(cb)
                + self._lenenc_str(cb)
                + b"\x0c"
                + struct.pack("<HIBHB", 33, 255, 0xFD, 0, 0)
                + b"\x00\x00"
            )
            self._send_packet(coldef)
        self._send_packet(b"\xfe\x00\x00\x02\x00")  # EOF
        for row in rows:
            out = b""
            for v in row:
                if v is None:
                    out += b"\xfb"
                else:
                    vb = str(v).encode()
                    out += self._lenenc_str(vb)
            self._send_packet(out)
        self._send_packet(b"\xfe\x00\x00\x02\x00")  # EOF

    def handle(self):
        try:
            self.seq = 0
            scramble = b"12345678" + b"901234567890"  # 20 bytes
            greeting = (
                b"\x0a"  # protocol 10
                + b"5.7.fake\0"
                + struct.pack("<I", 1)
                + scramble[:8]
                + b"\0"
                + struct.pack("<H", 0xF7FF)
                + b"\x21"
                + struct.pack("<H", 2)
                + struct.pack("<H", 0x8000 | 0x0008)
                + bytes([len(scramble) + 1])
                + b"\0" * 10
                + scramble[8:]
                + b"\0"
                + b"mysql_native_password\0"
            )
            self._send_packet(greeting)
            resp = self._read_packet()
            # parse HandshakeResponse41: caps(4) maxpkt(4) charset(1) 23x
            off = 32
            end = resp.index(b"\0", off)
            user = resp[off:end].decode()
            off = end + 1
            alen = resp[off]
            auth = resp[off + 1 : off + 1 + alen]
            want = b""
            if self.password:
                h1 = hashlib.sha1(self.password.encode()).digest()
                h2 = hashlib.sha1(h1).digest()
                h3 = hashlib.sha1(scramble + h2).digest()
                want = bytes(a ^ b for a, b in zip(h1, h3))
            if auth != want:
                self._err(1045, f"Access denied for user '{user}'")
                return
            self._ok()
            while True:
                self.seq = 0
                pkt = self._read_packet()
                if pkt[:1] == b"\x01":  # COM_QUIT
                    return
                if pkt[:1] != b"\x03":
                    self._err(1047, "unknown command")
                    continue
                self._query(pkt[1:].decode())
        except ConnectionError:
            return
        except Exception:
            return

    def _query(self, sql: str):
        kv, lock = self.fake_store.kv, self.fake_store.lock
        s = sql.strip().rstrip(";")
        low = s.lower()
        with lock:
            if low == "select 1":
                self._resultset(["1"], [[1]])
            elif low == "select boom":
                self._err(1213, "Deadlock found when trying to get lock")
            elif low.startswith("select val from kv where key="):
                key = s.split("=", 1)[1].strip().strip("'")
                v = kv.get(key)
                self._resultset(["val"], [[v]] if v is not None else [])
            elif low.startswith("insert into kv"):
                vals = s[low.index("values") + 6 :].strip()
                inner = vals[vals.index("(") + 1 : vals.index(")")]
                k, v = [x.strip().strip("'") for x in inner.split(",", 1)]
                if k in kv and "duplicate" not in low:
                    self._err(1062, f"Duplicate entry '{k}' for key 'PRIMARY'")
                    return
                kv[k] = v
                self._ok(affected=1)
            elif low.startswith("update kv set val="):
                rest = s[len("update kv set val=") :]
                v, where = _re.split(r"\s+where\s+", rest, 1, flags=_re.I)
                v = v.strip().strip("'")
                key = where.split("=", 1)[1].strip().strip("'")
                if key in kv:
                    kv[key] = v
                    self._ok(affected=1)
                else:
                    self._ok(affected=0)
            elif low.startswith(("begin", "commit", "rollback", "drop",
                                 "set ", "use ")):
                try:
                    self._backend().execute(s)
                except SqlBackendError:
                    pass
                self._ok()
            else:
                self._backend_query(s)


    def _backend(self):
        if not hasattr(self, "_sql_be"):
            self._sql_be = _SqlBackend(self.fake_store)
        return self._sql_be

    def _backend_query(self, s: str):
        try:
            cols, rows, affected = self._backend().execute(s)
        except SqlBackendError as e:
            code = {"conflict": 1213, "duplicate": 1062}.get(e.kind, 1064)
            self._err(code, str(e))
            return
        if cols:
            self._resultset(
                cols,
                [[None if v is None else str(v) for v in r] for r in rows],
            )
        else:
            self._ok(affected=min(affected, 250))


class FakeMysql(FakeServer):
    handler_class = _MysqlHandler

    def __init__(self, password="pw"):
        self.password = password
        super().__init__()
        self.server.RequestHandlerClass.password = password


# ---------------------------------------------------------------------------
# ZooKeeper jute
# ---------------------------------------------------------------------------


class _ZkHandler(_RecvExact, socketserver.BaseRequestHandler):
    ZK_OK, NO_NODE, BAD_VERSION, NODE_EXISTS = 0, -101, -103, -110

    def _read_frame(self):
        (n,) = struct.unpack("!i", self._recv_exact(4))
        return self._recv_exact(n)

    def _send_frame(self, payload):
        self.request.sendall(struct.pack("!i", len(payload)) + payload)

    @staticmethod
    def _buffer(b):
        if b is None:
            return struct.pack("!i", -1)
        return struct.pack("!i", len(b)) + b

    @staticmethod
    def _read_buffer(data, off):
        (n,) = struct.unpack("!i", data[off : off + 4])
        off += 4
        if n < 0:
            return None, off
        return data[off : off + n], off + n

    def _stat(self, version):
        # czxid mzxid ctime mtime version cversion aversion
        # ephemeralOwner dataLength numChildren pzxid
        return struct.pack("!qqqqiiiqiiq", 1, 1, 0, 0, version, 0, 0, 0, 0, 0, 1)

    def handle(self):
        try:
            self._read_frame()  # ConnectRequest
            # unique session ids per connection, like a real ensemble
            with self.fake_store.lock:
                sid = getattr(self.fake_store, "zk_next_session", 0x1234)
                self.fake_store.zk_next_session = sid + 1
            self._send_frame(
                struct.pack("!iiq", 0, 10000, sid) + self._buffer(b"\0" * 16)
            )
            nodes = self.fake_store.kv  # path → json {data(hexbytes), version}
            lock = self.fake_store.lock
            while True:
                frame = self._read_frame()
                xid, op = struct.unpack("!ii", frame[:8])
                body = frame[8:]
                if op == -11:  # close
                    self._send_frame(struct.pack("!iqi", xid, 1, 0))
                    return
                with lock:
                    err, payload = self._op(op, body, nodes)
                self._send_frame(struct.pack("!iqi", xid, 1, err) + payload)
        except ConnectionError:
            return
        except Exception:
            return

    def _op(self, op, body, nodes):
        path_b, off = self._read_buffer(body, 0)
        path = path_b.decode()
        if op == 1:  # create
            data, off = self._read_buffer(body, off)
            if path in nodes:
                return self.NODE_EXISTS, b""
            nodes[path] = json.dumps({"data": (data or b"").hex(), "version": 0})
            return self.ZK_OK, self._buffer(path.encode())
        if op == 2:  # delete
            (version,) = struct.unpack("!i", body[off : off + 4])
            if path not in nodes:
                return self.NO_NODE, b""
            node = json.loads(nodes[path])
            if version != -1 and version != node["version"]:
                return self.BAD_VERSION, b""
            del nodes[path]
            return self.ZK_OK, b""
        if op == 3:  # exists
            if path not in nodes:
                return self.NO_NODE, b""
            node = json.loads(nodes[path])
            return self.ZK_OK, self._stat(node["version"])
        if op == 4:  # getData
            if path not in nodes:
                return self.NO_NODE, b""
            node = json.loads(nodes[path])
            return (
                self.ZK_OK,
                self._buffer(bytes.fromhex(node["data"])) + self._stat(node["version"]),
            )
        if op == 5:  # setData
            data, off = self._read_buffer(body, off)
            (version,) = struct.unpack("!i", body[off : off + 4])
            if path not in nodes:
                return self.NO_NODE, b""
            node = json.loads(nodes[path])
            if version != -1 and version != node["version"]:
                return self.BAD_VERSION, b""
            node = {"data": (data or b"").hex(), "version": node["version"] + 1}
            nodes[path] = json.dumps(node)
            return self.ZK_OK, self._stat(node["version"])
        if op == 8:  # getChildren
            prefix = path.rstrip("/") + "/"
            kids = sorted(
                p[len(prefix) :]
                for p in nodes
                if p.startswith(prefix) and "/" not in p[len(prefix) :]
            )
            out = struct.pack("!i", len(kids))
            for k in kids:
                out += self._buffer(k.encode())
            return self.ZK_OK, out
        return -6, b""  # unimplemented


class FakeZk(FakeServer):
    handler_class = _ZkHandler


# ---------------------------------------------------------------------------
# MongoDB OP_MSG
# ---------------------------------------------------------------------------


class _MongoHandler(_RecvExact, socketserver.BaseRequestHandler):
    def handle(self):
        from jepsen_tpu.suites.proto.mongo import bson_decode, bson_encode

        if not hasattr(self.fake_store, "docs"):
            self.fake_store.docs = {}
        try:
            while True:
                ln, rid, _rto, opcode = struct.unpack("<iiii", self._recv_exact(16))
                payload = self._recv_exact(ln - 16)
                cmd = bson_decode(payload[5:])
                with self.fake_store.lock:
                    reply = self._command(cmd)
                body = struct.pack("<I", 0) + b"\x00" + bson_encode(reply)
                self.request.sendall(
                    struct.pack("<iiii", 16 + len(body), 1, rid, 2013) + body
                )
        except ConnectionError:
            return
        except Exception:
            return

    def _command(self, cmd):
        docs = self.fake_store.docs
        # the command name is the first key of an OP_MSG body
        name = next(iter(cmd))
        cmd = {name: cmd[name], **{k: v for k, v in cmd.items() if k != name}}
        if name == "insert":
            coll = docs.setdefault(cmd["insert"], [])
            for d in cmd["documents"]:
                if any(x.get("_id") == d.get("_id") for x in coll):
                    return {
                        "ok": 1,
                        "n": 0,
                        "writeErrors": [
                            {"index": 0, "code": 11000, "errmsg": "duplicate key"}
                        ],
                    }
                coll.append(dict(d))
            return {"ok": 1, "n": len(cmd["documents"])}
        if name == "find":
            coll = docs.get(cmd["find"], [])
            flt = cmd.get("filter", {})
            out = [d for d in coll if _mongo_match(d, flt)]
            return {
                "ok": 1,
                "cursor": {"id": 0, "ns": "test." + cmd["find"], "firstBatch": out},
            }
        if name == "update":
            coll = docs.setdefault(cmd["update"], [])
            n = 0
            for u in cmd["updates"]:
                q, mod = u["q"], u["u"]
                matched = [d for d in coll if _mongo_match(d, q)]
                if not matched and u.get("upsert"):
                    nd = {
                        k: v for k, v in q.items()
                        if not isinstance(v, dict)
                    }
                    nd.update(mod.get("$set", {}))
                    coll.append(nd)
                    n += 1
                for d in matched:
                    for k, v in mod.get("$set", {}).items():
                        d[k] = v
                    for k, v in mod.get("$inc", {}).items():
                        d[k] = d.get(k, 0) + v
                    for k, v in mod.get("$push", {}).items():
                        d.setdefault(k, []).append(v)
                    for k, v in mod.get("$pull", {}).items():
                        d[k] = [x for x in d.get(k, []) if x != v]
                    n += 1
            return {"ok": 1, "n": n}
        if name == "findAndModify":
            coll = docs.setdefault(cmd["findAndModify"], [])
            q = cmd["query"]
            matched = [d for d in coll if all(d.get(k) == v for k, v in q.items())]
            if not matched:
                if cmd.get("upsert"):
                    nd = dict(q)
                    nd.update(cmd["update"].get("$set", {}))
                    coll.append(nd)
                    return {"ok": 1, "value": nd if cmd.get("new") else None}
                return {"ok": 1, "value": None}
            d = matched[0]
            for k, v in cmd["update"].get("$set", {}).items():
                d[k] = v
            for k, v in cmd["update"].get("$inc", {}).items():
                d[k] = d.get(k, 0) + v
            return {"ok": 1, "value": d}
        if name in ("ismaster", "hello"):
            return {"ok": 1, "ismaster": True, "maxWireVersion": 13}
        return {"ok": 0, "errmsg": f"no such command: {list(cmd)[0]}", "code": 59}


def _mongo_match(doc, query) -> bool:
    """Mongo filter semantics for the subset the suites use: scalar
    equality (with array-contains for list fields), $ne (for arrays:
    does-not-contain), and $size."""
    for k, v in query.items():
        cur = doc.get(k)
        if isinstance(v, dict):
            unsupported = set(v) - {"$ne", "$size"}
            if unsupported:
                # fail LOUDLY: silently matching everything would let a
                # future suite filter corrupt state without a trace
                raise ValueError(
                    f"fake mongo: unsupported operators {unsupported}"
                )
            if "$ne" in v:
                ne = v["$ne"]
                if isinstance(cur, list):
                    if ne in cur:
                        return False
                elif cur == ne:
                    return False
            if "$size" in v:
                if not isinstance(cur, list) or len(cur) != v["$size"]:
                    return False
        elif isinstance(cur, list):
            if v != cur and v not in cur:
                return False
        elif cur != v:
            return False
    return True


class FakeMongo(FakeServer):
    handler_class = _MongoHandler


# ---------------------------------------------------------------------------
# CQL v4
# ---------------------------------------------------------------------------


class _CqlHandler(_RecvExact, socketserver.BaseRequestHandler):
    def _send(self, stream, opcode, body):
        self.request.sendall(
            struct.pack("!BBhBI", 0x84, 0, stream, opcode, len(body)) + body
        )

    def _error(self, stream, code, msg):
        mb = msg.encode()
        self._send(stream, 0x00, struct.pack("!IH", code, len(mb)) + mb)

    def _rows(self, stream, cols, rows):
        # metadata: flags=1 (global spec), ncols, ks, table, then per-col
        # name + type varchar(0x000D)
        body = struct.pack("!II", 1, len(cols))
        for name in ("ks", "t"):
            nb = name.encode()
            body += struct.pack("!H", len(nb)) + nb
        for c in cols:
            cb = c.encode()
            body += struct.pack("!H", len(cb)) + cb + struct.pack("!H", 0x000D)
        body += struct.pack("!I", len(rows))
        for row in rows:
            for v in row:
                if v is None:
                    body += struct.pack("!i", -1)
                else:
                    vb = str(v).encode()
                    body += struct.pack("!i", len(vb)) + vb
        self._send(stream, 0x08, struct.pack("!I", 2) + body)

    def handle(self):
        try:
            while True:
                header = self._recv_exact(9)
                _v, _f, stream, opcode, ln = struct.unpack("!BBhBI", header)
                body = self._recv_exact(ln)
                if opcode == 0x01:  # STARTUP
                    self._send(stream, 0x02, b"")
                    continue
                if opcode != 0x07:  # QUERY
                    self._error(stream, 0x000A, "protocol error")
                    continue
                (qlen,) = struct.unpack("!I", body[:4])
                cql = body[4 : 4 + qlen].decode()
                with self.fake_store.lock:
                    self._query(stream, cql)
        except ConnectionError:
            return
        except Exception:
            return

    def _query(self, stream, cql):
        kv = self.fake_store.kv
        s = cql.strip().rstrip(";")
        low = s.lower()
        if low == "select boom":
            self._error(stream, 0x1100, "Operation timed out")
        elif low.startswith("select val from kv where key="):
            key = s.split("=", 1)[1].strip().strip("'")
            v = kv.get(key)
            self._rows(stream, ["val"], [[v]] if v is not None else [])
        elif low.startswith("insert into kv"):
            vals = s[low.index("values") + 6 :].strip()
            inner = vals[vals.index("(") + 1 : vals.rindex(")")]
            k, v = [x.strip().strip("'") for x in inner.split(",", 1)]
            if low.endswith("if not exists") and k in kv:
                self._rows(stream, ["[applied]"], [["false"]])
                return
            kv[k] = v.split("'")[0] if "'" in v else v
            if "if not exists" in low:
                self._rows(stream, ["[applied]"], [["true"]])
            else:
                self._send(stream, 0x08, struct.pack("!I", 1))  # void
        elif low.startswith("update kv set val="):
            rest = s[len("update kv set val=") :]
            v, where = _re.split(r"\s+where\s+", rest, 1, flags=_re.I)
            v = v.strip().strip("'")
            # LWT: UPDATE ... WHERE key='k' IF val='x'
            m = _re.split(r"\s+if\s+val\s*=\s*", where, 1, flags=_re.I)
            key = m[0].split("=", 1)[1].strip().strip("'")
            if len(m) == 2:
                cond = m[1].strip().strip("'")
                if kv.get(key) == cond:
                    kv[key] = v
                    self._rows(stream, ["[applied]"], [["true"]])
                else:
                    self._rows(stream, ["[applied]"], [["false"]])
                return
            kv[key] = v
            self._send(stream, 0x08, struct.pack("!I", 1))
        elif low.startswith(("create", "drop", "use ", "truncate")):
            self._send(stream, 0x08, struct.pack("!I", 1))
        # yugabyte-style int tables: <ks>.registers (id, val) and
        # <ks>.elements (val) with LWT "IF val ="
        elif _re.match(r"select val from \S+\.registers where id\s*=", low):
            key = "reg:" + s.split("=", 1)[1].strip()
            v = kv.get(key)
            self._rows(stream, ["val"], [[v]] if v is not None else [])
        elif _re.match(r"insert into \S+\.registers", low):
            inner = s[s.index("(", s.lower().index("values")) + 1:
                      s.rindex(")")]
            k, v = [x.strip() for x in inner.split(",", 1)]
            kv["reg:" + k] = v
            self._send(stream, 0x08, struct.pack("!I", 1))
        elif _re.match(r"update \S+\.registers set val\s*=", low):
            m = _re.match(
                r"update \S+\.registers set val\s*=\s*(\S+)\s+where\s+id\s*="
                r"\s*(\S+)(?:\s+if\s+val\s*=\s*(\S+))?",
                low,
            )
            new, k, cond = m.group(1), m.group(2), m.group(3)
            if cond is not None:
                if kv.get("reg:" + k) == cond:
                    kv["reg:" + k] = new
                    self._rows(stream, ["[applied]"], [["true"]])
                else:
                    self._rows(stream, ["[applied]"], [["false"]])
            else:
                kv["reg:" + k] = new
                self._send(stream, 0x08, struct.pack("!I", 1))
        # yugabyte distributed transactions: BEGIN TRANSACTION
        # <stmt>; <stmt>; END TRANSACTION — the handler already runs
        # under the store lock, so the whole block applies atomically
        # (multi_key_acid writes, bank balance-arithmetic transfers)
        elif low.startswith("begin transaction"):
            inner = s[len("begin transaction"):]
            if inner.lower().rstrip().endswith("end transaction"):
                inner = inner.rstrip()[: -len("end transaction")]
            staged = {}
            for stmt in inner.split(";"):
                stmt = stmt.strip()
                if not stmt:
                    continue
                m = _re.match(
                    r"insert into \S+\.multi_key_acid\s*"
                    r"\(id, ik, val\)\s*values\s*"
                    r"\((\d+),\s*(\d+),\s*(\d+)\)",
                    stmt, _re.I,
                )
                if m:
                    id_, ik, val = m.groups()
                    staged[f"mka:{id_}:{ik}"] = val
                    continue
                m = _re.match(
                    r"update \S+\.accounts set balance\s*=\s*"
                    r"balance\s*([+-])\s*(\d+)\s+where\s+id\s*=\s*(\d+)",
                    stmt, _re.I,
                )
                if m:
                    sign, amt, id_ = m.groups()
                    key = f"acct:{id_}"
                    cur = int(staged.get(key, kv.get(key, 0)))
                    delta = int(amt) if sign == "+" else -int(amt)
                    staged[key] = str(cur + delta)
                    continue
                self._error(stream, 0x2000,
                            f"Invalid txn stmt: {stmt!r}")
                return
            kv.update(staged)  # all-or-nothing: parse fully, then apply
            self._send(stream, 0x08, struct.pack("!I", 1))
        elif _re.match(r"select id, val from \S+\.multi_key_acid", low):
            m = _re.search(r"ik\s*=\s*(\d+)\s+and\s+id\s+in\s*\(([^)]*)\)",
                           low)
            ik = m.group(1)
            ids = [x.strip() for x in m.group(2).split(",") if x.strip()]
            rows = [
                [i, kv[f"mka:{i}:{ik}"]]
                for i in ids
                if f"mka:{i}:{ik}" in kv
            ]
            self._rows(stream, ["id", "val"], rows)
        # yugabyte ycql bank: <ks>.accounts (id, balance)
        elif _re.match(r"insert into \S+\.accounts", low):
            inner = s[s.index("(", s.lower().index("values")) + 1:
                      s.rindex(")")]
            id_, bal = [x.strip() for x in inner.split(",", 1)]
            kv[f"acct:{id_}"] = bal
            self._send(stream, 0x08, struct.pack("!I", 1))
        elif _re.match(r"select id, balance from \S+\.accounts", low):
            rows = sorted(
                (int(k[5:]), kv[k]) for k in kv if k.startswith("acct:")
            )
            self._rows(stream, ["id", "balance"],
                       [[str(i), b] for i, b in rows])
        # yugabyte ycql long-fork: <ks>.long_fork (key, key2, val)
        elif _re.match(r"insert into \S+\.long_fork", low):
            inner = s[s.index("(", s.lower().index("values")) + 1:
                      s.rindex(")")]
            k, _k2, v = [x.strip() for x in inner.split(",")]
            kv[f"lf:{k}"] = v
            self._send(stream, 0x08, struct.pack("!I", 1))
        elif _re.match(r"select key2, val from \S+\.long_fork", low):
            m = _re.search(r"key2\s+in\s*\(([^)]*)\)", low)
            ks = [x.strip() for x in m.group(1).split(",") if x.strip()]
            rows = [[k, kv[f"lf:{k}"]] for k in ks if f"lf:{k}" in kv]
            self._rows(stream, ["key2", "val"], rows)
        elif _re.match(r"insert into \S+\.elements", low):
            inner = s[s.index("(", s.lower().index("values")) + 1:
                      s.rindex(")")]
            kv["elem:" + inner.strip()] = "1"
            self._send(stream, 0x08, struct.pack("!I", 1))
        elif _re.match(r"select val from \S+\.elements", low):
            vals = sorted(
                int(k[5:]) for k in kv if k.startswith("elem:")
            )
            self._rows(stream, ["val"], [[str(v)] for v in vals])
        else:
            self._error(stream, 0x2000, f"Invalid CQL: {s!r}")


class FakeCql(FakeServer):
    handler_class = _CqlHandler


# ---------------------------------------------------------------------------
# IRC
# ---------------------------------------------------------------------------


class _IrcHandler(socketserver.StreamRequestHandler):
    def handle(self):
        store = self.fake_store
        if not hasattr(store, "irc_members"):
            store.irc_members = {}  # channel → {nick: wfile}
        nick = None
        try:
            while True:
                line = self.rfile.readline()
                if not line:
                    return
                line = line.decode(errors="replace").strip()
                if not line:
                    continue
                parts = line.split(" ", 1)
                cmd = parts[0].upper()
                rest = parts[1] if len(parts) > 1 else ""
                if cmd == "NICK":
                    nick = rest.strip()
                elif cmd == "USER":
                    self.wfile.write(
                        f":fake 001 {nick} :Welcome\r\n".encode()
                    )
                elif cmd == "JOIN":
                    chan = rest.strip()
                    with store.lock:
                        store.irc_members.setdefault(chan, {})[nick] = self.wfile
                    self.wfile.write(f":{nick}!u@h JOIN {chan}\r\n".encode())
                elif cmd == "PRIVMSG":
                    target, msg = rest.split(" :", 1)
                    # write under the lock: BufferedWriter is not
                    # thread-safe and concurrent senders must not
                    # interleave bytes within a line
                    with store.lock:
                        members = store.irc_members.get(target.strip(), {})
                        for other, wf in members.items():
                            if other != nick:
                                try:
                                    wf.write(
                                        f":{nick}!u@h PRIVMSG {target} :{msg}\r\n".encode()
                                    )
                                    wf.flush()
                                except Exception:
                                    pass
                elif cmd == "TOPIC":
                    target, msg = rest.split(" :", 1)
                    # topic changes broadcast to every member, sender
                    # included (RFC 1459 §4.2.4)
                    with store.lock:
                        members = store.irc_members.get(target.strip(), {})
                        for other, wf in members.items():
                            try:
                                wf.write(
                                    f":{nick}!u@h TOPIC {target} :{msg}\r\n".encode()
                                )
                                wf.flush()
                            except Exception:
                                pass
                elif cmd == "QUIT":
                    return
        except Exception:
            return
        finally:
            if nick:
                with store.lock:
                    for members in getattr(store, "irc_members", {}).values():
                        members.pop(nick, None)


class FakeIrc(FakeServer):
    handler_class = _IrcHandler


# ---------------------------------------------------------------------------
# HTTP KV (etcd v2 keys API + consul KV + generic JSON endpoints)
# ---------------------------------------------------------------------------

from http.server import BaseHTTPRequestHandler
from urllib.parse import parse_qs, urlparse


class _HttpKvHandler(BaseHTTPRequestHandler):
    """Speaks just enough of the etcd v2 keys API and the consul KV API
    for the suite clients: quorum GETs, prevValue/prevIndex/prevExist
    CAS (etcd), ?cas= index CAS and base64 values (consul)."""

    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _send(self, status: int, obj, headers=None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    # -- etcd v2 --------------------------------------------------------
    def _etcd_node(self, key, rec) -> dict:
        return {"key": key, "value": rec[0], "modifiedIndex": rec[1]}

    def _etcd(self, method: str) -> None:
        st = self.fake_store
        u = urlparse(self.path)
        key = u.path[len("/v2/keys"):]
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        form = {k: v[0] for k, v in parse_qs(self._body().decode()).items()}
        with st.lock:
            idx = getattr(st, "etcd_index", 0)
            if method == "GET":
                rec = st.kv.get(key)
                if rec is None:
                    self._send(404, {"errorCode": 100, "cause": key})
                else:
                    self._send(200, {"action": "get",
                                     "node": self._etcd_node(key, rec)})
                return
            if method == "PUT":
                value = form.get("value", "")
                rec = st.kv.get(key)
                if form.get("prevExist") == "false" and rec is not None:
                    self._send(412, {"errorCode": 105, "cause": key})
                    return
                if "prevValue" in form:
                    if rec is None:
                        self._send(404, {"errorCode": 100, "cause": key})
                        return
                    if rec[0] != form["prevValue"]:
                        self._send(412, {"errorCode": 101, "cause": key})
                        return
                if "prevIndex" in form:
                    if rec is None or rec[1] != int(form["prevIndex"]):
                        self._send(412, {"errorCode": 101, "cause": key})
                        return
                st.etcd_index = idx + 1
                st.kv[key] = (value, st.etcd_index)
                self._send(201 if rec is None else 200,
                           {"action": "set",
                            "node": self._etcd_node(key, st.kv[key])})
                return
            if method == "DELETE":
                st.kv.pop(key, None)
                self._send(200, {"action": "delete"})
                return
        self._send(405, {"error": "bad method"})

    # -- consul KV ------------------------------------------------------
    def _consul(self, method: str) -> None:
        st = self.fake_store
        u = urlparse(self.path)
        key = u.path[len("/v1/kv/"):]
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        with st.lock:
            idx = getattr(st, "etcd_index", 0)
            rec = st.kv.get("consul/" + key)
            if method == "GET":
                if rec is None:
                    self._send(404, None)
                    return
                self._send(
                    200,
                    [{
                        "Key": key,
                        "Value": base64.b64encode(rec[0].encode()).decode(),
                        "ModifyIndex": rec[1],
                        "CreateIndex": rec[1],
                        "Flags": 0,
                    }],
                    headers={"X-Consul-Index": str(rec[1])},
                )
                return
            if method == "PUT":
                body = self._body().decode()
                if "cas" in q:
                    want = int(q["cas"])
                    have = rec[1] if rec is not None else 0
                    if want != have:
                        self._send(200, False)
                        return
                st.etcd_index = idx + 1
                st.kv["consul/" + key] = (body, st.etcd_index)
                self._send(200, True)
                return
            if method == "DELETE":
                st.kv.pop("consul/" + key, None)
                self._send(200, True)
                return
        self._send(405, None)

    def _route(self, method: str) -> None:
        try:
            if self.path.startswith("/v2/keys"):
                self._etcd(method)
            elif self.path.startswith("/v1/kv/"):
                self._consul(method)
            else:
                handler = getattr(self.server_ref, "extra_routes", None)
                if handler and handler(self, method):
                    return
                self._send(404, {"error": f"no route {self.path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_GET(self):
        self._route("GET")

    def do_PUT(self):
        self._route("PUT")

    def do_POST(self):
        self._route("POST")

    def do_DELETE(self):
        self._route("DELETE")


class FakeHttpKv(FakeServer):
    handler_class = _HttpKvHandler
    extra_routes = None


# ---------------------------------------------------------------------------
# Generic SQL backend for the pg/mysql fakes: an in-memory shared-cache
# sqlite database per store, so the suite SQL clients (registers, bank
# accounts, sets, list-append) exercise real DDL/DML + transactions.
# Concurrent write conflicts surface as lock errors, which the handlers
# map to serialization-failure codes (pg 40001 / mysql 1213) — the same
# clean-abort semantics real engines give the reference's clients.
# ---------------------------------------------------------------------------

import itertools as _it
import sqlite3

_sql_db_ids = _it.count()
_sql_setup_lock = threading.Lock()  # NOT store.lock: callers may hold it


class SqlBackendError(Exception):
    def __init__(self, kind: str, msg: str):
        super().__init__(msg)
        self.kind = kind  # "conflict" | "duplicate" | "syntax"


class _SqlBackend:
    """One per TCP connection; all connections share the store's DB."""

    def __init__(self, store):
        with _sql_setup_lock:
            if not hasattr(store, "sql_uri"):
                store.sql_uri = (
                    f"file:fakesql{next(_sql_db_ids)}"
                    "?mode=memory&cache=shared"
                )
                # anchor connection keeps the shared DB alive
                store.sql_anchor = sqlite3.connect(
                    store.sql_uri, uri=True, check_same_thread=False
                )
        self.conn = sqlite3.connect(
            store.sql_uri, uri=True, check_same_thread=False, timeout=0.2
        )
        self.conn.isolation_level = None  # explicit BEGIN/COMMIT only
        # DB-assigned logical timestamp for the monotonic workload: a
        # store-wide counter standing in for cockroach's
        # cluster_logical_timestamp() / pg's clock_timestamp()
        with _sql_setup_lock:
            if not hasattr(store, "sql_ts"):
                store.sql_ts = _it.count(1)
        self.conn.create_function(
            "cluster_logical_timestamp", 0, lambda: next(store.sql_ts)
        )

    _RE_TS = _re.compile(
        r"extract\(epoch from clock_timestamp\(\)\)"
        r"|unix_timestamp\(now\(6\)\)",
        _re.I,
    )

    _RE_UPSERT = _re.compile(
        r"^UPSERT\s+INTO\s+(\w+)\s*\(\s*(\w+)\s*,\s*(\w+)\s*\)\s*"
        r"VALUES\s*\((.+)\)\s*$",
        _re.I | _re.S,
    )
    _RE_ON_DUP = _re.compile(
        r"\s+ON\s+DUPLICATE\s+KEY\s+UPDATE\s+(.*)$", _re.I | _re.S
    )
    _RE_CONCAT = _re.compile(r"concat\(([^()]*)\)", _re.I)

    def _translate(self, sql: str) -> str:
        s = sql.strip().rstrip(";")
        s = self._RE_TS.sub("cluster_logical_timestamp()", s)
        m = self._RE_UPSERT.match(s)
        if m:  # cockroach UPSERT
            t, c1, c2, vals = m.groups()
            s = (
                f"INSERT INTO {t} ({c1}, {c2}) VALUES ({vals}) "
                f"ON CONFLICT ({c1}) DO UPDATE SET {c2} = excluded.{c2}"
            )
        m = self._RE_ON_DUP.search(s)
        if m:  # mysql upsert → sqlite ON CONFLICT on the first column
            update = m.group(1)
            head = s[: m.start()]
            cols = head[head.index("(") + 1 : head.index(")")]
            first_col = cols.split(",")[0].strip()
            s = f"{head} ON CONFLICT ({first_col}) DO UPDATE SET {update}"
        # concat(a, b, c) → (a || b || c); split args outside quotes
        while True:
            m = self._RE_CONCAT.search(s)
            if not m:
                break
            parts, cur, in_q = [], "", False
            for ch in m.group(1):
                if ch == "'":
                    in_q = not in_q
                    cur += ch
                elif ch == "," and not in_q:
                    parts.append(cur.strip())
                    cur = ""
                else:
                    cur += ch
            if cur.strip():
                parts.append(cur.strip())
            s = s[: m.start()] + "(" + " || ".join(parts) + ")" + s[m.end():]
        return s

    def execute(self, sql: str):
        """→ (columns, rows, affected) or raises SqlBackendError."""
        s = self._translate(sql)
        try:
            cur = self.conn.execute(s)
            rows = cur.fetchall() if cur.description else []
            cols = ([d[0] for d in cur.description]
                    if cur.description else [])
            return cols, rows, max(cur.rowcount, 0)
        except sqlite3.IntegrityError as e:
            raise SqlBackendError("duplicate", str(e))
        except sqlite3.OperationalError as e:
            msg = str(e)
            if "locked" in msg or "busy" in msg:
                try:
                    self.conn.execute("ROLLBACK")
                except sqlite3.Error:
                    pass
                raise SqlBackendError("conflict", msg)
            raise SqlBackendError("syntax", msg)

    def close(self):
        try:
            self.conn.close()
        except sqlite3.Error:
            pass


# ---------------------------------------------------------------------------
# AMQP 0-9-1 (rabbitmq)
# ---------------------------------------------------------------------------


class _AmqpHandler(_RecvExact, socketserver.BaseRequestHandler):
    def _send_method(self, channel, cid, mid, args=b""):
        payload = struct.pack("!HH", cid, mid) + args
        self.request.sendall(
            struct.pack("!BHI", 1, channel, len(payload)) + payload + b"\xce"
        )

    def _read_frame(self):
        t, ch, size = struct.unpack("!BHI", self._recv_exact(7))
        payload = self._recv_exact(size)
        assert self._recv_exact(1) == b"\xce"
        return t, ch, payload

    @staticmethod
    def _short_str(s):
        b = s.encode()
        return bytes([len(b)]) + b

    def handle(self):
        store = self.fake_store
        with store.lock:
            if not hasattr(store, "amqp_queues"):
                store.amqp_queues = {}   # name -> list of bodies
                store.amqp_tag = 0
        self.unacked = {}  # this connection's tag -> (queue, body)
        try:
            assert self._recv_exact(8) == b"AMQP\x00\x00\x09\x01"
            # connection.start: version 0.9, empty server-props table,
            # mechanisms PLAIN, locales en_US
            self._send_method(
                0, 10, 10,
                b"\x00\x09" + struct.pack("!I", 0)
                + struct.pack("!I", 5) + b"PLAIN"
                + struct.pack("!I", 5) + b"en_US",
            )
            while True:
                t, ch, payload = self._read_frame()
                if t != 1:
                    continue
                cid, mid = struct.unpack_from("!HH", payload, 0)
                args = payload[4:]
                if (cid, mid) == (10, 11):    # start-ok
                    self._send_method(0, 10, 30,
                                      struct.pack("!HIH", 0, 131072, 0))
                elif (cid, mid) == (10, 31):  # tune-ok
                    pass
                elif (cid, mid) == (10, 40):  # connection.open
                    self._send_method(0, 10, 41, b"\x00")
                elif (cid, mid) == (20, 10):  # channel.open
                    self._send_method(ch, 20, 11, struct.pack("!I", 0))
                elif (cid, mid) == (50, 10):  # queue.declare
                    ln = args[2]
                    qname = args[3:3 + ln].decode()
                    with store.lock:
                        store.amqp_queues.setdefault(qname, [])
                        n = len(store.amqp_queues[qname])
                    self._send_method(
                        ch, 50, 11,
                        self._short_str(qname) + struct.pack("!II", n, 0),
                    )
                elif (cid, mid) == (50, 30):  # queue.purge
                    ln = args[2]
                    qname = args[3:3 + ln].decode()
                    with store.lock:
                        n = len(store.amqp_queues.get(qname, []))
                        store.amqp_queues[qname] = []
                    self._send_method(ch, 50, 31, struct.pack("!I", n))
                elif (cid, mid) == (60, 40):  # basic.publish
                    off = 2
                    eln = args[off]; off += 1 + eln
                    rln = args[off]
                    routing = args[off + 1: off + 1 + rln].decode()
                    # content header + body frames follow
                    t2, _c2, hdr = self._read_frame()
                    assert t2 == 2
                    (body_size,) = struct.unpack_from("!Q", hdr, 4)
                    body = b""
                    while len(body) < body_size:
                        t3, _c3, chunk = self._read_frame()
                        assert t3 == 3
                        body += chunk
                    with store.lock:
                        store.amqp_queues.setdefault(routing, []).append(body)
                elif (cid, mid) == (60, 70):  # basic.get
                    ln = args[2]
                    qname = args[3:3 + ln].decode()
                    with store.lock:
                        q = store.amqp_queues.get(qname, [])
                        if not q:
                            self._send_method(ch, 60, 72,
                                              self._short_str(""))
                            continue
                        body = q.pop(0)
                        store.amqp_tag += 1
                        tag = store.amqp_tag
                        self.unacked[tag] = (qname, body)
                    getok = (struct.pack("!QB", tag, 0)
                             + self._short_str("") + self._short_str(qname)
                             + struct.pack("!I", len(q)))
                    self._send_method(ch, 60, 71, getok)
                    header = (struct.pack("!HHQH", 60, 0, len(body), 0x1000)
                              + b"\x02")
                    self.request.sendall(
                        struct.pack("!BHI", 2, ch, len(header))
                        + header + b"\xce")
                    self.request.sendall(
                        struct.pack("!BHI", 3, ch, len(body))
                        + body + b"\xce")
                elif (cid, mid) == (60, 80):  # basic.ack
                    (tag,) = struct.unpack_from("!Q", args, 0)
                    self.unacked.pop(tag, None)
                elif (cid, mid) == (10, 50):  # connection.close
                    self._send_method(0, 10, 51)
                    return
        except (ConnectionError, OSError, AssertionError, struct.error):
            # this connection's unacked messages redeliver on loss
            with store.lock:
                for _tag, (qname, body) in self.unacked.items():
                    store.amqp_queues.setdefault(qname, []).insert(0, body)
            self.unacked = {}
            return


class FakeAmqp(FakeServer):
    handler_class = _AmqpHandler


# ---------------------------------------------------------------------------
# ReQL (rethinkdb) — V0_4 JSON protocol, document store semantics
# ---------------------------------------------------------------------------


class _ReqlHandler(_RecvExact, socketserver.BaseRequestHandler):
    def _eval(self, term, row=None):
        """Evaluate the ReQL term subset the suite clients emit."""
        store = self.fake_store
        if not isinstance(term, list):
            if isinstance(term, dict):
                return {k: self._eval(v, row) for k, v in term.items()}
            return term
        tid = term[0]
        args = term[1] if len(term) > 1 else []
        opts = term[2] if len(term) > 2 else {}
        if tid == 14:   # DB
            return ("db", args[0])
        if tid == 57:   # DB_CREATE
            return {"dbs_created": 1}
        if tid == 60:   # TABLE_CREATE
            return {"tables_created": 1}
        if tid == 15:   # TABLE
            return ("table", args[1])
        if tid == 16:   # GET
            tbl = self._eval(args[0], row)
            key = self._eval(args[1], row)
            return store.kv.get(f"reql:{tbl[1]}:{key}")
        if tid == 56:   # INSERT
            tbl = self._eval(args[0], row)
            doc = self._eval(args[1], row)
            k = f"reql:{tbl[1]}:{doc['id']}"
            existed = k in store.kv
            if existed and opts.get("conflict") != "update":
                return {"inserted": 0, "errors": 1,
                        "first_error": "Duplicate primary key"}
            store.kv[k] = doc
            return {"inserted": 0 if existed else 1,
                    "replaced": 1 if existed else 0, "errors": 0}
        if tid == 53:   # UPDATE
            sel = args[0]
            if isinstance(sel, list) and sel[0] == 174:  # CONFIG update
                return {"replaced": 1, "errors": 0}
            if not (isinstance(sel, list) and sel[0] == 16):
                raise _ReqlAbort("fake reql: UPDATE selector must be GET")
            tbl = self._eval(sel[1][0], row)
            key = self._eval(sel[1][1], row)
            k = f"reql:{tbl[1]}:{key}"
            doc = store.kv.get(k)
            if doc is None:
                return {"skipped": 1, "replaced": 0, "unchanged": 0,
                        "errors": 0}
            updater = args[1]
            try:
                if isinstance(updater, list) and updater[0] == 69:  # FUNC
                    patch = self._eval(updater[1][1], row=doc)
                else:
                    patch = self._eval(updater, row=doc)
            except _ReqlAbort as e:
                return {"replaced": 0, "unchanged": 0, "errors": 1,
                        "first_error": str(e)}
            new = {**doc, **patch}
            if new == doc:
                return {"replaced": 0, "unchanged": 1, "errors": 0}
            store.kv[k] = new
            return {"replaced": 1, "unchanged": 0, "errors": 0}
        if tid == 65:   # BRANCH
            cond = self._eval(args[0], row)
            return self._eval(args[1] if cond else args[2], row)
        if tid == 17:   # EQ
            return self._eval(args[0], row) == self._eval(args[1], row)
        if tid == 31:   # GET_FIELD
            base = self._eval(args[0], row)
            return (base or {}).get(args[1])
        if tid == 10:   # VAR
            return row
        if tid == 12:   # ERROR
            raise _ReqlAbort(args[0])
        if tid == 2:    # MAKE_ARRAY
            return [self._eval(a, row) for a in args]
        raise _ReqlAbort(f"unsupported term {tid}")

    def handle(self):
        try:
            magic = struct.unpack("<I", self._recv_exact(4))[0]
            (keylen,) = struct.unpack("<I", self._recv_exact(4))
            self._recv_exact(keylen)
            self._recv_exact(4)  # protocol marker
            self.request.sendall(b"SUCCESS\x00")
            while True:
                token = struct.unpack("<q", self._recv_exact(8))[0]
                (ln,) = struct.unpack("<I", self._recv_exact(4))
                q = json.loads(self._recv_exact(ln))
                with self.fake_store.lock:
                    try:
                        result = self._eval(q[1])
                        reply = {"t": 1, "r": [result]}
                    except _ReqlAbort as e:
                        reply = {"t": 18, "r": [str(e)]}
                    except Exception as e:  # keep the connection alive
                        reply = {"t": 18, "r": [f"fake reql error: {e!r}"]}
                out = json.dumps(reply).encode()
                self.request.sendall(
                    struct.pack("<q", token) + struct.pack("<I", len(out))
                    + out)
        except (ConnectionError, OSError):
            return


class _ReqlAbort(Exception):
    pass


class FakeReql(FakeServer):
    handler_class = _ReqlHandler


# ---------------------------------------------------------------------------
# Aerospike AS_MSG
# ---------------------------------------------------------------------------


class _AerospikeHandler(_RecvExact, socketserver.BaseRequestHandler):
    def _reply(self, result_code, generation, bins):
        ops = b""
        for name, val in bins.items():
            nb = name.encode()
            if isinstance(val, str):
                vb, particle = val.encode(), 3  # string bin
            else:
                vb, particle = struct.pack(">q", val), 1
            ops += struct.pack(">IBBBB", 4 + len(nb) + len(vb), 1,
                               particle, 0, len(nb)) + nb + vb
        body = struct.pack(
            ">BBBBBBIIIHH", 22, 0, 0, 0, 0, result_code, generation, 0, 0,
            0, len(bins)) + ops
        self.request.sendall(
            struct.pack(">Q", (2 << 56) | (3 << 48) | len(body)) + body)

    def handle(self):
        store = self.fake_store
        with store.lock:
            if not hasattr(store, "as_records"):
                store.as_records = {}  # digest -> (bins dict, generation)
        try:
            while True:
                (proto,) = struct.unpack(">Q", self._recv_exact(8))
                payload = self._recv_exact(proto & 0xFFFFFFFFFFFF)
                info1, info2 = payload[1], payload[2]
                (gen_req,) = struct.unpack_from(">I", payload, 6)
                n_fields, n_ops = struct.unpack_from(">HH", payload, 18)
                off = payload[0]
                digest = None
                for _ in range(n_fields):
                    (sz,) = struct.unpack_from(">I", payload, off)
                    ftype = payload[off + 4]
                    if ftype == 4:
                        digest = payload[off + 5 : off + 4 + sz]
                    off += 4 + sz
                ops = []
                for _ in range(n_ops):
                    (sz,) = struct.unpack_from(">I", payload, off)
                    opid, particle, _v, nlen = struct.unpack_from(
                        ">BBBB", payload, off + 4)
                    name = payload[off + 8 : off + 8 + nlen].decode()
                    raw = payload[off + 8 + nlen : off + 4 + sz]
                    ops.append((opid, name, raw))
                    off += 4 + sz
                with store.lock:
                    rec = store.as_records.get(digest)
                    if info2 & 0x01:  # write
                        if info2 & 0x20 and rec is not None:  # create-only
                            self._reply(5, rec[1], {})
                            continue
                        if info2 & 0x04:  # generation check
                            cur_gen = rec[1] if rec else 0
                            if cur_gen != gen_req:
                                self._reply(3, cur_gen, {})
                                continue
                        bins = dict(rec[0]) if rec else {}
                        for opid, name, raw in ops:
                            if opid == 2:
                                bins[name] = struct.unpack(">q", raw)[0]
                            elif opid == 9:  # append to a string bin
                                bins[name] = (
                                    str(bins.get(name, "")) + raw.decode()
                                )
                        gen = (rec[1] if rec else 0) + 1
                        store.as_records[digest] = (bins, gen)
                        self._reply(0, gen, {})
                    elif info1 & 0x01:  # read
                        if rec is None:
                            self._reply(2, 0, {})
                        else:
                            self._reply(0, rec[1], rec[0])
                    else:
                        self._reply(4, 0, {})
        except (ConnectionError, OSError):
            return


class FakeAerospike(FakeServer):
    handler_class = _AerospikeHandler


# ---------------------------------------------------------------------------
# Dgraph alpha HTTP API (alter/query/mutate with upsert blocks) — enough
# for the dgraph suite's register and upsert clients.
# ---------------------------------------------------------------------------

_RE_DG_FUNC = _re.compile(
    r"q\(func:\s*eq\((\w+),\s*\"?([^\")]+)\"?\)\)"
    r"(?:\s*@filter\(eq\((\w+),\s*\"?([^\")]+)\"?\)\))?",
)
_RE_DG_NQUAD = _re.compile(
    r"^(uid\(u\)|_:\w+|<\w+>)\s+<(\w+)>\s+\"([^\"]*)\"\s+\.$"
)


class _DgraphHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _send(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _match(self, nodes, query: str):
        """uids matching the query's eq(func) (+ optional filter)."""
        m = _RE_DG_FUNC.search(query)
        if not m:
            return []
        pred, val, fpred, fval = m.groups()
        out = []
        for uid, preds in sorted(nodes.items()):
            if str(preds.get(pred)) != val:
                continue
            if fpred and str(preds.get(fpred)) != fval:
                continue
            out.append(uid)
        return out

    def _fields_for(self, raw):
        """Field names the query block requests: the identifier tokens
        inside the innermost block (striped preds like key_3 included)."""
        body = raw.split("{", 2)[-1]
        var_names = set(_re.findall(r"\b(\w+)\s+as\b", body))
        fields = []
        for tok in _re.findall(r"\b([A-Za-z_]\w*)\b(?!\s*\()", body):
            if (
                tok not in fields
                and tok not in ("as", "q", "func", "var")
                and tok not in var_names
            ):
                fields.append(tok)
        return fields

    # -- zero cluster-management surface (/state, /moveTablet) ---------
    # A toy two-group tablet map: every predicate seen in a mutation
    # lands in group "1"; /moveTablet reassigns it (500 for reserved
    # dgraph.* predicates, like the real zero).

    def _groups(self, st) -> dict:
        return st.kv.setdefault(
            "dgraph_groups", {"1": {"tablets": {}}, "2": {"tablets": {}}}
        )

    def _register_pred(self, st, pred) -> None:
        groups = self._groups(st)
        for g in groups.values():
            if pred in g["tablets"]:
                return
        groups["1"]["tablets"][pred] = {
            "predicate": pred, "groupId": 1,
        }

    def do_GET(self):
        st = self.fake_store
        parsed = urlparse(self.path)
        params = parse_qs(parsed.query)
        with st.lock:
            if parsed.path == "/state":
                groups = self._groups(st)
                self._send({
                    "groups": groups,
                    "zeros": {"1": {"addr": "n1:5080", "leader": True}},
                })
                return
            if parsed.path == "/moveTablet":
                pred = (params.get("tablet") or [""])[0]
                group = (params.get("group") or [""])[0]
                if pred.startswith("dgraph."):
                    self._send(
                        {"errors": [{"message":
                                     f"Unable to move reserved {pred}"}]},
                        500,
                    )
                    return
                groups = self._groups(st)
                tablet = None
                for g in groups.values():
                    tablet = g["tablets"].pop(pred, None)
                    if tablet is not None:
                        break
                if tablet is None:
                    tablet = {"predicate": pred}
                tablet["groupId"] = int(group) if group.isdigit() else group
                groups.setdefault(
                    str(group), {"tablets": {}}
                )["tablets"][pred] = tablet
                self._send({"data": f"moved {pred} to {group}"})
                return
        self._send({"errors": [{"message": f"no route {parsed.path}"}]}, 400)

    # -- txn-protocol plumbing (OCC, first-committer-wins) -------------
    # Versions are tracked per (uid, pred) and per (pred, value) index
    # entry; a txn's reads and writes are validated against them at
    # commit — the same conflict surface dgraph's real transactions
    # expose (TxnConflictException on racing upserts).

    def _txn(self, st, start_ts):
        txns = st.kv.setdefault("dgraph_txns", {})
        return txns.get(start_ts)

    def _new_ts(self, st) -> int:
        box = st.kv.setdefault("dgraph_ts", [1])
        box[0] += 1
        return box[0]

    def _bump(self, st, keys, commit_ts):
        vers = st.kv.setdefault("dgraph_vers", {})
        for k in keys:
            vers[k] = commit_ts

    def do_POST(self):
        st = self.fake_store
        parsed = urlparse(self.path)
        path = parsed.path
        params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        raw = self._body().decode()
        with st.lock:
            nodes = st.kv.setdefault("dgraph_nodes", {})
            vers = st.kv.setdefault("dgraph_vers", {})
            txns = st.kv.setdefault("dgraph_txns", {})
            if path == "/alter":
                self._send({"data": {"code": "Success"}})
                return
            if path == "/query":
                start_ts = int(params.get("startTs", 0))
                if not start_ts:
                    start_ts = self._new_ts(st)
                    txns[start_ts] = {"staged": [], "reads": set(),
                                      "writes": set()}
                txn = self._txn(st, start_ts)
                m = _RE_DG_FUNC.search(raw)
                uids = self._match(nodes, raw)
                fields = self._fields_for(raw)
                rows = []
                for uid in uids:
                    row = {}
                    for f in fields:
                        row[f] = uid if f == "uid" else nodes[uid].get(f)
                    rows.append(row)
                if txn is not None and m:
                    pred, val = m.group(1), m.group(2)
                    txn["reads"].add(f"idx|{pred}|{val}")
                    for uid in uids:
                        for f in fields:
                            if f != "uid":
                                txn["reads"].add(f"{uid}|{f}")
                self._send({
                    "data": {"q": rows},
                    "extensions": {"txn": {"start_ts": start_ts}},
                })
                return
            if path == "/commit":
                start_ts = int(params.get("startTs", 0))
                txn = txns.pop(start_ts, None)
                if txn is None:
                    self._send(
                        {"errors": [{"message": "unknown transaction"}]},
                        409,
                    )
                    return
                touched = txn["reads"] | txn["writes"]
                if any(vers.get(k, 0) > start_ts for k in touched):
                    self._send(
                        {"errors": [{"message":
                                     "Transaction has been aborted. "
                                     "Please retry"}]},
                        409,
                    )
                    return
                commit_ts = self._new_ts(st)
                write_keys = set(txn["writes"])
                for action in txn["staged"]:
                    kind = action[0]
                    if kind == "set":
                        _, uid, pred, val = action
                        nodes.setdefault(uid, {})[pred] = val
                        write_keys.add(f"{uid}|{pred}")
                        write_keys.add(f"idx|{pred}|{val}")
                    elif kind == "delnode":
                        _, uid = action
                        for pred, val in nodes.pop(uid, {}).items():
                            write_keys.add(f"{uid}|{pred}")
                            write_keys.add(f"idx|{pred}|{val}")
                    elif kind == "delpred":
                        _, uid, pred = action
                        val = nodes.get(uid, {}).pop(pred, None)
                        write_keys.add(f"{uid}|{pred}")
                        if val is not None:
                            write_keys.add(f"idx|{pred}|{val}")
                self._bump(st, write_keys, commit_ts)
                self._send({"data": {"code": "Success",
                                     "commit_ts": commit_ts}})
                return
            if path.startswith("/mutate") and "commitNow" not in params:
                # staged (transactional) mutation
                payload = json.loads(raw)
                if "mutations" not in payload and (
                    "set_nquads" in payload or "del_nquads" in payload
                ):
                    start_ts = int(params.get("startTs", 0))
                    if not start_ts:
                        start_ts = self._new_ts(st)
                        txns[start_ts] = {"staged": [], "reads": set(),
                                          "writes": set()}
                    txn = self._txn(st, start_ts)
                    created = {}
                    for line in payload.get("del_nquads", "").splitlines():
                        line = line.strip()
                        if not line:
                            continue
                        parts = line.split()
                        uid = parts[0].strip("<>")
                        if parts[1] == "*":
                            txn["staged"].append(("delnode", uid))
                            for pred, val in nodes.get(uid, {}).items():
                                txn["writes"].add(f"{uid}|{pred}")
                                txn["writes"].add(f"idx|{pred}|{val}")
                        else:
                            pred = parts[1].strip("<>")
                            txn["staged"].append(("delpred", uid, pred))
                            txn["writes"].add(f"{uid}|{pred}")
                    for line in payload.get("set_nquads", "").splitlines():
                        line = line.strip()
                        if not line:
                            continue
                        m = _RE_DG_NQUAD.match(line)
                        if not m:
                            continue
                        subj, pred, val = m.groups()
                        self._register_pred(st, pred)
                        if subj.startswith("<"):
                            uid = subj.strip("<>")
                        else:
                            blank = subj[2:]
                            uid = created.get(blank)
                            if uid is None:
                                n_id = st.kv.setdefault("dgraph_next", [1])
                                uid = f"0x{n_id[0]:x}"
                                n_id[0] += 1
                                created[blank] = uid
                        txn["staged"].append(("set", uid, pred, val))
                        txn["writes"].add(f"{uid}|{pred}")
                        txn["writes"].add(f"idx|{pred}|{val}")
                    self._send({
                        "data": {"code": "Success", "uids": created},
                        "extensions": {"txn": {"start_ts": start_ts}},
                    })
                    return
            if path.startswith("/mutate"):
                payload = json.loads(raw)
                uids = self._match(nodes, payload.get("query", ""))
                created = {}
                written = set()
                for mut in payload.get("mutations", []):
                    cond = mut.get("cond", "")
                    n = len(uids)
                    if "eq(len(u), 0)" in cond and n != 0:
                        continue
                    if "gt(len(u), 0)" in cond and n == 0:
                        continue
                    for line in mut.get("del_nquads", "").splitlines():
                        line = line.strip()
                        if not line:
                            continue
                        # `uid(u) * * .` deletes matched nodes wholesale;
                        # `uid(u) <pred> * .` deletes one predicate
                        if line.startswith("uid(u)"):
                            parts = line.split()
                            for uid in uids:
                                if parts[1] == "*":
                                    for pred, val in nodes.pop(
                                        uid, {}
                                    ).items():
                                        written.add(f"{uid}|{pred}")
                                        written.add(f"idx|{pred}|{val}")
                                else:
                                    pred = parts[1].strip("<>")
                                    val = nodes.get(uid, {}).pop(pred, None)
                                    written.add(f"{uid}|{pred}")
                                    if val is not None:
                                        written.add(f"idx|{pred}|{val}")
                    for line in mut.get("set_nquads", "").splitlines():
                        line = line.strip()
                        if not line:
                            continue
                        m = _RE_DG_NQUAD.match(line)
                        if not m:
                            continue
                        subj, pred, val = m.groups()
                        self._register_pred(st, pred)
                        if subj == "uid(u)":
                            for uid in uids:
                                nodes[uid][pred] = val
                                written.add(f"{uid}|{pred}")
                                written.add(f"idx|{pred}|{val}")
                        else:
                            blank = subj[2:]
                            uid = created.get(blank)
                            if uid is None:
                                n_id = st.kv.setdefault("dgraph_next", [1])
                                uid = f"0x{n_id[0]:x}"
                                n_id[0] += 1
                                nodes[uid] = {}
                                created[blank] = uid
                            nodes[uid][pred] = val
                            written.add(f"{uid}|{pred}")
                            written.add(f"idx|{pred}|{val}")
                if written:
                    self._bump(st, written, self._new_ts(st))
                self._send(
                    {
                        "data": {
                            "code": "Success",
                            "queries": {"q": [{"uid": u} for u in uids]},
                            "uids": created,
                        }
                    }
                )
                return
        self._send({"errors": [{"message": f"no route {path}"}]}, 400)


class FakeDgraph(FakeServer):
    handler_class = _DgraphHandler


# ---------------------------------------------------------------------------
# FaunaDB JSON wire API — evaluates the FQL-as-JSON subset the faunadb
# suite's register and g2 clients emit.  Everything runs under the store
# lock, so the fake is serializable by construction.
# ---------------------------------------------------------------------------


class _FaunaAbort(Exception):
    """Raised by the FQL ``abort`` form; rolls the transaction back."""


class _FaunaHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _send(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    # -- FQL-JSON evaluation ------------------------------------------
    def _ref_parts(self, r):
        # {"ref": {"@ref": "classes/cls/id"}} or {"@ref": "classes/cls/id"}
        if isinstance(r, dict):
            inner = r.get("ref", r)
            path = inner.get("@ref", "")
            parts = path.split("/")
            if len(parts) == 3 and parts[0] == "classes":
                return parts[1], parts[2]
            if len(parts) == 2 and parts[0] == "classes":
                return parts[1], None
        return None, None

    def _eval(self, docs, indexes, x):
        if not isinstance(x, (dict, list)):
            return x
        if isinstance(x, list):
            return [self._eval(docs, indexes, e) for e in x]
        if "create_class" in x:
            return {"ref": x["create_class"]["object"]["name"]}
        if "create_index" in x:
            obj = x["create_index"]["object"]
            cls, _ = self._ref_parts({"ref": obj["source"]})
            terms = obj.get("terms") or [{"field": ["data", "key"]}]
            values = obj.get("values") or [{"field": ["data", "value"]}]
            entry = {
                "cls": cls or obj["source"],
                "terms": terms[0]["field"][-1],
                "values": values[0]["field"][-1],
            }
            if len(values) > 1:
                # multi-value index rows (e.g. bank's [ref, balance])
                entry["values_multi"] = [v["field"] for v in values]
            indexes[obj["name"]] = entry
            return {"ref": obj["name"]}
        if "if" in x:
            cond = self._eval(docs, indexes, x["if"])
            branch = x["then"] if cond else x.get("else")
            return self._eval(docs, indexes, branch)
        if "not" in x:
            return not self._eval(docs, indexes, x["not"])
        if "equals" in x:
            vals = [self._eval(docs, indexes, v) for v in x["equals"]]
            return all(v == vals[0] for v in vals)
        if "exists" in x:
            tgt = x["exists"]
            if isinstance(tgt, dict) and "match" in tgt:
                idx = tgt["match"]["index"]
                terms = self._eval(docs, indexes, tgt.get("terms", []))
                entry = indexes.get(idx)
                if isinstance(entry, dict) and "cls" in entry:
                    cls, tfield = entry["cls"], entry["terms"]
                elif isinstance(entry, dict):
                    cls, tfield = self._ref_parts({"ref": entry})[0], "key"
                else:
                    cls, tfield = entry, "key"
                term = terms[0] if terms else None
                return any(
                    c == cls and d.get(tfield) == term
                    for (c, _i), d in docs.items()
                )
            cls, id_ = self._ref_parts(tgt)
            return (cls, id_) in docs
        if "paginate" in x:
            tgt = x["paginate"]
            if isinstance(tgt, dict) and "match" in tgt:
                idx = tgt["match"]["index"]
                terms = self._eval(docs, indexes, tgt["match"].get("terms", []))
                entry = indexes.get(idx) or {}
                cls = entry.get("cls") if isinstance(entry, dict) else entry
                tfield = entry.get("terms", "key") if isinstance(entry, dict) else "key"
                vfield = entry.get("values", "value") if isinstance(entry, dict) else "value"
                multi = entry.get("values_multi") if isinstance(entry, dict) else None
                term = terms[0] if terms else None
                matches = [
                    ((c, i), d)
                    for (c, i), d in sorted(docs.items(), key=lambda kv: str(kv[0]))
                    if c == cls and (term is None or d.get(tfield) == term)
                ]
                if multi:
                    # one row per doc: ["ref"] fields yield the ref map,
                    # data fields yield the stored value
                    rows = [
                        [
                            {"@ref": f"classes/{c}/{i}"}
                            if f == ["ref"]
                            else d.get(f[-1])
                            for f in multi
                        ]
                        for (c, i), d in matches
                    ]
                else:
                    rows = [d.get(vfield) for _ci, d in matches]
                return {"data": rows}
            return {"data": []}
        if "match" in x:
            return x  # only consumed via exists/paginate
        if "time" in x:
            return self._now_ts()
        if "add" in x:
            return sum(self._eval(docs, indexes, v) for v in x["add"])
        if "subtract" in x:
            vals = [self._eval(docs, indexes, v) for v in x["subtract"]]
            out = vals[0]
            for v in vals[1:]:
                out -= v
            return out
        if "lt" in x:
            vals = [self._eval(docs, indexes, v) for v in x["lt"]]
            return all(a < b for a, b in zip(vals, vals[1:]))
        if "do" in x:
            out = None
            for e in x["do"]:
                out = self._eval(docs, indexes, e)
            return out
        if "abort" in x:
            raise _FaunaAbort(str(self._eval(docs, indexes, x["abort"])))
        if "delete" in x:
            cls, id_ = self._ref_parts(x["delete"])
            if (cls, id_) not in docs:
                raise KeyError("instance not found")
            doc = docs.pop((cls, id_))
            self._log_version(cls, id_, None)  # tombstone for snapshots
            return {"data": doc}
        if "at" in x:
            ts = self._eval(docs, indexes, x["at"])
            snap = self._snapshot(ts)
            return self._eval(snap, indexes, x["expr"])
        if "create" in x:
            cls, id_ = self._ref_parts(x["create"])
            if id_ is None:  # class-only ref: the DB assigns the id
                box = self._st.kv.setdefault("fauna_ids", [0])
                box[0] += 1
                id_ = str(box[0])
            data = (
                x.get("params", {}).get("object", {}).get("data", {})
                .get("object", {})
            )
            data = {k: self._eval(docs, indexes, v) for k, v in data.items()}
            docs[(cls, id_)] = dict(data)
            self._log_version(cls, id_, docs[(cls, id_)])
            return {"ref": {"@ref": f"classes/{cls}/{id_}"}}
        if "update" in x:
            cls, id_ = self._ref_parts(x["update"])
            data = (
                x.get("params", {}).get("object", {}).get("data", {})
                .get("object", {})
            )
            if (cls, id_) not in docs:
                raise KeyError("instance not found")
            data = {k: self._eval(docs, indexes, v) for k, v in data.items()}
            # replace rather than mutate: rollback keeps a SHALLOW copy
            # of docs, so doc dicts must be treated as immutable
            docs[(cls, id_)] = {**docs[(cls, id_)], **data}
            self._log_version(cls, id_, docs[(cls, id_)])
            return {"ref": {"@ref": f"classes/{cls}/{id_}"}}
        if "select" in x:
            path = x["select"]
            src = x["from"]
            if isinstance(src, dict) and "get" in src:
                cls, id_ = self._ref_parts(src["get"])
                doc = docs.get((cls, id_))
                if doc is None:
                    return x.get("default")
                cur = {"data": doc}
            else:
                cur = self._eval(docs, indexes, src)
            for p in path:
                if not isinstance(cur, dict) or p not in cur:
                    return x.get("default")
                cur = cur[p]
            return cur
        if "get" in x:
            cls, id_ = self._ref_parts(x["get"])
            doc = docs.get((cls, id_))
            if doc is None:
                raise KeyError("instance not found")
            # real Fauna instances carry their last-write timestamp;
            # the multimonotonic workload reads it
            return {
                "data": doc,
                "ts": self._st.kv.get("fauna_doc_ts", {}).get((cls, id_)),
            }
        return x

    # -- time + versioned snapshots -----------------------------------
    # One timestamp per request (allocated lazily by the first Time()
    # or mutation); every create/update logs the doc state at that ts,
    # so At(ts, …) reads evaluate against a historical snapshot — the
    # temporal-query surface the monotonic workload exercises.

    def _now_ts(self) -> str:
        if self._req_ts is None:
            box = self._st.kv.setdefault("fauna_ts", [0])
            box[0] += 1
            self._req_ts = f"{box[0]:012d}"
        return self._req_ts

    def _log_version(self, cls, id_, data) -> None:
        log = self._st.kv.setdefault("fauna_log", [])
        log.append(
            (self._now_ts(), cls, id_, dict(data) if data is not None else None)
        )
        doc_ts = self._st.kv.setdefault("fauna_doc_ts", {})
        doc_ts[(cls, id_)] = self._now_ts()

    def _snapshot(self, ts: str) -> dict:
        snap: dict = {}
        for t, cls, id_, data in self._st.kv.get("fauna_log", []):
            if t <= str(ts):
                if data is None:  # tombstone: deleted at t
                    snap.pop((cls, id_), None)
                else:
                    snap[(cls, id_)] = data
        return snap

    def do_POST(self):
        st = self.fake_store
        raw = self._body().decode()
        self._st = st
        self._req_ts = None
        with st.lock:
            docs = st.kv.setdefault("fauna_docs", {})
            indexes = st.kv.setdefault("fauna_indexes", {})
            log = st.kv.setdefault("fauna_log", [])
            doc_ts = st.kv.setdefault("fauna_doc_ts", {})
            # transactions are atomic: an abort / error mid-`do` rolls
            # back earlier effects.  Shallow copies suffice — doc dicts
            # are replaced, never mutated, and the append-only log just
            # truncates — so rollback cost is O(live docs), not
            # O(version history).
            docs_backup = dict(docs)
            ts_backup = dict(doc_ts)
            log_len = len(log)

            def rollback():
                docs.clear()
                docs.update(docs_backup)
                doc_ts.clear()
                doc_ts.update(ts_backup)
                del log[log_len:]

            try:
                expr = json.loads(raw)
                out = self._eval(docs, indexes, expr)
            except _FaunaAbort as e:
                rollback()
                self._send({"errors": [{
                    "code": "transaction aborted",
                    "description": f"transaction aborted: {e}"}]})
                return
            except KeyError as e:
                rollback()
                self._send({"errors": [{"code": "instance not found",
                                        "description": str(e)}]})
                return
            except Exception as e:  # noqa: BLE001 - fake returns errors
                rollback()
                self._send({"errors": [{"description": repr(e)}]})
                return
        self._send({"resource": out})


class FakeFauna(FakeServer):
    handler_class = _FaunaHandler


# ---------------------------------------------------------------------------
# CrateDB HTTP _sql endpoint — evaluates the statement shapes the crate
# suite's register/dirty-read/lost-updates/version-divergence clients
# emit, with crate's _version optimistic-concurrency semantics.
# ---------------------------------------------------------------------------


class _CrateHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _send(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        st = self.fake_store
        n = int(self.headers.get("Content-Length") or 0)
        payload = json.loads(self.rfile.read(n).decode() or "{}")
        stmt = (payload.get("stmt") or "").strip().rstrip(";")
        args = list(payload.get("args") or [])
        low = stmt.lower()
        with st.lock:
            # registers: {id: [value, version]}
            regs = st.kv.setdefault("crate_regs", {})
            # dirty_read: set of ids
            dr = st.kv.setdefault("crate_dirty", set())
            # sets: {id: [elements_json, version]}
            sets_ = st.kv.setdefault("crate_sets", {})
            try:
                self._send(self._eval(low, args, regs, dr, sets_))
            except Exception as e:  # noqa: BLE001 - fake returns errors
                self._send({"error": {"message": repr(e)}}, 400)

    def _eval(self, low, args, regs, dr, sets_):
        if low.startswith(("create table", "refresh table", "alter table")):
            return {"rowcount": 1, "rows": []}
        if low.startswith("select value, _version from registers"):
            row = regs.get(args[0])
            return {"cols": ["value", "_version"],
                    "rows": [list(row)] if row else []}
        if low.startswith("select value from registers"):
            row = regs.get(args[0])
            return {"cols": ["value"], "rows": [[row[0]]] if row else []}
        if low.startswith("insert into registers"):
            k, v = args[0], args[1]
            if k in regs:
                if "on duplicate key" not in low:
                    raise ValueError("duplicate key")
                regs[k] = [args[2], regs[k][1] + 1]
            else:
                regs[k] = [v, 1]
            return {"rowcount": 1}
        if low.startswith("update registers set value"):
            new, k, old = args[0], args[1], args[2]
            if k in regs and regs[k][0] == old:
                regs[k] = [new, regs[k][1] + 1]
                return {"rowcount": 1}
            return {"rowcount": 0}
        if low.startswith("insert into dirty_read"):
            dr.add(args[0])
            return {"rowcount": 1}
        if low.startswith("select id from dirty_read where"):
            return {"cols": ["id"],
                    "rows": [[args[0]]] if args[0] in dr else []}
        if low.startswith("select id from dirty_read"):
            return {"cols": ["id"], "rows": [[i] for i in sorted(dr)]}
        if low.startswith("select elements, _version from sets"):
            row = sets_.get(args[0])
            return {"cols": ["elements", "_version"],
                    "rows": [list(row)] if row else []}
        if low.startswith("select elements from sets"):
            row = sets_.get(args[0])
            return {"cols": ["elements"], "rows": [[row[0]]] if row else []}
        if low.startswith("insert into sets"):
            k, els = args[0], args[1]
            if k in sets_:
                raise ValueError("duplicate key")
            sets_[k] = [els, 1]
            return {"rowcount": 1}
        if low.startswith("update sets set elements"):
            els2, k, version = args[0], args[1], args[2]
            if k in sets_ and sets_[k][1] == version:
                sets_[k] = [els2, version + 1]
                return {"rowcount": 1}
            return {"rowcount": 0}
        raise ValueError(f"unhandled stmt: {low!r}")


class FakeCrate(FakeServer):
    handler_class = _CrateHandler


# ---------------------------------------------------------------------------
# Elasticsearch HTTP subset — index-by-id PUT, GET-by-id, _refresh, and
# _search (match_all; single page, no scroll) for the es suite's set and
# dirty-read clients.
# ---------------------------------------------------------------------------


class _EsHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _send(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _docs(self):
        return self.fake_store.kv.setdefault("es_docs", {})

    def do_PUT(self):
        parts = urlparse(self.path).path.strip("/").split("/")
        n = int(self.headers.get("Content-Length") or 0)
        doc = json.loads(self.rfile.read(n).decode() or "{}")
        with self.fake_store.lock:
            if len(parts) == 3:
                index, _type, id_ = parts
                self._docs()[(index, id_)] = doc
                self._send({"result": "created"}, 201)
                return
            if len(parts) == 1:  # index creation with settings
                self._send({"acknowledged": True})
                return
        self._send({"error": "bad path"}, 400)

    def do_GET(self):
        parts = urlparse(self.path).path.strip("/").split("/")
        with self.fake_store.lock:
            if len(parts) == 3:
                index, _type, id_ = parts
                doc = self._docs().get((index, id_))
                if doc is None:
                    self._send({"found": False}, 404)
                else:
                    self._send({"found": True, "_source": doc})
                return
        self._send({"error": "bad path"}, 400)

    def do_POST(self):
        path = urlparse(self.path).path
        n = int(self.headers.get("Content-Length") or 0)
        self.rfile.read(n)
        with self.fake_store.lock:
            if path.endswith("/_refresh"):
                self._send({"_shards": {"total": 1, "successful": 1}})
                return
            if path.endswith("/_search"):
                index = path.strip("/").split("/")[0]
                hits = [
                    {"_id": id_, "_source": doc}
                    for (ix, id_), doc in sorted(self._docs().items())
                    if ix == index
                ]
                self._send({"hits": {"hits": hits}})
                return
            if path == "/_search/scroll":
                self._send({"hits": {"hits": []}})
                return
        self._send({"error": f"no route {path}"}, 400)


class FakeEs(FakeServer):
    handler_class = _EsHandler


# ---------------------------------------------------------------------------
# Ignite REST API fake: /ignite?cmd=get|put|add|cas over per-cache maps
# ---------------------------------------------------------------------------


class _IgniteHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _send(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        url = urlparse(self.path)
        if url.path != "/ignite":
            self._send({"error": f"no route {url.path}"}, 400)
            return
        q = {k: v[0] for k, v in parse_qs(url.query).items()}
        cmd = q.get("cmd")
        cache_name = q.get("cacheName", "default")
        with self.fake_store.lock:
            cache = self.fake_store.kv.setdefault(cache_name, {})
            key = q.get("key")
            if cmd == "get":
                resp = cache.get(key)
            elif cmd == "put":
                cache[key] = q.get("val")
                resp = True
            elif cmd == "add":  # putIfAbsent
                if key in cache:
                    resp = False
                else:
                    cache[key] = q.get("val")
                    resp = True
            elif cmd == "cas":  # set val1 if current == val2
                if cache.get(key) == q.get("val2"):
                    cache[key] = q.get("val1")
                    resp = True
                else:
                    resp = False
            else:
                self._send(
                    {"successStatus": 1, "error": f"bad cmd {cmd}"}
                )
                return
        self._send({"successStatus": 0, "response": resp})


class FakeIgnite(FakeServer):
    handler_class = _IgniteHandler


# ---------------------------------------------------------------------------
# Hazelcast open binary client protocol (1.x framing, IMDG 3.12)
# ---------------------------------------------------------------------------


class _HazelcastHandler(_RecvExact, socketserver.BaseRequestHandler):
    """Differential peer for jepsen_tpu.suites.proto.hazelcast: same
    frame spec, implementing maps, queues, locks (per client-uuid +
    thread-id ownership), semaphores, atomic longs/references, and
    flake-id batches over the shared store."""

    def _ensure(self):
        st = self.fake_store
        if not hasattr(st, "hz_maps"):
            st.hz_maps = {}        # name -> {key bytes: value bytes}
            st.hz_queues = {}      # name -> list[bytes]
            st.hz_locks = {}       # name -> (uuid, thread_id, count)
            st.hz_flocks = {}      # name -> [holder|None, count, fence, next_fence]
            st.hz_sems = {}        # name -> available permits
            st.hz_longs = {}       # name -> int
            st.hz_refs = {}        # name -> bytes | None
            st.hz_flake = {}       # name -> next id

    def _reply(self, corr, rtype, payload=b""):
        from jepsen_tpu.suites.proto.hazelcast import HEADER, HEADER_SIZE

        self.request.sendall(
            HEADER.pack(
                HEADER_SIZE + len(payload), 1, 0xC0, rtype, corr, -1,
                HEADER_SIZE,
            )
            + payload
        )

    def _error(self, corr, cls, msg):
        import struct as _s

        from jepsen_tpu.suites.proto import hazelcast as hz

        payload = (
            _s.pack("<i", 0)
            + b"\x00" + _s.pack("<i", len(cls)) + cls.encode()
            + b"\x00" + _s.pack("<i", len(msg)) + msg.encode()
        )
        self._reply(corr, hz.RESP_ERROR, payload)

    @staticmethod
    def _nullable_data(d):
        import struct as _s

        if d is None:
            return b"\x01"
        return b"\x00" + _s.pack("<i", len(d)) + d

    def handle(self):
        import struct as _s
        import time as _t

        from jepsen_tpu.suites.proto import hazelcast as hz

        self._ensure()
        st = self.fake_store
        try:
            prefix = self._recv_exact(3)
            if prefix != hz.PROTOCOL_PREFIX:
                return
            client_uuid = f"c-{id(self.request) & 0xFFFF:x}"
            while True:
                head = self._recv_exact(hz.HEADER_SIZE)
                ln, _v, _f, mtype, corr, _part, off = hz.HEADER.unpack(head)
                body = self._recv_exact(ln - hz.HEADER_SIZE)
                r = hz._Reader(head + body, off)

                if mtype == hz.AUTH:
                    group = r.string()
                    password = r.string()
                    if group != "jepsen" or password != "jepsen-pass":
                        self._reply(corr, hz.RESP_AUTH, b"\x01")
                        continue
                    payload = (
                        b"\x00"          # status ok
                        + b"\x01"        # null address
                        + b"\x00" + _s.pack("<i", len(client_uuid))
                        + client_uuid.encode()
                        + b"\x01"        # null owner uuid
                    )
                    self._reply(corr, hz.RESP_AUTH, payload)

                elif mtype == hz.MAP_GET:
                    name, key = r.string(), r.data()
                    with st.lock:
                        v = st.hz_maps.get(name, {}).get(key)
                    self._reply(corr, hz.RESP_DATA, self._nullable_data(v))
                elif mtype == hz.MAP_PUT:
                    name, key, val = r.string(), r.data(), r.data()
                    with st.lock:
                        prev = st.hz_maps.setdefault(name, {}).get(key)
                        st.hz_maps[name][key] = val
                    self._reply(corr, hz.RESP_DATA, self._nullable_data(prev))
                elif mtype == hz.MAP_PUT_IF_ABSENT:
                    name, key, val = r.string(), r.data(), r.data()
                    with st.lock:
                        m = st.hz_maps.setdefault(name, {})
                        prev = m.get(key)
                        if prev is None:
                            m[key] = val
                    self._reply(corr, hz.RESP_DATA, self._nullable_data(prev))
                elif mtype == hz.MAP_REPLACE_IF_SAME:
                    name, key = r.string(), r.data()
                    old, new = r.data(), r.data()
                    with st.lock:
                        m = st.hz_maps.setdefault(name, {})
                        okb = m.get(key) == old
                        if okb:
                            m[key] = new
                    self._reply(corr, hz.RESP_BOOL, bytes([okb]))

                elif mtype == hz.QUEUE_OFFER:
                    name, val = r.string(), r.data()
                    with st.lock:
                        st.hz_queues.setdefault(name, []).append(val)
                    self._reply(corr, hz.RESP_BOOL, b"\x01")
                elif mtype == hz.QUEUE_POLL:
                    name = r.string()
                    with st.lock:
                        q = st.hz_queues.setdefault(name, [])
                        v = q.pop(0) if q else None
                    self._reply(corr, hz.RESP_DATA, self._nullable_data(v))

                elif mtype in (hz.LOCK_LOCK, hz.LOCK_TRY_LOCK):
                    name = r.string()
                    if mtype == hz.LOCK_LOCK:
                        _lease = r.i64()
                        tid = r.i64()
                        deadline = _t.monotonic() + 30.0
                    else:
                        tid = r.i64()
                        _lease = r.i64()
                        timeout = r.i64()
                        deadline = _t.monotonic() + timeout / 1000.0
                    me = (client_uuid, tid)
                    got = False
                    while True:
                        with st.lock:
                            holder = st.hz_locks.get(name)
                            if holder is None:
                                st.hz_locks[name] = (me[0], me[1], 1)
                                got = True
                            elif holder[:2] == me:  # reentrant
                                st.hz_locks[name] = (
                                    me[0], me[1], holder[2] + 1
                                )
                                got = True
                        if got or _t.monotonic() >= deadline:
                            break
                        _t.sleep(0.002)
                    if mtype == hz.LOCK_LOCK:
                        self._reply(corr, hz.RESP_VOID)
                    else:
                        self._reply(corr, hz.RESP_BOOL, bytes([got]))
                elif mtype == hz.LOCK_UNLOCK:
                    name = r.string()
                    tid = r.i64()
                    with st.lock:
                        holder = st.hz_locks.get(name)
                        if holder is None or holder[:2] != (client_uuid, tid):
                            err = True
                        else:
                            err = False
                            if holder[2] == 1:
                                del st.hz_locks[name]
                            else:
                                st.hz_locks[name] = (
                                    holder[0], holder[1], holder[2] - 1
                                )
                    if err:
                        self._error(
                            corr, "IllegalMonitorStateException",
                            "not the lock owner",
                        )
                    else:
                        self._reply(corr, hz.RESP_VOID)

                elif mtype == hz.FENCED_LOCK_TRY_LOCK:
                    name = r.string()
                    tid = r.i64()
                    timeout = r.i64()
                    deadline = _t.monotonic() + timeout / 1000.0
                    me = (client_uuid, tid)
                    fence = 0
                    while True:
                        with st.lock:
                            lk = st.hz_flocks.setdefault(
                                name, [None, 0, 0, 1]
                            )
                            if lk[0] is None:
                                lk[0], lk[1] = me, 1
                                lk[2] = lk[3]  # grant a fresh token
                                lk[3] += 1
                                fence = lk[2]
                            elif lk[0] == me:
                                lk[1] += 1
                                fence = lk[2]  # reuse the hold's token
                        if fence or _t.monotonic() >= deadline:
                            break
                        _t.sleep(0.002)
                    self._reply(corr, hz.RESP_LONG, _s.pack("<q", fence))
                elif mtype == hz.FENCED_LOCK_UNLOCK:
                    name = r.string()
                    tid = r.i64()
                    me = (client_uuid, tid)
                    with st.lock:
                        lk = st.hz_flocks.get(name)
                        err = lk is None or lk[0] != me
                        if not err:
                            lk[1] -= 1
                            if lk[1] == 0:
                                lk[0] = None
                                lk[2] = 0
                    if err:
                        self._error(
                            corr, "IllegalMonitorStateException",
                            "not the fenced-lock owner",
                        )
                    else:
                        self._reply(corr, hz.RESP_VOID)

                elif mtype == hz.SEMAPHORE_INIT:
                    name, permits = r.string(), r.i32()
                    with st.lock:
                        fresh = name not in st.hz_sems
                        if fresh:
                            st.hz_sems[name] = permits
                    self._reply(corr, hz.RESP_BOOL, bytes([fresh]))
                elif mtype == hz.SEMAPHORE_TRY_ACQUIRE:
                    name, permits = r.string(), r.i32()
                    timeout = r.i64()
                    deadline = _t.monotonic() + timeout / 1000.0
                    got = False
                    while True:
                        with st.lock:
                            avail = st.hz_sems.get(name, 0)
                            if avail >= permits:
                                st.hz_sems[name] = avail - permits
                                got = True
                        if got or _t.monotonic() >= deadline:
                            break
                        _t.sleep(0.002)
                    self._reply(corr, hz.RESP_BOOL, bytes([got]))
                elif mtype == hz.SEMAPHORE_RELEASE:
                    name, permits = r.string(), r.i32()
                    with st.lock:
                        st.hz_sems[name] = st.hz_sems.get(name, 0) + permits
                    self._reply(corr, hz.RESP_VOID)

                elif mtype == hz.ATOMIC_LONG_ADD_AND_GET:
                    name, delta = r.string(), r.i64()
                    with st.lock:
                        v = st.hz_longs.get(name, 0) + delta
                        st.hz_longs[name] = v
                    self._reply(corr, hz.RESP_LONG, _s.pack("<q", v))
                elif mtype == hz.ATOMIC_LONG_INCREMENT_AND_GET:
                    name = r.string()
                    with st.lock:
                        v = st.hz_longs.get(name, 0) + 1
                        st.hz_longs[name] = v
                    self._reply(corr, hz.RESP_LONG, _s.pack("<q", v))
                elif mtype == hz.ATOMIC_LONG_GET:
                    name = r.string()
                    with st.lock:
                        v = st.hz_longs.get(name, 0)
                    self._reply(corr, hz.RESP_LONG, _s.pack("<q", v))
                elif mtype == hz.ATOMIC_LONG_SET:
                    name, v = r.string(), r.i64()
                    with st.lock:
                        st.hz_longs[name] = v
                    self._reply(corr, hz.RESP_VOID)
                elif mtype == hz.ATOMIC_LONG_COMPARE_AND_SET:
                    name, old, new = r.string(), r.i64(), r.i64()
                    with st.lock:
                        okb = st.hz_longs.get(name, 0) == old
                        if okb:
                            st.hz_longs[name] = new
                    self._reply(corr, hz.RESP_BOOL, bytes([okb]))

                elif mtype == hz.ATOMIC_REF_GET:
                    name = r.string()
                    with st.lock:
                        v = st.hz_refs.get(name)
                    self._reply(corr, hz.RESP_DATA, self._nullable_data(v))
                elif mtype == hz.ATOMIC_REF_SET:
                    name = r.string()
                    v = r.nullable_data()
                    with st.lock:
                        st.hz_refs[name] = v
                    self._reply(corr, hz.RESP_VOID)
                elif mtype == hz.ATOMIC_REF_COMPARE_AND_SET:
                    name = r.string()
                    old, new = r.nullable_data(), r.nullable_data()
                    with st.lock:
                        okb = st.hz_refs.get(name) == old
                        if okb:
                            st.hz_refs[name] = new
                    self._reply(corr, hz.RESP_BOOL, bytes([okb]))

                elif mtype == hz.FLAKE_ID_NEW_BATCH:
                    name, n = r.string(), r.i32()
                    with st.lock:
                        base = st.hz_flake.get(name, 0)
                        st.hz_flake[name] = base + n
                    self._reply(
                        corr, hz.RESP_LONG,
                        _s.pack("<qqi", base, 1, n),
                    )
                else:
                    self._error(
                        corr, "UnsupportedOperationException",
                        f"fake hazelcast: message type {mtype:#06x}",
                    )
        except ConnectionError:
            return


class FakeHazelcast(FakeServer):
    handler_class = _HazelcastHandler
