"""Hazelcast suite: binary protocol roundtrips, the full workload
matrix run in-process against the fake server, and the lock/permit
models catching real violations (reference:
hazelcast/src/jepsen/hazelcast.clj:117-768)."""

import sys

import pytest

sys.path.insert(0, "tests")

from fake_servers import FakeHazelcast  # noqa: E402

from jepsen_tpu import checker as checker_mod  # noqa: E402
from jepsen_tpu import core  # noqa: E402
from jepsen_tpu import db as db_mod  # noqa: E402
from jepsen_tpu import models  # noqa: E402
from jepsen_tpu.history import History  # noqa: E402
from jepsen_tpu.suites import hazelcast  # noqa: E402
from jepsen_tpu.suites.proto.hazelcast import (  # noqa: E402
    HzClient,
    HzError,
    data_long,
    data_string,
    parse_data,
)


def _suite_test(server, workload, **extra):
    t = hazelcast.test(
        {
            "nodes": ["n1", "n2", "n3"],
            "host": "127.0.0.1",
            "client-port": server.port,
            "time-limit": 1.5,
            "op-limit": 24,
            "workload": workload,
            "faults": [],
            **extra,
        }
    )
    t["db"] = db_mod.noop()
    t["ssh"] = {"dummy?": True}
    return t


# -- protocol ---------------------------------------------------------------


def test_hz_proto_roundtrip():
    s = FakeHazelcast().start()
    try:
        c = HzClient("127.0.0.1", s.port).connect()
        assert c.uuid
        # map CAS primitives
        k = data_string("hi")
        assert c.map_put_if_absent("m", k, data_long(1)) is None
        assert parse_data(c.map_get("m", k)) == 1
        assert c.map_replace_if_same("m", k, data_long(1), data_long(2))
        assert not c.map_replace_if_same("m", k, data_long(1), data_long(3))
        # queue
        assert c.queue_offer("q", data_long(7))
        assert parse_data(c.queue_poll("q")) == 7
        assert c.queue_poll("q") is None
        # lock: exclusivity across sessions, unlock by non-owner errors
        c2 = HzClient("127.0.0.1", s.port).connect()
        assert c.try_lock("L")
        assert not c2.try_lock("L", timeout_ms=10)
        with pytest.raises(HzError):
            c2.unlock("L")
        c.unlock("L")
        assert c2.try_lock("L")
        # semaphore: 2 permits
        assert c.semaphore_init("S", 2)
        assert c.semaphore_try_acquire("S")
        assert c2.semaphore_try_acquire("S")
        assert not c.semaphore_try_acquire("S", timeout_ms=10)
        c2.semaphore_release("S")
        assert c.semaphore_try_acquire("S")
        # atomics
        assert c.atomic_add_and_get("a", 5) == 5
        assert c.atomic_compare_and_set("a", 5, 9)
        assert not c.atomic_compare_and_set("a", 5, 9)
        assert c.atomic_increment_and_get("a") == 10
        # atomic reference
        assert c.ref_get("r") is None
        c.ref_set("r", data_long(3))
        assert c.ref_compare_and_set("r", data_long(3), data_long(4))
        assert parse_data(c.ref_get("r")) == 4
        # flake ids: disjoint across sessions
        ids = c.new_id_batch("f", 3) + c2.new_id_batch("f", 3)
        assert len(set(ids)) == 6
        c.close()
        c2.close()
        # bad credentials (either field) are rejected
        for group, pw in (("wrong", "jepsen-pass"), ("jepsen", "wrong")):
            with pytest.raises(HzError):
                HzClient(
                    "127.0.0.1", s.port, group=group, password=pw
                ).connect()
    finally:
        s.stop()


def test_hz_fenced_lock_tokens_are_monotonic():
    """CP fenced lock: grants carry strictly increasing tokens across
    holds; a holder's re-acquire reuses the hold's token; contended
    tryLock times out with INVALID_FENCE; non-owner unlock errors."""
    from jepsen_tpu.suites.proto.hazelcast import INVALID_FENCE

    s = FakeHazelcast().start()
    try:
        c1 = HzClient("127.0.0.1", s.port).connect()
        c2 = HzClient("127.0.0.1", s.port).connect()
        f1 = c1.try_lock_fenced("FL")
        assert f1 != INVALID_FENCE
        # re-acquire returns the same token (reentrant hold)
        assert c1.try_lock_fenced("FL") == f1
        # contended: invalid fence
        assert c2.try_lock_fenced("FL", timeout_ms=10) == INVALID_FENCE
        with pytest.raises(HzError):
            c2.unlock_fenced("FL")
        c1.unlock_fenced("FL")
        c1.unlock_fenced("FL")  # second hold
        f2 = c2.try_lock_fenced("FL")
        assert f2 > f1  # strictly increasing across holds
        c2.unlock_fenced("FL")
        c1.close()
        c2.close()
    finally:
        s.stop()


def test_hz_fenced_workloads_carry_real_tokens():
    """The fenced workloads' clients stamp live fencing tokens on
    completions (not the INVALID placeholder), so the
    fence-monotonicity models check real tokens end-to-end."""
    s = FakeHazelcast().start()
    try:
        t = _suite_test(s, "non-reentrant-fenced-lock")
        c = t["client"].open(t, "n1")
        r1 = c.invoke(t, {"f": "acquire", "type": "invoke", "value": None})
        assert r1["type"] == "ok" and r1["value"]["fence"] >= 1
        c.invoke(t, {"f": "release", "type": "invoke", "value": None})
        r2 = c.invoke(t, {"f": "acquire", "type": "invoke", "value": None})
        assert r2["value"]["fence"] > r1["value"]["fence"]
        c.close(t)
    finally:
        s.stop()


def test_hz_crdt_map_targets_crdt_map_name():
    """The crdt-map workload must drive jepsen.crdt-map, not the plain
    map (reference: hazelcast.clj:450-451 map-name/crdt-map-name)."""
    t = hazelcast.test({"workload": "crdt-map", "nodes": ["n1"]})
    assert t["client"].map_name == "jepsen.crdt-map"
    t2 = hazelcast.test({"workload": "map", "nodes": ["n1"]})
    assert t2["client"].map_name == "jepsen.map"


def test_hz_map_client_cas_race():
    """Two map clients race an add: the loser reports cas-failed, the
    final read contains the winner (reference map-client semantics:
    one CAS attempt per invoke)."""
    s = FakeHazelcast().start()
    try:
        t = {"nodes": ["n1"]}
        c1 = hazelcast.HzMapClient(
            {"host": "127.0.0.1", "client-port": s.port}
        ).open(t, "n1")
        c2 = hazelcast.HzMapClient(
            {"host": "127.0.0.1", "client-port": s.port}
        ).open(t, "n1")
        assert c1.invoke(t, {"f": "add", "value": 1, "type": "invoke"})[
            "type"] == "ok"
        assert c2.invoke(t, {"f": "add", "value": 2, "type": "invoke"})[
            "type"] == "ok"
        r = c1.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["value"] == [1, 2]
        # force a lost race: swap the stored value between c2's read
        # and CAS by writing through c1 concurrently is racy to stage
        # reliably here; the protocol-level replace_if_same false path
        # is already pinned in test_hz_proto_roundtrip
        c1.close(t)
        c2.close(t)
    finally:
        s.stop()


# -- full in-process runs ---------------------------------------------------


@pytest.mark.parametrize(
    "workload",
    [
        "map",
        "lock",
        "non-reentrant-cp-lock",
        "reentrant-cp-lock",
        "non-reentrant-fenced-lock",
        "reentrant-fenced-lock",
        "cp-semaphore",
        "queue",
        "atomic-long-ids",
        "atomic-ref-ids",
        "id-gen-ids",
    ],
)
def test_hz_workload_full_test_in_process(workload):
    s = FakeHazelcast().start()
    try:
        t = _suite_test(s, workload)
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_hz_cas_long_full_test_in_process():
    s = FakeHazelcast().start()
    try:
        t = _suite_test(s, "cp-cas-long", **{"per-key-limit": 12})
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_hz_cas_reference_client_roundtrip():
    s = FakeHazelcast().start()
    try:
        t = {"nodes": ["n1"]}
        c = hazelcast.HzCasRefClient(
            {"host": "127.0.0.1", "client-port": s.port}
        ).open(t, "n1")
        r = c.invoke(t, {"f": "read", "value": [0, None], "type": "invoke"})
        assert r["type"] == "ok" and tuple(r["value"]) == (0, 0)
        assert c.invoke(t, {"f": "write", "value": [0, 5],
                            "type": "invoke"})["type"] == "ok"
        assert c.invoke(t, {"f": "cas", "value": [0, [5, 6]],
                            "type": "invoke"})["type"] == "ok"
        assert c.invoke(t, {"f": "cas", "value": [0, [5, 7]],
                            "type": "invoke"})["type"] == "fail"
        assert tuple(
            c.invoke(t, {"f": "read", "value": [0, None],
                         "type": "invoke"})["value"]
        ) == (0, 6)
        c.close(t)
    finally:
        s.stop()


# -- the models catch real violations ---------------------------------------


def _h(ops):
    return History.from_dicts(ops)


def test_owner_mutex_checker_catches_double_grant():
    """Two clients both told they hold the lock: no linearization
    exists, whatever the order."""
    chk = checker_mod.linearizable(models.owner_mutex(), pure_fs=())
    bad = _h([
        {"process": 0, "type": "invoke", "f": "acquire", "value": None},
        {"process": 0, "type": "ok", "f": "acquire",
         "value": {"client": "a"}},
        {"process": 1, "type": "invoke", "f": "acquire", "value": None},
        {"process": 1, "type": "ok", "f": "acquire",
         "value": {"client": "b"}},
    ])
    assert chk.check({}, bad)["valid?"] is False
    good = _h([
        {"process": 0, "type": "invoke", "f": "acquire", "value": None},
        {"process": 0, "type": "ok", "f": "acquire",
         "value": {"client": "a"}},
        {"process": 0, "type": "invoke", "f": "release", "value": None},
        {"process": 0, "type": "ok", "f": "release",
         "value": {"client": "a"}},
        {"process": 1, "type": "invoke", "f": "acquire", "value": None},
        {"process": 1, "type": "ok", "f": "acquire",
         "value": {"client": "b"}},
    ])
    assert chk.check({}, good)["valid?"] is True


def test_owner_mutex_indeterminate_release_stays_checkable():
    """An indeterminate release (network timeout, op may have applied)
    must not poison the model: the info completion carries WHO acted,
    so a later legitimate acquire by another client linearizes (info
    release happened first).  Regression: info values propagate onto
    invocations in the oracle's pairing pass."""
    chk = checker_mod.linearizable(models.owner_mutex(), pure_fs=())
    h = _h([
        {"process": 0, "type": "invoke", "f": "acquire", "value": None},
        {"process": 0, "type": "ok", "f": "acquire",
         "value": {"client": "a"}},
        {"process": 0, "type": "invoke", "f": "release", "value": None},
        {"process": 0, "type": "info", "f": "release",
         "value": {"client": "a"}},
        {"process": 1, "type": "invoke", "f": "acquire", "value": None},
        {"process": 1, "type": "ok", "f": "acquire",
         "value": {"client": "b"}},
    ])
    assert chk.check({}, h)["valid?"] is True


def test_owner_mutex_checker_catches_foreign_release():
    chk = checker_mod.linearizable(models.owner_mutex(), pure_fs=())
    bad = _h([
        {"process": 0, "type": "invoke", "f": "acquire", "value": None},
        {"process": 0, "type": "ok", "f": "acquire",
         "value": {"client": "a"}},
        {"process": 1, "type": "invoke", "f": "release", "value": None},
        {"process": 1, "type": "ok", "f": "release",
         "value": {"client": "b"}},
    ])
    assert chk.check({}, bad)["valid?"] is False


def test_fenced_mutex_checker_catches_stale_fence():
    chk = checker_mod.linearizable(models.fenced_mutex(), pure_fs=())
    bad = _h([
        {"process": 0, "type": "invoke", "f": "acquire", "value": None},
        {"process": 0, "type": "ok", "f": "acquire",
         "value": {"client": "a", "fence": 7}},
        {"process": 0, "type": "invoke", "f": "release", "value": None},
        {"process": 0, "type": "ok", "f": "release",
         "value": {"client": "a", "fence": 0}},
        # fence goes backwards: 7 then 7 again
        {"process": 1, "type": "invoke", "f": "acquire", "value": None},
        {"process": 1, "type": "ok", "f": "acquire",
         "value": {"client": "b", "fence": 7}},
    ])
    assert chk.check({}, bad)["valid?"] is False


def test_acquired_permits_checker_catches_over_issue():
    """Three grants against two permits can never linearize."""
    chk = checker_mod.linearizable(
        models.acquired_permits(2), pure_fs=()
    )
    ops = []
    for p, client in ((0, "a"), (1, "b"), (2, "c")):
        ops.append({"process": p, "type": "invoke", "f": "acquire",
                    "value": None})
        ops.append({"process": p, "type": "ok", "f": "acquire",
                    "value": {"client": client}})
    assert chk.check({}, _h(ops))["valid?"] is False
    # two grants + a release + a third grant is fine
    ok_ops = ops[:4] + [
        {"process": 0, "type": "invoke", "f": "release", "value": None},
        {"process": 0, "type": "ok", "f": "release",
         "value": {"client": "a"}},
    ] + ops[4:]
    assert chk.check({}, _h(ok_ops))["valid?"] is True


def test_reentrant_mutex_checker_bounds_reacquires():
    chk = checker_mod.linearizable(models.reentrant_mutex(), pure_fs=())
    ops = []
    for _ in range(3):  # three acquires by the same holder: one too many
        ops.append({"process": 0, "type": "invoke", "f": "acquire",
                    "value": None})
        ops.append({"process": 0, "type": "ok", "f": "acquire",
                    "value": {"client": "a"}})
    assert chk.check({}, _h(ops))["valid?"] is False
    assert chk.check({}, _h(ops[:4]))["valid?"] is True
