"""Model state-machine tests (knossos.model oracle semantics)."""

from jepsen_tpu import models as m
from jepsen_tpu.history import invoke_op


def op(f, value=None):
    return invoke_op(0, f, value)


def test_register():
    r = m.register()
    r = r.step(op("write", 5))
    assert r == m.register(5)
    assert not r.step(op("read", 5)).is_inconsistent
    assert r.step(op("read", 6)).is_inconsistent
    assert not r.step(op("read", None)).is_inconsistent  # unknown read passes


def test_cas_register():
    r = m.cas_register(0)
    assert r.step(op("cas", (0, 5))) == m.cas_register(5)
    assert r.step(op("cas", (1, 5))).is_inconsistent
    assert r.step(op("write", 7)) == m.cas_register(7)
    assert r.step(op("read", 0)) == r
    assert r.step(op("read", 3)).is_inconsistent
    assert r.step(op("bogus")).is_inconsistent


def test_mutex():
    mu = m.mutex()
    assert mu.step(op("release")).is_inconsistent
    locked = mu.step(op("acquire"))
    assert locked == m.Mutex(True)
    assert locked.step(op("acquire")).is_inconsistent
    assert locked.step(op("release")) == m.mutex()


def test_multi_register():
    r = m.multi_register({})
    r = r.step(op("txn", [("w", "x", 1), ("w", "y", 2)]))
    assert not r.step(op("txn", [("r", "x", 1), ("r", "y", 2)])).is_inconsistent
    assert r.step(op("txn", [("r", "x", 2)])).is_inconsistent
    # read-your-writes inside one txn
    assert not r.step(op("txn", [("w", "x", 9), ("r", "x", 9)])).is_inconsistent


def test_fifo_queue():
    q = m.fifo_queue()
    assert q.step(op("dequeue", 1)).is_inconsistent
    q = q.step(op("enqueue", 1)).step(op("enqueue", 2))
    assert q.step(op("dequeue", 2)).is_inconsistent
    q = q.step(op("dequeue", 1))
    assert q == m.FIFOQueue((2,))


def test_unordered_queue():
    q = m.unordered_queue()
    q = q.step(op("enqueue", 1)).step(op("enqueue", 2)).step(op("enqueue", 1))
    assert not q.step(op("dequeue", 2)).is_inconsistent
    q2 = q.step(op("dequeue", 1)).step(op("dequeue", 1))
    assert not q2.is_inconsistent
    assert q2.step(op("dequeue", 1)).is_inconsistent


def test_inconsistent_absorbing():
    bad = m.inconsistent("x")
    assert bad.step(op("write", 1)).is_inconsistent
    assert bad == m.inconsistent("y")  # equality ignores message


def test_models_hashable_for_dedup():
    assert len({m.register(1), m.register(1), m.register(2)}) == 2
    assert len({m.Mutex(True), m.Mutex(True)}) == 1
    assert hash(m.cas_register(3)) == hash(m.cas_register(3))


# -- unordered-queue device kernel ------------------------------------------


def _gen_queue_history(rng, n_procs=4, n_ops=24, corrupt=False):
    """A simulated concurrent unique-element unordered queue: enqueues
    of fresh values, dequeues returning any present element; ops
    linearize at completion.  corrupt=True makes one dequeue claim a
    value that was never (or no longer) in the queue."""
    from jepsen_tpu.history import History, invoke_op, ok_op, fail_op

    present = set()
    next_v = 1
    pending = {}
    idle = list(range(n_procs))
    hist = []
    done = 0
    while done < n_ops or pending:
        if idle and done < n_ops and (not pending or rng.random() < 0.6):
            p = idle.pop(rng.randrange(len(idle)))
            if present and rng.random() < 0.45:
                hist.append(invoke_op(p, "dequeue", None))
                pending[p] = ("dequeue", None)
            else:
                v = next_v
                next_v += 1
                hist.append(invoke_op(p, "enqueue", v))
                pending[p] = ("enqueue", v)
            done += 1
        else:
            p = rng.choice(list(pending))
            f, v = pending.pop(p)
            idle.append(p)
            if f == "enqueue":
                present.add(v)
                hist.append(ok_op(p, "enqueue", v))
            else:
                if present:
                    got = rng.choice(sorted(present))
                    present.discard(got)
                    hist.append(ok_op(p, "dequeue", got))
                else:
                    hist.append(fail_op(p, "dequeue", None, error="empty"))
    h = History(hist)
    if corrupt and len(h) > 4:
        deqs = [i for i, op in enumerate(h)
                if op.type == "ok" and op.f == "dequeue"]
        if deqs:
            i = rng.choice(deqs)
            h[i] = h[i].copy(value=next_v + 7)  # never enqueued
    for i, op in enumerate(h):
        op.index = i
        op.time = i
    return h.index_ops()


def test_unordered_queue_kernel_differential():
    """check_batch verdicts must match the exponential search on random
    queue histories — the knossos model-set parity item
    (jepsen/src/jepsen/checker.clj:19-26).  Since the direct
    per-value-matching checker measured 4.6x the dense kernel, auto
    dispatch routes queue batches to it (engine "oracle-routed"); the
    search here is the un-hooked generic one so the comparison stays a
    real differential."""
    import random

    from jepsen_tpu import models
    from jepsen_tpu.checker import linear
    from jepsen_tpu.ops import wgl

    rng = random.Random(45100)
    hists = [
        _gen_queue_history(rng, corrupt=(i % 3 == 0)) for i in range(24)
    ]
    model = models.unordered_queue()
    oracle = []
    for h in hists:
        ev, op_l = linear.prepare(h)
        oracle.append(
            linear._search_fast(
                model, ev, op_l, linear.DEFAULT_MAX_CONFIGS, None, None
            )["valid?"]
        )
    outs = wgl.check_batch(model, hists)
    got = [o["valid?"] for o in outs]
    assert got == oracle, list(zip(got, oracle))
    assert {o["engine"] for o in outs} == {"oracle-routed"}
    assert {o.get("algorithm") for o in outs} == {"direct-unordered-queue"}
    assert any(v is False for v in oracle), "no corrupted history failed"


def test_unordered_queue_kernel_envelope_fallbacks():
    """Histories outside the bitset envelope (duplicate enqueues, >31
    values, unknown dequeue values) ride the oracle, not a wrong
    device verdict."""
    from jepsen_tpu import models
    from jepsen_tpu.history import History, invoke_op, ok_op
    from jepsen_tpu.ops import wgl

    def mk(ops):
        h = History(ops)
        for i, op in enumerate(h):
            op.index = i
            op.time = i
        return h.index_ops()

    model = models.unordered_queue()

    # duplicate enqueue of one value: multiset semantics, oracle-only
    dup = mk([
        invoke_op(0, "enqueue", 5), ok_op(0, "enqueue", 5),
        invoke_op(0, "enqueue", 5), ok_op(0, "enqueue", 5),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 5),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 5),
    ])
    out = wgl.check_batch(model, [dup])[0]
    assert out["valid?"] is True
    assert out["engine"].startswith("oracle"), out

    # too many distinct values for the 31-bit set
    wide = []
    for v in range(1, 40):
        wide += [invoke_op(0, "enqueue", v), ok_op(0, "enqueue", v)]
    out = wgl.check_batch(model, [mk(wide)])[0]
    assert out["valid?"] is True
    assert out["engine"].startswith("oracle"), out


def test_unordered_queue_kernel_basics():
    from jepsen_tpu import models
    from jepsen_tpu.history import History, invoke_op, ok_op
    from jepsen_tpu.ops import wgl

    def mk(ops):
        h = History(ops)
        for i, op in enumerate(h):
            op.index = i
            op.time = i
        return h.index_ops()

    model = models.unordered_queue()
    good = mk([
        invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
        invoke_op(1, "enqueue", 2), ok_op(1, "enqueue", 2),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 2),
        invoke_op(1, "dequeue", None), ok_op(1, "dequeue", 1),
    ])
    out = wgl.check_batch(model, [good])[0]
    assert out["valid?"] is True, out
    assert out["engine"] == "oracle-routed", out  # direct-first routing

    # dequeue of a value never enqueued
    bad = mk([
        invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 3),
    ])
    out = wgl.check_batch(model, [bad])[0]
    assert out["valid?"] is False, out


def test_unordered_queue_sufficient_rung_keeps_device():
    """The queue's 2^C sufficient bound: many distinct values at modest
    concurrency must resolve on-device even from a tiny frontier —
    never the oracle (state is a function of the linset, so 2^C configs
    bound the space)."""
    import random

    from jepsen_tpu import models
    from jepsen_tpu.checker import linear
    from jepsen_tpu.ops import wgl

    assert wgl.sufficient_frontier(30, 8, "unordered-queue") == 256
    assert wgl.sufficient_frontier(40, 8) is None  # 40·256 > cap

    rng = random.Random(5)
    hists = [
        _gen_queue_history(rng, n_procs=6, n_ops=24,
                           corrupt=(i % 3 == 0))
        for i in range(8)
    ]
    model = models.unordered_queue()
    # max_closure forces the GENERIC kernel (auto dispatch now picks the
    # dense queue kernel): the 2^C rung must still rescue its overflows
    C = 6
    outs = wgl.check_batch(model, hists, frontier=8, escalation=(),
                           max_closure=C + 1, slot_cap=C)
    assert all(o["engine"] == "tpu" for o in outs), [
        o["engine"] for o in outs
    ]
    assert {o.get("kernel") for o in outs} == {"frontier"}
    oracle = [linear.analysis(model, h)["valid?"] for h in hists]
    assert [o["valid?"] for o in outs] == oracle


def test_unordered_queue_dense_kernel_three_way_differential():
    """The dense queue kernel (bitset over 2^C linsets, no sorts) must
    agree with both the generic frontier kernel and the CPU oracle on
    random queue histories, including double-dequeue corruptions."""
    import random

    from jepsen_tpu import models
    from jepsen_tpu.checker import linear
    from jepsen_tpu.ops import wgl

    rng = random.Random(77)
    hists = []
    for i in range(30):
        h = _gen_queue_history(rng, n_procs=5, n_ops=20,
                               corrupt=(i % 3 == 0))
        hists.append(h)
    # a targeted double-dequeue corruption: two dequeues claim one value
    from jepsen_tpu.history import History, invoke_op, ok_op

    dd = History([
        invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1),
        invoke_op(1, "dequeue", None), ok_op(1, "dequeue", 1),
    ])
    for i, op in enumerate(dd):
        op.index = i
        op.time = i
    hists.append(dd.index_ops())

    model = models.unordered_queue()
    oracle = [linear.analysis(model, h)["valid?"] for h in hists]
    # the dense bitset kernel stays differential-tested even though
    # production routes queue batches to the direct checker: dispatch
    # it explicitly at the batch's encoded shapes
    import numpy as np

    from jepsen_tpu.ops import dense, encode

    batch = encode.batch_encode(hists, model, slot_cap=8)
    assert not batch.fallback
    E = batch.ev_slot.shape[1]
    C = batch.cand_slot.shape[2]
    ok_d, _f, _o = dense.make_dense_fn("unordered-queue", E, C, 0)(
        batch.init_state, batch.ev_slot, batch.cand_slot,
        batch.cand_f, batch.cand_a, batch.cand_b,
    )
    dense_verdicts = [bool(v) for v in np.asarray(ok_d)]
    assert dense_verdicts == [v is True for v in oracle]
    assert oracle[-1] is False  # the double dequeue is caught
    # generic kernel agreement at the same shapes
    generic = wgl.check_batch(model, hists, max_closure=9, slot_cap=8,
                              frontier=512)
    assert [o["valid?"] for o in generic] == oracle


# -- owner-mutex dense reduction --------------------------------------------


def _gen_owner_lock_history(rng, n_procs=4, n_ops=24, corrupt=False,
                            crash_p=0.0):
    """A simulated distributed lock with session identities: each
    process is one client; acquires succeed only on a free lock,
    releases only by the holder (linearizing at completion).
    corrupt=True fabricates a double grant — the violation the
    owner-aware model exists to catch."""
    from jepsen_tpu.history import History, invoke_op, ok_op, fail_op, info_op

    holder = None
    state = {p: 0 for p in range(n_procs)}  # 0 = out, 1 = holding
    open_release = set()  # procs with an unresolved (info) release
    pending = {}
    idle = list(range(n_procs))
    hist = []
    done = 0
    corrupted = False
    while done < n_ops or pending:
        if idle and done < n_ops and (not pending or rng.random() < 0.6):
            p = idle.pop(rng.randrange(len(idle)))
            f = "release" if state[p] else "acquire"
            hist.append(invoke_op(p, f, None))
            pending[p] = f
            done += 1
        else:
            p = rng.choice(list(pending))
            f = pending.pop(p)
            idle.append(p)
            me = {"client": f"c{p}"}
            if rng.random() < crash_p:
                hist.append(info_op(p, f, me, error="maybe"))
                # the op may or may not have applied; model it applied
                # half the time so later sim stays coherent
                applied = rng.random() < 0.5
            else:
                applied = True
            if f == "acquire":
                if holder is None:
                    if applied:
                        holder = p
                        state[p] = 1
                    if hist[-1].type != "info":
                        hist.append(ok_op(p, f, me))
                elif (corrupt and not corrupted
                      and holder not in open_release
                      and pending.get(holder) != "release"):
                    # fabricate a grant while held: double ownership.
                    # Only a definite violation counts: the completion
                    # must be OK (an info grant is indeterminate) and
                    # the holder must have NO open release that could
                    # linearize before this grant
                    if hist[-1].type != "info":
                        hist.append(ok_op(p, f, me))
                        corrupted = True
                else:
                    if hist[-1].type != "info":
                        hist.append(fail_op(p, f, None, error="held"))
            else:  # release
                if holder == p:
                    if applied:
                        holder = None
                        state[p] = 0
                    if hist[-1].type != "info":
                        hist.append(ok_op(p, f, me))
                    else:
                        open_release.add(p)
                else:
                    state[p] = 0
                    if hist[-1].type != "info":
                        hist.append(fail_op(p, f, None, error="not-owner"))
    h = History(hist)
    for i, op in enumerate(h):
        op.index = i
        op.time = i
    return h.index_ops(), corrupted


def test_owner_mutex_dense_reduction_differential():
    """OwnerMutex rides the cas-register kernel family (acquire =
    cas(free -> c), release = cas(c -> free)); device verdicts must
    match the CPU oracle, and clean in-envelope histories must land on
    the dense kernel, not the oracle."""
    import random

    from jepsen_tpu import models
    from jepsen_tpu.checker import linear
    from jepsen_tpu.ops import wgl

    rng = random.Random(45103)
    hists = []
    expect_invalid = []
    for i in range(24):
        h, corrupted = _gen_owner_lock_history(
            rng, n_procs=4, n_ops=20, corrupt=(i % 3 == 0),
            crash_p=0.1 if i % 5 == 0 else 0.0,
        )
        hists.append(h)
        expect_invalid.append(corrupted)
    model = models.owner_mutex()
    oracle = [linear.analysis(model, h)["valid?"] for h in hists]
    outs = wgl.check_batch(model, hists)
    got = [o["valid?"] for o in outs]
    assert got == oracle
    # fabricated double grants are caught
    for v, bad in zip(got, expect_invalid):
        if bad:
            assert v is False
    # the reduction really engages the device: every history without
    # identity gaps encodes, and in-envelope batches run dense
    stats = wgl.batch_stats(outs)
    assert stats["device-rate"] > 0.9, stats
    # 5 clients + free = 6 value ids, C = 4: inside the dense envelope
    assert stats["kernels"].get("dense", 0) == max(
        stats["kernels"].values()
    ), stats


def _gen_reentrant_lock_history(rng, n_procs=4, n_ops=24, corrupt=False):
    """Simulated reentrant lock (hold bound 2): the holder may
    re-acquire; releases peel one hold.  corrupt=True fabricates either
    a grant to a non-holder while held, or a third re-acquire."""
    from jepsen_tpu.history import History, invoke_op, ok_op, fail_op

    holder = None
    count = 0
    pending = {}
    idle = list(range(n_procs))
    hist = []
    done = 0
    corrupted = False
    while done < n_ops or pending:
        if idle and done < n_ops and (not pending or rng.random() < 0.6):
            p = idle.pop(rng.randrange(len(idle)))
            wants_release = holder == p and count > 0 and rng.random() < 0.6
            f = "release" if wants_release else "acquire"
            hist.append(invoke_op(p, f, None))
            pending[p] = f
            done += 1
        else:
            p = rng.choice(list(pending))
            f = pending.pop(p)
            idle.append(p)
            me = {"client": f"c{p}"}
            if f == "acquire":
                if holder is None:
                    holder, count = p, 1
                    hist.append(ok_op(p, f, me))
                elif holder == p and count < 2:
                    count += 1
                    hist.append(ok_op(p, f, me))
                elif corrupt and not corrupted and not any(
                    pf == "release" for pp, pf in pending.items()
                    if pp == holder
                ):
                    # fabricate: grant while fully held (foreign or 3rd)
                    hist.append(ok_op(p, f, me))
                    corrupted = True
                else:
                    hist.append(fail_op(p, f, None, error="held"))
            else:  # release (only the holder ever invokes one here)
                if holder == p and count > 0:
                    count -= 1
                    if count == 0:
                        holder = None
                    hist.append(ok_op(p, f, me))
                else:
                    hist.append(fail_op(p, f, None, error="not-owner"))
    h = History(hist)
    for i, op in enumerate(h):
        op.index = i
        op.time = i
    return h.index_ops(), corrupted


def test_reentrant_mutex_dense_kernel_differential():
    """ReentrantMutex runs its own dense automaton (state ids 0 free /
    2c-1 once / 2c twice); device verdicts must match the CPU oracle,
    fabricated over-grants must be caught, and in-envelope batches land
    on the dense kernel."""
    import random

    from jepsen_tpu import models
    from jepsen_tpu.checker import linear
    from jepsen_tpu.ops import wgl

    rng = random.Random(45104)
    hists = []
    expect_invalid = []
    for i in range(24):
        h, corrupted = _gen_reentrant_lock_history(
            rng, n_procs=4, n_ops=20, corrupt=(i % 3 == 0)
        )
        hists.append(h)
        expect_invalid.append(corrupted)
    model = models.reentrant_mutex()
    oracle = [linear.analysis(model, h)["valid?"] for h in hists]
    outs = wgl.check_batch(model, hists)
    got = [o["valid?"] for o in outs]
    assert got == oracle, list(zip(got, oracle))
    for v, bad in zip(got, expect_invalid):
        if bad:
            assert v is False
    assert any(expect_invalid)
    stats = wgl.batch_stats(outs)
    assert stats["device-rate"] > 0.9, stats
    assert stats["kernels"].get("dense", 0) == max(
        stats["kernels"].values()
    ), stats
    # a non-default hold bound has no kernel: oracle fallback, same
    # verdicts
    m3 = models.reentrant_mutex(max_count=3)
    out3 = wgl.check_batch(m3, hists[:4])
    assert all(o["engine"].startswith("oracle") for o in out3), out3
    # a held owner with count outside the {1,2} algebra (count=0 is
    # constructible) must also fall back, never silently diverge
    weird = models.ReentrantMutex(owner="c", count=0)
    outw = wgl.check_batch(weird, hists[:2])
    assert all(o["engine"].startswith("oracle") for o in outw), outw


def test_synth_lock_history_generator():
    """synth.generate_lock_history (the benchmark corpus): clean
    histories are valid, corrupt ones definitely invalid, and every
    history encodes for the device kernels even at contended shapes
    (engines stay "tpu" — nothing falls back to the oracle)."""
    import random

    from jepsen_tpu import models, synth
    from jepsen_tpu.ops import wgl

    rng = random.Random(45105)
    for reentrant, model in (
        (False, models.owner_mutex()),
        (True, models.reentrant_mutex()),
    ):
        hists = [
            synth.generate_lock_history(
                rng, n_procs=8, n_ops=60, reentrant=reentrant,
                corrupt=(i % 4 == 0),
            )
            for i in range(12)
        ]
        # contended: histories are dense with successful cycles
        assert all(
            sum(1 for op in h if op.type == "ok") >= 40 for h in hists
        )
        out = wgl.check_batch(model, hists)
        assert {o["engine"] for o in out} == {"tpu"}, wgl.batch_stats(out)
        got = [o["valid?"] for o in out]
        assert got == [False if i % 4 == 0 else True for i in range(12)]


def test_acquired_permits_dense_kernel_differential():
    """The semaphore (acquired-permits) automaton — table-built state
    enumeration over client multisets — must match the oracle on
    random contended permit histories with fabricated over-issues, and
    serve them dense (the spec is dense_only: no frontier kernel
    exists)."""
    import random

    from jepsen_tpu import models, synth
    from jepsen_tpu.checker import linear
    from jepsen_tpu.ops import wgl

    rng = random.Random(45108)
    hists = [
        synth.generate_permits_history(
            rng, n_procs=5, n_ops=24, corrupt=(i % 3 == 0)
        )
        for i in range(16)
    ]
    model = models.acquired_permits(2)
    oracle = [linear.analysis(model, h)["valid?"] for h in hists]
    outs = wgl.check_batch(model, hists)
    assert [o["valid?"] for o in outs] == oracle
    assert False in oracle and True in oracle
    stats = wgl.batch_stats(outs)
    assert stats["engines"] == {"tpu": 16}, stats
    assert stats["kernels"] == {"dense": 16}, stats


def test_acquired_permits_dense_only_fallbacks():
    """Outside the dense envelope the permits spec has NO kernel at
    all: an explicit max_closure (which would force the frontier
    kernel) and a non-empty initial multiset both route to the oracle
    with identical verdicts."""
    import random

    from jepsen_tpu import models, synth
    from jepsen_tpu.ops import wgl

    rng = random.Random(45109)
    hists = [
        synth.generate_permits_history(
            rng, n_procs=4, n_ops=16, corrupt=(i % 2 == 0)
        )
        for i in range(4)
    ]
    model = models.acquired_permits(2)
    base = [o["valid?"] for o in wgl.check_batch(model, hists)]
    forced = wgl.check_batch(model, hists, max_closure=6)
    assert [o["valid?"] for o in forced] == base
    assert all(o["engine"].startswith("oracle") for o in forced), forced
    # a non-empty initial multiset has no state id until the client
    # count is known: encode refuses, oracle answers
    seeded = models.AcquiredPermits(2, (("c9", 1),))
    out = wgl.check_batch(seeded, hists[:2])
    assert all(o["engine"].startswith("oracle") for o in out), out
