"""Model state-machine tests (knossos.model oracle semantics)."""

from jepsen_tpu import models as m
from jepsen_tpu.history import invoke_op


def op(f, value=None):
    return invoke_op(0, f, value)


def test_register():
    r = m.register()
    r = r.step(op("write", 5))
    assert r == m.register(5)
    assert not r.step(op("read", 5)).is_inconsistent
    assert r.step(op("read", 6)).is_inconsistent
    assert not r.step(op("read", None)).is_inconsistent  # unknown read passes


def test_cas_register():
    r = m.cas_register(0)
    assert r.step(op("cas", (0, 5))) == m.cas_register(5)
    assert r.step(op("cas", (1, 5))).is_inconsistent
    assert r.step(op("write", 7)) == m.cas_register(7)
    assert r.step(op("read", 0)) == r
    assert r.step(op("read", 3)).is_inconsistent
    assert r.step(op("bogus")).is_inconsistent


def test_mutex():
    mu = m.mutex()
    assert mu.step(op("release")).is_inconsistent
    locked = mu.step(op("acquire"))
    assert locked == m.Mutex(True)
    assert locked.step(op("acquire")).is_inconsistent
    assert locked.step(op("release")) == m.mutex()


def test_multi_register():
    r = m.multi_register({})
    r = r.step(op("txn", [("w", "x", 1), ("w", "y", 2)]))
    assert not r.step(op("txn", [("r", "x", 1), ("r", "y", 2)])).is_inconsistent
    assert r.step(op("txn", [("r", "x", 2)])).is_inconsistent
    # read-your-writes inside one txn
    assert not r.step(op("txn", [("w", "x", 9), ("r", "x", 9)])).is_inconsistent


def test_fifo_queue():
    q = m.fifo_queue()
    assert q.step(op("dequeue", 1)).is_inconsistent
    q = q.step(op("enqueue", 1)).step(op("enqueue", 2))
    assert q.step(op("dequeue", 2)).is_inconsistent
    q = q.step(op("dequeue", 1))
    assert q == m.FIFOQueue((2,))


def test_unordered_queue():
    q = m.unordered_queue()
    q = q.step(op("enqueue", 1)).step(op("enqueue", 2)).step(op("enqueue", 1))
    assert not q.step(op("dequeue", 2)).is_inconsistent
    q2 = q.step(op("dequeue", 1)).step(op("dequeue", 1))
    assert not q2.is_inconsistent
    assert q2.step(op("dequeue", 1)).is_inconsistent


def test_inconsistent_absorbing():
    bad = m.inconsistent("x")
    assert bad.step(op("write", 1)).is_inconsistent
    assert bad == m.inconsistent("y")  # equality ignores message


def test_models_hashable_for_dedup():
    assert len({m.register(1), m.register(1), m.register(2)}) == 2
    assert len({m.Mutex(True), m.Mutex(True)}) == 1
    assert hash(m.cas_register(3)) == hash(m.cas_register(3))
