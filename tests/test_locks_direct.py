"""Differential validation of the polynomial plain-mutex checker
(checker/locks_direct.py) against the generic exponential search —
the correctness gate for replacing search with greedy alternation
scheduling (SURVEY.md §4's golden-history + differential strategy)."""

import random

import pytest

from jepsen_tpu import models as m
from jepsen_tpu.checker import linear, locks_direct
from jepsen_tpu.history import History, invoke_op, ok_op, info_op, fail_op


def h(*ops) -> History:
    hist = History(ops)
    for i, op in enumerate(hist):
        op.index = i
        op.time = i
    return hist


def generic_search(model, history):
    """The un-hooked exponential search (what linear.analysis runs for
    every non-plain-mutex model) — the differential reference."""
    events, ops = linear.prepare(history)
    return linear._search_fast(
        model, events, ops, linear.DEFAULT_MAX_CONFIGS, None, None
    )


def gen_mutex_history(rng, n_procs, n_events, corrupt=False, crash_p=0.0):
    """Contended-lock history with optional double-grant corruption and
    crashed (info) ops."""
    hist = []
    idle = list(range(n_procs))
    waiting, holding, releasing = [], [], []
    lock_free = True
    corrupted = False
    while len(hist) < n_events or waiting or holding or releasing:
        moves = []
        if idle and len(hist) < n_events:
            moves.append("inv_acq")
        if waiting and (lock_free or (corrupt and not corrupted)):
            moves.append("grant")
        if holding:
            moves.append("inv_rel")
        if releasing:
            moves.append("ok_rel")
        if not moves:
            break
        mv = rng.choice(moves)
        if mv == "inv_acq":
            p = idle.pop(rng.randrange(len(idle)))
            hist.append(invoke_op(p, "acquire", None))
            waiting.append(p)
        elif mv == "grant":
            if not lock_free:
                corrupted = True
            p = waiting.pop(rng.randrange(len(waiting)))
            if crash_p and rng.random() < crash_p:
                # process crashes mid-acquire and leaves every pool;
                # the lock state it leaves behind is ambiguous
                hist.append(info_op(p, "acquire", None))
            else:
                hist.append(ok_op(p, "acquire", None))
                holding.append(p)
                lock_free = False
        elif mv == "inv_rel":
            p = holding.pop(rng.randrange(len(holding)))
            hist.append(invoke_op(p, "release", None))
            releasing.append(p)
            lock_free = True
        else:
            p = releasing.pop(rng.randrange(len(releasing)))
            if crash_p and rng.random() < crash_p:
                hist.append(info_op(p, "release", None))
            else:
                hist.append(ok_op(p, "release", None))
            idle.append(p)
    return h(*hist)


def test_golden_valid():
    good = h(
        invoke_op(0, "acquire"), ok_op(0, "acquire"),
        invoke_op(1, "acquire"),  # blocks
        invoke_op(0, "release"), ok_op(0, "release"),
        ok_op(1, "acquire"),
        invoke_op(1, "release"), ok_op(1, "release"),
    )
    assert locks_direct.analysis(m.mutex(), good)["valid?"] is True


def test_golden_double_hold():
    bad = h(
        invoke_op(0, "acquire"), ok_op(0, "acquire"),
        invoke_op(1, "acquire"), ok_op(1, "acquire"),
    )
    out = locks_direct.analysis(m.mutex(), bad)
    assert out["valid?"] is False
    assert out["op"]["process"] == 1


def test_golden_release_free_lock():
    bad = h(invoke_op(0, "release"), ok_op(0, "release"))
    assert locks_direct.analysis(m.mutex(), bad)["valid?"] is False


def test_crashed_acquire_enables_release():
    """An info acquire may linearize (knossos: concurrent forever), so
    a later completed release IS linearizable."""
    ok = h(
        invoke_op(0, "acquire"), info_op(0, "acquire"),
        invoke_op(1, "release"), ok_op(1, "release"),
    )
    assert locks_direct.analysis(m.mutex(), ok)["valid?"] is True


def test_failed_ops_dropped():
    ok = h(
        invoke_op(0, "acquire"), fail_op(0, "acquire"),
        invoke_op(1, "release"), ok_op(1, "release"),
    )
    # the failed acquire never happened; the release has no lock
    assert locks_direct.analysis(m.mutex(), ok)["valid?"] is False


def test_initial_locked_state():
    hist = h(invoke_op(0, "release"), ok_op(0, "release"))
    assert locks_direct.analysis(m.Mutex(True), hist)["valid?"] is True


def test_non_lock_history_returns_none():
    hist = h(invoke_op(0, "write", 1), ok_op(0, "write", 1))
    assert locks_direct.analysis(m.mutex(), hist) is None
    assert locks_direct.analysis(m.owner_mutex(), hist) is None
    # and the owner-aware model is refused even on lock ops
    lk = h(invoke_op(0, "acquire"), ok_op(0, "acquire"))
    assert locks_direct.analysis(m.owner_mutex(), lk) is None


def test_differential_fuzz_vs_generic_search():
    """The load-bearing gate: a large mixed corpus (contention,
    corruption, crashes) must agree verdict-for-verdict with the
    exponential search."""
    rng = random.Random(20260731)
    n_false = n_true = 0
    for trial in range(1000):
        n_procs = rng.choice([2, 3, 4, 5, 6, 8, 12])
        n_events = rng.choice([8, 16, 30, 60, 100])
        corrupt = trial % 3 == 0
        crash_p = rng.choice([0.0, 0.0, 0.1, 0.3])
        hist = gen_mutex_history(
            rng, n_procs, n_events, corrupt=corrupt, crash_p=crash_p
        )
        want = generic_search(m.mutex(), hist)["valid?"]
        got = locks_direct.analysis(m.mutex(), hist)["valid?"]
        assert got == want, (trial, n_procs, n_events, corrupt, crash_p)
        n_false += want is False
        n_true += want is True
    # the corpus must actually exercise both verdicts
    assert n_false > 30 and n_true > 100


def test_owner_golden():
    c = lambda name: {"client": name}
    good = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(1, "acquire", c("n1")),  # blocks
        invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")),
        ok_op(1, "acquire", c("n1")),
        invoke_op(1, "release", c("n1")), ok_op(1, "release", c("n1")),
    )
    out = locks_direct.analysis(m.owner_mutex(), good)
    assert out["valid?"] is True
    assert out["algorithm"] == "direct-owner-mutex"
    # double grant: both holds' cores overlap
    bad = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
    )
    assert locks_direct.analysis(m.owner_mutex(), bad)["valid?"] is False
    # release by a client that never held
    rel = h(invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")))
    assert locks_direct.analysis(m.owner_mutex(), rel)["valid?"] is False
    # completed-but-never-released acquire blocks every later hold
    forever = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
        invoke_op(1, "release", c("n1")), ok_op(1, "release", c("n1")),
    )
    assert locks_direct.analysis(m.owner_mutex(), forever)["valid?"] is False


def test_owner_crashed_structures_fall_back():
    """Crashed ops mid-client-sequence make holds point-flexible; the
    direct checker must hand those to the generic search, not guess."""
    c = lambda name: {"client": name}
    flex = h(
        invoke_op(0, "acquire", c("n0")), info_op(0, "acquire", c("n0")),
        invoke_op(1, "release", c("n0")), ok_op(1, "release", c("n0")),
    )
    assert locks_direct.analysis(m.owner_mutex(), flex) is None
    # trailing crashed release still decides directly (fixed core)
    tail = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "release", c("n0")), info_op(0, "release", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
    )
    out = locks_direct.analysis(m.owner_mutex(), tail)
    assert out is not None and out["valid?"] is True
    # pre-owned locks are out of scope
    assert locks_direct.analysis(m.OwnerMutex("n0"), tail) is None
    assert locks_direct.analysis(m.ReentrantMutex("n0", 1), tail) is None


def test_owner_differential_fuzz_vs_generic_search():
    """The owner-mutex gate: the suite-shaped lock generator (real
    contention, optional fabricated double grants) must agree with the
    exponential search verdict-for-verdict wherever the direct checker
    answers at all — and it must answer the clean (crash-free) corpus."""
    from jepsen_tpu import synth

    rng = random.Random(20260732)
    answered = n_false = 0
    for trial in range(400):
        hist = synth.generate_lock_history(
            rng,
            n_procs=rng.choice([2, 3, 4, 6, 8]),
            n_ops=rng.choice([10, 24, 40, 80]),
            corrupt=trial % 3 == 0,
        )
        want = generic_search(m.owner_mutex(), hist)["valid?"]
        got = locks_direct.analysis(m.owner_mutex(), hist)
        if got is None:
            continue
        answered += 1
        assert got["valid?"] == want, trial
        n_false += want is False
    assert answered > 350  # crash-free corpus: direct must answer
    assert n_false > 50


def test_reentrant_golden():
    c = lambda name: {"client": name}
    # nested re-acquire within the bound, then fully released
    good = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")),
        invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
        invoke_op(1, "release", c("n1")), ok_op(1, "release", c("n1")),
    )
    out = locks_direct.analysis(m.reentrant_mutex(), good)
    assert out["valid?"] is True
    assert out["algorithm"] == "direct-reentrant-mutex"
    # third acquire exceeds the hold bound (max_count = 2)
    over = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
    )
    assert locks_direct.analysis(m.reentrant_mutex(), over)["valid?"] is False
    # cross-client span overlap while n0 still holds (count 1)
    cross = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
    )
    assert locks_direct.analysis(m.reentrant_mutex(), cross)["valid?"] is False
    # release by a client that never held
    rel = h(invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")))
    assert locks_direct.analysis(m.reentrant_mutex(), rel)["valid?"] is False


def test_reentrant_crashed_structures():
    """The crashed-op branches of the spans argument: trailing info
    ops with a fixed core decide directly; mid-sequence crashes fall
    back — each verdict cross-checked against the generic search."""
    c = lambda name: {"client": name}
    # trailing crashed release at count 1: span may close at its
    # invocation, so a later hold is fine
    close = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "release", c("n0")), info_op(0, "release", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
    )
    out = locks_direct.analysis(m.reentrant_mutex(), close)
    assert out is not None and out["valid?"] is True
    assert generic_search(m.reentrant_mutex(), close)["valid?"] is True
    # trailing crashed release at count 2: the span stays open either
    # way, so a later hold by another client overlaps it
    open_span = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "release", c("n0")), info_op(0, "release", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
    )
    out = locks_direct.analysis(m.reentrant_mutex(), open_span)
    assert out is not None and out["valid?"] is False
    assert generic_search(m.reentrant_mutex(), open_span)["valid?"] is False
    # trailing crashed acquire: optional, never placed
    opt = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")),
        invoke_op(0, "acquire", c("n0")), info_op(0, "acquire", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
    )
    out = locks_direct.analysis(m.reentrant_mutex(), opt)
    assert out is not None and out["valid?"] is True
    assert generic_search(m.reentrant_mutex(), opt)["valid?"] is True
    # crashed unmatched release (count 0): optional, skipped
    stray = h(
        invoke_op(0, "release", c("n0")), info_op(0, "release", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
    )
    out = locks_direct.analysis(m.reentrant_mutex(), stray)
    assert out is not None and out["valid?"] is True
    assert generic_search(m.reentrant_mutex(), stray)["valid?"] is True
    # crashed op mid-sequence: the client's spans lose their fixed
    # cores, so the direct checker must hand off
    flex = h(
        invoke_op(0, "acquire", c("n0")), info_op(0, "acquire", c("n0")),
        invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")),
    )
    assert locks_direct.analysis(m.reentrant_mutex(), flex) is None


def test_reentrant_differential_fuzz_vs_generic_search():
    from jepsen_tpu import synth

    rng = random.Random(20260733)
    answered = n_false = 0
    for trial in range(400):
        hist = synth.generate_lock_history(
            rng,
            n_procs=rng.choice([2, 3, 4, 6, 8]),
            n_ops=rng.choice([10, 24, 40, 80]),
            reentrant=True,
            corrupt=trial % 3 == 0,
        )
        want = generic_search(m.reentrant_mutex(), hist)["valid?"]
        got = locks_direct.analysis(m.reentrant_mutex(), hist)
        if got is None:
            continue
        answered += 1
        assert got["valid?"] == want, trial
        n_false += want is False
    assert answered > 350
    assert n_false > 40


def test_fenced_golden():
    from jepsen_tpu.models.locks import FencedMutex, ReentrantFencedMutex

    cf = lambda name, fence: {"client": name, "fence": fence}
    good = h(
        invoke_op(0, "acquire", cf("n0", 1)), ok_op(0, "acquire", cf("n0", 1)),
        invoke_op(0, "release", cf("n0", 0)), ok_op(0, "release", cf("n0", 0)),
        invoke_op(1, "acquire", cf("n1", 5)), ok_op(1, "acquire", cf("n1", 5)),
        invoke_op(1, "release", cf("n1", 0)), ok_op(1, "release", cf("n1", 0)),
    )
    out = locks_direct.analysis(FencedMutex(), good)
    assert out["valid?"] is True
    assert out["algorithm"] == "direct-fenced-mutex"
    # the second hold's fence regresses: stale token
    stale = h(
        invoke_op(0, "acquire", cf("n0", 5)), ok_op(0, "acquire", cf("n0", 5)),
        invoke_op(0, "release", cf("n0", 0)), ok_op(0, "release", cf("n0", 0)),
        invoke_op(1, "acquire", cf("n1", 3)), ok_op(1, "acquire", cf("n1", 3)),
    )
    out = locks_direct.analysis(FencedMutex(), stale)
    assert out["valid?"] is False
    assert "fence" in out["error"]
    assert generic_search(FencedMutex(), stale)["valid?"] is False
    # reentrant fenced: re-acquire must reuse the hold's fence or none
    rgood = h(
        invoke_op(0, "acquire", cf("n0", 2)), ok_op(0, "acquire", cf("n0", 2)),
        invoke_op(0, "acquire", cf("n0", 2)), ok_op(0, "acquire", cf("n0", 2)),
        invoke_op(0, "release", cf("n0", 0)), ok_op(0, "release", cf("n0", 0)),
        invoke_op(0, "release", cf("n0", 0)), ok_op(0, "release", cf("n0", 0)),
    )
    out = locks_direct.analysis(ReentrantFencedMutex(), rgood)
    assert out["valid?"] is True
    assert out["algorithm"] == "direct-reentrant-fenced-mutex"
    rbad = h(
        invoke_op(0, "acquire", cf("n0", 2)), ok_op(0, "acquire", cf("n0", 2)),
        invoke_op(0, "acquire", cf("n0", 7)), ok_op(0, "acquire", cf("n0", 7)),
    )
    out = locks_direct.analysis(ReentrantFencedMutex(), rbad)
    assert out["valid?"] is False
    assert generic_search(ReentrantFencedMutex(), rbad)["valid?"] is False


def _stamp_fences(rng, hist, corrupt):
    """Assign fencing tokens to a lock history's acquires: fresh holds
    in grant order get increasing tokens (sometimes none), re-acquires
    reuse the hold fence (sometimes none); ``corrupt`` regresses or
    reuses one token.  Returns a NEW history; verdict correctness is
    irrelevant here — the differential fuzz compares whatever comes
    out against the generic search."""
    from jepsen_tpu.history import History

    next_fence = 1
    hold_fence: dict = {}
    ops = []
    corrupted = False
    for op in hist:
        v = op.value if isinstance(op.value, dict) else {"client": op.value}
        client = v.get("client")
        op2 = op.copy()
        fence = 0
        if op.f == "acquire" and op.type in ("ok", "info"):
            if client not in hold_fence:  # fresh hold
                if corrupt and not corrupted and next_fence > 2 \
                        and rng.random() < 0.5:
                    fence = rng.randrange(1, next_fence)  # stale token
                    corrupted = True
                elif rng.random() < 0.75:
                    fence = next_fence
                    next_fence += 1
                hold_fence[client] = fence
            else:
                fence = hold_fence[client] if rng.random() < 0.6 else 0
        elif op.f == "release" and op.type in ("ok", "info"):
            hold_fence.pop(client, None)
        op2.value = {"client": client, "fence": fence}
        ops.append(op2)
    out = History(ops)
    for i, op in enumerate(out):
        op.index = i
        op.time = i
    return out


def test_fenced_differential_fuzz_vs_generic_search():
    from jepsen_tpu import synth
    from jepsen_tpu.models.locks import FencedMutex, ReentrantFencedMutex

    rng = random.Random(20260734)
    for reentrant, model_f in ((False, FencedMutex), (True,
                                                      ReentrantFencedMutex)):
        answered = n_false = 0
        for trial in range(200):
            base = synth.generate_lock_history(
                rng,
                n_procs=rng.choice([2, 3, 4, 6]),
                n_ops=rng.choice([10, 24, 48]),
                reentrant=reentrant,
                corrupt=trial % 4 == 0,
            )
            hist = _stamp_fences(rng, base, corrupt=trial % 3 == 0)
            want = generic_search(model_f(), hist)["valid?"]
            got = locks_direct.analysis(model_f(), hist)
            if got is None:
                continue
            answered += 1
            assert got["valid?"] == want, (reentrant, trial)
            n_false += want is False
        assert answered > 150, reentrant
        assert n_false > 30, reentrant


def test_fenced_crashed_differential_fuzz():
    """Crash-injecting arm for the fenced replay's crashed-op
    branches (which synth.generate_lock_history never produces):
    flip a suffix of completions to info and truncate, then compare
    whatever the direct checker answers against the generic search."""
    from jepsen_tpu.history import History
    from jepsen_tpu.models.locks import FencedMutex, ReentrantFencedMutex
    from jepsen_tpu import synth

    rng = random.Random(20260735)
    answered = n_false = 0
    for trial in range(200):
        reentrant = trial % 2 == 1
        base = synth.generate_lock_history(
            rng,
            n_procs=rng.choice([2, 3, 4]),
            n_ops=rng.choice([8, 16, 30]),
            reentrant=reentrant,
            corrupt=trial % 4 == 0,
        )
        stamped = _stamp_fences(rng, base, corrupt=trial % 3 == 0)
        # crash-inject TRAILING ops only (a client's LAST completion
        # flips to info) — mid-sequence crashes would just exercise
        # the None fallback, which has its own test
        ops = list(stamped)
        last_ok = {}
        for i, op in enumerate(ops):
            v = op.value if isinstance(op.value, dict) else {}
            if op.type == "ok":
                last_ok[v.get("client")] = i
        for c, i in last_ok.items():
            if rng.random() < 0.5:
                op2 = ops[i].copy()
                op2.type = "info"
                ops[i] = op2
        hist = History(ops)
        for i, op in enumerate(hist):
            op.index = i
            op.time = i
        model_f = ReentrantFencedMutex if reentrant else FencedMutex
        want = generic_search(model_f(), hist)["valid?"]
        got = locks_direct.analysis(model_f(), hist)
        if got is None or want == "unknown":
            continue
        answered += 1
        assert got["valid?"] == want, (trial, reentrant)
        n_false += want is False
    assert answered > 100
    assert n_false > 20


def test_permits_golden():
    c = lambda name: {"client": name}
    # two permits: two concurrent holders fine, a third must wait
    good = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
        invoke_op(2, "acquire", c("n2")),  # blocks
        invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")),
        ok_op(2, "acquire", c("n2")),
    )
    out = locks_direct.analysis(m.acquired_permits(2), good)
    assert out["valid?"] is True
    assert out["algorithm"] == "direct-acquired-permits"
    # three concurrent grants on a 2-permit semaphore
    over = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
        invoke_op(2, "acquire", c("n2")), ok_op(2, "acquire", c("n2")),
    )
    out = locks_direct.analysis(m.acquired_permits(2), over)
    assert out["valid?"] is False
    assert "outstanding" in out["error"]
    # one client may hold both permits
    both = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")),
        invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")),
    )
    assert locks_direct.analysis(m.acquired_permits(2), both)["valid?"] is True
    # release of a permit never held
    rel = h(invoke_op(0, "release", c("n0")), ok_op(0, "release", c("n0")))
    assert locks_direct.analysis(m.acquired_permits(2), rel)["valid?"] is False
    # an open release can free a permit for a later grant: n0 holds
    # both, starts releasing one (invoke only visible), n1's grant may
    # linearize after that release's point
    overlap = h(
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "acquire", c("n0")), ok_op(0, "acquire", c("n0")),
        invoke_op(0, "release", c("n0")),
        invoke_op(1, "acquire", c("n1")), ok_op(1, "acquire", c("n1")),
        ok_op(0, "release", c("n0")),
    )
    assert (
        locks_direct.analysis(m.acquired_permits(2), overlap)["valid?"]
        is True
    )
    # pre-seeded semaphores are out of scope
    from jepsen_tpu.models.locks import AcquiredPermits

    seeded = AcquiredPermits(2, (("n9", 1),))
    assert locks_direct.analysis(seeded, rel) is None


def test_permits_differential_fuzz_vs_generic_search():
    from jepsen_tpu import synth

    rng = random.Random(20260736)
    answered = n_false = 0
    for trial in range(400):
        hist = synth.generate_permits_history(
            rng,
            n_procs=rng.choice([2, 3, 4, 6, 8]),
            n_ops=rng.choice([10, 24, 40, 80]),
            corrupt=trial % 3 == 0,
        )
        want = generic_search(m.acquired_permits(2), hist)["valid?"]
        got = locks_direct.analysis(m.acquired_permits(2), hist)
        if got is None or want == "unknown":
            continue
        answered += 1
        assert got["valid?"] == want, trial
        n_false += want is False
    assert answered > 350
    assert n_false > 40


def test_opsoup_differential_all_models():
    """Adversarial differential: ARBITRARY interleavings (not
    generator-shaped) — random op kinds, crashes anywhere, fail ops,
    and client names deliberately shared across concurrent processes
    (two processes acting as one client breaks the sequentiality the
    spans/permits arguments rest on, so the gate must hand off — and
    when it does answer, the verdict must match the search)."""
    from jepsen_tpu.models.locks import FencedMutex, ReentrantFencedMutex

    fenced_val = lambda r, c: {
        "client": c, "fence": r.choice([0, 0, r.randrange(1, 6)])
    }
    rng = random.Random(20260737)
    models_pool = [
        (m.mutex, lambda r, c: c),
        (m.owner_mutex, lambda r, c: {"client": c}),
        (m.reentrant_mutex, lambda r, c: {"client": c}),
        (lambda: FencedMutex(), fenced_val),
        (lambda: ReentrantFencedMutex(), fenced_val),
        (lambda: m.acquired_permits(2), lambda r, c: {"client": c}),
    ]
    ctor = {
        "invoke": invoke_op, "ok": ok_op, "fail": fail_op, "info": info_op,
    }
    stats = {}
    for trial in range(1800):
        model_f, val_f = models_pool[trial % len(models_pool)]
        n_procs = rng.choice([2, 3, 4])
        n_clients = rng.choice([n_procs, n_procs, max(1, n_procs - 1)])
        hist_ops, open_f = [], {}
        for _ in range(rng.randrange(4, 22)):
            p = rng.randrange(n_procs)
            c = f"c{rng.randrange(n_clients)}"
            if p in open_f:
                kind = rng.choice(["ok", "ok", "info", "fail"])
                f, v = open_f.pop(p)
            else:
                kind = "invoke"
                f = rng.choice(["acquire", "release"])
                v = val_f(rng, c)
                open_f[p] = (f, v)
            hist_ops.append(ctor[kind](p, f, v))
        hist = h(*hist_ops)
        model = model_f()
        want = generic_search(model, hist)["valid?"]
        got = locks_direct.analysis(model, hist)
        key = type(model).__name__
        a, t = stats.get(key, (0, 0))
        if got is None or want == "unknown":
            stats[key] = (a, t + 1)
            continue
        stats[key] = (a + 1, t + 1)
        assert got["valid?"] == want, (trial, key, [o.to_dict() for o in hist])
    # every model must have been answered a meaningful number of times
    for key, (answered, total) in stats.items():
        assert answered >= 20, (key, answered, total)


def test_queue_golden():
    good = h(
        invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
        invoke_op(1, "dequeue"), ok_op(1, "dequeue", 1),
    )
    out = locks_direct.analysis(m.unordered_queue(), good)
    assert out["valid?"] is True
    assert out["algorithm"] == "direct-unordered-queue"
    # dequeue completes before the matching enqueue is even invoked
    early = h(
        invoke_op(1, "dequeue"), ok_op(1, "dequeue", 1),
        invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
    )
    assert locks_direct.analysis(m.unordered_queue(), early)["valid?"] is False
    # concurrent: the enqueue's invocation precedes the dequeue's ok,
    # so the points can interleave — valid
    conc = h(
        invoke_op(1, "dequeue"),
        invoke_op(0, "enqueue", 1),
        ok_op(1, "dequeue", 1),
        ok_op(0, "enqueue", 1),
    )
    assert locks_direct.analysis(m.unordered_queue(), conc)["valid?"] is True
    # two dequeues of one enqueue: non-unique counting must catch it
    double = h(
        invoke_op(0, "enqueue", 7), ok_op(0, "enqueue", 7),
        invoke_op(1, "dequeue"), ok_op(1, "dequeue", 7),
        invoke_op(2, "dequeue"), ok_op(2, "dequeue", 7),
    )
    assert locks_direct.analysis(m.unordered_queue(), double)["valid?"] is False
    # ...but two enqueues of the same value serve both
    twice = h(
        invoke_op(0, "enqueue", 7), ok_op(0, "enqueue", 7),
        invoke_op(3, "enqueue", 7), ok_op(3, "enqueue", 7),
        invoke_op(1, "dequeue"), ok_op(1, "dequeue", 7),
        invoke_op(2, "dequeue"), ok_op(2, "dequeue", 7),
    )
    assert locks_direct.analysis(m.unordered_queue(), twice)["valid?"] is True
    # a crashed enqueue may linearize and serve the dequeue
    crashed = h(
        invoke_op(0, "enqueue", 5), info_op(0, "enqueue", 5),
        invoke_op(1, "dequeue"), ok_op(1, "dequeue", 5),
    )
    assert locks_direct.analysis(m.unordered_queue(), crashed)["valid?"] is True
    # initial items serve dequeues with no enqueue at all
    from jepsen_tpu.models import UnorderedQueue

    seeded = UnorderedQueue(frozenset({(9, 1)}))
    first = h(invoke_op(1, "dequeue"), ok_op(1, "dequeue", 9))
    assert locks_direct.analysis(seeded, first)["valid?"] is True


def test_queue_differential_fuzz_vs_generic_search():
    """Queue histories with NON-unique values, crashes, and adversarial
    interleavings vs the generic search."""
    from jepsen_tpu.history import History

    rng = random.Random(20260738)
    answered = n_false = 0
    for trial in range(600):
        n_procs = rng.choice([2, 3, 4, 5])
        n_values = rng.choice([2, 3, 6])
        hist_ops, open_op = [], {}
        for _ in range(rng.randrange(4, 26)):
            p = rng.randrange(n_procs)
            if p in open_op:
                kind = rng.choice(["ok", "ok", "ok", "info", "fail"])
                f, v = open_op.pop(p)
                if f == "dequeue" and kind == "ok":
                    v = rng.randrange(n_values)  # observed value
                hist_ops.append(
                    {"invoke": invoke_op, "ok": ok_op,
                     "fail": fail_op, "info": info_op}[kind](p, f, v)
                )
            else:
                if rng.random() < 0.55:
                    f, v = "enqueue", rng.randrange(n_values)
                else:
                    f, v = "dequeue", None
                open_op[p] = (f, v)
                hist_ops.append(invoke_op(p, f, v))
        hist = h(*hist_ops)
        want = generic_search(m.unordered_queue(), hist)["valid?"]
        got = locks_direct.analysis(m.unordered_queue(), hist)
        if got is None or want == "unknown":
            continue
        answered += 1
        assert got["valid?"] == want, (trial, [o.to_dict() for o in hist])
        n_false += want is False
    assert answered > 550
    assert n_false > 100


def test_analysis_hook_routes_mutex():
    """linear.analysis must answer plain-mutex histories via the direct
    checker (same verdicts, never 'unknown') and still produce witness
    reports on failure."""
    rng = random.Random(7)
    for _ in range(40):
        hist = gen_mutex_history(
            rng, 4, 24, corrupt=rng.random() < 0.5, crash_p=0.1
        )
        a = linear.analysis(m.mutex(), hist)
        b = generic_search(m.mutex(), hist)
        assert a["valid?"] == b["valid?"]
        assert a["valid?"] != "unknown"
    bad = h(
        invoke_op(0, "acquire"), ok_op(0, "acquire"),
        invoke_op(1, "acquire"), ok_op(1, "acquire"),
    )
    w = linear.analysis(m.mutex(), bad, witness=True)
    assert w["valid?"] is False
    assert "final-paths" in w or "op" in w
