"""Suite tests: clients driven against in-process fake servers, plus
full in-interpreter runs with the fake DB (no SSH, no real database) —
the reference's in-JVM integration style (core_test.clj:62-120)."""

from __future__ import annotations

import pytest

from jepsen_tpu import core
from jepsen_tpu import db as db_mod
from fake_servers import FakeHttpKv


@pytest.fixture()
def httpkv():
    s = FakeHttpKv().start()
    yield s
    s.stop()


def _open(client, opts, test=None):
    return client.open(test or {"nodes": ["n1"]}, "n1")


# -- etcd -------------------------------------------------------------


def test_etcd_register_ops(httpkv):
    from jepsen_tpu.suites import etcd

    c = _open(etcd.EtcdClient({"host": "127.0.0.1", "port": httpkv.port}), {})
    r = c.invoke({}, {"f": "read", "value": [0, None], "type": "invoke"})
    assert r["type"] == "ok" and tuple(r["value"]) == (0, None)

    w = c.invoke({}, {"f": "write", "value": [0, 3], "type": "invoke"})
    assert w["type"] == "ok"
    r = c.invoke({}, {"f": "read", "value": [0, None], "type": "invoke"})
    assert tuple(r["value"]) == (0, 3)

    ok = c.invoke({}, {"f": "cas", "value": [0, [3, 4]], "type": "invoke"})
    assert ok["type"] == "ok"
    bad = c.invoke({}, {"f": "cas", "value": [0, [3, 5]], "type": "invoke"})
    assert bad["type"] == "fail"
    r = c.invoke({}, {"f": "read", "value": [0, None], "type": "invoke"})
    assert tuple(r["value"]) == (0, 4)
    c.close({})


def test_etcd_set_adds(httpkv):
    from jepsen_tpu.suites import etcd

    opts = {"host": "127.0.0.1", "port": httpkv.port}
    c = _open(etcd._SetReadClient(opts), {})
    for i in range(5):
        assert c.invoke({}, {"f": "add", "value": i, "type": "invoke"})[
            "type"] == "ok"
    r = c.invoke({}, {"f": "read", "value": None, "type": "invoke"})
    assert r["type"] == "ok" and sorted(r["value"]) == [0, 1, 2, 3, 4]
    c.close({})


def test_etcd_full_test_in_process(httpkv):
    """Full lifecycle: generator → interpreter → history → checker, with
    the real etcd client talking to the fake server."""
    from jepsen_tpu.suites import etcd

    t = etcd.test(
        {
            "nodes": ["n1", "n2", "n3"],
            "host": "127.0.0.1",
            "port": httpkv.port,
            "time-limit": 2,
            "rate": 50,
            "workload": "register",
            "faults": [],
        }
    )
    t["db"] = db_mod.noop()  # no real node to install onto
    t["ssh"] = {"dummy?": True}
    result = core.run(t)
    assert result["history"], "expected a non-empty history"
    assert result["results"]["valid?"] in (True, "unknown")
    oks = [op for op in result["history"] if op["type"] == "ok"]
    assert oks, "expected some ok completions through the fake server"


def test_etcd_set_full_test_in_process(httpkv):
    from jepsen_tpu.suites import etcd

    t = etcd.test(
        {
            "nodes": ["n1", "n2", "n3"],
            "host": "127.0.0.1",
            "port": httpkv.port,
            "time-limit": 2,
            "rate": 50,
            "workload": "set",
            "faults": [],
        }
    )
    t["db"] = db_mod.noop()
    t["ssh"] = {"dummy?": True}
    result = core.run(t)
    assert result["results"]["valid?"] is True, result["results"]


# -- consul -----------------------------------------------------------


def test_consul_register_ops(httpkv):
    from jepsen_tpu.suites import consul

    c = _open(consul.ConsulClient({"host": "127.0.0.1", "port": httpkv.port}), {})
    r = c.invoke({}, {"f": "read", "value": [1, None], "type": "invoke"})
    assert r["type"] == "ok" and tuple(r["value"]) == (1, None)
    assert c.invoke({}, {"f": "write", "value": [1, 7], "type": "invoke"})[
        "type"] == "ok"
    assert c.invoke({}, {"f": "read", "value": [1, None], "type": "invoke"})[
        "value"] == (1, 7)
    assert c.invoke({}, {"f": "cas", "value": [1, [7, 8]], "type": "invoke"})[
        "type"] == "ok"
    assert c.invoke({}, {"f": "cas", "value": [1, [7, 9]], "type": "invoke"})[
        "type"] == "fail"
    assert c.invoke({}, {"f": "read", "value": [1, None], "type": "invoke"})[
        "value"] == (1, 8)
    c.close({})


# -- assembly smoke test over every implemented suite ------------------


def test_all_suites_assemble():
    from jepsen_tpu import suites

    missing = []
    for name in suites.SUITES:
        try:
            mod = suites.suite(name)
        except (ImportError, ModuleNotFoundError):
            missing.append(name)
            continue
        t = mod.test({"nodes": ["n1", "n2", "n3"],
                      "faults": ["partition", "kill"]})
        for key in ("db", "client", "generator", "checker", "nemesis"):
            assert key in t, f"{name} missing {key}"
    if missing:
        pytest.xfail(f"suites not yet implemented: {missing}")


# -- SQL family (pg / cockroach / mysql dialects over sqlite-backed fakes)


import itertools as _it

from fake_servers import FakeCql, FakeMysql, FakePg

_DIALECTS = [
    ("pg", FakePg, {"user": "postgres"}),
    ("cockroach", FakePg, {"user": "postgres"}),
    ("mysql", FakeMysql, {"user": "root", "password": "pw"}),
]


@pytest.mark.parametrize("dialect,fake,extra",
                         _DIALECTS, ids=[d[0] for d in _DIALECTS])
def test_sql_clients_roundtrip(dialect, fake, extra):
    from jepsen_tpu.suites import sql

    s = fake().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "dialect": dialect,
                **extra}
        c = sql.RegisterClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        assert c.invoke({}, {"f": "write", "value": [0, 5],
                             "type": "invoke"})["type"] == "ok"
        assert tuple(c.invoke({}, {"f": "read", "value": [0, None],
                                   "type": "invoke"})["value"]) == (0, 5)
        assert c.invoke({}, {"f": "cas", "value": [0, [5, 6]],
                             "type": "invoke"})["type"] == "ok"
        assert c.invoke({}, {"f": "cas", "value": [0, [5, 7]],
                             "type": "invoke"})["type"] == "fail"
        c.close({})

        t = {"accounts": [0, 1, 2], "total-amount": 30, "max-transfer": 5}
        b = sql.BankClient(opts).open({"nodes": ["n1"]}, "n1")
        b.setup(t)
        assert b.invoke(t, {"f": "transfer", "type": "invoke",
                            "value": {"from": 0, "to": 1, "amount": 3}}
                        )["type"] == "ok"
        r = b.invoke(t, {"f": "read", "type": "invoke", "value": None})
        assert sum(r["value"].values()) == 30 and r["value"][1] == 13
        b.close({})

        a = sql.AppendClient(opts).open({"nodes": ["n1"]}, "n1")
        a.setup({})
        r = a.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["append", 1, 10], ["r", 1, None]]})
        assert r["type"] == "ok" and r["value"][1] == ["r", 1, [10]]
        r = a.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["append", 1, 11], ["r", 1, None]]})
        assert r["value"][1] == ["r", 1, [10, 11]]
        a.close({})

        x = sql.TxnClient(opts).open({"nodes": ["n1"]}, "n1")
        x.setup({})
        r = x.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["w", 3, 9], ["r", 3, None]]})
        assert r["type"] == "ok" and r["value"][1] == ["r", 3, 9]
        x.close({})
    finally:
        s.stop()


def test_ycql_register_roundtrip():
    from jepsen_tpu.suites import yugabyte

    s = FakeCql().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = yugabyte.YcqlRegisterClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        r = c.invoke({}, {"f": "read", "value": [0, None], "type": "invoke"})
        assert r["type"] == "ok" and tuple(r["value"]) == (0, None)
        assert c.invoke({}, {"f": "write", "value": [0, 4],
                             "type": "invoke"})["type"] == "ok"
        assert tuple(c.invoke({}, {"f": "read", "value": [0, None],
                                   "type": "invoke"})["value"]) == (0, 4)
        assert c.invoke({}, {"f": "cas", "value": [0, [4, 5]],
                             "type": "invoke"})["type"] == "ok"
        assert c.invoke({}, {"f": "cas", "value": [0, [4, 6]],
                             "type": "invoke"})["type"] == "fail"
        c.close({})

        sc = yugabyte.YcqlSetClient(opts).open({"nodes": ["n1"]}, "n1")
        sc.setup({})
        for i in range(3):
            assert sc.invoke({}, {"f": "add", "value": i,
                                  "type": "invoke"})["type"] == "ok"
        r = sc.invoke({}, {"f": "read", "value": None, "type": "invoke"})
        assert r["value"] == [0, 1, 2]
        sc.close({})
    finally:
        s.stop()


def test_sql_full_register_test_in_process():
    """Full interpreter run: cockroach-dialect register workload against
    the sqlite-backed fake pg."""
    from jepsen_tpu.suites import cockroachdb

    s = FakePg().start()
    try:
        t = cockroachdb.test(
            {
                "nodes": ["n1", "n2", "n3"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "postgres",
                "time-limit": 2,
                "rate": 50,
                "workload": "register",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        oks = [op for op in result["history"] if op["type"] == "ok"]
        assert oks, "expected ok completions"
        assert result["results"]["valid?"] in (True, "unknown")
    finally:
        s.stop()


# -- new wire protocols: AMQP, ReQL, Aerospike ------------------------


def test_amqp_rabbitmq_queue_roundtrip():
    from fake_servers import FakeAmqp
    from jepsen_tpu.suites import rabbitmq

    s = FakeAmqp().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = rabbitmq.RabbitQueueClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        for i in (1, 2, 3):
            assert c.invoke({}, {"f": "enqueue", "value": i,
                                 "type": "invoke"})["type"] == "ok"
        r = c.invoke({}, {"f": "dequeue", "value": None, "type": "invoke"})
        assert r["type"] == "ok" and r["value"] == 1
        r = c.invoke({}, {"f": "drain", "value": None, "type": "invoke"})
        assert r["type"] == "ok" and r["value"] == [2, 3]
        r = c.invoke({}, {"f": "dequeue", "value": None, "type": "invoke"})
        assert r["type"] == "fail" and r["error"] == "empty"
        c.close({})
    finally:
        s.stop()


def test_reql_rethinkdb_cas_roundtrip():
    from fake_servers import FakeReql
    from jepsen_tpu.suites import rethinkdb

    s = FakeReql().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = rethinkdb.RethinkCasClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        r = c.invoke({}, {"f": "read", "value": [0, None], "type": "invoke"})
        assert r["type"] == "ok" and tuple(r["value"]) == (0, None)
        assert c.invoke({}, {"f": "write", "value": [0, 3],
                             "type": "invoke"})["type"] == "ok"
        assert tuple(c.invoke({}, {"f": "read", "value": [0, None],
                                   "type": "invoke"})["value"]) == (0, 3)
        assert c.invoke({}, {"f": "cas", "value": [0, [3, 4]],
                             "type": "invoke"})["type"] == "ok"
        assert c.invoke({}, {"f": "cas", "value": [0, [3, 5]],
                             "type": "invoke"})["type"] == "fail"
        assert tuple(c.invoke({}, {"f": "read", "value": [0, None],
                                   "type": "invoke"})["value"]) == (0, 4)
        # same-value CAS must count as applied
        assert c.invoke({}, {"f": "cas", "value": [0, [4, 4]],
                             "type": "invoke"})["type"] == "ok"
        c.close({})
    finally:
        s.stop()


def test_aerospike_cas_roundtrip():
    from fake_servers import FakeAerospike
    from jepsen_tpu.suites import aerospike

    s = FakeAerospike().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = aerospike.CasRegisterClient(opts).open({"nodes": ["n1"]}, "n1")
        r = c.invoke({}, {"f": "read", "value": [0, None], "type": "invoke"})
        assert r["type"] == "ok" and tuple(r["value"]) == (0, None)
        assert c.invoke({}, {"f": "write", "value": [0, 7],
                             "type": "invoke"})["type"] == "ok"
        assert tuple(c.invoke({}, {"f": "read", "value": [0, None],
                                   "type": "invoke"})["value"]) == (0, 7)
        assert c.invoke({}, {"f": "cas", "value": [0, [7, 8]],
                             "type": "invoke"})["type"] == "ok"
        assert c.invoke({}, {"f": "cas", "value": [0, [7, 9]],
                             "type": "invoke"})["type"] == "fail"
        assert tuple(c.invoke({}, {"f": "read", "value": [0, None],
                                   "type": "invoke"})["value"]) == (0, 8)
        c.close({})

        cc = aerospike.CounterClient(opts).open({"nodes": ["n1"]}, "n1")
        for _ in range(3):
            assert cc.invoke({}, {"f": "add", "value": 1,
                                  "type": "invoke"})["type"] == "ok"
        assert cc.invoke({}, {"f": "read", "value": None,
                              "type": "invoke"})["value"] == 3
        cc.close({})
    finally:
        s.stop()


def test_zk_register_roundtrip():
    from fake_servers import FakeZk
    from jepsen_tpu.suites import zookeeper

    s = FakeZk().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = zookeeper.ZkRegisterClient(opts).open({"nodes": ["n1"]}, "n1")
        r = c.invoke({}, {"f": "read", "value": [0, None], "type": "invoke"})
        assert r["type"] == "ok" and tuple(r["value"]) == (0, None)
        assert c.invoke({}, {"f": "write", "value": [0, 2],
                             "type": "invoke"})["type"] == "ok"
        assert tuple(c.invoke({}, {"f": "read", "value": [0, None],
                                   "type": "invoke"})["value"]) == (0, 2)
        assert c.invoke({}, {"f": "cas", "value": [0, [2, 3]],
                             "type": "invoke"})["type"] == "ok"
        assert c.invoke({}, {"f": "cas", "value": [0, [2, 4]],
                             "type": "invoke"})["type"] == "fail"
        c.close({})
    finally:
        s.stop()


def test_robustirc_set_roundtrip():
    from fake_servers import FakeIrc
    from jepsen_tpu.suites import robustirc

    s = FakeIrc().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        t = {"nodes": ["n1"]}
        c1 = robustirc.RobustIrcSetClient(opts).open(t, "n1")
        c2 = robustirc.RobustIrcSetClient(opts).open(t, "n1")
        for i in (10, 11):
            assert c1.invoke(t, {"f": "add", "value": i,
                                 "type": "invoke"})["type"] == "ok"
        import time
        time.sleep(0.3)
        r = c2.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["type"] == "ok" and set(r["value"]) >= {10, 11}, r
        c1.close(t)
        c2.close(t)
    finally:
        s.stop()


def test_aerospike_set_append_roundtrip():
    """The set client's string-bin appends accumulate and parse back.
    (reference: aerospike/set.clj:12-41)"""
    from fake_servers import FakeAerospike

    from jepsen_tpu.suites import aerospike

    s = FakeAerospike().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = aerospike.SetClient(opts).open({}, "n1")
        for v in (5, 2, 9):
            r = c.invoke({}, {"f": "add", "type": "invoke", "value": (3, v)})
            assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": (3, None)})
        assert r["type"] == "ok" and r["value"][1] == [2, 5, 9], r
        # a different key is empty
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": (4, None)})
        assert r["type"] == "ok" and r["value"][1] == [], r
        c.close({})
    finally:
        s.stop()


def test_aerospike_set_full_test_in_process():
    from fake_servers import FakeAerospike

    from jepsen_tpu import core
    from jepsen_tpu import db as db_mod
    from jepsen_tpu.suites import aerospike

    s = FakeAerospike().start()
    try:
        t = aerospike.test({
            "nodes": ["n1", "n2"],
            "host": "127.0.0.1",
            "port": s.port,
            "time-limit": 2,
            "workload": "set",
            "per-key-limit": 8,
            "faults": [],
        })
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_every_suite_workload_assembles():
    """Every named workload of every suite must assemble into a full
    runnable test map — catching client-map omissions and workload
    builders that break under default opts (the assembly-smoke above
    only exercises each suite's default workload)."""
    from jepsen_tpu import suites

    checked = 0
    for name in suites.SUITES:
        try:
            mod = suites.suite(name)
        except (ImportError, ModuleNotFoundError):
            continue
        if not hasattr(mod, "workloads"):
            continue
        for wname in mod.workloads({"nodes": ["n1", "n2", "n3"]}):
            t = mod.test({"nodes": ["n1", "n2", "n3"],
                          "workload": wname, "faults": []})
            for key in ("db", "client", "generator", "checker"):
                assert key in t and t[key] is not None, (
                    f"{name}/{wname} missing {key}"
                )
            checked += 1
    assert checked > 50, f"only {checked} suite workloads enumerated"


def test_chronos_mesos_cluster_config():
    """Masters run on the first master-count sorted nodes; mesos reads
    the zk ensemble + quorum from config files (reference:
    chronos/src/jepsen/mesosphere.clj:17,38-57,60-67)."""
    from jepsen_tpu import control
    from jepsen_tpu.control.core import DummyRemote
    from jepsen_tpu.suites import chronos

    nodes = ["n5", "n1", "n3", "n2", "n4"]
    t = {"nodes": nodes, "remote": DummyRemote(), "ssh": {"dummy?": True}}
    db = chronos.ChronosDB({})
    assert db.master_nodes(t) == ["n1", "n2", "n3"]
    assert db.zk_uri(t) == (
        "zk://n5:2181,n1:2181,n3:2181,n2:2181,n4:2181/mesos"
    )
    with control.with_session(t, t["remote"]):
        control.on_nodes(t, nodes, db.configure)  # dummy: must not raise
