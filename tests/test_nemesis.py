"""Nemesis tests: grudge math (pure) and fault command emission against
the dummy remote (reference: jepsen/test/jepsen/nemesis_test.clj)."""

import pytest

from jepsen_tpu import control, generator as gen
from jepsen_tpu import nemesis as n
from jepsen_tpu import net
from jepsen_tpu.nemesis import combined
from jepsen_tpu.util import majority

NODES = ["n1", "n2", "n3", "n4", "n5"]


def setup_function(_):
    gen.set_seed(45100)


# -- grudges ----------------------------------------------------------------


def test_bisect():
    assert n.bisect([1, 2, 3, 4]) == [[1, 2], [3, 4]]
    assert n.bisect([1, 2, 3, 4, 5]) == [[1, 2], [3, 4, 5]]
    assert n.bisect([]) == [[], []]


def test_split_one():
    loner, rest = n.split_one(NODES, loner="n3")
    assert loner == ["n3"]
    assert set(rest) == {"n1", "n2", "n4", "n5"}


def test_complete_grudge():
    g = n.complete_grudge(n.bisect(NODES))
    assert g["n1"] == {"n3", "n4", "n5"}
    assert g["n4"] == {"n1", "n2"}
    # every node appears; nobody grudges their own component
    assert set(g.keys()) == set(NODES)


def test_bridge():
    g = n.bridge(NODES)
    # bridge node (first of second half = n3) is absent and never snubbed
    assert "n3" not in g
    for node, snubbed in g.items():
        assert "n3" not in snubbed
    # the two sides still can't see each other
    assert "n4" in g["n1"] and "n1" in g["n4"]


def test_majorities_ring_perfect():
    g = n.majorities_ring(NODES)
    m = majority(len(NODES))
    # every node sees a majority (incl. itself): n - dropped >= majority
    for node in NODES:
        dropped = g.get(node, set())
        assert len(NODES) - len(dropped) >= m
    # at least two distinct drop-sets (no shared majority)
    assert len({frozenset(v) for v in g.values()}) > 1


def test_majorities_ring_stochastic():
    nodes = [f"m{i}" for i in range(7)]
    g = n.majorities_ring(nodes)
    m = majority(len(nodes))
    for node in nodes:
        visible = len(nodes) - len(g.get(node, set()))
        assert visible >= m, (node, g)


def test_invert_grudge():
    conns = {"a": {"a", "b"}, "b": {"a", "b"}, "c": {"c"}}
    g = n.invert_grudge(["a", "b", "c"], conns)
    assert g["a"] == {"c"}
    assert g["c"] == {"a", "b"}


# -- partitioner against dummy remote --------------------------------------


def dummy_test(**kw):
    t = {
        "name": "nemtest",
        "nodes": list(NODES),
        "net": net.iptables,
        "concurrency": 2,
    }
    t.update(kw)
    return t


def run_nemesis(nem, ops, test=None):
    test = test or dummy_test()
    remote = control.DummyRemote()
    results = []
    with control.with_session(test, remote):
        nem = nem.setup(test)
        for op in ops:
            results.append(nem.invoke(test, op))
        nem.teardown(test)
    return results, remote.log


def test_partitioner_emits_iptables():
    results, log = run_nemesis(
        n.partition_halves(),
        [
            {"f": "start", "value": None, "process": "nemesis", "time": 0},
            {"f": "stop", "value": None, "process": "nemesis", "time": 1},
        ],
    )
    assert results[0]["value"][0] == "isolated"
    assert results[1]["value"] == "network-healed"
    cmds = [c.cmd for node, c in log if hasattr(c, "cmd")]
    drops = [c for c in cmds if "iptables -A INPUT -s" in c and "DROP" in c]
    assert drops, cmds
    flushes = [c for c in cmds if "iptables -F" in c]
    assert flushes  # heal on setup, stop, and teardown


def test_partitioner_sudo_wrapping():
    _, log = run_nemesis(
        n.partition_random_node(),
        [{"f": "start", "value": None, "process": "nemesis", "time": 0}],
    )
    sudos = [c for node, c in log if hasattr(c, "sudo") and c.sudo]
    assert sudos, "iptables commands must run under sudo"


def test_partitioner_explicit_grudge_value():
    grudge = {"n1": {"n2"}}
    results, log = run_nemesis(
        n.partitioner(),
        [{"f": "start", "value": grudge, "process": "nemesis", "time": 0}],
    )
    assert results[0]["value"][0] == "isolated"
    cmds = [c.cmd for node, c in log if hasattr(c, "cmd")]
    assert any("-s n2" in c or "-s " in c for c in cmds)


def test_f_map_remaps():
    lifted = n.f_map(lambda f: f"net-{f}", n.partition_halves())
    assert lifted.fs() == {"net-start", "net-stop"}
    results, _ = run_nemesis(
        lifted, [{"f": "net-start", "value": None, "process": "nemesis", "time": 0}]
    )
    assert results[0]["f"] == "net-start"


def test_compose_reflection_routing():
    class A(n.Nemesis):
        def invoke(self, test, op):
            return {**op, "type": "info", "value": "A"}

        def fs(self):
            return {"a"}

    class B(n.Nemesis):
        def invoke(self, test, op):
            return {**op, "type": "info", "value": "B"}

        def fs(self):
            return {"b"}

    c = n.compose([A(), B()])
    assert c.invoke({}, {"f": "a"})["value"] == "A"
    assert c.invoke({}, {"f": "b"})["value"] == "B"
    with pytest.raises(ValueError):
        c.invoke({}, {"f": "zzz"})
    assert c.fs() == {"a", "b"}


def test_compose_conflicting_fs_raises():
    class A(n.Nemesis):
        def fs(self):
            return {"x"}

    with pytest.raises(ValueError, match="incompatible"):
        n.compose([A(), A()])


def test_compose_map_rewrites_f():
    class Partish(n.Nemesis):
        def invoke(self, test, op):
            assert op["f"] in ("start", "stop")
            return {**op, "type": "info", "value": op["f"]}

        def fs(self):
            return {"start", "stop"}

    c = n.compose([({"split-start": "start", "split-stop": "stop"}, Partish())])
    out = c.invoke({}, {"f": "split-start"})
    assert out["value"] == "start"
    assert out["f"] == "split-start"
    assert c.fs() == {"split-start", "split-stop"}


def test_hammer_time_emits_killall():
    _, log = run_nemesis(
        n.hammer_time("mydb"),
        [
            {"f": "start", "value": None, "process": "nemesis", "time": 0},
            {"f": "stop", "value": None, "process": "nemesis", "time": 1},
        ],
    )
    cmds = [c.cmd for node, c in log if hasattr(c, "cmd")]
    assert any("killall -s STOP mydb" in c for c in cmds)
    assert any("killall -s CONT mydb" in c for c in cmds)


def test_truncate_file():
    _, log = run_nemesis(
        n.truncate_file(),
        [
            {
                "f": "truncate",
                "process": "nemesis",
                "time": 0,
                "value": {"n1": {"file": "/var/lib/db/wal", "drop": 64}},
            }
        ],
    )
    cmds = [(node, c.cmd) for node, c in log if hasattr(c, "cmd")]
    assert any(
        node == "n1" and "truncate -c -s -64 /var/lib/db/wal" in cmd
        for node, cmd in cmds
    )


# -- combined packages -------------------------------------------------------


def test_db_nodes_specs():
    test = dummy_test()
    from jepsen_tpu import db as db_mod

    db = db_mod.noop()
    assert combined.db_nodes(test, db, "all") == NODES
    assert len(combined.db_nodes(test, db, "one")) == 1
    assert len(combined.db_nodes(test, db, "majority")) == 3
    assert len(combined.db_nodes(test, db, "minority")) == 2
    assert combined.db_nodes(test, db, ["n2"]) == ["n2"]
    sub = combined.db_nodes(test, db, None)
    assert 1 <= len(sub) <= 5


def test_grudge_specs():
    test = dummy_test()
    from jepsen_tpu import db as db_mod

    db = db_mod.noop()
    g = combined.grudge(test, db, "one")
    isolated = [node for node, v in g.items() if len(v) == 4]
    assert len(isolated) == 1
    g2 = combined.grudge(test, db, "majority")
    sizes = sorted(len(v) for v in g2.values())
    assert sizes == [2, 2, 2, 3, 3]
    g3 = combined.grudge(test, db, "majorities-ring")
    assert set(g3.keys()) <= set(NODES)


def test_partition_package_lifecycle():
    from jepsen_tpu import db as db_mod

    pkg = combined.partition_package(
        {"db": db_mod.noop(), "faults": {"partition"}, "interval": 1}
    )
    assert pkg["generator"] is not None
    assert pkg["nemesis"].fs() == {"start-partition", "stop-partition"}
    test = dummy_test()
    remote = control.DummyRemote()
    with control.with_session(test, remote):
        nem = pkg["nemesis"].setup(test)
        out = nem.invoke(
            test,
            {"f": "start-partition", "value": "majority", "process": "nemesis", "time": 0},
        )
        assert out["f"] == "start-partition"
        out2 = nem.invoke(
            test, {"f": "stop-partition", "value": None, "process": "nemesis", "time": 1}
        )
        assert out2["value"] == "network-healed"


def test_nemesis_package_composes():
    from jepsen_tpu import db as db_mod

    pkg = combined.nemesis_package(
        {"db": db_mod.noop(), "faults": {"partition"}, "interval": 1}
    )
    # only partition faults are enabled, but the composed nemesis still
    # routes all three packages' fs
    fs = pkg["nemesis"].fs()
    assert "start-partition" in fs
    assert "reset-clock" in fs
    assert pkg["generator"] is not None


def test_package_f_map():
    from jepsen_tpu import db as db_mod

    pkg = combined.partition_package(
        {"db": db_mod.noop(), "faults": {"partition"}}
    )
    lifted = combined.f_map(lambda f: ("db1", f), pkg)
    assert ("db1", "start-partition") in lifted["nemesis"].fs()
