"""Built-in checker tests on literal histories (mirrors the reference's
test strategy: jepsen/test/jepsen/checker_test.clj)."""

import pytest

from jepsen_tpu import checker as c
from jepsen_tpu import models as m
from jepsen_tpu.history import (
    History,
    invoke_op,
    ok_op,
    fail_op,
    info_op,
)


def h(*ops) -> History:
    hist = History(ops)
    for i, op in enumerate(hist):
        op.index = i
        if op.time == 0:
            op.time = i
    return hist


def test_merge_valid():
    assert c.merge_valid([]) is True
    assert c.merge_valid([True, True]) is True
    assert c.merge_valid([True, "unknown"]) == "unknown"
    assert c.merge_valid([True, "unknown", False]) is False
    with pytest.raises(ValueError):
        c.merge_valid([None])


def test_check_safe_wraps_exceptions():
    class Boom(c.Checker):
        def check(self, test, history, opts=None):
            raise RuntimeError("boom")

    out = c.check_safe(Boom(), {}, h())
    assert out["valid?"] == "unknown"
    assert "boom" in out["error"]


def test_compose():
    out = c.compose(
        {"opt": c.unbridled_optimism(), "noop": c.noop()}
    ).check({}, h(), {})
    assert out["valid?"] is True
    assert out["opt"] == {"valid?": True}


def test_compose_merges_worst():
    class Bad(c.Checker):
        def check(self, test, history, opts=None):
            return {"valid?": False}

    out = c.compose({"good": c.unbridled_optimism(), "bad": Bad()}).check({}, h(), {})
    assert out["valid?"] is False


def test_stats():
    # mirrors reference stats-test (checker_test.clj:44-66)
    out = c.stats().check(
        {},
        h(
            ok_op(0, "foo"),
            fail_op(0, "foo"),
            info_op(0, "bar"),
            fail_op(0, "bar"),
            fail_op(0, "bar"),
        ),
        {},
    )
    assert out["valid?"] is False
    assert out["count"] == 5
    assert out["ok-count"] == 1
    assert out["by-f"]["foo"]["valid?"] is True
    assert out["by-f"]["bar"]["valid?"] is False
    assert out["by-f"]["bar"]["info-count"] == 1


def test_stats_ignores_nemesis_and_invokes():
    out = c.stats().check(
        {}, h(invoke_op(0, "foo"), ok_op(0, "foo"), info_op("nemesis", "kill")), {}
    )
    assert out["count"] == 1
    assert out["valid?"] is True


def test_queue_checker():
    # reference checker_test.clj:68-88
    assert c.queue(m.unordered_queue()).check({}, h(), {})["valid?"] is True
    assert (
        c.queue(m.unordered_queue())
        .check({}, h(invoke_op(1, "enqueue", 1)), {})["valid?"]
        is True
    )
    # concurrent enqueue/dequeue: dequeue sees the possibly-enqueued value
    out = c.queue(m.unordered_queue()).check(
        {},
        h(
            invoke_op(2, "dequeue"),
            invoke_op(1, "enqueue", 1),
            ok_op(2, "dequeue", 1),
        ),
        {},
    )
    assert out["valid?"] is True
    # dequeue of something never enqueued
    out = c.queue(m.unordered_queue()).check(
        {}, h(invoke_op(2, "dequeue"), ok_op(2, "dequeue", 9)), {}
    )
    assert out["valid?"] is False


def test_set_checker():
    out = c.set_checker().check(
        {},
        h(
            invoke_op(0, "add", 0),
            ok_op(0, "add", 0),
            invoke_op(0, "add", 1),
            fail_op(0, "add", 1),
            invoke_op(0, "add", 2),
            info_op(0, "add", 2),
            invoke_op(1, "read"),
            ok_op(1, "read", [0, 2]),
        ),
        {},
    )
    assert out["valid?"] is True
    assert out["recovered-count"] == 1  # 2 was indeterminate but observed
    assert out["ok-count"] == 2


def test_set_checker_lost_and_unexpected():
    out = c.set_checker().check(
        {},
        h(
            invoke_op(0, "add", 0),
            ok_op(0, "add", 0),
            invoke_op(1, "read"),
            ok_op(1, "read", [5]),
        ),
        {},
    )
    assert out["valid?"] is False
    assert out["lost-count"] == 1
    assert out["unexpected-count"] == 1


def test_set_checker_never_read():
    out = c.set_checker().check({}, h(invoke_op(0, "add", 0), ok_op(0, "add", 0)), {})
    assert out["valid?"] == "unknown"


def test_total_queue_sane():
    # reference checker_test.clj:94-115
    out = c.total_queue().check(
        {},
        h(
            invoke_op(1, "enqueue", 1),
            invoke_op(2, "enqueue", 2),
            ok_op(2, "enqueue", 2),
            invoke_op(3, "dequeue", 1),
            ok_op(3, "dequeue", 1),
            invoke_op(3, "dequeue", 2),
            ok_op(3, "dequeue", 2),
        ),
        {},
    )
    assert out["valid?"] is True
    assert out["attempt-count"] == 2
    assert out["acknowledged-count"] == 1
    assert out["ok-count"] == 2
    assert out["recovered-count"] == 1


def test_total_queue_pathological():
    # reference checker_test.clj:117-143
    out = c.total_queue().check(
        {},
        h(
            invoke_op(1, "enqueue", "hung"),
            invoke_op(2, "enqueue", "enqueued"),
            ok_op(2, "enqueue", "enqueued"),
            invoke_op(3, "enqueue", "dup"),
            ok_op(3, "enqueue", "dup"),
            invoke_op(4, "dequeue"),
            invoke_op(5, "dequeue"),
            ok_op(5, "dequeue", "wtf"),
            invoke_op(6, "dequeue"),
            ok_op(6, "dequeue", "dup"),
            invoke_op(7, "dequeue"),
            ok_op(7, "dequeue", "dup"),
        ),
        {},
    )
    assert out["valid?"] is False
    assert out["lost"] == {"enqueued": 1}
    assert out["unexpected"] == {"wtf": 1}
    assert out["duplicated"] == {"dup": 1}
    assert out["ok-count"] == 1


def test_total_queue_drain_expansion():
    out = c.total_queue().check(
        {},
        h(
            invoke_op(1, "enqueue", "a"),
            ok_op(1, "enqueue", "a"),
            invoke_op(2, "drain"),
            ok_op(2, "drain", ["a"]),
        ),
        {},
    )
    assert out["valid?"] is True
    assert out["ok-count"] == 1


def test_unique_ids():
    out = c.unique_ids().check(
        {},
        h(
            invoke_op(0, "generate"),
            ok_op(0, "generate", 10),
            invoke_op(0, "generate"),
            ok_op(0, "generate", 11),
            invoke_op(0, "generate"),
            ok_op(0, "generate", 10),
        ),
        {},
    )
    assert out["valid?"] is False
    assert out["duplicated"] == {10: 2}
    assert out["range"] == [10, 11]
    assert out["attempted-count"] == 3


def test_counter_empty_and_initial():
    # reference checker_test.clj:145-180
    assert c.counter().check({}, h(), {}) == {
        "valid?": True,
        "reads": [],
        "errors": [],
    }
    out = c.counter().check({}, h(invoke_op(0, "read"), ok_op(0, "read", 0)), {})
    assert out == {"valid?": True, "reads": [[0, 0, 0]], "errors": []}
    out = c.counter().check({}, h(invoke_op(0, "read"), ok_op(0, "read", 1)), {})
    assert out == {"valid?": False, "reads": [[0, 1, 0]], "errors": [[0, 1, 0]]}


def test_counter_ignores_failed_adds():
    out = c.counter().check(
        {},
        h(
            invoke_op(0, "add", 1),
            fail_op(0, "add", 1),
            invoke_op(0, "read"),
            ok_op(0, "read", 0),
        ),
        {},
    )
    assert out == {"valid?": True, "reads": [[0, 0, 0]], "errors": []}


def test_counter_concurrent_bounds():
    # a read concurrent with an add may see either value
    out = c.counter().check(
        {},
        h(
            invoke_op(0, "read"),
            invoke_op(1, "add", 1),
            ok_op(1, "add", 1),
            ok_op(0, "read", 1),
        ),
        {},
    )
    assert out["valid?"] is True
    assert out["reads"] == [[0, 1, 1]]
    # reading 2 when at most 1 was ever added is invalid
    out = c.counter().check(
        {},
        h(
            invoke_op(0, "read"),
            invoke_op(1, "add", 1),
            ok_op(1, "add", 1),
            ok_op(0, "read", 2),
        ),
        {},
    )
    assert out["valid?"] is False


def test_counter_indeterminate_add_widens_upper():
    out = c.counter().check(
        {},
        h(
            invoke_op(1, "add", 5),
            info_op(1, "add", 5),
            invoke_op(0, "read"),
            ok_op(0, "read", 5),
        ),
        {},
    )
    assert out["valid?"] is True
    out2 = c.counter().check(
        {},
        h(
            invoke_op(1, "add", 5),
            info_op(1, "add", 5),
            invoke_op(0, "read"),
            ok_op(0, "read", 0),
        ),
        {},
    )
    assert out2["valid?"] is True  # lower bound stays 0


def test_set_full_never_read():
    # reference checker_test.clj:516-533
    out = c.set_full().check({}, h(invoke_op(0, "add", 0), ok_op(0, "add", 0)), {})
    assert out["valid?"] == "unknown"
    assert out["never-read"] == [0]
    assert out["attempt-count"] == 1


def test_set_full_stable_and_lost():
    out = c.set_full().check(
        {},
        h(
            invoke_op(0, "add", 0),
            ok_op(0, "add", 0),
            invoke_op(1, "read"),
            ok_op(1, "read", [0]),
        ),
        {},
    )
    assert out["valid?"] is True
    assert out["stable-count"] == 1

    out = c.set_full().check(
        {},
        h(
            invoke_op(0, "add", 0),
            ok_op(0, "add", 0),
            invoke_op(1, "read"),
            ok_op(1, "read", [0]),
            invoke_op(1, "read"),
            ok_op(1, "read", []),
        ),
        {},
    )
    assert out["valid?"] is False
    assert out["lost"] == [0]


def test_set_full_stale_read_linearizable():
    second = 1_000_000_000
    hist = h(
        invoke_op(0, "add", 0, time=0 * second),
        ok_op(0, "add", 0, time=1 * second),
        invoke_op(1, "read", time=2 * second),   # read begins after add ok...
        ok_op(1, "read", [], time=3 * second),   # ...but misses it: stale
        invoke_op(1, "read", time=4 * second),
        ok_op(1, "read", [0], time=5 * second),  # later it appears
    )
    relaxed = c.set_full(linearizable=False).check({}, hist, {})
    assert relaxed["valid?"] is True
    assert relaxed["stale"] == [0]
    strict = c.set_full(linearizable=True).check({}, hist, {})
    assert strict["valid?"] is False


def test_set_full_duplicates():
    out = c.set_full().check(
        {},
        h(
            invoke_op(0, "add", 0),
            ok_op(0, "add", 0),
            invoke_op(1, "read"),
            ok_op(1, "read", [0, 0]),
        ),
        {},
    )
    assert out["valid?"] is False
    assert out["duplicated"] == {0: 2}


def test_set_full_concurrent_absent_read_is_never_read():
    # A read concurrent with the add that misses the element could have
    # linearized before it: never-read, not lost (checker.clj:363-381).
    out = c.set_full().check(
        {},
        h(
            invoke_op(1, "read"),
            invoke_op(0, "add", 0),
            ok_op(1, "read", []),
            ok_op(0, "add", 0),
        ),
        {},
    )
    assert out["lost-count"] == 0
    assert out["never-read"] == [0]


def test_unhandled_exceptions():
    hist = h(
        info_op(0, "write", 1, exception="boom", exception_class="RuntimeError"),
        info_op(1, "write", 2, exception="boom", exception_class="RuntimeError"),
        ok_op(2, "write", 3),
    )
    out = c.unhandled_exceptions().check({}, hist, {})
    assert out["valid?"] is True
    assert out["exceptions"][0]["class"] == "RuntimeError"
    assert out["exceptions"][0]["count"] == 2


def test_log_file_pattern(tmp_path):
    test = {"name": "t", "start-time": "now", "store-base": str(tmp_path), "nodes": ["n1", "n2"]}
    import os

    from jepsen_tpu import store

    p = store.path_(test, "n1", "db.log")
    with open(p, "w") as f:
        f.write("starting up\npanic: invariant violation\nok\n")
    os.makedirs(os.path.dirname(store.path(test, "n2", "db.log")), exist_ok=True)
    with open(store.path(test, "n2", "db.log"), "w") as f:
        f.write("all good\n")
    out = c.log_file_pattern(r"panic: (\w+)", "db.log").check(test, h(), {})
    assert out["valid?"] is False
    assert out["count"] == 1
    assert out["matches"][0]["node"] == "n1"
    out2 = c.log_file_pattern(r"unfindable", "db.log").check(test, h(), {})
    assert out2["valid?"] is True


def test_linearizable_race_mode():
    good = h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(1, "read"),
        ok_op(1, "read", 1),
    )
    bad = h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(1, "read"),
        ok_op(1, "read", 9),
    )
    race = c.linearizable(m.register(0), algorithm="race")
    rg = race.check({}, good)
    assert rg["valid?"] is True and rg["engine"] in ("tpu", "oracle")
    rb = race.check({}, bad)
    assert rb["valid?"] is False and rb["engine"] in ("tpu", "oracle")
    # models with no kernel still get a verdict (oracle arm wins)
    q = c.linearizable(m.fifo_queue(), algorithm="race")
    qh = h(
        invoke_op(0, "enqueue", 5),
        ok_op(0, "enqueue", 5),
        invoke_op(1, "dequeue"),
        ok_op(1, "dequeue", 5),
    )
    rq = q.check({}, qh)
    assert rq["valid?"] is True and rq["engine"] == "oracle"


# -- race-mode hung-arm behavior --------------------------------------------


def _small_valid_history():
    from jepsen_tpu.history import History, invoke_op, ok_op

    ops = [
        invoke_op(0, "write", 1, time=0), ok_op(0, "write", 1, time=1),
        invoke_op(1, "read", None, time=2), ok_op(1, "read", 1, time=3),
    ]
    h = History(ops)
    return h.index_ops()


def test_race_hung_kernel_arm_oracle_wins_promptly(monkeypatch):
    """A kernel arm that blocks forever must not delay the oracle's
    definite verdict, and must leak no non-daemon thread."""
    import threading
    import time

    from jepsen_tpu import checker as checker_mod
    from jepsen_tpu import models
    from jepsen_tpu.ops import wgl

    def hang_forever(*a, **kw):
        threading.Event().wait()

    monkeypatch.setattr(wgl, "analysis", hang_forever)
    before = set(threading.enumerate())
    ck = checker_mod.linearizable(models.cas_register(0), algorithm="race")
    t0 = time.perf_counter()
    res = ck.check({}, _small_valid_history())
    elapsed = time.perf_counter() - t0
    assert res["valid?"] is True, res
    assert res.get("engine") == "oracle"
    assert elapsed < 10, f"oracle win took {elapsed:.1f}s"
    leaked = [
        t for t in set(threading.enumerate()) - before if not t.daemon
    ]
    assert not leaked, leaked


def test_race_hung_arm_with_indefinite_winner_respects_loser_wait(monkeypatch):
    """When the only answer in hand is indefinite ("unknown") and the
    other arm hangs, the race must settle after the (overridden)
    loser-wait rather than stalling the full 60 s default."""
    import threading
    import time

    from jepsen_tpu import checker as checker_mod
    from jepsen_tpu import models
    from jepsen_tpu.checker import linear
    from jepsen_tpu.ops import wgl

    def hang_forever(*a, **kw):
        threading.Event().wait()

    def unknown_analysis(*a, **kw):
        return {"valid?": "unknown", "error": "synthetic"}

    monkeypatch.setattr(wgl, "analysis", hang_forever)
    monkeypatch.setattr(linear, "analysis", unknown_analysis)
    monkeypatch.setattr(checker_mod, "RACE_LOSER_WAIT_S", 0.3)
    before = set(threading.enumerate())
    ck = checker_mod.linearizable(models.cas_register(0), algorithm="race")
    t0 = time.perf_counter()
    res = ck.check({}, _small_valid_history())
    elapsed = time.perf_counter() - t0
    assert res["valid?"] == "unknown", res
    assert elapsed < 5, f"hung loser stalled the race {elapsed:.1f}s"
    leaked = [
        t for t in set(threading.enumerate()) - before if not t.daemon
    ]
    assert not leaked, leaked
