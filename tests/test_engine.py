"""Pipelined checker engine tests (jepsen_tpu/engine/).

The contract under test: verdicts are a pure function of the
histories — NEVER of the dispatch window size, the shape bucketing,
chunk boundaries, or how oracle fallbacks interleave with device work.
window=1 must reproduce the historical serial dispatch-sync-dispatch
path exactly; window≥2 must actually overlap (pinned via the
in-flight-depth gauge).  Runs on the CPU backend; the same code path
runs on real TPU hardware unmodified.
"""

import os
import random

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu import obs
from jepsen_tpu.checker import linear
from jepsen_tpu.engine import DispatchWindow, pipeline, planning
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.ops import encode, wgl
from jepsen_tpu.synth import generate_history as _gen


def h(*ops) -> History:
    hist = History(ops)
    for i, op in enumerate(hist):
        op.index = i
        op.time = i
    return hist


def wide_history(n=40) -> History:
    """n concurrently-open ops: exceeds every slot cap → oracle row."""
    w = History([invoke_op(p, "write", 1) for p in range(n)])
    return w.index_ops()


def mixed_corpus(seed=45100, wide=True):
    """Seeded histories spanning two event buckets and two concurrency
    buckets, with a corrupted minority, plus one unencodable row."""
    rng = random.Random(seed)
    hists = []
    for i in range(4):
        hists.append(
            _gen(rng, n_procs=3, n_ops=10, crash_p=0.02, corrupt=(i % 2 == 0))
        )
    for i in range(4):
        hists.append(
            _gen(rng, n_procs=3, n_ops=75, crash_p=0.01, corrupt=(i % 2 == 0))
        )
    for i in range(3):
        hists.append(_gen(rng, n_procs=7, n_ops=14, corrupt=(i == 0)))
    if wide:
        hists.append(wide_history())
    return hists


def sig(r: dict):
    """The verdict-relevant projection of one result dict (excludes
    fields like sampled configs whose ordering is representational)."""
    return (
        r.get("valid?"),
        r.get("engine"),
        r.get("failed-event"),
        r.get("error"),
    )


# ---------------------------------------------------------------------------
# determinism: window sizes, bucket splits, chunk boundaries
# ---------------------------------------------------------------------------


def test_window_and_bucketing_preserve_verdicts_dense_route():
    hists = mixed_corpus()
    model = m.cas_register(0)
    oracle = [
        linear.analysis(model, h0, pure_fs=("read",))["valid?"]
        for h0 in hists
    ]
    assert True in oracle and False in oracle  # corpus mixes verdicts
    serial = wgl.check_batch(model, hists, window=1, bucketed=False)
    assert [o["valid?"] for o in serial] == oracle
    for window, bucketed in ((1, True), (2, True), (4, True), (4, False)):
        outs = wgl.check_batch(
            model, hists, window=window, bucketed=bucketed
        )
        assert [o["valid?"] for o in outs] == oracle, (window, bucketed)
        # device rows stay device rows, the wide row stays an oracle row
        assert outs[-1]["engine"] == "oracle-fallback"
        assert all(o["engine"] == "tpu" for o in outs[:-1])


def test_window_preserves_verdicts_frontier_route_across_chunks():
    """Explicit max_closure forces the generic frontier kernel; a tiny
    max_dispatch forces several padded chunks per bucket.  Verdicts and
    failure events must be identical at every window size."""
    hists = mixed_corpus(seed=7, wide=False)
    model = m.cas_register(0)
    base = wgl.check_batch(
        model, hists, max_closure=9, window=1, bucketed=False
    )
    for window in (1, 4):
        outs = wgl.check_batch(
            model, hists, max_closure=9, max_dispatch=3, window=window,
            bucketed=True,
        )
        assert [sig(o) for o in outs] == [sig(o) for o in base], window
        assert {o.get("kernel") for o in outs} == {"frontier"}


def test_escalation_interacts_with_pipelining():
    """Overflow rows must escalate (and resolve on-device) identically
    whether the base dispatches were pipelined or serial."""
    rng = random.Random(61)
    model = m.cas_register(0)
    hists = [
        _gen(rng, n_procs=5, n_ops=30, crash_p=0.02, corrupt=(i % 3 == 0))
        for i in range(9)
    ]
    base = wgl.check_batch(model, hists, window=1, bucketed=False)
    esc = wgl.check_batch(
        model, hists, frontier=2, escalation=(4, 16), max_closure=7,
        slot_cap=6, max_dispatch=4, window=4, bucketed=True,
    )
    assert [o["valid?"] for o in esc] == [o["valid?"] for o in base]


def test_tight_frontier_shapes_serialize_instead_of_overshooting():
    """When a frontier shape's safe dispatch cap is smaller than the
    window (per-row footprint near the whole crash-calibrated budget),
    the engine must dispatch that bucket strictly serially — windowed
    one-row dispatches would hold more concurrent footprint than the
    budget was measured for.  Verdicts must be unaffected."""
    rng = random.Random(31)
    model = m.cas_register(0)
    hists = [
        _gen(rng, n_procs=4, n_ops=16, crash_p=0.0, corrupt=(i % 2 == 0))
        for i in range(6)
    ]
    base = wgl.check_batch(model, hists, max_closure=8, window=1)
    old = wgl.FRONTIER_DISPATCH_BUDGET
    # E=64, C=4, F=128 → 1280 words/row: a 3000-word budget gives a
    # safe cap of 2 rows — below the window of 4
    wgl.FRONTIER_DISPATCH_BUDGET = 3000
    wgl.make_check_fn.cache_clear()  # cached fns carry stale caps
    obs.enable(reset=True)
    try:
        outs = wgl.check_batch(model, hists, max_closure=8, window=4)
    finally:
        wgl.FRONTIER_DISPATCH_BUDGET = old
        wgl.make_check_fn.cache_clear()
    assert [o["valid?"] for o in outs] == [o["valid?"] for o in base]
    # the frontier bucket never had two dispatches in flight
    assert obs.registry().value("jepsen_engine_inflight_depth") == 1
    obs.enable(reset=True)


def test_unknown_tags_without_oracle_fallback_are_window_invariant():
    """oracle_fallback=False (the race-mode contract): unresolved rows
    report the same unknown/engine tags at every window size."""
    hists = mixed_corpus(seed=3)
    model = m.cas_register(0)
    expected = None
    for window in (1, 4):
        outs = wgl.check_batch(
            model, hists, frontier=1, escalation=(), sufficient_rung=False,
            max_closure=1, oracle_fallback=False, window=window,
        )
        tags = [(o["valid?"], o["engine"]) for o in outs]
        assert tags[-1] == ("unknown", "unencodable")
        assert all(
            v == "unknown" and e == "overflow" for v, e in tags[:-1]
        ) or any(v is not None for v, _ in tags)  # overflow rows unknown
        if expected is None:
            expected = outs
        else:
            assert outs == expected, window


def test_oracle_deadline_abort_is_window_invariant():
    """The abort/deadline path: a zero oracle budget turns every
    fallback row into a deterministic budget-exceeded unknown, and the
    pipelined run must report it exactly like the serial one."""
    hists = mixed_corpus(seed=11)
    model = m.cas_register(0)
    runs = []
    for window in (1, 4):
        outs = wgl.check_batch(
            model, hists, frontier=1, escalation=(), sufficient_rung=False,
            max_closure=1, oracle_budget_s=0.0, window=window,
        )
        # device rows overflowed (frontier 1 + truncated closure) and the
        # oracle aborted on its budget: every verdict is an honest unknown
        assert all(o["valid?"] == "unknown" for o in outs if "budget"
                   in (o.get("error") or ""))
        runs.append([sig(o) for o in outs])
    assert runs[0] == runs[1]


def test_repeat_runs_identical_under_concurrent_oracle():
    """Oracle-pool interleaving must never leak into results: two
    identical pipelined runs produce identical result lists."""
    hists = mixed_corpus(seed=19)
    model = m.cas_register(0)
    a = wgl.check_batch(model, hists, window=4, bucketed=True)
    b = wgl.check_batch(model, hists, window=4, bucketed=True)
    assert [sig(o) for o in a] == [sig(o) for o in b]


# ---------------------------------------------------------------------------
# DispatchWindow mechanics
# ---------------------------------------------------------------------------


def test_dispatch_window_serializes_at_one_and_overlaps_above():
    events = []
    retired = []

    def mk(i):
        def thunk():
            events.append(("dispatch", i))
            return np.array([i])

        return thunk

    def on_retire(key, mat, _t):
        events.append(("retire", key))
        retired.append((key, int(mat[0])))

    win = DispatchWindow(1, on_retire=on_retire)
    for i in range(3):
        win.submit(i, mk(i))
    win.drain()
    # window=1 == the serial path: dispatch k+1 strictly after retire k
    assert events == [
        ("dispatch", 0), ("retire", 0),
        ("dispatch", 1), ("retire", 1),
        ("dispatch", 2), ("retire", 2),
    ]
    assert retired == [(0, 0), (1, 1), (2, 2)]
    assert win.peak_depth == 1

    events.clear()
    retired.clear()
    win = DispatchWindow(4, on_retire=on_retire)
    for i in range(3):
        win.submit(i, mk(i))
    # window not full: every dispatch issued before any sync
    assert events == [("dispatch", 0), ("dispatch", 1), ("dispatch", 2)]
    win.drain()
    assert retired == [(0, 0), (1, 1), (2, 2)]  # oldest-first
    assert win.peak_depth == 3


def test_dispatch_window_retires_oldest_when_full():
    order = []
    win = DispatchWindow(2, on_retire=lambda k, _m, _t: order.append(k))
    for i in range(5):
        win.submit(i, lambda i=i: np.array([i]))
    assert order == [0, 1, 2]  # forced out as the window refilled
    win.drain()
    assert order == [0, 1, 2, 3, 4]


# ---------------------------------------------------------------------------
# bucketed encoding
# ---------------------------------------------------------------------------


def test_batch_encode_bucketed_tight_shapes_and_row_coverage():
    hists = mixed_corpus(wide=True)
    model = m.cas_register(0)
    single = encode.batch_encode(hists, model, slot_cap=32)
    buckets = encode.batch_encode(hists, model, slot_cap=32, bucketed=True)
    assert isinstance(buckets, list) and len(buckets) >= 2
    # every encodable history lands in exactly one bucket row
    covered = sorted(i for b in buckets for i in b.row_history)
    assert covered == sorted(single.row_history)
    # the global fallback list rides on the first bucket only
    assert buckets[0].fallback == single.fallback
    assert all(not b.fallback for b in buckets[1:])
    # shapes are tight: some bucket is strictly smaller than the global
    # padded shape in events or candidate lanes
    E_glob, C_glob = single.ev_slot.shape[1], single.cand_slot.shape[2]
    assert any(
        b.ev_slot.shape[1] < E_glob or b.cand_slot.shape[2] < C_glob
        for b in buckets
    )
    # bucket rows carry the same encoded data as the global stack
    # (modulo padding): compare each row's live event prefix
    pos = {idx: (bi, ri) for bi, b in enumerate(buckets)
           for ri, idx in enumerate(b.row_history)}
    for row, idx in enumerate(single.row_history):
        bi, ri = pos[idx]
        b = buckets[bi]
        E_b, C_b = b.ev_slot.shape[1], b.cand_slot.shape[2]
        np.testing.assert_array_equal(
            b.ev_slot[ri], single.ev_slot[row, :E_b]
        )
        np.testing.assert_array_equal(
            b.cand_slot[ri], single.cand_slot[row, :E_b, :C_b]
        )


def test_batch_encode_bucketed_all_fallback():
    model = m.cas_register(0)
    out = encode.batch_encode(
        [wide_history(), wide_history()], model, slot_cap=32, bucketed=True
    )
    assert len(out) == 1
    assert out[0].init_state.shape[0] == 0
    assert out[0].fallback == [0, 1]


def test_bucket_key_matches_single_batch_rounding():
    e = encode.encode_history(
        h(
            invoke_op(0, "write", 1),
            invoke_op(1, "read"),
            ok_op(0, "write", 1),
            ok_op(1, "read", 1),
        ),
        m.cas_register(None),
    )
    assert encode.bucket_key(e, slot_cap=32) == (64, 4)
    assert encode.bucket_key(e, slot_cap=2) == (64, 2)  # capped


# ---------------------------------------------------------------------------
# telemetry + satellite integrations
# ---------------------------------------------------------------------------


def test_pipeline_metrics_and_span():
    hists = mixed_corpus(wide=False)
    model = m.cas_register(0)
    obs.enable(reset=True)
    wgl.check_batch(model, hists, window=4, bucketed=True, max_dispatch=3)
    reg = obs.registry()
    assert (reg.value("jepsen_engine_inflight_depth") or 0) > 1
    assert (reg.value("jepsen_engine_bucket_count") or 0) >= 2
    # the engine's streaming bucketer and batch_encode(bucketed=True)
    # share bucket_key/stack_encoded; this pins that they also AGREE on
    # the partition, so neither implementation can silently drift
    assert reg.value("jepsen_engine_bucket_count") == len(
        encode.batch_encode(hists, model, bucketed=True)
    )
    occ = reg.value("jepsen_engine_occupancy_ratio")
    assert occ is not None and 0.0 <= occ <= 1.0
    bubble = [
        d for d in reg.snapshot()
        if d["name"] == "jepsen_engine_bubble_seconds"
    ]
    assert bubble and bubble[0]["count"] > 0
    names = {s.name for s in obs.tracer().finished(cat="engine")}
    assert "engine/pipeline" in names
    assert "engine/dispatch" in names
    # the summary surfaces the pipeline facts
    s = obs.summary()
    assert s.get("engine-inflight-depth", 0) > 1
    assert "engine-occupancy" in s
    obs.enable(reset=True)


def test_window_one_records_serial_depth():
    hists = mixed_corpus(wide=False)
    model = m.cas_register(0)
    obs.enable(reset=True)
    wgl.check_batch(model, hists, window=1, bucketed=True, max_dispatch=3)
    assert obs.registry().value("jepsen_engine_inflight_depth") == 1
    obs.enable(reset=True)


def test_per_chip_budget_accounting_under_mesh():
    """The acceptance hook: with a mesh of n devices and a window of
    W, no compiled fn's peak concurrently-in-flight PER-CHIP rows may
    exceed its single-chip cap (frontier chunks take n × disp/W rows
    globally = disp/W per chip; dense keeps the full per-chip cap ×
    window, the measured bench pattern) — asserted through the
    executor's chip_row_accounting."""
    import jax

    from jepsen_tpu.engine import execution, planning
    from jepsen_tpu.parallel import mesh as mesh_mod

    devs = jax.devices("cpu")
    assert len(devs) >= 8
    mesh = mesh_mod.default_mesh(devs[:8])
    model = m.cas_register(0)
    hists = mixed_corpus(seed=13, wide=False)
    # frontier route (max_closure), small max_dispatch so several
    # chunks are in flight at once
    ctx = planning.RunContext(model, hists)
    planner = planning.Planner(
        model, spec=ctx.spec, slot_cap=32, frontier=64, max_closure=9,
        max_dispatch=8, n_devices=8,
    )
    ex = pipeline.Executor(4, mesh=mesh, max_dispatch=8)
    for pb in planner.stream(ctx):
        ex.submit(pb)
    ex.drain()
    ctx.drain_oracles()
    assert ex.n_devices == 8
    accts = list(ex.chip_row_accounting.values())
    frontier_accts = [a for a in accts if a["kernel"] == "frontier"]
    assert frontier_accts, "no frontier dispatches recorded"
    for a in accts:
        cap = a["chip_cap"]
        if a["kernel"] == "dense":
            cap = cap * ex.window_size  # multi-in-flight dense is by design
        assert 0 < a["peak_chip_rows"] <= cap, a
    # in-flight accounting fully settles at drain
    assert all(v == 0 for v in ex._chip_rows_inflight.values())
    # verdicts unharmed by the accounting path
    assert [r["valid?"] for r in ctx.results] == [
        linear.analysis(model, h0, pure_fs=("read",))["valid?"]
        for h0 in hists
    ]


def test_executor_reset_clears_chip_accounting():
    from jepsen_tpu.engine import execution

    ex = execution.Executor(2, mesh=None)
    ex._chip_rows_inflight[123] = 7
    ex.reset()
    assert ex._chip_rows_inflight == {}


def test_analysis_async_matches_sync():
    model = m.cas_register(0)
    hist = mixed_corpus(wide=False)[0]
    fut = linear.analysis_async(model, hist, pure_fs=("read",))
    assert fut.result() == linear.analysis(model, hist, pure_fs=("read",))


def test_engine_window_env_default(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_ENGINE_WINDOW", "7")
    assert pipeline.default_window() == 7
    monkeypatch.setenv("JEPSEN_TPU_ENGINE_WINDOW", "junk")
    assert pipeline.default_window() == pipeline.DEFAULT_WINDOW
    monkeypatch.setenv("JEPSEN_TPU_ENGINE_BUCKETED", "0")
    assert pipeline.default_bucketed() is False


def test_cycles_screen_windowed_and_cache_bounded():
    from jepsen_tpu.ops import cycles as ops_cycles

    assert (
        ops_cycles._closure_fn.cache_info().maxsize
        == ops_cycles.CLOSURE_CACHE_SIZE
    )
    assert (
        ops_cycles._reach_fn.cache_info().maxsize
        == ops_cycles.CLOSURE_CACHE_SIZE
    )
    rng = np.random.default_rng(5)
    mats = []
    expected = []
    for n in (3, 10, 20, 40):
        a = np.zeros((n, n), dtype=bool)
        for i in range(n - 1):
            a[i, i + 1] = True
        cyclic = bool(rng.integers(0, 2))
        if cyclic:
            a[n - 1, 0] = True  # close the chain into a ring
        mats.append(a)
        expected.append(cyclic)
    for window in (None, 1, 3):
        got = ops_cycles.has_cycle_batch(mats, window=window)
        assert got.tolist() == expected, window


def test_cli_engine_window_validated_and_exported(monkeypatch):
    """--engine-window rejects values below serial (0 is NOT a disable
    switch) and exports the bound to JEPSEN_TPU_ENGINE_WINDOW so every
    DispatchWindow in the process (e.g. the Elle screen) honors it."""
    import argparse

    from jepsen_tpu import cli

    with pytest.raises(argparse.ArgumentTypeError):
        cli._engine_window_arg("0")
    with pytest.raises(argparse.ArgumentTypeError):
        cli._engine_window_arg("-2")
    assert cli._engine_window_arg("3") == 3

    monkeypatch.delenv("JEPSEN_TPU_ENGINE_WINDOW", raising=False)
    args = argparse.Namespace(
        nodes="n1", node=None, nodes_file=None, time_limit=1,
        store_base="store", leave_db_running=False, logging_json=False,
        username="root", password=None, ssh_private_key=None,
        concurrency=None, dummy=True, engine_window=2,
    )
    test = cli.test_opts_to_map(args)
    assert test["engine-window"] == 2
    # no process-wide leak from option mapping …
    assert "JEPSEN_TPU_ENGINE_WINDOW" not in os.environ
    # … run_test scopes the export to the run and restores afterwards
    from jepsen_tpu import core

    seen = {}

    def fake_run(t):
        seen["win"] = os.environ.get("JEPSEN_TPU_ENGINE_WINDOW")
        return {"results": {"valid?": True}}

    monkeypatch.setattr(core, "run", fake_run)
    assert cli.run_test(test) == cli.EXIT_VALID
    assert seen["win"] == "2"
    assert "JEPSEN_TPU_ENGINE_WINDOW" not in os.environ


def test_batched_linearizable_reads_engine_window():
    """The CLI's --engine-window lands in test['engine-window'] and
    flows through batched_linearizable into the engine."""
    from jepsen_tpu import independent as ind

    rng = random.Random(23)
    hists = {
        k: _gen(rng, n_procs=3, n_ops=8, crash_p=0.0) for k in ("a", "b")
    }
    history = History()
    for k, sub in hists.items():
        for op in sub:
            history.append(op.copy(value=ind.kv(k, op.value)))
    history.index_ops()
    chk = ind.batched_linearizable(m.cas_register(0))
    out = chk.check(
        {"engine-window": 2, "store?": False}, history, {}
    )
    assert out["valid?"] is True
    assert set(out["results"]) == {"a", "b"}


def test_bucket_stream_finish_orders_big_buckets_first():
    """End-of-input buckets dispatch largest-estimated-cost first
    (BucketStream.finish) — the per-run half of the daemon's
    largest-cost-first scheduling — with first-seen order preserved
    between equal-cost buckets."""
    model = m.cas_register(0)
    # 2 short rows land in a small bucket first, then 6 long rows in a
    # bigger-cost bucket: first-seen order is small-first, finish must
    # flip it
    rng = random.Random(7)
    hists = [_gen(rng, n_procs=3, n_ops=8, crash_p=0.0) for _ in range(2)]
    hists += [_gen(rng, n_procs=3, n_ops=75, crash_p=0.0) for _ in range(6)]
    ctx = planning.RunContext(model, hists)
    planner = planning.Planner(model, spec=ctx.spec, slot_cap=32,
                               frontier=64)
    stream = planner.open_stream()
    for idx in range(len(hists)):
        assert list(stream.feed(ctx, idx)) == []  # below flush_rows
    out = list(stream.finish())
    assert len(out) >= 2
    costs = [planning.estimated_cost(pb) for pb in out]
    assert costs == sorted(costs, reverse=True)
    assert costs[0] > costs[-1]


def test_planner_stream_equals_feed_finish_composition():
    """Planner.stream is exactly open_stream + feed* + finish: same
    buckets, same rows, same plans."""
    model = m.cas_register(0)
    hists = mixed_corpus(seed=3, wide=False)
    ctx_a = planning.RunContext(model, hists)
    planner_a = planning.Planner(model, spec=ctx_a.spec, slot_cap=32,
                                 frontier=64)
    via_stream = [
        (pb.key, len(pb.rows)) for pb in planner_a.stream(ctx_a)
    ]
    ctx_b = planning.RunContext(model, hists)
    planner_b = planning.Planner(model, spec=ctx_b.spec, slot_cap=32,
                                 frontier=64)
    s = planner_b.open_stream()
    via_feed = []
    for idx in range(len(hists)):
        via_feed.extend((pb.key, len(pb.rows)) for pb in s.feed(ctx_b, idx))
    via_feed.extend((pb.key, len(pb.rows)) for pb in s.finish())
    assert via_stream == via_feed
    assert planner_a.n_buckets == planner_b.n_buckets
    assert planner_a.n_flushes == planner_b.n_flushes
