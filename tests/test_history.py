"""History/op data model tests (reference test style:
jepsen/test/jepsen/checker_test.clj builds literal histories)."""

from jepsen_tpu.history import (
    History,
    Op,
    invoke_op,
    ok_op,
    fail_op,
    info_op,
    strip_indeterminate_reads,
)


def h(*ops) -> History:
    return History(ops).index_ops()


def test_index_ops():
    hist = h(invoke_op(0, "read"), ok_op(0, "read", 1))
    assert [op.index for op in hist] == [0, 1]


def test_pairing():
    hist = h(
        invoke_op(0, "write", 1),
        invoke_op(1, "read"),
        ok_op(0, "write", 1),
        ok_op(1, "read", 1),
    )
    assert hist.pair_index() == [2, 3, 0, 1]
    pairs = list(hist.pairs())
    assert pairs[0][0].process == 0 and pairs[0][1].type == "ok"
    assert pairs[1][0].process == 1 and pairs[1][1].value == 1


def test_unpaired_invoke():
    hist = h(invoke_op(0, "write", 1))
    assert hist.pair_index() == [-1]
    assert list(hist.pairs())[0][1] is None


def test_complete_propagates_read_values():
    hist = h(invoke_op(0, "read"), ok_op(0, "read", 42))
    c = hist.complete()
    assert c[0].value == 42


def test_complete_fills_completion_from_invoke():
    # a write acked without echoing the value: invoke keeps 7, ok inherits it
    hist = h(invoke_op(0, "write", 7), ok_op(0, "write"))
    c = hist.complete()
    assert c[0].value == 7
    assert c[1].value == 7


def test_without_failures():
    hist = h(
        invoke_op(0, "write", 1),
        fail_op(0, "write", 1),
        invoke_op(1, "write", 2),
        ok_op(1, "write", 2),
    )
    cleaned = hist.without_failures()
    assert len(cleaned) == 2
    assert all(op.process == 1 for op in cleaned)


def test_strip_indeterminate_reads():
    hist = h(
        invoke_op(0, "read"),
        invoke_op(1, "write", 5),
        info_op(0, "read"),
        ok_op(1, "write", 5),
    )
    out = strip_indeterminate_reads(hist, ["read"])
    assert len(out) == 2
    assert all(op.f == "write" for op in out)


def test_completion_of_unindexed_and_filtered():
    hist = History([invoke_op(0, "read"), ok_op(0, "read", 1)])  # never indexed
    assert hist.completion_of(hist[0]).type == "ok"
    indexed = h(
        invoke_op(0, "write", 1),
        invoke_op(1, "read"),
        ok_op(1, "read", 1),
        ok_op(0, "write", 1),
    )
    sub = History(op for op in indexed if op.process == 1)  # stale indices
    assert sub.completion_of(sub[0]).value == 1


def test_op_dict_roundtrip():
    op = invoke_op(3, "cas", (1, 2), time=17, error="boom")
    d = op.to_dict()
    assert d["error"] == "boom"
    op2 = Op.from_dict(d)
    assert op2 == op


def test_op_extra_access():
    op = ok_op("nemesis", "start-partition", "majority")
    op["grudge"] = {1: [2]}
    assert op["grudge"] == {1: [2]}
    assert op.get("missing", "d") == "d"
    assert "grudge" in op


def test_views():
    hist = h(
        invoke_op(0, "read"),
        info_op("nemesis", "start"),
        ok_op(0, "read", 1),
    )
    assert len(hist.client_ops()) == 2
    assert len(hist.nemesis_ops()) == 1
    assert len(list(hist.oks())) == 1
    assert len(list(hist.invocations())) == 1
