"""Per-suite nemesis tests: yugabyte master/tserver targeting, fauna
topology churn on the membership state machine, aerospike capped kills
with revive/recluster — all against dummy remotes (reference:
yugabyte/nemesis.clj, faunadb/topology.clj, aerospike/nemesis.clj)."""

import contextlib
import os

import pytest

from jepsen_tpu import control
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import DummyRemote


NODES = ["n1", "n2", "n3", "n4", "n5"]


def dummy_test(**extra):
    return {"nodes": list(NODES), "remote": DummyRemote(),
            "ssh": {"dummy?": True}, **extra}


@contextlib.contextmanager
def sessions(test):
    with control.with_session(test, test["remote"]):
        yield


# -- yugabyte ---------------------------------------------------------------


def test_yb_process_nemesis_targets_components():
    from jepsen_tpu.suites import yb_nemesis, yugabyte

    db = yugabyte.YugabyteDB({"replication-factor": 3})
    t = dummy_test(db=db)
    with sessions(t):
        nem = yb_nemesis.YbProcessNemesis(db).setup(t)
        masters = db.master_nodes(t)
        assert masters == ["n1", "n2", "n3"]

        res = nem.invoke(t, {"type": "info", "f": "kill-master",
                             "value": None})
        assert res["type"] == "info"
        assert set(res["value"]) <= set(masters), res

        res = nem.invoke(t, {"type": "info", "f": "kill-tserver",
                             "value": None})
        assert set(res["value"]) <= set(NODES)

        # recovery ops hit every relevant node
        res = nem.invoke(t, {"type": "info", "f": "start-master",
                             "value": None})
        assert sorted(res["value"]) == masters
        res = nem.invoke(t, {"type": "info", "f": "start-tserver",
                             "value": None})
        assert sorted(res["value"]) == NODES

        res = nem.invoke(t, {"type": "info", "f": "pause-tserver",
                             "value": None})
        assert res["type"] == "info"


def test_yb_full_nemesis_routes_partitions_and_clock():
    from jepsen_tpu.suites import yb_nemesis, yugabyte

    db = yugabyte.YugabyteDB({})
    t = dummy_test(db=db)
    nem = yb_nemesis.full_nemesis(db)
    fs = nem.fs()
    for f in ("kill-master", "start-partition", "stop-partition",
              "bump-clock", "reset-clock"):
        assert f in fs, f
    with sessions(t):
        nem = nem.setup(t)
        grudge = {"n1": {"n2"}, "n2": {"n1"}}
        res = nem.invoke(t, {"type": "info", "f": "start-partition",
                             "value": grudge})
        assert res["f"] == "start-partition"
        res = nem.invoke(t, {"type": "info", "f": "stop-partition",
                             "value": None})
        assert res["f"] == "stop-partition"
        nem.teardown(t)


def test_yb_generators_expand_and_recover():
    from jepsen_tpu.suites import yb_nemesis

    n = yb_nemesis.expand_options({"kill": True, "partition": True,
                                   "clock-skew": True, "interval": 0.01})
    assert n["kill-master"] and n["kill-tserver"]
    assert n["partition-ring"]
    g = yb_nemesis.full_generator(n)
    assert g is not None
    final = yb_nemesis.final_generator(n)
    fs = [op["f"] for op in final]
    assert "start-tserver" in fs and "start-master" in fs
    assert "stop-partition" in fs and "reset-clock" in fs

    # partition generators produce grudges over the test's nodes
    t = dummy_test()
    op = yb_nemesis.partition_ring_gen(t, {})
    assert op["f"] == "start-partition"
    assert set(op["value"]) == set(NODES)


def test_yb_suite_test_uses_fault_menu():
    from jepsen_tpu.suites import yugabyte

    t = yugabyte.test({
        "nodes": NODES, "workload": "ycql.register",
        "faults": ["kill-master", "partition-one"], "time-limit": 5,
    })
    fs = t["nemesis"].fs()
    assert "kill-master" in fs and "start-partition" in fs
    assert t["generator"] is not None


# -- fauna topology ---------------------------------------------------------


def test_fauna_topology_state_machine():
    from jepsen_tpu.suites.fauna_topology import FaunaTopology

    t = dummy_test(replicas=2)
    with sessions(t):
        st = FaunaTopology(replicas=2).setup(t)
        by_rep = st.nodes_by_replica()
        assert set(by_rep) == {"replica-0", "replica-1"}
        assert sorted(sum(by_rep.values(), [])) == NODES

        # with every node active, only removes are possible
        op = st.op(t)
        assert op["f"] == "remove-node"

        # removing nodes never empties a replica
        while True:
            removes = st._remove_ops()
            if not removes:
                break
            res = st.invoke(t, removes[0])
            assert res["type"] == "info"
            for nodes in st.nodes_by_replica().values():
                assert len(nodes) >= 1
        # converged: every replica is at its 1-node floor
        assert all(
            len(ns) == 1 for ns in st.nodes_by_replica().values()
        )
        # removed nodes can now rejoin
        adds = st._add_ops(t)
        assert adds
        res = st.invoke(t, adds[0])
        assert res["type"] == "info"
        assert adds[0]["value"]["node"] in {
            n["node"] for n in st.topo["nodes"]
        }


def test_fauna_topology_package_multi_node_dummy_run():
    """Drive the membership nemesis end-to-end against dummy remotes:
    ops flow through MembershipNemesis.invoke and the topology keeps
    its invariants."""
    from jepsen_tpu.suites import fauna_topology

    t = dummy_test(replicas=2)
    pkg = fauna_topology.package({"interval": 0.01, "replicas": 2})
    with sessions(t):
        nem = pkg["nemesis"].setup(t)
        try:
            state = nem.state
            for _ in range(8):
                op = state.op(t)
                if op == "pending":
                    break
                out = nem.invoke(t, dict(op))
                assert out["type"] in ("info", "fail"), out
                for nodes in state.nodes_by_replica().values():
                    assert len(nodes) >= 1
        finally:
            nem.teardown(t)


def test_fauna_suite_test_wires_topology_package():
    from jepsen_tpu.suites import faunadb

    t = faunadb.test({
        "nodes": NODES, "workload": "register",
        "faults": ["topology"], "time-limit": 5,
    })
    assert "add-node" in t["nemesis"].fs()
    assert t["generator"] is not None


# -- aerospike --------------------------------------------------------------


def test_aerospike_kill_nemesis_caps_dead_nodes():
    from jepsen_tpu.suites import aerospike

    t = dummy_test()
    with sessions(t):
        nem = aerospike.AsKillNemesis(max_dead=2).setup(t)
        res = nem.invoke(t, {"type": "info", "f": "kill",
                             "value": ["n1", "n2", "n3", "n4"]})
        vals = res["value"]
        assert sum(1 for v in vals.values() if v == "killed") == 2
        assert sum(1 for v in vals.values() if v == "still-alive") == 2
        assert len(nem.dead) == 2

        # restart frees the cap
        res = nem.invoke(t, {"type": "info", "f": "restart",
                             "value": sorted(nem.dead)})
        assert all(v == "started" for v in res["value"].values())
        assert not nem.dead

        # revive/recluster run on every node without error
        res = nem.invoke(t, {"type": "info", "f": "revive", "value": None})
        assert sorted(res["value"]) == NODES
        res = nem.invoke(t, {"type": "info", "f": "recluster",
                             "value": None})
        assert sorted(res["value"]) == NODES


def test_aerospike_full_nemesis_and_package():
    from jepsen_tpu.suites import aerospike

    t = dummy_test()
    pkg = aerospike.nemesis_package({"max-dead-nodes": 2, "interval": 0.01})
    with sessions(t):
        nem = pkg["nemesis"].setup(t)
        fs = nem.fs()
        for f in ("kill", "restart", "revive", "recluster",
                  "partition-start", "partition-stop", "clock-reset"):
            assert f in fs, f
        res = nem.invoke(t, {"type": "info", "f": "partition-start",
                             "value": None})
        assert res["f"] == "partition-start"
        nem.invoke(t, {"type": "info", "f": "partition-stop",
                       "value": None})
        nem.teardown(t)
    assert pkg["generator"] is not None
    finals = [op["f"] for op in pkg["final_generator"]]
    assert finals[-2:] == ["revive", "recluster"]


def test_aerospike_suite_test_uses_fault_menu():
    from jepsen_tpu.suites import aerospike

    t = aerospike.test({
        "nodes": NODES, "workload": "cas-register",
        "faults": ["kill", "partition"], "time-limit": 5,
    })
    fs = t["nemesis"].fs()
    assert "revive" in fs and "partition-start" in fs


# -- integration + edge cases -----------------------------------------------


def test_yb_long_recovery_alternates_windows():
    """long-recovery mode cycles fault windows with recovery + calm —
    the generator must keep producing ops after the first 120 s fault
    window ends (reference: nemesis.clj:211-223 full-generator).
    Virtual time advances 10 s per drawn op so the run actually crosses
    window boundaries."""
    from jepsen_tpu.suites import yb_nemesis

    n = yb_nemesis.expand_options(
        {"kill": True, "interval": 0.001, "long-recovery": True}
    )
    g = yb_nemesis.full_generator(n)
    t = dummy_test()
    ctx = gen.context({"concurrency": 1, "nodes": NODES})
    fs_with_time = []
    guard = 0
    while len(fs_with_time) < 60 and guard < 10_000:
        guard += 1
        res = gen.op(g, t, ctx)
        if res is None:
            break
        o, g = res
        if o == gen.PENDING:
            # jump virtual time past the pending wait (sleep phases)
            ctx = {**ctx, "time": ctx["time"] + int(10e9)}
            continue
        if isinstance(o, dict) and o.get("f"):
            fs_with_time.append((ctx["time"], o["f"]))
        ctx = {**ctx, "time": ctx["time"] + int(10e9)}
    fs = [f for _, f in fs_with_time]
    assert fs.count("start-tserver") >= 1, fs
    assert any(f in ("kill-tserver", "kill-master") for f in fs), fs
    # ops continue PAST the first 120 s fault window: the cycle/phases
    # machinery restarted a fresh window rather than ending the gen
    window_ns = 120 * 1_000_000_000
    assert any(ts > 2 * window_ns for ts, _ in fs_with_time), (
        fs_with_time[-3:]
    )


def test_partition_targets_flow_to_leftover_package():
    """partition-targets must reach the generic partition package when
    partition runs alongside a suite menu: its start-partition ops carry
    the requested target spec, not the defaults."""
    from jepsen_tpu.suites import common, fauna_topology
    from jepsen_tpu.suites.faunadb import FaunaDB

    opts = {
        "nodes": NODES,
        "faults": ["topology", "partition"],
        "partition-targets": ["one"],
        "interval": 0.001,
    }
    db = FaunaDB(opts)
    pkg = common.suite_nemesis_package(
        opts, db, fauna_topology.package(opts), {"topology"}
    )
    assert "start-partition" in pkg["nemesis"].fs()
    # pull ops until a start-partition appears; its value must be the
    # requested "one" spec (the package default would draw from the
    # full spec list)
    t = dummy_test(db=db)
    with sessions(t):
        pkg["nemesis"].setup(t)
    ctx = gen.context({"concurrency": 1, "nodes": NODES})
    g = pkg["generator"]
    values = []
    guard = 0
    while len(values) < 8 and guard < 10_000:
        guard += 1
        res = gen.op(g, t, ctx)
        if res is None:
            break
        o, g = res
        if o == gen.PENDING:
            ctx = {**ctx, "time": ctx["time"] + int(1e9)}
            continue
        if isinstance(o, dict) and o.get("f") == "start-partition":
            values.append(o["value"])
        ctx = {**ctx, "time": ctx["time"] + int(1e9)}
    assert values, "no start-partition op ever drawn"
    assert set(values) == {"one"}, values


def test_aerospike_full_run_under_fault_menu():
    """An in-process aerospike run with the suite fault menu active:
    kills/restarts/revives flow through the whole loop against the fake
    server and the verdict holds."""
    from fake_servers import FakeAerospike

    from jepsen_tpu import core
    from jepsen_tpu import db as db_mod
    from jepsen_tpu.suites import aerospike

    s = FakeAerospike().start()
    try:
        t = aerospike.test({
            "nodes": ["n1", "n2", "n3"],
            "host": "127.0.0.1",
            "port": s.port,
            "time-limit": 3,
            "rate": 30,
            "interval": 0.5,
            "workload": "cas-register",
            "faults": ["kill"],
        })
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        hist = result["history"]
        nem_fs = {op["f"] for op in hist if op["process"] == "nemesis"}
        assert nem_fs & {"kill", "restart", "revive", "recluster"}, nem_fs
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- dgraph -----------------------------------------------------------------


def test_dgraph_component_nemeses_target_alpha_and_zero():
    from jepsen_tpu.suites import dgraph, dgraph_nemesis

    db = dgraph.DgraphDB({})
    t = dummy_test(db=db)
    with sessions(t):
        killer = dgraph_nemesis.AlphaKiller(db).setup(t)
        # alpha kill/restart targets EVERY node (reference targeter is
        # identity, nemesis.clj:17-23)
        res = killer.invoke(t, {"type": "info", "f": "kill-alpha",
                                "value": None})
        assert sorted(res["value"]) == NODES
        res = killer.invoke(t, {"type": "info", "f": "restart-alpha",
                                "value": None})
        assert sorted(res["value"]) == NODES

        zk = dgraph_nemesis.ZeroKiller(db).setup(t)
        res = zk.invoke(t, {"type": "info", "f": "kill-zero",
                            "value": None})
        # zero runs on the first node only
        assert set(res["value"]) <= {"n1"}
        res = zk.invoke(t, {"type": "info", "f": "restart-zero",
                            "value": None})
        assert sorted(res["value"]) == ["n1"]

        fixer = dgraph_nemesis.AlphaFixer(db).setup(t)
        res = fixer.invoke(t, {"type": "info", "f": "fix-alpha",
                               "value": None})
        # dummy remotes report no pidfile, so every target restarts
        assert set(res["value"].values()) <= {"restarted",
                                              "already-running"}


def test_dgraph_tablet_mover_against_fake_zero():
    from fake_servers import FakeDgraph

    from jepsen_tpu.suites import dgraph, dgraph_nemesis
    from jepsen_tpu import independent, trace

    s = FakeDgraph().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port,
                "zero-public-port": s.port}
        # write through the real client so predicates register as
        # tablets in the fake zero's group map
        c = dgraph.DgraphSequentialClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        r = c.invoke({}, {"f": "inc", "type": "invoke",
                          "value": independent.kv(0, None)})
        assert r["type"] == "ok", r

        db = dgraph.DgraphDB(opts)
        t = dummy_test(db=db)
        spans = []
        trace.tracing(exporter=spans.append)
        try:
            mover = dgraph_nemesis.TabletMover(db).setup(t)
            res = mover.invoke(t, {"type": "info", "f": "move-tablet",
                                   "value": None})
        finally:
            trace.tracing()  # sampling back off
        assert res["type"] == "info"
        assert isinstance(res["value"], dict), res
        # the fake zero seeded key/value predicates into group 1; any
        # executed move is recorded as pred -> [from, to]
        for pred, (g_from, g_to) in res["value"]["moved"].items():
            assert g_from != g_to
        # the move is wrapped in a tracing span like the reference
        assert any(
            sp.name == "nemesis.tablet-mover.invoke" for sp in spans
        )
        state = db.zero_state(t, "n1")
        moved = {
            p: g["tablets"][p]["groupId"]
            for g in state["groups"].values()
            for p in g["tablets"]
        }
        for pred, (_g_from, g_to) in res["value"]["moved"].items():
            assert str(moved[pred]) == str(g_to)
    finally:
        s.stop()


def test_dgraph_generators_expand_and_recover():
    from jepsen_tpu.suites import dgraph_nemesis

    flags = dgraph_nemesis._flags({
        "faults": ["kill-alpha", "kill-zero", "partition-ring",
                   "skew-clock", "move-tablet"],
        "interval": 0.01, "skew": "big",
    })
    assert flags["kill-alpha?"] and flags["move-tablet?"]
    g = dgraph_nemesis.full_generator(flags)
    assert g is not None
    final = dgraph_nemesis.final_generator(flags)
    fs = [op["f"] for op in final]
    assert "restart-alpha" in fs and "restart-zero" in fs
    assert "stop-partition-ring" in fs and "stop-skew" in fs

    op = dgraph_nemesis._partition_ring_gen(dummy_test(), {})
    assert op["f"] == "start-partition-ring"
    assert set(op["value"]) == set(NODES)


def test_dgraph_suite_test_uses_fault_menu():
    from jepsen_tpu.suites import dgraph, dgraph_nemesis

    t = dgraph.test({
        "nodes": NODES,
        "workload": "sequential",
        "faults": ["kill-alpha", "move-tablet"],
    })
    fs = t["nemesis"].fs()
    for f in ("kill-alpha", "restart-alpha", "move-tablet"):
        assert f in fs, f
    assert t["name"] == "dgraph-sequential"


def test_dgraph_skew_presets():
    from jepsen_tpu.suites import dgraph_nemesis

    assert dgraph_nemesis.skew_nemesis({"skew": "huge"}).dt_ms == 7500
    assert dgraph_nemesis.skew_nemesis({"skew": "tiny"}).dt_ms == 100
    assert dgraph_nemesis.skew_nemesis({}).dt_ms == 0
    # a requested skew-clock fault defaults to a real preset
    flags = dgraph_nemesis._flags({"faults": ["skew-clock"]})
    assert flags["skew"] == "small"
    assert dgraph_nemesis.skew_nemesis(flags).dt_ms == 250


def test_trace_spans_nest_and_export():
    from jepsen_tpu import trace

    spans = []
    trace.tracing(exporter=spans.append)
    try:
        with trace.with_trace("outer"):
            outer_ctx = trace.context()
            trace.attribute("k", 1)
            with trace.with_trace("inner"):
                trace.annotate("hello")
                inner_ctx = trace.context()
    finally:
        trace.tracing()
    assert [s.name for s in spans] == ["inner", "outer"]
    inner, outer = spans
    assert inner.parent_id == outer.span_id
    assert inner.trace_id == outer.trace_id == outer_ctx["trace-id"]
    assert inner_ctx["span-id"] == inner.span_id
    assert outer.attributes == {"k": "1"}
    assert inner.annotations[0]["message"] == "hello"
    # sampling off: with_trace is a no-op and context is the zero ctx
    with trace.with_trace("ignored"):
        assert trace.context()["trace-id"] == "0" * 32


def test_traced_client_wrapper_spans_protocol_calls():
    """trace.traced wraps every Client call in a span, tagging invokes
    with the op's f and independent key (reference: dgraph/client.clj
    wraps open!/close!/query/mutate bodies in with-trace)."""
    from jepsen_tpu import client as client_mod
    from jepsen_tpu import trace

    class Probe(client_mod.Client):
        def open(self, test, node):
            return self

        def setup(self, test):
            pass

        def invoke(self, test, op):
            return {**op, "type": "ok"}

        def close(self, test):
            pass

    spans = []
    trace.tracing(exporter=spans.append)
    try:
        c = trace.Traced(Probe())
        opened = c.open({}, "n1")
        opened.invoke({}, {"f": "read", "value": [3, None]})
        opened.close({})
        # a 2-micro-op txn value is NOT an independent [k v] pair
        c.invoke(
            {}, {"f": "txn", "value": [["r", 3, None], ["append", 3, 2]]}
        )
    finally:
        trace.tracing()
    names = [s.name for s in spans]
    assert names == [
        "client.open", "client.invoke", "client.close", "client.invoke",
    ]
    inv = spans[1]
    assert inv.attributes["f"] == "read"
    assert inv.attributes["key"] == "3"
    assert "key" not in spans[3].attributes
    # sampling off: spans cost nothing and export nowhere
    spans.clear()
    with trace.with_trace("ignored"):
        pass
    assert spans == []
    # wire(): no endpoint → test map untouched; endpoint → wrapped
    p = Probe()
    t = {"client": p}
    assert trace.wire(t, None)["client"] is p
    assert isinstance(trace.wire(t, "spans.jsonl")["client"], trace.Traced)


def test_dgraph_test_wires_tracing_endpoint(tmp_path):
    """dgraph.test({"tracing": path}) wraps the suite client and
    records the endpoint; building a test must NOT flip the global
    tracer (core.run configures it at run start, so building two
    traced tests can't cross-wire exporters).  (reference:
    dgraph/core.clj:118,175)"""
    import json as _json

    from jepsen_tpu import trace
    from jepsen_tpu.suites import dgraph

    path = str(tmp_path / "spans.jsonl")
    t = dgraph.test({"tracing": path, "dummy?": True})
    assert isinstance(t["client"], trace.Traced)
    assert t["tracing"] == path
    # building did not enable sampling
    with trace.with_trace("not-sampled"):
        pass
    assert not os.path.exists(path)
    # run start configures the tracer from the test map's endpoint
    try:
        trace.tracing(t["tracing"])
        with trace.with_trace("probe"):
            pass
    finally:
        trace.tracing()
    with open(path) as f:
        recs = [_json.loads(line) for line in f]
    assert recs and recs[0]["name"] == "probe"


def test_run_scopes_tracing_to_the_run(tmp_path):
    """core.run turns the tracer on from test["tracing"] and OFF again
    afterwards, so later runs in the same process don't inherit a stale
    exporter."""
    from jepsen_tpu import core, trace
    from jepsen_tpu.fake import AtomClient, AtomState
    from jepsen_tpu import generator as gen

    path = str(tmp_path / "spans.jsonl")

    def mktest(endpoint=None):
        return trace.wire(
            {
                "name": "trace-scope",
                "client": AtomClient(AtomState(0)),
                "generator": gen.limit(
                    4, gen.clients({"f": "read", "value": None})
                ),
                "store?": False,
                "nodes": ["n1"],
                "concurrency": 1,
            },
            endpoint,
        )

    core.run(mktest(path))
    n_traced = sum(1 for _ in open(path))
    assert n_traced > 0
    # sampling is off again after the run...
    with trace.with_trace("after"):
        pass
    # ...and an untraced run appends nothing to the old spans file
    core.run(mktest())
    assert sum(1 for _ in open(path)) == n_traced


# -- tidb -------------------------------------------------------------------


def test_tidb_process_nemesis_targets_components():
    from jepsen_tpu.suites import tidb, tidb_nemesis

    db = tidb.TiDB({})
    t = dummy_test(db=db)
    with sessions(t):
        nem = tidb_nemesis.TidbProcessNemesis(db).setup(t)
        for comp in ("pd", "kv", "db"):
            res = nem.invoke(t, {"type": "info", "f": f"kill-{comp}",
                                 "value": None})
            assert res["type"] == "info"
            assert set(res["value"]) <= set(NODES)
            # recovery targets every node
            res = nem.invoke(t, {"type": "info", "f": f"start-{comp}",
                                 "value": None})
            assert sorted(res["value"]) == NODES
            res = nem.invoke(t, {"type": "info", "f": f"pause-{comp}",
                                 "value": None})
            assert set(res["value"]) <= set(NODES)
            res = nem.invoke(t, {"type": "info", "f": f"resume-{comp}",
                                 "value": None})
            assert sorted(res["value"]) == NODES
        # an op :value overrides the targets (nemesis.clj:31-33)
        res = nem.invoke(t, {"type": "info", "f": "kill-kv",
                             "value": ["n2"]})
        assert sorted(res["value"]) == ["n2"]


def test_tidb_schedule_nemesis_runs_pd_ctl():
    from jepsen_tpu.suites import tidb, tidb_nemesis

    db = tidb.TiDB({})
    t = dummy_test(db=db)
    with sessions(t):
        nem = tidb_nemesis.ScheduleNemesis(db).setup(t)
        res = nem.invoke(t, {"type": "info", "f": "shuffle-leader",
                             "value": None})
        assert res["type"] == "info"
        assert list(res["value"].values()) == ["ok"]
        res = nem.invoke(t, {"type": "info", "f": "del-random-merge",
                             "value": None})
        assert list(res["value"].values()) == ["ok"]


def test_tidb_slow_primary_fails_gracefully_without_pd():
    from jepsen_tpu.suites import tidb, tidb_nemesis

    db = tidb.TiDB({})
    t = dummy_test(db=db)
    with sessions(t):
        nem = tidb_nemesis.SlowPrimaryNemesis(db).setup(t)
        res = nem.invoke(t, {"type": "info", "f": "slow-primary",
                             "value": None})
        # PD is unreachable on dummy nodes: recorded, never raised
        assert res["type"] == "info"
        assert res["value"] == "failed"
        assert res["error"] == "pd-members-unreachable"


def test_tidb_generators_expand_and_recover():
    from jepsen_tpu.suites import tidb_nemesis

    n = tidb_nemesis.expand_options(
        {"kill": True, "pause": True, "schedules": True,
         "partition": True, "clock-skew": True, "interval": 1})
    assert n["kill-pd"] and n["pause-kv"] and n["random-merge"]
    assert n["partition-pd-leader"]
    g = tidb_nemesis.mixed_generator(n)
    assert g is not None
    final = tidb_nemesis.final_generator(n)
    fs = [op["f"] for op in final]
    # pauses resume before kills restart; partition heals; schedulers drop
    assert "resume-pd" in fs and "start-kv" in fs
    assert "stop-partition" in fs and "del-shuffle-leader" in fs
    assert fs.index("resume-pd") < fs.index("start-pd")

    # pd-leader partition generator falls back when PD is dead
    op = tidb_nemesis.partition_pd_leader_gen(dummy_test(), {})
    assert op["f"] == "start-partition"
    assert op["partition_type"] == "pd-leader"
    grudge = op["value"]
    assert set(grudge) == set(NODES)
    # one loner cut from four followers
    sizes = sorted(len(v) for v in grudge.values())
    assert sizes == [1, 1, 1, 1, 4]


def _drain_fs(g, t, n_ops, step_ns=int(10e9)):
    """Draw up to n_ops op f's from g, jumping virtual time past
    pending waits (sleep phases)."""
    ctx = gen.context({"concurrency": 1, "nodes": NODES})
    fs = []
    guard = 0
    while len(fs) < n_ops and guard < 10_000:
        guard += 1
        res = gen.op(g, t, ctx)
        if res is None:
            break
        o, g = res
        if o != gen.PENDING and isinstance(o, dict) and o.get("f"):
            fs.append(o["f"])
        ctx = {**ctx, "time": ctx["time"] + step_ns}
    return fs


def test_tidb_special_schedules():
    from jepsen_tpu.suites import tidb_nemesis

    t = dummy_test()
    # restart-kv-without-pd: kill all kv, pause all pd, start kv,
    # wait, resume pd — in that order
    g = tidb_nemesis.full_generator({"restart-kv-without-pd": True})
    fs = _drain_fs(g, t, 4)
    assert fs == ["kill-kv", "pause-pd", "start-kv", "resume-pd"], fs

    # slow-primary: alternates slow-primary and partition heals forever
    g = tidb_nemesis.full_generator({"slow-primary": True})
    fs = _drain_fs(g, t, 4)
    assert fs == ["slow-primary", "stop-partition"] * 2, fs


def test_tidb_suite_test_uses_fault_menu():
    from jepsen_tpu.suites import tidb, tidb_nemesis

    t = tidb.test({
        "nodes": list(NODES),
        "faults": ["kill-kv", "partition-pd-leader", "clock-skew"],
        "time-limit": 5,
    })
    assert t["name"] == "tidb-register"
    fs = t["nemesis"].fs()
    assert "kill-kv" in fs and "start-partition" in fs
    assert "bump-clock" in fs and "shuffle-leader" in fs

    # a generic-only fault composes the leftover package alongside
    t = tidb.test({
        "nodes": list(NODES),
        "faults": ["kill-kv", "disk"],
        "time-limit": 5,
    })
    fs = t["nemesis"].fs()
    assert "kill-kv" in fs and "break-disk" in fs


# -- cockroachdb ------------------------------------------------------------


def test_crdb_named_bundles_compose_with_tagged_ops():
    from jepsen_tpu.suites import cockroachdb, crdb_nemesis

    db = cockroachdb.CockroachDB({})
    pkg = crdb_nemesis.package(
        {"nemesis": ["parts", "start-kill-2"]}, db
    )
    assert pkg["name"] == "parts+startkill2"
    t = dummy_test(db=db)
    with sessions(t):
        nem = pkg["nemesis"].setup(t)
        # tagged routing: (name, inner-f) reaches the named client
        res = nem.invoke(t, {"type": "info",
                             "f": ("parts", "start"), "value": None})
        assert res["f"] == ("parts", "start")
        res = nem.invoke(t, {"type": "info",
                             "f": ("parts", "stop"), "value": None})
        assert res["value"] == "network-healed"
        res = nem.invoke(t, {"type": "info",
                             "f": ("startkill2", "start"), "value": None})
        assert res["f"][0] == "startkill2"
        # two nodes killed
        assert len(res["value"]) == 2
        res = nem.invoke(t, {"type": "info",
                             "f": ("startkill2", "stop"), "value": None})
        assert len(res["value"]) == 2
        # untagged / unknown names are hard errors, not silent no-ops
        with pytest.raises(ValueError):
            nem.invoke(t, {"type": "info", "f": "start", "value": None})
        with pytest.raises(ValueError):
            nem.invoke(t, {"type": "info", "f": ("nope", "start"),
                           "value": None})


def test_crdb_schedules_tag_and_interleave():
    from jepsen_tpu.suites import crdb_nemesis

    pkg = crdb_nemesis.package({"nemesis": "parts"}, None)
    t = dummy_test()
    fs = _drain_fs(pkg["generator"], t, 4)
    assert fs == [("parts", "start"), ("parts", "stop")] * 2, fs
    # final stops every bundle
    finals = crdb_nemesis.package(
        {"nemesis": ["parts", "small-skews"]}, None
    )["final_generator"]
    fin_fs = _drain_fs(finals, t, 10)
    assert ("parts", "stop") in fin_fs and ("small-skews", "stop") in fin_fs


def test_crdb_skew_ladder_and_restarting_wrapper():
    from jepsen_tpu.suites import cockroachdb, crdb_nemesis

    db = cockroachdb.CockroachDB({})
    assert crdb_nemesis.small_skews(db)["clocks"] is True
    assert crdb_nemesis.huge_skews(db)["name"] == "huge-skews"
    # big/huge skews pair the bump with a netem slowdown wrapper
    assert isinstance(crdb_nemesis.big_skews(db)["client"],
                      crdb_nemesis.Slowing)

    t = dummy_test(db=db)
    with sessions(t):
        nem = crdb_nemesis.Restarting(
            crdb_nemesis.BumpTime(0.25), db).setup(t)
        res = nem.invoke(t, {"type": "info", "f": "stop", "value": None})
        # after stop, every node's DB got a restart attempt
        clock_value, restarts = res["value"]
        assert sorted(restarts) == NODES


def test_crdb_split_nemesis_keyrange_paths():
    from jepsen_tpu.suites import crdb_nemesis

    nem = crdb_nemesis.SplitNemesis({})
    nem.client = None  # no live cluster: probe path degrades cleanly
    res = nem.invoke({"nodes": NODES}, {"type": "info", "f": "split",
                                        "value": None})
    assert res["value"] == "no-keyrange"
    res = nem.invoke({"nodes": NODES, "keyrange": {}},
                     {"type": "info", "f": "split", "value": None})
    assert res["value"] == "nothing-to-split"


def test_crdb_suite_test_wires_menu():
    from jepsen_tpu.suites import cockroachdb

    t = cockroachdb.test({
        "nodes": list(NODES), "nemesis": "parts", "time-limit": 5,
    })
    assert t["name"] == "cockroachdb-register-parts"
    assert ("parts", "start") in t["nemesis"].fs()
    import pytest as _pytest
    with _pytest.raises(ValueError):
        cockroachdb.test({"nodes": list(NODES), "nemesis": "bogus"})


def test_crdb_double_schedule_interleaves_two_bundles():
    from jepsen_tpu.suites import cockroachdb, crdb_nemesis

    db = cockroachdb.CockroachDB({})
    pkg = crdb_nemesis.package(
        {"nemesis": ["parts", "start-stop"],
         "nemesis-schedule": "double"}, db)
    assert pkg["name"] == "parts~startstop"
    t = dummy_test(db=db)
    fs = _drain_fs(pkg["generator"], t, 8, step_ns=int(3e9))
    # instance windows overlap and alternate which leads
    assert fs[:4] == [("parts", "start"), ("startstop", "start"),
                      ("parts", "stop"), ("startstop", "stop")], fs
    assert fs[4:6] == [("startstop", "start"), ("parts", "start")], fs
    fin = _drain_fs(pkg["final_generator"], t, 4)
    assert fin == [("parts", "stop"), ("startstop", "stop")]

    with pytest.raises(ValueError):
        crdb_nemesis.package(
            {"nemesis": ["parts"], "nemesis-schedule": "double"}, db)


def test_package_perf_specs_reach_plot_regions():
    """Fault-window shading: a package's perf entries must land in
    test["plot"]["nemeses"] and produce colored regions (the perf sets
    were previously built by every package and consumed by nothing)."""
    from jepsen_tpu.checker.perf import nemesis_regions
    from jepsen_tpu.history import History, info_op
    from jepsen_tpu.suites import tidb

    t = tidb.test({
        "nodes": list(NODES), "faults": ["kill-kv"], "time-limit": 5,
    })
    specs = t["plot"]["nemeses"]
    kill = next(s for s in specs if s["name"] == "kill")
    assert "kill-kv" in kill["start"] and "start-kv" in kill["stop"]
    assert kill["color"]

    hist = History([
        info_op("nemesis", "kill-kv", None),
        info_op("nemesis", "start-kv", None),
        info_op("nemesis", "other", None),
    ])
    for i, op in enumerate(hist):
        op.index = i
        op.time = int(i * 1e9)
    regions = nemesis_regions(t, hist)
    assert [r.label for r in regions].count("kill") == 1
    kill_region = next(r for r in regions if r.label == "kill")
    assert kill_region.color == kill["color"]

    # cockroach named bundles shade per bundle with tagged fs
    from jepsen_tpu.suites import cockroachdb
    t2 = cockroachdb.test({
        "nodes": list(NODES), "nemesis": ["parts", "start-stop"],
        "time-limit": 5,
    })
    names = {s["name"] for s in t2["plot"]["nemeses"]}
    assert names == {"parts", "startstop"}
