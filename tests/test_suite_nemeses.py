"""Per-suite nemesis tests: yugabyte master/tserver targeting, fauna
topology churn on the membership state machine, aerospike capped kills
with revive/recluster — all against dummy remotes (reference:
yugabyte/nemesis.clj, faunadb/topology.clj, aerospike/nemesis.clj)."""

import contextlib

import pytest

from jepsen_tpu import control
from jepsen_tpu import generator as gen
from jepsen_tpu.control.core import DummyRemote


NODES = ["n1", "n2", "n3", "n4", "n5"]


def dummy_test(**extra):
    return {"nodes": list(NODES), "remote": DummyRemote(),
            "ssh": {"dummy?": True}, **extra}


@contextlib.contextmanager
def sessions(test):
    with control.with_session(test, test["remote"]):
        yield


# -- yugabyte ---------------------------------------------------------------


def test_yb_process_nemesis_targets_components():
    from jepsen_tpu.suites import yb_nemesis, yugabyte

    db = yugabyte.YugabyteDB({"replication-factor": 3})
    t = dummy_test(db=db)
    with sessions(t):
        nem = yb_nemesis.YbProcessNemesis(db).setup(t)
        masters = db.master_nodes(t)
        assert masters == ["n1", "n2", "n3"]

        res = nem.invoke(t, {"type": "info", "f": "kill-master",
                             "value": None})
        assert res["type"] == "info"
        assert set(res["value"]) <= set(masters), res

        res = nem.invoke(t, {"type": "info", "f": "kill-tserver",
                             "value": None})
        assert set(res["value"]) <= set(NODES)

        # recovery ops hit every relevant node
        res = nem.invoke(t, {"type": "info", "f": "start-master",
                             "value": None})
        assert sorted(res["value"]) == masters
        res = nem.invoke(t, {"type": "info", "f": "start-tserver",
                             "value": None})
        assert sorted(res["value"]) == NODES

        res = nem.invoke(t, {"type": "info", "f": "pause-tserver",
                             "value": None})
        assert res["type"] == "info"


def test_yb_full_nemesis_routes_partitions_and_clock():
    from jepsen_tpu.suites import yb_nemesis, yugabyte

    db = yugabyte.YugabyteDB({})
    t = dummy_test(db=db)
    nem = yb_nemesis.full_nemesis(db)
    fs = nem.fs()
    for f in ("kill-master", "start-partition", "stop-partition",
              "bump-clock", "reset-clock"):
        assert f in fs, f
    with sessions(t):
        nem = nem.setup(t)
        grudge = {"n1": {"n2"}, "n2": {"n1"}}
        res = nem.invoke(t, {"type": "info", "f": "start-partition",
                             "value": grudge})
        assert res["f"] == "start-partition"
        res = nem.invoke(t, {"type": "info", "f": "stop-partition",
                             "value": None})
        assert res["f"] == "stop-partition"
        nem.teardown(t)


def test_yb_generators_expand_and_recover():
    from jepsen_tpu.suites import yb_nemesis

    n = yb_nemesis.expand_options({"kill": True, "partition": True,
                                   "clock-skew": True, "interval": 0.01})
    assert n["kill-master"] and n["kill-tserver"]
    assert n["partition-ring"]
    g = yb_nemesis.full_generator(n)
    assert g is not None
    final = yb_nemesis.final_generator(n)
    fs = [op["f"] for op in final]
    assert "start-tserver" in fs and "start-master" in fs
    assert "stop-partition" in fs and "reset-clock" in fs

    # partition generators produce grudges over the test's nodes
    t = dummy_test()
    op = yb_nemesis.partition_ring_gen(t, {})
    assert op["f"] == "start-partition"
    assert set(op["value"]) == set(NODES)


def test_yb_suite_test_uses_fault_menu():
    from jepsen_tpu.suites import yugabyte

    t = yugabyte.test({
        "nodes": NODES, "workload": "ycql.register",
        "faults": ["kill-master", "partition-one"], "time-limit": 5,
    })
    fs = t["nemesis"].fs()
    assert "kill-master" in fs and "start-partition" in fs
    assert t["generator"] is not None


# -- fauna topology ---------------------------------------------------------


def test_fauna_topology_state_machine():
    from jepsen_tpu.suites.fauna_topology import FaunaTopology

    t = dummy_test(replicas=2)
    with sessions(t):
        st = FaunaTopology(replicas=2).setup(t)
        by_rep = st.nodes_by_replica()
        assert set(by_rep) == {"replica-0", "replica-1"}
        assert sorted(sum(by_rep.values(), [])) == NODES

        # with every node active, only removes are possible
        op = st.op(t)
        assert op["f"] == "remove-node"

        # removing nodes never empties a replica
        while True:
            removes = st._remove_ops()
            if not removes:
                break
            res = st.invoke(t, removes[0])
            assert res["type"] == "info"
            for nodes in st.nodes_by_replica().values():
                assert len(nodes) >= 1
        # converged: every replica is at its 1-node floor
        assert all(
            len(ns) == 1 for ns in st.nodes_by_replica().values()
        )
        # removed nodes can now rejoin
        adds = st._add_ops(t)
        assert adds
        res = st.invoke(t, adds[0])
        assert res["type"] == "info"
        assert adds[0]["value"]["node"] in {
            n["node"] for n in st.topo["nodes"]
        }


def test_fauna_topology_package_multi_node_dummy_run():
    """Drive the membership nemesis end-to-end against dummy remotes:
    ops flow through MembershipNemesis.invoke and the topology keeps
    its invariants."""
    from jepsen_tpu.suites import fauna_topology

    t = dummy_test(replicas=2)
    pkg = fauna_topology.package({"interval": 0.01, "replicas": 2})
    with sessions(t):
        nem = pkg["nemesis"].setup(t)
        try:
            state = nem.state
            for _ in range(8):
                op = state.op(t)
                if op == "pending":
                    break
                out = nem.invoke(t, dict(op))
                assert out["type"] in ("info", "fail"), out
                for nodes in state.nodes_by_replica().values():
                    assert len(nodes) >= 1
        finally:
            nem.teardown(t)


def test_fauna_suite_test_wires_topology_package():
    from jepsen_tpu.suites import faunadb

    t = faunadb.test({
        "nodes": NODES, "workload": "register",
        "faults": ["topology"], "time-limit": 5,
    })
    assert "add-node" in t["nemesis"].fs()
    assert t["generator"] is not None


# -- aerospike --------------------------------------------------------------


def test_aerospike_kill_nemesis_caps_dead_nodes():
    from jepsen_tpu.suites import aerospike

    t = dummy_test()
    with sessions(t):
        nem = aerospike.AsKillNemesis(max_dead=2).setup(t)
        res = nem.invoke(t, {"type": "info", "f": "kill",
                             "value": ["n1", "n2", "n3", "n4"]})
        vals = res["value"]
        assert sum(1 for v in vals.values() if v == "killed") == 2
        assert sum(1 for v in vals.values() if v == "still-alive") == 2
        assert len(nem.dead) == 2

        # restart frees the cap
        res = nem.invoke(t, {"type": "info", "f": "restart",
                             "value": sorted(nem.dead)})
        assert all(v == "started" for v in res["value"].values())
        assert not nem.dead

        # revive/recluster run on every node without error
        res = nem.invoke(t, {"type": "info", "f": "revive", "value": None})
        assert sorted(res["value"]) == NODES
        res = nem.invoke(t, {"type": "info", "f": "recluster",
                             "value": None})
        assert sorted(res["value"]) == NODES


def test_aerospike_full_nemesis_and_package():
    from jepsen_tpu.suites import aerospike

    t = dummy_test()
    pkg = aerospike.nemesis_package({"max-dead-nodes": 2, "interval": 0.01})
    with sessions(t):
        nem = pkg["nemesis"].setup(t)
        fs = nem.fs()
        for f in ("kill", "restart", "revive", "recluster",
                  "partition-start", "partition-stop", "clock-reset"):
            assert f in fs, f
        res = nem.invoke(t, {"type": "info", "f": "partition-start",
                             "value": None})
        assert res["f"] == "partition-start"
        nem.invoke(t, {"type": "info", "f": "partition-stop",
                       "value": None})
        nem.teardown(t)
    assert pkg["generator"] is not None
    finals = [op["f"] for op in pkg["final_generator"]]
    assert finals[-2:] == ["revive", "recluster"]


def test_aerospike_suite_test_uses_fault_menu():
    from jepsen_tpu.suites import aerospike

    t = aerospike.test({
        "nodes": NODES, "workload": "cas-register",
        "faults": ["kill", "partition"], "time-limit": 5,
    })
    fs = t["nemesis"].fs()
    assert "revive" in fs and "partition-start" in fs


# -- integration + edge cases -----------------------------------------------


def test_yb_long_recovery_alternates_windows():
    """long-recovery mode cycles fault windows with recovery + calm —
    the generator must keep producing ops after the first 120 s fault
    window ends (reference: nemesis.clj:211-223 full-generator).
    Virtual time advances 10 s per drawn op so the run actually crosses
    window boundaries."""
    from jepsen_tpu.suites import yb_nemesis

    n = yb_nemesis.expand_options(
        {"kill": True, "interval": 0.001, "long-recovery": True}
    )
    g = yb_nemesis.full_generator(n)
    t = dummy_test()
    ctx = gen.context({"concurrency": 1, "nodes": NODES})
    fs_with_time = []
    guard = 0
    while len(fs_with_time) < 60 and guard < 10_000:
        guard += 1
        res = gen.op(g, t, ctx)
        if res is None:
            break
        o, g = res
        if o == gen.PENDING:
            # jump virtual time past the pending wait (sleep phases)
            ctx = {**ctx, "time": ctx["time"] + int(10e9)}
            continue
        if isinstance(o, dict) and o.get("f"):
            fs_with_time.append((ctx["time"], o["f"]))
        ctx = {**ctx, "time": ctx["time"] + int(10e9)}
    fs = [f for _, f in fs_with_time]
    assert fs.count("start-tserver") >= 1, fs
    assert any(f in ("kill-tserver", "kill-master") for f in fs), fs
    # ops continue PAST the first 120 s fault window: the cycle/phases
    # machinery restarted a fresh window rather than ending the gen
    window_ns = 120 * 1_000_000_000
    assert any(ts > 2 * window_ns for ts, _ in fs_with_time), (
        fs_with_time[-3:]
    )


def test_partition_targets_flow_to_leftover_package():
    """partition-targets must reach the generic partition package when
    partition runs alongside a suite menu: its start-partition ops carry
    the requested target spec, not the defaults."""
    from jepsen_tpu.suites import common, fauna_topology
    from jepsen_tpu.suites.faunadb import FaunaDB

    opts = {
        "nodes": NODES,
        "faults": ["topology", "partition"],
        "partition-targets": ["one"],
        "interval": 0.001,
    }
    db = FaunaDB(opts)
    pkg = common.suite_nemesis_package(
        opts, db, fauna_topology.package(opts), {"topology"}
    )
    assert "start-partition" in pkg["nemesis"].fs()
    # pull ops until a start-partition appears; its value must be the
    # requested "one" spec (the package default would draw from the
    # full spec list)
    t = dummy_test(db=db)
    with sessions(t):
        pkg["nemesis"].setup(t)
    ctx = gen.context({"concurrency": 1, "nodes": NODES})
    g = pkg["generator"]
    values = []
    guard = 0
    while len(values) < 8 and guard < 10_000:
        guard += 1
        res = gen.op(g, t, ctx)
        if res is None:
            break
        o, g = res
        if o == gen.PENDING:
            ctx = {**ctx, "time": ctx["time"] + int(1e9)}
            continue
        if isinstance(o, dict) and o.get("f") == "start-partition":
            values.append(o["value"])
        ctx = {**ctx, "time": ctx["time"] + int(1e9)}
    assert values, "no start-partition op ever drawn"
    assert set(values) == {"one"}, values


def test_aerospike_full_run_under_fault_menu():
    """An in-process aerospike run with the suite fault menu active:
    kills/restarts/revives flow through the whole loop against the fake
    server and the verdict holds."""
    from fake_servers import FakeAerospike

    from jepsen_tpu import core
    from jepsen_tpu import db as db_mod
    from jepsen_tpu.suites import aerospike

    s = FakeAerospike().start()
    try:
        t = aerospike.test({
            "nodes": ["n1", "n2", "n3"],
            "host": "127.0.0.1",
            "port": s.port,
            "time-limit": 3,
            "rate": 30,
            "interval": 0.5,
            "workload": "cas-register",
            "faults": ["kill"],
        })
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        hist = result["history"]
        nem_fs = {op["f"] for op in hist if op["process"] == "nemesis"}
        assert nem_fs & {"kill", "restart", "revive", "recluster"}, nem_fs
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()
