"""Small parity items (reference gap-closing): tcpdump DB, control
command tracing, SmartOS, the agent-ssh auth-ladder transport, and
chunked lazy history storage."""

import logging
import os
import struct

import pytest

from jepsen_tpu import control, db as db_mod
from jepsen_tpu.control.core import DummyRemote
from jepsen_tpu.history import History, invoke_op, ok_op


# -- control tracing (reference: control.clj:43, 115-119) -------------------


def test_trace_logs_commands(caplog):
    test = {"nodes": ["n1"], "ssh": {"dummy?": True}}
    with control.dummy_session(test):
        def body():
            with caplog.at_level(logging.INFO, logger="jepsen_tpu.control"):
                with control.trace():
                    control.execute("echo", "hello")
                caplog_traced = [
                    r for r in caplog.records if "cmd:" in r.getMessage()
                ]
                assert caplog_traced, "trace() must log the command"
                assert "echo hello" in caplog_traced[0].getMessage()
                caplog.clear()
                control.execute("echo", "quiet")
                assert not [
                    r for r in caplog.records if "cmd:" in r.getMessage()
                ], "no tracing outside the context"
        control.with_node("n1", body)


# -- tcpdump DB (reference: db.clj:49-115) ----------------------------------


def test_tcpdump_filter_and_logfiles():
    t = db_mod.tcpdump({"ports": [2379, 2380], "filter": "tcp"})
    fs = t._filter_str()
    assert fs == "(port 2379 or port 2380) and tcp"
    assert list(t.log_files({}, "n1")) == [
        "/tmp/jepsen/tcpdump/log",
        "/tmp/jepsen/tcpdump/tcpdump",
    ]
    assert db_mod.tcpdump({"ports": [9042]})._filter_str() == "port 9042"
    only = db_mod.tcpdump({"clients-only?": True})._filter_str()
    assert only.startswith("host ")


def test_tcpdump_setup_teardown_on_dummy():
    # commands flow through the control DSL without error on the dummy
    test = {"nodes": ["n1"], "ssh": {"dummy?": True}}
    t = db_mod.tcpdump({"ports": [1234]})
    with control.dummy_session(test):
        control.with_node("n1", lambda: t.setup(test, "n1"))
        control.with_node("n1", lambda: t.teardown(test, "n1"))


# -- SmartOS (reference: os/smartos.clj) ------------------------------------


class _ScriptedRemote(DummyRemote):
    """Dummy remote that answers specific commands from a script."""

    def __init__(self, responses):
        super().__init__()
        self.responses = responses
        self.commands = []

    def connect(self, node, test=None):
        r = _ScriptedRemote(self.responses)
        r.commands = self.commands
        r.node = node
        return r

    def execute(self, command):
        from jepsen_tpu.control.core import Result

        self.commands.append(command.cmd)
        for prefix, out in self.responses.items():
            if command.cmd.startswith(prefix):
                return Result(cmd=command.cmd, exit=0, out=out, err="",
                              node=self.node)
        return Result(cmd=command.cmd, exit=0, out="", err="", node=self.node)


def test_smartos_package_parsing():
    from jepsen_tpu.os_setup import SmartOS

    remote = _ScriptedRemote({
        "pkgin -p list": "curl-8.1.2;x\nwget-1.21nb1;y\nvim-9.0.1;z",
    })
    test = {"nodes": ["n1"]}
    with control.with_session(test, remote):
        def body():
            os_ = SmartOS()
            got = os_.installed(["curl", "wget", "rsyslog"])
            assert got == {"curl", "wget"}
            assert os_.installed_version("curl") == "8.1.2"
            assert os_.installed_version("wget") == "1.21nb1"
            assert os_.installed_version("nope") is None
            os_.install(["curl", "rsyslog"])  # only rsyslog is missing
            installs = [c for c in remote.commands if "pkgin -y install" in c]
            assert installs and "rsyslog" in installs[-1]
            assert "curl" not in installs[-1]
        control.with_node("n1", body)


def test_smartos_setup_runs_on_dummy():
    from jepsen_tpu.os_setup import smartos

    remote = _ScriptedRemote({
        "hostname": "smarty",
        "cat /etc/hosts": "127.0.0.1\tlocalhost",
        "date +%s": "1000000",
        "stat -c %Y": "999999",
        "pkgin -p list": "",
    })
    test = {"nodes": ["n1"]}
    with control.with_session(test, remote):
        control.with_node("n1", lambda: smartos.setup(test, "n1"))
    joined = "\n".join(remote.commands)
    assert "svcadm enable -r ipfilter" in joined
    assert "pkgin -y install" in joined


# -- agent-ssh transport (reference: control/sshj.clj:43-70) ----------------


def test_agent_ssh_auth_ladder_order(tmp_path, monkeypatch):
    from jepsen_tpu.control.agent_ssh import AgentSSHRemote

    monkeypatch.setenv("SSH_AUTH_SOCK", "/tmp/fake-agent.sock")
    r = AgentSSHRemote(
        username="u", password="pw", private_key_path="/k/id", port=2222
    )
    r.node = "n1"
    r._tmpdir = str(tmp_path)
    rungs = r.auth_rungs()
    # key first, then agent, then default identities, then password
    assert len(rungs) == 4
    assert "/k/id" in rungs[0][0] and "IdentitiesOnly=yes" in rungs[0][0]
    assert any("IdentityAgent=" in a for a in rungs[1][0])
    assert rungs[2][0] == ["-o", "BatchMode=yes"]
    args, env = rungs[3]
    assert "SSH_ASKPASS" in env and env["SSH_ASKPASS_REQUIRE"] == "force"
    script = open(env["SSH_ASKPASS"]).read()
    assert "pw" in script
    assert os.stat(env["SSH_ASKPASS"]).st_mode & 0o077 == 0  # private

    # without agent/key/password: only the default-identities rung
    monkeypatch.delenv("SSH_AUTH_SOCK", raising=False)
    r2 = AgentSSHRemote(username="u")
    r2._tmpdir = str(tmp_path)
    assert len(r2.auth_rungs()) == 1


def test_agent_ssh_remembers_first_working_rung(monkeypatch):
    from jepsen_tpu.control.agent_ssh import AgentSSHRemote

    r = AgentSSHRemote(username="u", private_key_path="/k/id")
    r.node = "n1"
    r._tmpdir = "/tmp"
    calls = []

    class FakeProc:
        def __init__(self, rc):
            self.returncode = rc
            self.stdout = b""
            self.stderr = b"denied"

    def fake_run(args, env, cmd, stdin):
        calls.append((tuple(args), cmd))
        # first rung (pinned key) fails; second (default ids) works
        return FakeProc(255 if "IdentitiesOnly=yes" in args else 0)

    monkeypatch.setattr(r, "_run_ssh", fake_run)
    args, env = r._authed()
    assert "IdentitiesOnly=yes" not in args
    n = len(calls)
    # subsequent auth lookups don't re-probe
    assert r._authed() == (args, env)
    assert len(calls) == n


def test_cli_ssh_transport_flag():
    import argparse

    from jepsen_tpu import cli
    from jepsen_tpu.control.agent_ssh import AgentSSHRemote
    from jepsen_tpu.control.core import DummyRemote as DR
    from jepsen_tpu.control.ssh import SSHRemote

    def build(argv):
        p = argparse.ArgumentParser()
        cli.add_test_opts(p)
        return cli.test_opts_to_map(p.parse_args(argv))

    t = build(["--nodes", "n1", "--ssh-transport", "agent-ssh",
               "--password", "pw"])
    assert isinstance(t["remote"], AgentSSHRemote)
    assert t["remote"].password == "pw"
    t2 = build(["--nodes", "n1", "--ssh-transport", "ssh"])
    assert isinstance(t2["remote"], SSHRemote)
    t3 = build(["--nodes", "n1", "--dummy"])
    assert isinstance(t3["remote"], DR)


# -- chunked lazy history (reference: store/format.clj chunked loading) -----


def _mk_history(n):
    ops = []
    for i in range(n):
        ops.append(invoke_op(i % 5, "write", i, time=2 * i))
        ops.append(ok_op(i % 5, "write", i, time=2 * i + 1))
    return History(ops).index_ops()


def test_chunked_history_roundtrip(tmp_path):
    from jepsen_tpu.store import format as fmt

    h = _mk_history(300)  # 600 ops > chunk_size=128
    p = str(tmp_path / "t.jtpu")
    with fmt.Writer(p) as w:
        hid = w.write_history(h, chunk_size=128)
        w.set_root(w.write_json({"history": fmt.block_ref(hid)}))
        w.save_index()
    r = fmt.Reader(p)
    # the root block id resolved the chunked history transparently
    assert r.read_id(hid)[0] == fmt.CHUNKED_HISTORY
    got = r.read_history(hid)
    assert len(got) == len(h)
    assert [op.value for op in got] == [op.value for op in h]
    assert got[0].type == "invoke" and got[1].type == "ok"
    # lazy iteration yields the same ops without a full materialize
    it = r.iter_history(hid)
    first = next(it)
    assert first.value == 0
    assert r.history_len(hid) == len(h)
    # packed device arrays survive chunking
    packed = r.read_packed_history(hid)
    assert packed["arrays"]["process"].shape[0] == len(h)


def test_chunked_history_jsonl_blank_lines_do_not_inflate_counts(tmp_path):
    """Caller-supplied jsonl with stray blank lines must not skew the
    chunk table's op counts (history_len treats them as authoritative);
    a genuine line/op mismatch must be refused, not silently written."""
    import json as _json

    import pytest

    from jepsen_tpu.store import format as fmt

    h = _mk_history(150)  # 300 ops
    lines = [_json.dumps(op.to_dict(), default=repr) for op in h]
    # interior blank line + trailing newline
    jsonl = ("\n".join(lines[:100]) + "\n\n" + "\n".join(lines[100:]) + "\n").encode()
    p = str(tmp_path / "b.jtpu")
    with fmt.Writer(p) as w:
        hid = w.write_history(h, jsonl=jsonl, chunk_size=128)
        w.set_root(hid)
        w.save_index()
    r = fmt.Reader(p)
    assert r.history_len(hid) == len(h)
    assert len(r.read_history(hid)) == len(h)

    # the non-chunked branch normalizes too: a trailing newline must not
    # skew the newline-count history_len
    small = _mk_history(5)  # 10 ops, stays single-block
    small_lines = [_json.dumps(op.to_dict(), default=repr) for op in small]
    with fmt.Writer(str(tmp_path / "s.jtpu")) as w:
        hid2 = w.write_history(
            small, jsonl=("\n".join(small_lines) + "\n").encode()
        )
        w.set_root(hid2)
        w.save_index()
    r2 = fmt.Reader(str(tmp_path / "s.jtpu"))
    assert r2.history_len(hid2) == len(small)

    # a real mismatch (missing line) is an error in either branch
    bad = "\n".join(lines[:-1]).encode()
    for cs in (128, 10_000):
        with fmt.Writer(str(tmp_path / f"c{cs}.jtpu")) as w:
            with pytest.raises(ValueError, match="refusing"):
                w.write_history(h, jsonl=bad, chunk_size=cs)


def test_small_history_stays_single_block(tmp_path):
    from jepsen_tpu.store import format as fmt

    h = _mk_history(10)
    p = str(tmp_path / "s.jtpu")
    with fmt.Writer(p) as w:
        hid = w.write_history(h)
        w.set_root(hid)
        w.save_index()
    r = fmt.Reader(p)
    assert r.read_id(hid)[0] == fmt.HISTORY
    assert len(r.read_history(hid)) == 20
    assert r.history_len(hid) == 20


def test_store_save_roundtrips_large_history(tmp_path):
    """The full store save path writes chunked histories that load()
    transparently reassembles."""
    from jepsen_tpu import store as store_mod
    from jepsen_tpu.store import format as fmt

    h = _mk_history(fmt.HISTORY_CHUNK_SIZE)  # 2× chunk size in ops
    test = {
        "name": "chunky",
        "start-time": "t0",
        "store-base": str(tmp_path),
        "nodes": [],
        "history": h,
    }
    with store_mod.with_writer(test) as test_w:
        test_w = store_mod.save_1(test_w)
    loaded = store_mod.load(test)
    assert len(loaded["history"]) == len(h)
    assert loaded["history"][0].value == h[0].value


def test_trace_restore_preserves_module_default(caplog):
    # exiting trace() must not shadow control.TRACE with a stale None
    test = {"nodes": ["n1"], "ssh": {"dummy?": True}}
    with control.dummy_session(test):
        def body():
            with control.trace(False):
                pass
            control.TRACE = True
            try:
                with caplog.at_level(
                    logging.INFO, logger="jepsen_tpu.control"
                ):
                    control.execute("echo", "default-on")
                assert any(
                    "cmd:" in r.getMessage() for r in caplog.records
                )
            finally:
                control.TRACE = False
        control.with_node("n1", body)


def test_trace_conveys_to_on_nodes_workers(caplog):
    test = {"nodes": ["n1", "n2"], "ssh": {"dummy?": True}}
    with control.dummy_session(test):
        with caplog.at_level(logging.INFO, logger="jepsen_tpu.control"):
            with control.trace():
                control.on_nodes(test, lambda t, n: control.execute("true"))
    traced = [r for r in caplog.records if "cmd:" in r.getMessage()]
    assert len(traced) == 2  # one per worker thread
