"""End-to-end run of the localkv suite: the native repregd binary is
compiled ON THE NODE through the control layer, three replicas run as
real daemons, the standard partition + kill nemeses hit them
mid-workload, logs are snarfed, and the history checks linearizable —
the full reference test shape (install → run → fault → check; reference:
core_test.clj:122-177, doc/tutorial/05-nemesis.md) with zero external
dependencies."""

import os
import shutil
import subprocess

import pytest

from jepsen_tpu import core
from jepsen_tpu import generator as gen
from jepsen_tpu import suites

needs_cluster = pytest.mark.skipif(
    shutil.which("start-stop-daemon") is None or shutil.which("g++") is None,
    reason="needs start-stop-daemon and g++",
)


@needs_cluster
def test_localkv_full_run_partition_and_kill(tmp_path):
    localkv = suites.suite("localkv")
    t = localkv.test(
        {
            "nodes": ["n1", "n2", "n3"],
            "dir": str(tmp_path / "localkv"),
            "store-base": str(tmp_path / "store"),
            "store?": True,
            "faults": ["partition", "kill"],
            "interval": 2,
            "time-limit": 8,
            "concurrency": 6,
            "rate": 30,
        }
    )
    try:
        result = core.run(t)
    finally:
        subprocess.run(
            ["pkill", "-9", "-f", str(tmp_path / "localkv")],
            capture_output=True,
        )
    r = result["results"]
    hist = result["history"]
    oks = [o for o in hist if o["type"] == "ok"
           and isinstance(o["process"], int)]
    nem_fs = {o["f"] for o in hist
              if o["process"] == "nemesis" and o["type"] == "info"}
    assert len(oks) > 20, "workload barely ran"
    assert nem_fs & {"start-partition", "start-kill", "kill"}, nem_fs
    assert r["valid?"] is True, {k: v for k, v in r.items()
                                 if k != "history"}
    # install really happened on-node: the snarfed daemon log (below)
    # records the compiled binary's startup (teardown rm -rf's the node
    # dirs, so the binary itself is gone by now — the log survives in
    # the store)
    base = os.path.join(str(tmp_path / "store"), "localkv",
                        result["start-time"])
    log_copy = os.path.join(base, "n1", "server.log")
    assert os.path.exists(log_copy), os.listdir(base)
    assert "repregd" in open(log_copy).read()
