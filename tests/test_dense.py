"""Dense subset-automaton kernel tests: golden histories, differential
fuzz vs the CPU oracle AND vs the generic frontier kernel, and envelope/
dispatch checks.

The dense kernel (jepsen_tpu.ops.dense) is the TPU fast path for the
register-family models the reference's linearizable checker runs
(jepsen/src/jepsen/checker.clj:19-26); it must agree exactly with the
oracle on every verdict — there is no "unknown" escape hatch to hide
behind, since the dense representation cannot overflow.
"""

import random

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu.checker import linear
from jepsen_tpu.history import History, invoke_op, ok_op, fail_op, info_op
from jepsen_tpu.ops import dense, encode, wgl
from jepsen_tpu.synth import generate_history as _gen

from test_wgl import GOLDEN, h


def _dense_verdicts(model, hists, pure_fs):
    """Run histories through the dense kernel directly (no dispatch)."""
    batch = encode.batch_encode(hists, model)
    assert not batch.fallback
    E = batch.ev_slot.shape[1]
    C = batch.cand_slot.shape[2]
    V = encode.round_up(
        1 + int(max(batch.init_state.max(), batch.cand_a.max(), batch.cand_b.max())),
        4,
    )
    assert dense.applicable(_spec_name(model), C, V)
    fn = dense.make_dense_fn(_spec_name(model), E, C, V)
    ok, failed_at, overflow = fn(
        batch.init_state,
        batch.ev_slot,
        batch.cand_slot,
        batch.cand_f,
        batch.cand_a,
        batch.cand_b,
    )
    assert not bool(np.asarray(overflow).any())  # dense can never overflow
    out = [None] * len(hists)
    for row, hi in enumerate(batch.row_history):
        out[hi] = bool(np.asarray(ok)[row])
    return out


def _spec_name(model):
    from jepsen_tpu.ops.step_kernels import spec_for

    return spec_for(model).name


@pytest.mark.parametrize("case", range(len(GOLDEN)))
def test_golden_dense(case):
    model_fn, hist_fn, expected = GOLDEN[case]
    model = model_fn()
    spec = __import__(
        "jepsen_tpu.ops.step_kernels", fromlist=["spec_for"]
    ).spec_for(model)
    got = _dense_verdicts(model, [hist_fn()], spec.pure_fs)
    assert got == [expected]


def test_applicable_envelope():
    assert dense.applicable("cas-register", 8, 8)
    assert dense.applicable("mutex", 4, 4)
    assert not dense.applicable("cas-register", 16, 8)   # 2^16 subsets
    assert not dense.applicable("cas-register", 8, 64)   # value domain
    assert not dense.applicable("multi-register", 8, 8)  # packed state


def test_dispatch_prefers_dense():
    fn = wgl.make_best_check_fn("cas-register", 64, 8, 64, 9, n_values=6)
    assert fn is dense.make_dense_fn("cas-register", 64, 8, 8)
    # out-of-envelope value domains ride the generic frontier kernel
    fn2 = wgl.make_best_check_fn("cas-register", 64, 8, 64, 9, n_values=500)
    assert fn2 is wgl.make_check_fn("cas-register", 64, 8, 64, 9)


def test_differential_oracle_and_frontier():
    """Oracle, frontier kernel, and dense kernel must agree verdict-for-
    verdict on a mixed corpus (valid + corrupted + crashy)."""
    rng = random.Random(777)
    hists = (
        [_gen(rng, n_procs=4, n_ops=25) for _ in range(12)]
        + [_gen(rng, n_procs=4, n_ops=25, corrupt=True) for _ in range(12)]
        + [_gen(rng, n_procs=5, n_ops=18, crash_p=0.35) for _ in range(8)]
    )
    model = m.cas_register(0)
    oracle = [
        linear.analysis(model, h0, pure_fs=("read",))["valid?"] for h0 in hists
    ]
    d = _dense_verdicts(model, hists, ("read",))
    assert d == oracle
    # check_batch dispatch lands on the dense kernel and matches too
    outs = wgl.check_batch(model, hists)
    assert [o["valid?"] for o in outs] == oracle
    assert False in oracle and True in oracle  # corpus exercises both


def test_differential_register():
    rng = random.Random(4242)
    hists = [
        _gen(rng, n_procs=4, n_ops=20, corrupt=bool(i % 3 == 0), op_weights=(2, 2, 0))
        for i in range(20)
    ]
    model = m.register(0)
    oracle = [
        linear.analysis(model, h0, pure_fs=("read",))["valid?"] for h0 in hists
    ]
    assert _dense_verdicts(model, hists, ("read",)) == oracle


def _mutex_history(rng, n_procs=3, n_ops=20, corrupt=False):
    """Random acquire/release interleavings; valid by construction when
    corrupt=False (completions happen only when legal)."""
    held = None
    hist = []
    pending = {}
    idle = list(range(n_procs))
    wants = {p: "acquire" for p in range(n_procs)}
    done = 0
    while done < n_ops or pending:
        if idle and done < n_ops and (not pending or rng.random() < 0.5):
            p = rng.choice(idle)
            idle.remove(p)
            f = wants[p]
            hist.append(invoke_op(p, f))
            pending[p] = f
            done += 1
        elif pending:
            # complete a legal one if possible, else any (as a crash)
            legal = [
                p
                for p, f in pending.items()
                if (f == "acquire" and held is None)
                or (f == "release" and held == p)
            ]
            if legal:
                p = rng.choice(legal)
                f = pending.pop(p)
                held = p if f == "acquire" else None
                hist.append(ok_op(p, f))
                wants[p] = "release" if f == "acquire" else "acquire"
                idle.append(p)
            else:
                p = rng.choice(list(pending.keys()))
                f = pending.pop(p)
                hist.append(info_op(p, f))
        else:
            break
    out = History(hist)
    if corrupt:
        # double-grant: a second acquire completes while the lock is held
        out = History(
            [
                invoke_op(0, "acquire"),
                ok_op(0, "acquire"),
                invoke_op(1, "acquire"),
                ok_op(1, "acquire"),
            ]
        )
    for i, op in enumerate(out):
        op.index = i
        op.time = i
    return out


def test_differential_mutex():
    rng = random.Random(99)
    hists = [_mutex_history(rng, corrupt=bool(i % 4 == 0)) for i in range(16)]
    model = m.mutex()
    oracle = [linear.analysis(model, h0)["valid?"] for h0 in hists]
    assert _dense_verdicts(model, hists, ()) == oracle
    assert False in oracle and True in oracle


def test_dense_wide_concurrency():
    """C > 5 exercises the cross-word union/drop gathers (crashed ops
    retire their process and accumulate open slots via replace_crashed,
    mirroring interpreter process retirement)."""
    rng = random.Random(31337)
    hists = [
        _gen(
            rng,
            n_procs=9,
            n_ops=40,
            crash_p=0.1,
            corrupt=bool(i % 2),
            replace_crashed=True,
        )
        for i in range(10)
    ]
    model = m.cas_register(0)
    batch = encode.batch_encode(hists, model)
    assert batch.cand_slot.shape[2] > 5  # must actually cross words
    oracle = [
        linear.analysis(model, h0, pure_fs=("read",))["valid?"] for h0 in hists
    ]
    assert _dense_verdicts(model, hists, ("read",)) == oracle


def test_failed_event_index_matches_frontier_kernel():
    model = m.register(0)
    bad = h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(1, "read"),
        ok_op(1, "read", 7),
    )
    out = wgl.check_batch(model, [bad])[0]
    assert out["valid?"] is False
    assert out["engine"] == "tpu"
    assert out["failed-event"] == 1  # second ok event kills the frontier


# ---------------------------------------------------------------------------
# multi-register composite-state dense kernel
# ---------------------------------------------------------------------------


def test_mr_dense_applicability():
    from jepsen_tpu.ops import dense

    assert dense.applicable("multi-register", 8, (5, 2))       # 25 states
    assert dense.applicable("multi-register", 8, (3, 4))       # 81 = V^4
    assert not dense.applicable("multi-register", 8, (6, 3))   # 216 > cap
    assert not dense.applicable("multi-register", 16, (2, 2))  # C past cap
    assert not dense.applicable("multi-register", 8, 25)       # needs pair


def test_mr_dense_differential_two_keys():
    """K=2 composite automaton vs the CPU oracle over the fuzz corpus:
    the batch must ride kernel=dense and agree everywhere."""
    import random

    from jepsen_tpu import models as m
    from jepsen_tpu.checker import linear
    from jepsen_tpu.ops import wgl
    from jepsen_tpu.synth import generate_mr_history

    rng = random.Random(777)
    model = m.multi_register({k: 0 for k in range(2)})
    hists = [
        generate_mr_history(rng, n_keys=2, n_values=3, corrupt=(i % 3 == 0))
        for i in range(30)
    ]
    oracle = [linear.analysis(model, h)["valid?"] for h in hists]
    outs = wgl.check_batch(model, hists)
    stats = wgl.batch_stats(outs)
    assert stats["kernels"] == {"dense": 30}, stats
    assert [o["valid?"] for o in outs] == oracle
    assert True in oracle and False in oracle


def test_mr_dense_v4_four_keys():
    """The V^4 shape: four registers with a tiny per-register domain
    run dense (81 composite states at Vr=3)."""
    import random

    from jepsen_tpu import models as m
    from jepsen_tpu.checker import linear
    from jepsen_tpu.ops import wgl
    from jepsen_tpu.synth import generate_mr_history

    rng = random.Random(4100)
    model = m.multi_register({k: 0 for k in range(4)})
    # valid-only, single-value pool: corrupt/extra values widen the
    # per-register domain past the composite cap (invalid coverage
    # lives in the two-key test); Vr = 3 → 81 composite states
    hists = [
        generate_mr_history(rng, n_keys=4, n_values=1, n_ops=30)
        for i in range(20)
    ]
    oracle = [linear.analysis(model, h)["valid?"] for h in hists]
    outs = wgl.check_batch(model, hists)
    stats = wgl.batch_stats(outs)
    assert stats["kernels"] == {"dense": len(hists)}, stats
    assert [o["valid?"] for o in outs] == oracle


def test_mr_dense_golden_cross_register():
    """Writes must not bleed across registers in the composite map."""
    from jepsen_tpu import models as m
    from jepsen_tpu.history import History, invoke_op, ok_op
    from jepsen_tpu.ops import wgl

    def h(*ops):
        hist = History(ops)
        for i, op in enumerate(hist):
            op.index = i
            op.time = i
        return hist

    model = m.multi_register({0: 0, 1: 0})
    good = h(
        invoke_op(0, "txn", [("w", 0, 5)]),
        ok_op(0, "txn", [("w", 0, 5)]),
        invoke_op(0, "txn", [("r", 1, None)]),
        ok_op(0, "txn", [("r", 1, 0)]),
        invoke_op(0, "txn", [("r", 0, None)]),
        ok_op(0, "txn", [("r", 0, 5)]),
    )
    bad = h(
        invoke_op(0, "txn", [("w", 0, 5)]),
        ok_op(0, "txn", [("w", 0, 5)]),
        invoke_op(0, "txn", [("r", 1, None)]),
        ok_op(0, "txn", [("r", 1, 5)]),  # wrong register
    )
    out_good = wgl.check_batch(model, [good])[0]
    out_bad = wgl.check_batch(model, [bad])[0]
    assert out_good["kernel"] == "dense", out_good
    assert out_good["valid?"] is True
    assert out_bad["valid?"] is False


@pytest.mark.parametrize("union", ["unroll", "matmul"])
def test_union_mode_matches_gather(monkeypatch, union):
    """The unrolled static-shuffle and one-hot-matmul subset maps
    (JEPSEN_TPU_DENSE_UNION=unroll/matmul) must produce identical
    verdicts and failure indices to the default take_along_axis path
    on a corrupted mixed corpus — the on-chip A/B in RESULTS.md's
    roofline plan is only meaningful if the lowerings are
    bit-equivalent."""
    import random

    from jepsen_tpu import models as m
    from jepsen_tpu import synth
    from jepsen_tpu.ops import dense, encode

    rng = random.Random(45109)
    hists = [
        synth.generate_history(
            rng, n_procs=8, n_ops=120, crash_p=0.01, corrupt=(i % 3 == 0)
        )
        for i in range(12)
    ]
    batch = encode.batch_encode(hists, m.cas_register(0), slot_cap=8)
    E = batch.ev_slot.shape[1]
    C = batch.cand_slot.shape[2]
    V = encode.round_up(
        int(max(batch.cand_a.max(), batch.cand_b.max(),
                batch.init_state.max())) + 1, 4)
    args = (batch.init_state, batch.ev_slot, batch.cand_slot,
            batch.cand_f, batch.cand_a, batch.cand_b)

    monkeypatch.setenv("JEPSEN_TPU_DENSE_UNION", "gather")
    ok_g, fail_g, _ = dense.make_dense_fn("cas-register", E, C, V)(*args)
    monkeypatch.setenv("JEPSEN_TPU_DENSE_UNION", union)
    ok_u, fail_u, _ = dense.make_dense_fn("cas-register", E, C, V)(*args)
    import numpy as np

    assert (np.asarray(ok_g) == np.asarray(ok_u)).all()
    assert (np.asarray(fail_g) == np.asarray(fail_u)).all()
    assert not np.asarray(ok_g).all()  # the corpus really has invalids


@pytest.mark.parametrize("union", ["unroll", "matmul"])
def test_queue_union_mode_matches_gather(monkeypatch, union):
    """The unroll and matmul lowerings must also be bit-equivalent on
    the queue kernel (its own closure/completion use the same subset
    maps)."""
    import random

    import numpy as np

    from jepsen_tpu import models as m
    from jepsen_tpu.ops import dense, encode

    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_models import _gen_queue_history  # noqa: E402

    rng = random.Random(45110)
    hists = [_gen_queue_history(rng, n_procs=6, n_ops=24) for _ in range(8)]
    batch = encode.batch_encode(hists, m.unordered_queue(), slot_cap=6)
    E = batch.ev_slot.shape[1]
    C = batch.cand_slot.shape[2]
    args = (batch.init_state, batch.ev_slot, batch.cand_slot,
            batch.cand_f, batch.cand_a, batch.cand_b)
    monkeypatch.setenv("JEPSEN_TPU_DENSE_UNION", "gather")
    ok_g, fail_g, _ = dense.make_dense_fn("unordered-queue", E, C, 0)(*args)
    monkeypatch.setenv("JEPSEN_TPU_DENSE_UNION", union)
    ok_u, fail_u, _ = dense.make_dense_fn("unordered-queue", E, C, 0)(*args)
    assert (np.asarray(ok_g) == np.asarray(ok_u)).all()
    assert (np.asarray(fail_g) == np.asarray(fail_u)).all()


def test_unknown_union_mode_rejected():
    from jepsen_tpu.ops import dense

    with pytest.raises(ValueError):
        dense.build_dense("cas-register", 8, 4, 8, union="zip")
