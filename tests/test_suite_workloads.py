"""Suite-specific workload tests: cockroach monotonic + sequential,
yugabyte multi-key ACID, dgraph upsert, faunadb g2 — each driven
end-to-end against its fake server, plus checker unit tests on crafted
histories (reference workloads: cockroach/monotonic.clj,
cockroach/sequential.clj, yugabyte/ysql/multi_key_acid.clj,
dgraph/upsert.clj, faunadb/g2.clj)."""

import pytest

from jepsen_tpu import core, independent
from jepsen_tpu import db as db_mod
from jepsen_tpu.history import History, invoke_op, ok_op, fail_op, info_op

from fake_servers import FakeDgraph, FakeFauna, FakePg


def h(*ops) -> History:
    hist = History(ops)
    for i, op in enumerate(hist):
        op.index = i
        op.time = i
    return hist


# -- cockroach monotonic ----------------------------------------------------


def test_monotonic_client_roundtrip():
    from jepsen_tpu.suites import monotonic

    s = FakePg().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "dialect": "cockroach",
                "user": "postgres"}
        c = monotonic.MonotonicClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        for v in range(6):
            r = c.invoke({}, {"f": "add", "value": v, "type": "invoke",
                              "process": v % 2})
            assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "read", "value": None, "type": "invoke"})
        assert r["type"] == "ok"
        rows = r["value"]
        assert [row[0] for row in rows] == list(range(6))
        # DB timestamps strictly increase with insertion order
        stss = [float(row[1]) for row in rows]
        assert stss == sorted(stss)
        c.close({})
    finally:
        s.stop()


def test_monotonic_checker_valid_and_invalid():
    from jepsen_tpu.suites.monotonic import MonotonicChecker

    ok_rows = [[0, "1", 0, 0], [1, "2", 0, 1], [2, "3", 1, 0]]
    hist = h(
        invoke_op(0, "add", 0), ok_op(0, "add", 0),
        invoke_op(0, "add", 1), ok_op(0, "add", 1),
        invoke_op(1, "add", 2), ok_op(1, "add", 2),
        invoke_op(0, "read"), ok_op(0, "read", ok_rows),
    )
    assert MonotonicChecker().check({}, hist)["valid?"] is True

    # lost: value 1 added but missing from the final read
    lost_hist = h(
        invoke_op(0, "add", 0), ok_op(0, "add", 0),
        invoke_op(0, "add", 1), ok_op(0, "add", 1),
        invoke_op(0, "read"),
        ok_op(0, "read", [[0, "1", 0, 0]]),
    )
    res = MonotonicChecker().check({}, lost_hist)
    assert res["valid?"] is False and res["lost"] == [1]

    # per-process value reorder: proc 0 saw 5 then 3
    bad_rows = [[5, "1", 0, 0], [3, "2", 0, 1]]
    reorder_hist = h(
        invoke_op(0, "add", 5), ok_op(0, "add", 5),
        invoke_op(0, "add", 3), ok_op(0, "add", 3),
        invoke_op(0, "read"), ok_op(0, "read", bad_rows),
    )
    res = MonotonicChecker().check({}, reorder_hist)
    assert res["valid?"] is False
    assert res["value-reorders-per-process"]

    # revived: a failed add shows up anyway
    revived_hist = h(
        invoke_op(0, "add", 0), ok_op(0, "add", 0),
        invoke_op(0, "add", 9), fail_op(0, "add", 9),
        invoke_op(0, "read"),
        ok_op(0, "read", [[0, "1", 0, 0], [9, "2", 0, 1]]),
    )
    res = MonotonicChecker().check({}, revived_hist)
    assert res["valid?"] is False and res["revived"] == [9]

    # recovered (indeterminate seen) is informational, not an error
    rec_hist = h(
        invoke_op(0, "add", 0), ok_op(0, "add", 0),
        invoke_op(0, "add", 4), info_op(0, "add", 4),
        invoke_op(0, "read"),
        ok_op(0, "read", [[0, "1", 0, 0], [4, "2", 0, 1]]),
    )
    res = MonotonicChecker().check({}, rec_hist)
    assert res["valid?"] is True and res["recovered"] == [4]

    # no final read → unknown
    res = MonotonicChecker().check({}, h(invoke_op(0, "add", 0),
                                         ok_op(0, "add", 0)))
    assert res["valid?"] == "unknown"


def test_monotonic_full_test_in_process():
    from jepsen_tpu.suites import cockroachdb

    s = FakePg().start()
    try:
        t = cockroachdb.test(
            {
                "nodes": ["n1", "n2", "n3"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "postgres",
                "time-limit": 2,
                "rate": 50,
                "workload": "monotonic",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- cockroach sequential ---------------------------------------------------


def test_sequential_trailing_nil():
    from jepsen_tpu.suites.sequential import trailing_nil

    assert not trailing_nil([None, None, "a", "b"])
    assert not trailing_nil(["a", "b"])
    assert not trailing_nil([None, None])
    assert trailing_nil(["a", None])
    assert trailing_nil([None, "a", None, "b"])


def test_sequential_client_and_checker():
    from jepsen_tpu.suites import sequential as seq

    s = FakePg().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "dialect": "cockroach",
                "user": "postgres", "key-count": 3}
        c = seq.SequentialClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        assert c.invoke({}, {"f": "write", "value": 7,
                             "type": "invoke"})["type"] == "ok"
        r = c.invoke({}, {"f": "read", "value": 7, "type": "invoke"})
        assert r["type"] == "ok"
        k, ks = r["value"]
        assert k == 7 and ks == ["7_2", "7_1", "7_0"]
        # unwritten key reads all-nil (legal)
        r2 = c.invoke({}, {"f": "read", "value": 99, "type": "invoke"})
        assert r2["value"][1] == [None, None, None]
        c.close({})

        chk = seq.SequentialChecker(key_count=3)
        good = h(
            invoke_op(0, "read", 7),
            ok_op(0, "read", [7, ["7_2", "7_1", "7_0"]]),
            invoke_op(0, "read", 9),
            ok_op(0, "read", [9, [None, "9_1", "9_0"]]),
        )
        res = chk.check({}, good)
        assert res["valid?"] is True and res["all-count"] == 1
        bad = h(
            invoke_op(0, "read", 7),
            ok_op(0, "read", [7, ["7_2", None, "7_0"]]),
        )
        res = chk.check({}, bad)
        assert res["valid?"] is False and res["bad-count"] == 1
    finally:
        s.stop()


def test_sequential_full_test_in_process():
    from jepsen_tpu.suites import cockroachdb

    s = FakePg().start()
    try:
        t = cockroachdb.test(
            {
                "nodes": ["n1", "n2", "n3"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "postgres",
                "time-limit": 2,
                "rate": 50,
                "workload": "sequential",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- yugabyte multi-key ACID ------------------------------------------------


def test_multi_key_acid_client_roundtrip():
    from jepsen_tpu.suites import yugabyte

    s = FakePg().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "dialect": "pg",
                "user": "postgres"}
        c = yugabyte.MultiKeyAcidClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        w = c.invoke({}, {
            "f": "write", "type": "invoke",
            "value": independent.kv(5, [["w", 0, 3], ["w", 2, 4]]),
        })
        assert w["type"] == "ok", w
        r = c.invoke({}, {
            "f": "read", "type": "invoke",
            "value": independent.kv(5, [["r", 0, None], ["r", 1, None],
                                        ["r", 2, None]]),
        })
        assert r["type"] == "ok"
        k, mops = r["value"]
        assert k == 5
        assert mops == [["r", 0, 3], ["r", 1, None], ["r", 2, 4]]
        # overwrite via upsert inside a txn
        w2 = c.invoke({}, {
            "f": "write", "type": "invoke",
            "value": independent.kv(5, [["w", 0, 9]]),
        })
        assert w2["type"] == "ok"
        r2 = c.invoke({}, {
            "f": "read", "type": "invoke",
            "value": independent.kv(5, [["r", 0, None]]),
        })
        assert r2["value"][1] == [["r", 0, 9]]
        # other independent keys are isolated
        r3 = c.invoke({}, {
            "f": "read", "type": "invoke",
            "value": independent.kv(6, [["r", 0, None]]),
        })
        assert r3["value"][1] == [["r", 0, None]]
        c.close({})
    finally:
        s.stop()


def test_multi_key_acid_checker():
    from jepsen_tpu import checker as checker_mod
    from jepsen_tpu import models

    chk = checker_mod.linearizable(models.multi_register({}), pure_fs=())
    good = h(
        invoke_op(0, "write", [["w", 0, 1], ["w", 1, 2]]),
        ok_op(0, "write", [["w", 0, 1], ["w", 1, 2]]),
        invoke_op(1, "read", [["r", 0, None], ["r", 1, None]]),
        ok_op(1, "read", [["r", 0, 1], ["r", 1, 2]]),
    )
    assert chk.check({}, good)["valid?"] is True
    bad = h(
        invoke_op(0, "write", [["w", 0, 1], ["w", 1, 2]]),
        ok_op(0, "write", [["w", 0, 1], ["w", 1, 2]]),
        invoke_op(1, "read", [["r", 0, 1], ["r", 1, 7]]),
        ok_op(1, "read", [["r", 0, 1], ["r", 1, 7]]),
    )
    assert chk.check({}, bad)["valid?"] is False


def test_multi_key_acid_workload_shape():
    from jepsen_tpu.suites import yugabyte

    w = yugabyte.workloads({"nodes": ["n1", "n2", "n3"]})
    assert "ysql.multi-key-acid" in w
    assert "generator" in w["ysql.multi-key-acid"]
    assert "checker" in w["ysql.multi-key-acid"]


def test_ysql_counter_client_roundtrip():
    """SQL counter: int-column arithmetic adds + reads (reference:
    yugabyte/ysql/counter.clj:12-28 — SQL has no counter type, so a
    single row's int is bumped)."""
    from jepsen_tpu.suites import sql, yugabyte

    assert "ysql.counter" in yugabyte.workloads({"nodes": ["n1"]})
    s = FakePg().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "dialect": "pg",
                "user": "postgres"}
        c = sql.client_for("counter", opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        assert c.invoke({}, {"f": "add", "type": "invoke", "value": 3})[
            "type"] == "ok"
        assert c.invoke({}, {"f": "add", "type": "invoke", "value": 4})[
            "type"] == "ok"
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": None})
        assert r["type"] == "ok" and r["value"] == 7
        # second client sees the same row (shared backing store),
        # and setup is idempotent (seed row insert tolerated)
        c2 = sql.client_for("counter", opts).open({"nodes": ["n1"]}, "n1")
        c2.setup({})
        r2 = c2.invoke({}, {"f": "read", "type": "invoke", "value": None})
        assert r2["value"] == 7
        c.close({})
        c2.close({})
    finally:
        s.stop()


# -- dgraph upsert ----------------------------------------------------------


def test_dgraph_register_client_roundtrip():
    """The fake alpha also unlocks the existing register client."""
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = dgraph.DgraphClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        assert c.invoke({}, {"f": "write", "value": [1, 5],
                             "type": "invoke"})["type"] == "ok"
        r = c.invoke({}, {"f": "read", "value": [1, None], "type": "invoke"})
        assert r["type"] == "ok" and tuple(r["value"]) == (1, "5")
        assert c.invoke({}, {"f": "cas", "value": [1, [5, 6]],
                             "type": "invoke"})["type"] == "ok"
        assert c.invoke({}, {"f": "cas", "value": [1, [5, 7]],
                             "type": "invoke"})["type"] == "fail"
        c.close({})
    finally:
        s.stop()


def test_dgraph_upsert_client_and_checker():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = dgraph.DgraphUpsertClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        r1 = c.invoke({}, {"f": "upsert", "type": "invoke",
                           "value": independent.kv("a@x", None)})
        assert r1["type"] == "ok", r1
        # second upsert of the same key must lose
        r2 = c.invoke({}, {"f": "upsert", "type": "invoke",
                           "value": independent.kv("a@x", None)})
        assert r2["type"] == "fail"
        rr = c.invoke({}, {"f": "read", "type": "invoke",
                           "value": independent.kv("a@x", None)})
        assert rr["type"] == "ok"
        k, uids = rr["value"]
        assert k == "a@x" and len(uids) == 1
        c.close({})

        chk = dgraph.UpsertChecker()
        good = h(
            invoke_op(0, "upsert"), ok_op(0, "upsert"),
            invoke_op(1, "upsert"), fail_op(1, "upsert"),
            invoke_op(0, "read"), ok_op(0, "read", ["0x1"]),
        )
        assert chk.check({}, good)["valid?"] is True
        bad = h(
            invoke_op(0, "upsert"), ok_op(0, "upsert"),
            invoke_op(1, "upsert"), ok_op(1, "upsert"),
            invoke_op(0, "read"), ok_op(0, "read", ["0x1", "0x2"]),
        )
        res = chk.check({}, bad)
        assert res["valid?"] is False and res["bad-reads"]
    finally:
        s.stop()


def test_dgraph_upsert_full_test_in_process():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        t = dgraph.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 30,
                "workload": "upsert",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- faunadb g2 -------------------------------------------------------------


def test_fauna_register_client_roundtrip():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = faunadb.FaunaClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        assert c.invoke({}, {"f": "write", "value": [0, 3],
                             "type": "invoke"})["type"] == "ok"
        r = c.invoke({}, {"f": "read", "value": [0, None], "type": "invoke"})
        assert r["type"] == "ok" and tuple(r["value"]) == (0, 3)
        assert c.invoke({}, {"f": "cas", "value": [0, [3, 4]],
                             "type": "invoke"})["type"] == "ok"
        assert c.invoke({}, {"f": "cas", "value": [0, [3, 9]],
                             "type": "invoke"})["type"] == "fail"
        c.close({})
    finally:
        s.stop()


def test_fauna_g2_client():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = faunadb.FaunaG2Client(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        # first insert of the pair commits...
        r1 = c.invoke({}, {"f": "insert", "type": "invoke",
                           "value": independent.kv(1, [10, None])})
        assert r1["type"] == "ok", r1
        # ...the partner (other class, same key) must be refused
        r2 = c.invoke({}, {"f": "insert", "type": "invoke",
                           "value": independent.kv(1, [None, 11])})
        assert r2["type"] == "fail"
        # a different key is free to insert
        r3 = c.invoke({}, {"f": "insert", "type": "invoke",
                           "value": independent.kv(2, [None, 12])})
        assert r3["type"] == "ok"
        c.close({})
    finally:
        s.stop()


def test_fauna_g2_full_test_in_process():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        t = faunadb.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 30,
                "workload": "g2",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- cockroach comments ------------------------------------------------------


def test_comments_client_and_checker():
    from jepsen_tpu.suites import comments

    s = FakePg().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "dialect": "cockroach",
                "user": "postgres"}
        c = comments.CommentsClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        for i in (1, 2, 3):
            r = c.invoke({}, {"f": "write", "type": "invoke",
                              "value": independent.kv(0, i)})
            assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "read", "type": "invoke",
                          "value": independent.kv(0, None)})
        assert r["type"] == "ok" and list(r["value"][1]) == [1, 2, 3]
        # other keys see nothing
        r2 = c.invoke({}, {"f": "read", "type": "invoke",
                           "value": independent.kv(9, None)})
        assert r2["value"][1] == []
        c.close({})
    finally:
        s.stop()

    chk = comments.CommentsChecker()
    good = h(
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(1, "write", 2), ok_op(1, "write", 2),
        invoke_op(2, "read"), ok_op(2, "read", [1, 2]),
    )
    assert chk.check({}, good)["valid?"] is True
    # write 2 invoked AFTER write 1 completed; a read seeing 2 but not 1
    # violates strict serializability
    bad = h(
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(1, "write", 2), ok_op(1, "write", 2),
        invoke_op(2, "read"), ok_op(2, "read", [2]),
    )
    res = chk.check({}, bad)
    assert res["valid?"] is False and res["errors"][0]["missing"] == [1]
    # concurrent writes have no mutual expectation: seeing one alone is OK
    conc = h(
        invoke_op(0, "write", 1),
        invoke_op(1, "write", 2),
        ok_op(0, "write", 1), ok_op(1, "write", 2),
        invoke_op(2, "read"), ok_op(2, "read", [2]),
    )
    assert chk.check({}, conc)["valid?"] is True


def test_comments_full_test_in_process():
    from jepsen_tpu.suites import cockroachdb

    s = FakePg().start()
    try:
        t = cockroachdb.test(
            {
                "nodes": ["n1", "n2", "n3"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "postgres",
                "time-limit": 2,
                "rate": 50,
                "workload": "comments",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- cockroach g2 (predicate anti-dependency) --------------------------------


def test_g2_sql_client():
    from jepsen_tpu.suites import g2_sql

    s = FakePg().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "dialect": "cockroach",
                "user": "postgres"}
        c = g2_sql.G2Client(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        r1 = c.invoke({}, {"f": "insert", "type": "invoke",
                           "value": independent.kv(1, [10, None])})
        assert r1["type"] == "ok", r1
        # the pair partner sees the predicate hit and must refuse
        r2 = c.invoke({}, {"f": "insert", "type": "invoke",
                           "value": independent.kv(1, [None, 11])})
        assert r2["type"] == "fail"
        # other keys unaffected
        r3 = c.invoke({}, {"f": "insert", "type": "invoke",
                           "value": independent.kv(2, [None, 12])})
        assert r3["type"] == "ok"
        c.close({})
    finally:
        s.stop()


def test_g2_full_test_in_process():
    from jepsen_tpu.suites import cockroachdb

    s = FakePg().start()
    try:
        t = cockroachdb.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "postgres",
                "time-limit": 2,
                "rate": 40,
                "workload": "g2",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- dgraph delete -----------------------------------------------------------


def test_dgraph_delete_client_and_checker():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = dgraph.DgraphDeleteClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        # create, read (one well-formed record), delete, read (empty)
        r1 = c.invoke({}, {"f": "upsert", "type": "invoke",
                           "value": independent.kv(5, None)})
        assert r1["type"] == "ok", r1
        r2 = c.invoke({}, {"f": "upsert", "type": "invoke",
                           "value": independent.kv(5, None)})
        assert r2["type"] == "fail" and r2["error"] == "present"
        rr = c.invoke({}, {"f": "read", "type": "invoke",
                           "value": independent.kv(5, None)})
        assert rr["type"] == "ok"
        recs = rr["value"][1]
        assert len(recs) == 1 and set(recs[0]) == {"uid", "key"}
        rd = c.invoke({}, {"f": "delete", "type": "invoke",
                           "value": independent.kv(5, None)})
        assert rd["type"] == "ok", rd
        rd2 = c.invoke({}, {"f": "delete", "type": "invoke",
                            "value": independent.kv(5, None)})
        assert rd2["type"] == "fail" and rd2["error"] == "not-found"
        rr2 = c.invoke({}, {"f": "read", "type": "invoke",
                            "value": independent.kv(5, None)})
        assert rr2["value"][1] == []
        c.close({})

        chk = dgraph.DeleteChecker()
        good = h(
            invoke_op(0, "read"), ok_op(0, "read", []),
            invoke_op(0, "read"),
            ok_op(0, "read", [{"uid": "0x1", "key": "5"}]),
        )
        assert chk.check({}, good, {"history-key": 5})["valid?"] is True
        bad = h(
            invoke_op(0, "read"),
            ok_op(0, "read", [{"uid": "0x1", "key": "5"},
                              {"uid": "0x2", "key": "5"}]),
        )
        res = chk.check({}, bad, {"history-key": 5})
        assert res["valid?"] is False and res["bad-reads"]
        # a record missing its key predicate (half-indexed) is bad too
        half = h(
            invoke_op(0, "read"), ok_op(0, "read", [{"uid": "0x1"}]),
        )
        assert chk.check({}, half, {"history-key": 5})["valid?"] is False
    finally:
        s.stop()


def test_dgraph_delete_full_test_in_process():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        t = dgraph.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 40,
                "workload": "delete",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_ycql_multi_key_acid_roundtrip():
    from fake_servers import FakeCql
    from jepsen_tpu.suites import yugabyte

    s = FakeCql().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = yugabyte.YcqlMultiKeyAcidClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        w = c.invoke({}, {
            "f": "write", "type": "invoke",
            "value": independent.kv(3, [["w", 0, 7], ["w", 2, 8]]),
        })
        assert w["type"] == "ok", w
        r = c.invoke({}, {
            "f": "read", "type": "invoke",
            "value": independent.kv(3, [["r", 0, None], ["r", 1, None],
                                        ["r", 2, None]]),
        })
        assert r["type"] == "ok"
        ik, mops = r["value"]
        assert ik == 3
        assert mops == [["r", 0, 7], ["r", 1, None], ["r", 2, 8]]
        # other independent keys isolated
        r2 = c.invoke({}, {
            "f": "read", "type": "invoke",
            "value": independent.kv(4, [["r", 0, None]]),
        })
        assert r2["value"][1] == [["r", 0, None]]
        c.close({})
        # the workload table exposes both flavors
        w = yugabyte.workloads({"nodes": ["n1", "n2", "n3"]})
        assert "ycql.multi-key-acid" in w and "ysql.multi-key-acid" in w
    finally:
        s.stop()


# -- yugabyte ycql bank / long-fork / ysql default-value --------------------


def test_ycql_bank_roundtrip():
    """Transfers ride one BEGIN/END TRANSACTION statement; balances move
    atomically (reference: ycql/bank.clj:46-56)."""
    from fake_servers import FakeCql

    from jepsen_tpu.suites import yugabyte

    s = FakeCql().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        t = {"nodes": ["n1"], "accounts": [0, 1, 2, 3], "total-amount": 20}
        c = yugabyte.YcqlBankClient(opts).open(t, "n1")
        c.setup(t)
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["type"] == "ok" and sum(r["value"].values()) == 20, r
        assert r["value"][0] == 20
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 0, "to": 2, "amount": 7}})
        assert r["type"] == "ok", r
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["value"] == {0: 13, 1: 0, 2: 7, 3: 0}
        c.close(t)
    finally:
        s.stop()


def test_ycql_bank_full_test_in_process():
    from fake_servers import FakeCql

    from jepsen_tpu.suites import yugabyte

    s = FakeCql().start()
    try:
        t = yugabyte.test(
            {
                "nodes": ["n1", "n2", "n3"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 30,
                "workload": "ycql.bank",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_ycql_long_fork_roundtrip():
    from fake_servers import FakeCql

    from jepsen_tpu.suites import yugabyte
    from jepsen_tpu.txn import R, W

    s = FakeCql().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = yugabyte.YcqlLongForkClient(opts).open({}, "n1")
        c.setup({})
        r = c.invoke({}, {"f": "write", "type": "invoke",
                          "value": [[W, 0, 1]]})
        assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "read", "type": "invoke",
                          "value": [[R, 0, None], [R, 1, None]]})
        assert r["type"] == "ok"
        assert r["value"] == [[R, 0, 1], [R, 1, None]]
        c.close({})
    finally:
        s.stop()


def test_ycql_long_fork_full_test_in_process():
    from fake_servers import FakeCql

    from jepsen_tpu.suites import yugabyte

    s = FakeCql().start()
    try:
        t = yugabyte.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 30,
                "workload": "ycql.long-fork",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_ysql_default_value_client_and_checker():
    from jepsen_tpu.suites import yugabyte

    s = FakePg().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "user": "postgres"}
        c = yugabyte.DefaultValueClient(opts).open({"nodes": ["n1"]}, "n1")
        r = c.invoke({}, {"f": "create-table", "type": "invoke", "value": None})
        assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "insert", "type": "invoke", "value": None})
        assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": None})
        assert r["type"] == "ok" and r["value"] == [0], r
        r = c.invoke({}, {"f": "drop-table", "type": "invoke", "value": None})
        assert r["type"] == "ok", r
        # racing reads of a dropped table fail, not crash
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": None})
        assert r["type"] == "fail", r
        c.close({})
    finally:
        s.stop()

    ck = yugabyte.DefaultValueChecker()
    good = h(
        invoke_op(0, "read"), ok_op(0, "read", [0, 0, 0]),
    )
    assert ck.check({}, good)["valid?"] is True
    bad = h(
        invoke_op(0, "read"), ok_op(0, "read", [0, None, 0]),
    )
    res = ck.check({}, bad)
    assert res["valid?"] is False and res["bad-read-count"] == 1


def test_ysql_default_value_full_test_in_process():
    from jepsen_tpu.suites import yugabyte

    s = FakePg().start()
    try:
        t = yugabyte.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "postgres",
                "time-limit": 2,
                "rate": 30,
                "workload": "ysql.default-value",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_yugabyte_flagship_workload_names():
    from jepsen_tpu.suites import yugabyte

    w = yugabyte.workloads({"nodes": ["n1", "n2", "n3"]})
    for name in ("ycql.single-key-acid", "ysql.single-key-acid",
                 "ycql.bank", "ycql.long-fork", "ysql.default-value"):
        assert name in w, name


# -- cockroach sets ---------------------------------------------------------


def test_crdb_sets_checker():
    from jepsen_tpu.suites.crdb_sets import SetsChecker

    ck = SetsChecker()
    good = h(
        invoke_op(0, "add", 0), ok_op(0, "add", 0),
        invoke_op(0, "add", 1), ok_op(0, "add", 1),
        invoke_op(1, "add", 2), info_op(1, "add", 2),
        invoke_op(0, "read"), ok_op(0, "read", [0, 1, 2]),
    )
    res = ck.check({}, good)
    assert res["valid?"] is True, res
    assert res["recovered"] == "#{2}"
    assert res["ok"] == "#{0 1}"

    # lost + revived + duplicate + unexpected all fail
    bad = h(
        invoke_op(0, "add", 0), ok_op(0, "add", 0),
        invoke_op(0, "add", 1), fail_op(0, "add", 1),
        invoke_op(0, "read"), ok_op(0, "read", [1, 1, 9]),
    )
    res = ck.check({}, bad)
    assert res["valid?"] is False
    assert res["lost"] == "#{0}" and res["revived"] == "#{1}"
    assert res["duplicates"] == [1] and res["unexpected"] == "#{9}"

    res = ck.check({}, h(invoke_op(0, "add", 0), ok_op(0, "add", 0)))
    assert res["valid?"] == "unknown"


def test_crdb_sets_full_test_in_process():
    from jepsen_tpu.suites import cockroachdb

    s = FakePg().start()
    try:
        t = cockroachdb.test(
            {
                "nodes": ["n1", "n2", "n3"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "postgres",
                "time-limit": 2,
                "rate": 50,
                "workload": "sets",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- tidb txn + table -------------------------------------------------------


def test_tidb_txn_client_roundtrip():
    from fake_servers import FakeMysql

    from jepsen_tpu.suites import tidb

    s = FakeMysql().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "user": "root",
                "password": "pw", "dialect": "mysql"}
        c = tidb.TidbTxnClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        r = c.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["w", 5, 3], ["r", 5, None]]})
        assert r["type"] == "ok" and r["value"] == [["w", 5, 3], ["r", 5, 3]], r
        # single-mop txns skip BEGIN (reference txn.clj:58-66)
        r = c.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["r", 5, None]]})
        assert r["type"] == "ok" and r["value"] == [["r", 5, 3]]
        # striping: different keys land on txn<hash % 7> tables
        r = c.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["w", 12, 9], ["r", 12, None]]})
        assert r["type"] == "ok" and r["value"][1] == ["r", 12, 9]
        c.close({})
    finally:
        s.stop()


def test_tidb_txn_append_mops():
    from fake_servers import FakeMysql

    from jepsen_tpu.suites import tidb

    s = FakeMysql().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "user": "root",
                "password": "pw", "dialect": "mysql", "val-type": "text"}
        c = tidb.TidbTxnClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        for v in (1, 2):
            r = c.invoke({}, {"f": "txn", "type": "invoke",
                              "value": [["append", 3, v]]})
            assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["r", 3, None]]})
        assert r["type"] == "ok" and r["value"] == [["r", 3, [1, 2]]], r
        c.close({})
    finally:
        s.stop()


def test_tidb_table_client_and_checker():
    from fake_servers import FakeMysql

    from jepsen_tpu.suites import tidb

    s = FakeMysql().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "user": "root",
                "password": "pw", "dialect": "mysql"}
        c = tidb.TableClient(opts).open({"nodes": ["n1"]}, "n1")
        r = c.invoke({}, {"f": "insert", "type": "invoke", "value": [1, 0]})
        assert r["type"] == "fail" and r["error"] == "doesn't-exist", r
        r = c.invoke({}, {"f": "create-table", "type": "invoke", "value": 1})
        assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "insert", "type": "invoke", "value": [1, 0]})
        assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "insert", "type": "invoke", "value": [1, 0]})
        assert r["type"] == "fail" and r["error"] == "duplicate-key", r
        c.close({})
    finally:
        s.stop()

    from jepsen_tpu.suites.tidb import TableChecker

    ck = TableChecker()
    ok_hist = h(
        invoke_op(0, "create-table", 1), ok_op(0, "create-table", 1),
        invoke_op(0, "insert", [1, 0]), ok_op(0, "insert", [1, 0]),
    )
    assert ck.check({}, ok_hist)["valid?"] is True
    bad = h(
        invoke_op(0, "insert", [1, 0]),
        fail_op(0, "insert", [1, 0], error="doesn't-exist"),
    )
    assert ck.check({}, bad)["valid?"] is False


def test_tidb_table_full_test_in_process():
    from fake_servers import FakeMysql

    from jepsen_tpu.suites import tidb

    s = FakeMysql().start()
    try:
        t = tidb.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "root",
                "password": "pw",
                "time-limit": 2,
                "rate": 30,
                "workload": "table",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_tidb_txn_full_test_in_process():
    from fake_servers import FakeMysql

    from jepsen_tpu.suites import tidb

    s = FakeMysql().start()
    try:
        t = tidb.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "root",
                "password": "pw",
                "time-limit": 2,
                "rate": 30,
                "workload": "txn",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- dgraph bank / wr / long-fork -------------------------------------------


def test_dgraph_bank_client_roundtrip():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        t = {"nodes": ["n1"], "accounts": [0, 1, 2], "total-amount": 30}
        c = dgraph.DgraphBankClient(opts).open(t, "n1")
        c.setup(t)
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["type"] == "ok" and r["value"] == {0: 30}, r
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 0, "to": 1, "amount": 10}})
        assert r["type"] == "ok", r
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["type"] == "ok" and r["value"] == {0: 20, 1: 10}, r
        # draining an account deletes its node (write-account! zero path)
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 1, "to": 0, "amount": 10}})
        assert r["type"] == "ok", r
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["type"] == "ok" and r["value"] == {0: 30}, r
        # insufficient funds fails without mutating
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 2, "to": 0, "amount": 5}})
        assert r["type"] == "fail", r
        c.close(t)
    finally:
        s.stop()


def test_dgraph_txn_client_occ_conflict():
    """Two overlapping transactions on one key: the second commit must
    abort (first-committer-wins), mirroring TxnConflictException."""
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c1 = dgraph.DgraphTxnClient(opts).open({}, "n1")
        c1.setup({})
        # seed key 5
        r = c1.invoke({}, {"f": "txn", "type": "invoke",
                           "value": [["w", 5, 1]]})
        assert r["type"] == "ok", r

        t1 = dgraph._DgraphTxn(c1.conn)
        local1: dict = {}
        c1._mop(t1, local1, "r", 5, None)
        c1._mop(t1, local1, "w", 5, 2)

        c2 = dgraph.DgraphTxnClient(opts).open({}, "n1")
        t2 = dgraph._DgraphTxn(c2.conn)
        local2: dict = {}
        c2._mop(t2, local2, "r", 5, None)
        c2._mop(t2, local2, "w", 5, 3)

        t1.commit()  # first wins
        with pytest.raises(dgraph.TxnAborted):
            t2.commit()
        # committed state reflects only t1
        r = c1.invoke({}, {"f": "txn", "type": "invoke",
                           "value": [["r", 5, None]]})
        assert r["value"] == [["r", 5, 2]], r
        c1.close({})
        c2.close({})
    finally:
        s.stop()


def test_dgraph_txn_read_your_writes():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = dgraph.DgraphTxnClient(opts).open({}, "n1")
        c.setup({})
        r = c.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["w", 9, 4], ["r", 9, None]]})
        assert r["type"] == "ok" and r["value"] == [["w", 9, 4], ["r", 9, 4]], r
        c.close({})
    finally:
        s.stop()


def test_dgraph_bank_full_test_in_process():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        t = dgraph.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 3,
                "rate": 20,
                "workload": "bank",
                "faults": [],
            }
        )
        # two accounts keep every transfer direction viable, so the run
        # can't flake with zero ok transfers (stats checker needs >=1)
        t["accounts"] = [0, 1]
        t["total-amount"] = 20
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_dgraph_wr_full_test_in_process():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        t = dgraph.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 20,
                "workload": "wr",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_dgraph_long_fork_full_test_in_process():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        t = dgraph.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 20,
                "workload": "long-fork",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- fauna pages + monotonic ------------------------------------------------


def test_fauna_pages_client_and_checker():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = faunadb.FaunaPagesClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        r = c.invoke({}, {"f": "add", "type": "invoke",
                          "value": (7, [1, 5, -15, 23])})
        assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": (7, None)})
        assert r["type"] == "ok"
        assert sorted(r["value"][1]) == [-15, 1, 5, 23], r
        # a different key reads empty
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": (8, None)})
        assert r["type"] == "ok" and r["value"][1] == [], r
        c.close({})
    finally:
        s.stop()

    ck = faunadb.PagesChecker()
    good = h(
        invoke_op(0, "add", (1, 2, 3)), ok_op(0, "add", (1, 2, 3)),
        invoke_op(0, "read"), ok_op(0, "read", [1, 2, 3]),
        invoke_op(0, "read"), ok_op(0, "read", []),
    )
    assert ck.check({}, good)["valid?"] is True

    # torn group: read observed only part of an atomic add
    torn = h(
        invoke_op(0, "add", (1, 2, 3)), ok_op(0, "add", (1, 2, 3)),
        invoke_op(0, "read"), ok_op(0, "read", [1, 3]),
    )
    res = ck.check({}, torn)
    assert res["valid?"] is False and res["error-count"] == 1, res

    # duplicates
    dup = h(
        invoke_op(0, "add", (1, 2)), ok_op(0, "add", (1, 2)),
        invoke_op(0, "read"), ok_op(0, "read", [1, 1, 2]),
    )
    assert ck.check({}, dup)["valid?"] is False

    # a definitely-failed add showing up in a read is an error, not a
    # silently-accepted singleton
    revived = h(
        invoke_op(0, "add", (1, 2, 3)), fail_op(0, "add", (1, 2, 3)),
        invoke_op(0, "read"), ok_op(0, "read", [1, 3]),
    )
    res = ck.check({}, revived)
    assert res["valid?"] is False, res
    assert any(
        "unexpected" in e for e in res["first-error"]["errors"]
    ), res


def test_fauna_monotonic_client_and_checkers():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = faunadb.FaunaMonotonicClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        r = c.invoke({}, {"f": "inc", "type": "invoke", "value": None})
        assert r["type"] == "ok" and r["value"][1] == 0, r
        r = c.invoke({}, {"f": "inc", "type": "invoke", "value": None})
        assert r["type"] == "ok" and r["value"][1] == 1, r
        ts1 = r["value"][0]
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": None})
        assert r["type"] == "ok" and r["value"][1] == 2, r
        # temporal read at the captured past ts sees the old value
        r = c.invoke({}, {"f": "read-at", "type": "invoke",
                          "value": [ts1, None]})
        assert r["type"] == "ok" and r["value"] == [ts1, 2], r
        # read-at with nil ts picks a jittered recent ts
        r = c.invoke({}, {"f": "read-at", "type": "invoke",
                          "value": [None, None]})
        assert r["type"] == "ok" and isinstance(r["value"][1], int), r
        c.close({})
    finally:
        s.stop()

    mono = faunadb.MonotonicChecker()
    good = h(
        invoke_op(0, "inc"), ok_op(0, "inc", ["000000000001", 0]),
        invoke_op(0, "read"), ok_op(0, "read", ["000000000002", 1]),
    )
    assert mono.check({}, good)["valid?"] is True
    bad = h(
        invoke_op(0, "read"), ok_op(0, "read", ["000000000002", 5]),
        invoke_op(0, "read"), ok_op(0, "read", ["000000000003", 3]),
    )
    res = mono.check({}, bad)
    assert res["valid?"] is False and res["value-errors"], res

    tsv = faunadb.TimestampValueChecker()
    bad_ts = h(
        invoke_op(0, "read-at"), ok_op(0, "read-at", ["000000000001", 5]),
        invoke_op(1, "read-at"), ok_op(1, "read-at", ["000000000002", 3]),
    )
    assert tsv.check({}, bad_ts)["valid?"] is False

    nf = faunadb.NotFoundChecker()
    assert nf.check({}, h(
        invoke_op(0, "read"), fail_op(0, "read", error="not-found"),
    ))["valid?"] is False


def test_fauna_pages_full_test_in_process():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        t = faunadb.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "workload": "pages",
                "per-key-limit": 24,
                "value-range": 200,
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_fauna_monotonic_full_test_in_process():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        t = faunadb.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 30,
                "workload": "monotonic",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- tidb monotonic + sequential (dialect-generic over mysql) ---------------


def test_tidb_monotonic_full_test_in_process():
    from fake_servers import FakeMysql

    from jepsen_tpu.suites import tidb

    s = FakeMysql().start()
    try:
        t = tidb.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "root",
                "password": "pw",
                "time-limit": 2,
                "rate": 40,
                "workload": "monotonic",
                "faults": [],
            }
        )
        # mysql's now(6) is wall-clock, so the strict global check must
        # stay off even if linearizable? is requested
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


def test_tidb_sequential_full_test_in_process():
    from fake_servers import FakeMysql

    from jepsen_tpu.suites import tidb

    s = FakeMysql().start()
    try:
        t = tidb.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "root",
                "password": "pw",
                "time-limit": 2,
                "rate": 40,
                "workload": "sequential",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- stolon ledger (double-spend) -------------------------------------------


def test_stolon_ledger_client_and_checker():
    from jepsen_tpu.suites import stolon

    s = FakePg().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "user": "postgres",
                "dialect": "pg"}
        c = stolon.LedgerClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        r = c.invoke({}, {"f": "transfer", "type": "invoke",
                          "value": [0, 10]})
        assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "transfer", "type": "invoke",
                          "value": [0, -9]})
        assert r["type"] == "ok", r
        # second withdrawal must fail: only 1 left
        r = c.invoke({}, {"f": "transfer", "type": "invoke",
                          "value": [0, -9]})
        assert r["type"] == "fail", r
        c.close({})
    finally:
        s.stop()

    ck = stolon.LedgerChecker()
    good = h(
        invoke_op(0, "transfer", [0, 10]), ok_op(0, "transfer", [0, 10]),
        invoke_op(0, "transfer", [0, -9]), ok_op(0, "transfer", [0, -9]),
        invoke_op(1, "transfer", [0, -9]), fail_op(1, "transfer", [0, -9]),
    )
    assert ck.check({}, good)["valid?"] is True

    # the double-spend: both withdrawals acknowledged
    bad = h(
        invoke_op(0, "transfer", [0, 10]), ok_op(0, "transfer", [0, 10]),
        invoke_op(0, "transfer", [0, -9]), ok_op(0, "transfer", [0, -9]),
        invoke_op(1, "transfer", [0, -9]), ok_op(1, "transfer", [0, -9]),
    )
    res = ck.check({}, bad)
    assert res["valid?"] is False and res["errors"][0]["balance"] == -8

    # charitable reading: indeterminate withdrawals don't count,
    # indeterminate deposits do
    charitable = h(
        invoke_op(0, "transfer", [0, 10]), info_op(0, "transfer", [0, 10]),
        invoke_op(1, "transfer", [0, -9]), info_op(1, "transfer", [0, -9]),
    )
    assert ck.check({}, charitable)["valid?"] is True


def test_stolon_ledger_full_test_in_process():
    from jepsen_tpu.suites import stolon

    s = FakePg().start()
    try:
        t = stolon.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "user": "postgres",
                "time-limit": 2,
                "rate": 40,
                "workload": "ledger",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- crate dirty-read / lost-updates / version-divergence -------------------


def test_crate_dirty_read_client_and_checker():
    from fake_servers import FakeCrate

    from jepsen_tpu.suites import crate

    s = FakeCrate().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = crate.CrateDirtyReadClient(opts).open({}, "n1")
        c.setup({})
        assert c.invoke({}, {"f": "write", "type": "invoke",
                             "value": 0})["type"] == "ok"
        assert c.invoke({}, {"f": "read", "type": "invoke",
                             "value": 0})["type"] == "ok"
        assert c.invoke({}, {"f": "read", "type": "invoke",
                             "value": 99})["type"] == "fail"
        assert c.invoke({}, {"f": "refresh", "type": "invoke",
                             "value": None})["type"] == "ok"
        r = c.invoke({}, {"f": "strong-read", "type": "invoke",
                          "value": None})
        assert r["type"] == "ok" and r["value"] == [0], r
        c.close({})
    finally:
        s.stop()

    ck = crate.DirtyReadChecker()
    good = h(
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read", 1), ok_op(0, "read", 1),
        invoke_op(0, "strong-read"), ok_op(0, "strong-read", [1]),
    )
    assert ck.check({}, good)["valid?"] is True
    # dirty: read saw id 2 which no strong read contains
    dirty = h(
        invoke_op(0, "read", 2), ok_op(0, "read", 2),
        invoke_op(0, "strong-read"), ok_op(0, "strong-read", [1]),
    )
    res = ck.check({}, dirty)
    assert res["valid?"] is False and res["dirty"] == [2]
    # lost: acknowledged write missing from strong reads
    lost = h(
        invoke_op(0, "write", 3), ok_op(0, "write", 3),
        invoke_op(0, "strong-read"), ok_op(0, "strong-read", []),
    )
    res = ck.check({}, lost)
    assert res["valid?"] is False and res["lost"] == [3]
    assert ck.check({}, h(invoke_op(0, "write", 1),
                          ok_op(0, "write", 1)))["valid?"] == "unknown"


def test_crate_lost_updates_client_roundtrip():
    from fake_servers import FakeCrate

    from jepsen_tpu.suites import crate

    s = FakeCrate().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = crate.CrateLostUpdatesClient(opts).open({}, "n1")
        c.setup({})
        for v in (1, 2, 3):
            r = c.invoke({}, {"f": "add", "type": "invoke", "value": (7, v)})
            assert r["type"] == "ok", r
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": (7, None)})
        assert r["type"] == "ok" and r["value"][1] == [1, 2, 3], r
        c.close({})
    finally:
        s.stop()


def test_crate_version_divergence_client_and_checker():
    from fake_servers import FakeCrate

    from jepsen_tpu.suites import crate

    s = FakeCrate().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = crate.CrateVersionClient(opts).open({}, "n1")
        c.setup({})
        assert c.invoke({}, {"f": "write", "type": "invoke",
                             "value": (3, 5)})["type"] == "ok"
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": (3, None)})
        assert r["type"] == "ok" and r["value"][1] == [5, 1], r
        assert c.invoke({}, {"f": "write", "type": "invoke",
                             "value": (3, 6)})["type"] == "ok"
        r = c.invoke({}, {"f": "read", "type": "invoke", "value": (3, None)})
        assert r["value"][1] == [6, 2], r
        c.close({})
    finally:
        s.stop()

    ck = crate.MultiversionChecker()
    good = h(
        invoke_op(0, "read"), ok_op(0, "read", [5, 1]),
        invoke_op(1, "read"), ok_op(1, "read", [5, 1]),
        invoke_op(0, "read"), ok_op(0, "read", [6, 2]),
    )
    assert ck.check({}, good)["valid?"] is True
    # two different values under ONE version: replica divergence
    bad = h(
        invoke_op(0, "read"), ok_op(0, "read", [5, 1]),
        invoke_op(1, "read"), ok_op(1, "read", [9, 1]),
    )
    res = ck.check({}, bad)
    assert res["valid?"] is False and "1" in res["multis"], res


def test_crate_full_tests_in_process():
    from fake_servers import FakeCrate

    from jepsen_tpu.suites import crate

    for wl, extra in (("dirty-read", {"rate": 40}),
                      ("lost-updates", {"per-key-limit": 8}),
                      ("version-divergence", {"per-key-limit": 10})):
        s = FakeCrate().start()
        try:
            t = crate.test({
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "workload": wl,
                "faults": [],
                **extra,
            })
            t["db"] = db_mod.noop()
            t["ssh"] = {"dummy?": True}
            result = core.run(t)
            assert result["results"]["valid?"] is True, (wl, result["results"])
        finally:
            s.stop()


# -- elasticsearch dirty-read -----------------------------------------------


def test_es_dirty_read_client_roundtrip():
    from fake_servers import FakeEs

    from jepsen_tpu.suites import elasticsearch as es

    s = FakeEs().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = es.EsDirtyReadClient(opts).open({}, "n1")
        assert c.invoke({}, {"f": "write", "type": "invoke",
                             "value": 3})["type"] == "ok"
        assert c.invoke({}, {"f": "read", "type": "invoke",
                             "value": 3})["type"] == "ok"
        assert c.invoke({}, {"f": "read", "type": "invoke",
                             "value": 9})["type"] == "fail"
        assert c.invoke({}, {"f": "refresh", "type": "invoke",
                             "value": None})["type"] == "ok"
        r = c.invoke({}, {"f": "strong-read", "type": "invoke",
                          "value": None})
        assert r["type"] == "ok" and r["value"] == [3], r
        c.close({})
    finally:
        s.stop()


def test_es_dirty_read_full_test_in_process():
    from fake_servers import FakeEs

    from jepsen_tpu.suites import elasticsearch as es

    s = FakeEs().start()
    try:
        t = es.test({
            "nodes": ["n1", "n2"],
            "host": "127.0.0.1",
            "port": s.port,
            "time-limit": 2,
            "rate": 40,
            "workload": "dirty-read",
            "faults": [],
        })
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- mongodb-smartos transfer (two-phase commit) ----------------------------


def test_mongo_transfer_client_roundtrip():
    from fake_servers import FakeMongo

    from jepsen_tpu.suites import mongodb_smartos as ms

    s = FakeMongo().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        t = {"nodes": ["n1"], "accounts": [0, 1], "starting-balance": 10}
        c = ms.TransferClient(opts).open(t, "n1")
        c.setup(t)
        r = c.invoke(t, {"f": "read", "type": "invoke", "value": None})
        assert r["type"] == "ok" and r["value"] == {0: 10, 1: 10}, r
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 0, "to": 1, "amount": 3}})
        assert r["type"] == "ok", r
        r = c.invoke(t, {"f": "read", "type": "invoke", "value": None})
        assert r["value"] == {0: 7, 1: 13}, r
        c.close(t)
    finally:
        s.stop()


def test_mongo_transfer_checker():
    from jepsen_tpu.suites.mongodb_smartos import TransferChecker

    t = {"accounts": [0, 1], "starting-balance": 10}
    ck = TransferChecker()
    good = h(
        invoke_op(0, "transfer", {"from": 0, "to": 1, "amount": 3}),
        ok_op(0, "transfer", {"from": 0, "to": 1, "amount": 3}),
        invoke_op(1, "read"), ok_op(1, "read", {0: 7, 1: 13}),
    )
    assert ck.check(t, good)["valid?"] is True
    # a torn final total (half-applied transfer) fails
    torn = h(
        invoke_op(0, "transfer", {"from": 0, "to": 1, "amount": 3}),
        ok_op(0, "transfer", {"from": 0, "to": 1, "amount": 3}),
        invoke_op(1, "read"), ok_op(1, "read", {0: 7, 1: 10}),
    )
    res = ck.check(t, torn)
    assert res["valid?"] is False and res["errors"][0]["total"] == 17
    # mid-run reads are not judged
    midrun = h(
        invoke_op(1, "read"), ok_op(1, "read", {0: 7, 1: 10}),
        invoke_op(0, "transfer", {"from": 0, "to": 1, "amount": 3}),
        ok_op(0, "transfer", {"from": 0, "to": 1, "amount": 3}),
        invoke_op(1, "read"), ok_op(1, "read", {0: 4, 1: 16}),
    )
    assert ck.check(t, midrun)["valid?"] is True
    assert ck.check(t, h(
        invoke_op(0, "transfer", {"from": 0, "to": 1, "amount": 1}),
        ok_op(0, "transfer", {"from": 0, "to": 1, "amount": 1}),
    ))["valid?"] == "unknown"
    # an indeterminate transfer may have half-applied: totals within the
    # slack envelope pass, beyond it fail
    half = h(
        invoke_op(0, "transfer", {"from": 0, "to": 1, "amount": 3}),
        info_op(0, "transfer", {"from": 0, "to": 1, "amount": 3}),
        invoke_op(1, "read"), ok_op(1, "read", {0: 7, 1: 10}),
    )
    assert ck.check(t, half)["valid?"] is True
    beyond = h(
        invoke_op(0, "transfer", {"from": 0, "to": 1, "amount": 3}),
        info_op(0, "transfer", {"from": 0, "to": 1, "amount": 3}),
        invoke_op(1, "read"), ok_op(1, "read", {0: 2, 1: 10}),
    )
    assert ck.check(t, beyond)["valid?"] is False


def test_mongo_transfer_full_test_in_process():
    from fake_servers import FakeMongo

    from jepsen_tpu.suites import mongodb_smartos as ms

    s = FakeMongo().start()
    try:
        t = ms.test({
            "nodes": ["n1", "n2"],
            "host": "127.0.0.1",
            "port": s.port,
            "time-limit": 2,
            "rate": 30,
            "workload": "transfer",
            "faults": [],
        })
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- faunadb bank / set / multimonotonic -------------------------------------


def test_fauna_bank_client_roundtrip():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        t = {"nodes": ["n1"], "accounts": [0, 1, 2], "total-amount": 100}
        c = faunadb.FaunaBankClient(opts).open(t, "n1")
        c.setup(t)
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["type"] == "ok" and r["value"] == {0: 100}
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 0, "to": 1, "amount": 30}})
        assert r["type"] == "ok", r
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["value"] == {0: 70, 1: 30}
        # overdraft aborts and rolls back: balances unchanged
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 1, "to": 2, "amount": 31}})
        assert r["type"] == "fail" and r["error"] == "negative", r
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["value"] == {0: 70, 1: 30}
        # draining an account deletes it (no fixed-instances)
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 1, "to": 2, "amount": 30}})
        assert r["type"] == "ok", r
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["value"] == {0: 70, 2: 30}
        c.close(t)
    finally:
        s.stop()


def test_fauna_bank_index_client_reads_via_index():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        t = {"nodes": ["n1"], "accounts": [0, 1], "total-amount": 50,
             "fixed-instances": True}
        c = faunadb.FaunaBankIndexClient(opts).open(t, "n1")
        c.setup(t)
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 0, "to": 1, "amount": 20}})
        assert r["type"] == "ok", r
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["type"] == "ok" and r["value"] == {0: 30, 1: 20}, r
        # fixed-instances: draining writes 0 instead of deleting
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 0, "to": 1, "amount": 30}})
        assert r["type"] == "ok", r
        r = c.invoke(t, {"f": "read", "value": None, "type": "invoke"})
        assert r["value"] == {0: 0, 1: 50}, r
        c.close(t)
    finally:
        s.stop()


def test_fauna_set_client_and_strong_read():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        for strong in (False, True):
            opts = {"host": "127.0.0.1", "port": s.port,
                    "strong-read": strong, "serialized-indices": True}
            c = faunadb.FaunaSetClient(opts).open({"nodes": ["n1"]}, "n1")
            c.setup({})
            base = 100 if strong else 0
            for v in (base + 1, base + 2, base + 3):
                r = c.invoke({}, {"f": "add", "value": v, "type": "invoke"})
                assert r["type"] == "ok", r
            r = c.invoke({}, {"f": "read", "value": None, "type": "invoke"})
            assert r["type"] == "ok", r
            for v in (base + 1, base + 2, base + 3):
                assert v in r["value"]
            c.close({})
    finally:
        s.stop()


def test_fauna_multimonotonic_client_roundtrip():
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = faunadb.FaunaMultiMonotonicClient(opts).open(
            {"nodes": ["n1"]}, "n1"
        )
        c.setup({})
        assert c.invoke({}, {"f": "write", "value": {3: 0},
                             "type": "invoke"})["type"] == "ok"
        assert c.invoke({}, {"f": "write", "value": {3: 1, 4: 0},
                             "type": "invoke"})["type"] == "ok"
        r = c.invoke({}, {"f": "read", "value": [3, 4, 9],
                          "type": "invoke"})
        assert r["type"] == "ok", r
        v = r["value"]
        assert v["ts"]
        assert v["registers"][3]["value"] == 1
        assert v["registers"][4]["value"] == 0
        assert v["registers"][3]["ts"]
        assert 9 not in v["registers"]
        c.close({})
    finally:
        s.stop()


def _mm_read(proc, ts, regs, t):
    value = {
        "ts": ts,
        "registers": {
            k: {"ts": f"{ts}-w", "value": v} for k, v in regs.items()
        },
    }
    return (
        invoke_op(proc, "read", None, time=t),
        ok_op(proc, "read", value, time=t + 1),
    )


def test_ts_order_checker():
    from jepsen_tpu.suites.faunadb import TsOrderChecker

    good = h(
        *_mm_read(0, "001", {1: 0, 2: 5}, 0),
        *_mm_read(1, "002", {1: 1, 2: 5}, 2),
        *_mm_read(0, "003", {1: 1, 2: 6}, 4),
    )
    assert TsOrderChecker().check({}, good)["valid?"] is True

    # a later-timestamped read sees register 1 go BACKWARDS
    bad = h(
        *_mm_read(0, "001", {1: 4}, 0),
        *_mm_read(1, "002", {1: 3, 2: 0}, 2),
    )
    out = TsOrderChecker().check({}, bad)
    assert out["valid?"] is False
    assert out["errors"][0]["errors"][1][0]["value"] == 4
    assert out["errors"][0]["errors"][1][1]["value"] == 3


def test_read_skew_checker():
    from jepsen_tpu.suites.faunadb import ReadSkewChecker

    good = h(
        *_mm_read(0, "001", {1: 0, 2: 0}, 0),
        *_mm_read(1, "002", {1: 1, 2: 2}, 2),
    )
    assert ReadSkewChecker().check({}, good)["valid?"] is True

    # r1 sees x=1,y=2; r2 sees x=2,y=1: incompatible per-key orders
    bad = h(
        *_mm_read(0, "001", {"x": 1, "y": 2}, 0),
        *_mm_read(1, "002", {"x": 2, "y": 1}, 2),
    )
    out = ReadSkewChecker().check({}, bad)
    assert out["valid?"] is False
    assert out["read-skew"], out


@pytest.mark.parametrize("wname", ["bank", "bank-index", "set",
                                   "multimonotonic"])
def test_fauna_workload_full_test_in_process(wname):
    from jepsen_tpu.suites import faunadb

    s = FakeFauna().start()
    try:
        t = faunadb.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 30,
                "workload": wname,
                "faults": [],
                # few accounts keep the short window's transfer mix from
                # all drawing empty sources (bank only; ignored elsewhere)
                "accounts": [0, 1, 2],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- dgraph sequential -------------------------------------------------------


def test_dgraph_sequential_client_roundtrip():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c = dgraph.DgraphSequentialClient(opts).open({"nodes": ["n1"]}, "n1")
        c.setup({})
        r = c.invoke({}, {"f": "read", "type": "invoke",
                          "value": independent.kv(3, None)})
        assert r["type"] == "ok" and tuple(r["value"]) == (3, 0)
        for expect in (1, 2, 3):
            r = c.invoke({}, {"f": "inc", "type": "invoke",
                              "value": independent.kv(3, None)})
            assert r["type"] == "ok" and tuple(r["value"]) == (3, expect), r
        r = c.invoke({}, {"f": "read", "type": "invoke",
                          "value": independent.kv(3, None)})
        assert tuple(r["value"]) == (3, 3)
        # other keys are independent
        r = c.invoke({}, {"f": "inc", "type": "invoke",
                          "value": independent.kv(4, None)})
        assert tuple(r["value"]) == (4, 1)
        c.close({})
    finally:
        s.stop()


def test_dgraph_sequential_checker():
    from jepsen_tpu.suites.dgraph import (
        SequentialChecker,
        merged_windows,
        sequential_non_monotonic_pairs,
    )

    # per-process monotone: valid even when processes interleave
    good = h(
        invoke_op(0, "inc", None), ok_op(0, "inc", 1),
        invoke_op(1, "read", None), ok_op(1, "read", 0),
        invoke_op(1, "read", None), ok_op(1, "read", 2),
        invoke_op(0, "read", None), ok_op(0, "read", 2),
    )
    assert SequentialChecker().check({}, good)["valid?"] is True

    # process 1 observes 2 then 1: non-monotonic
    bad = h(
        invoke_op(0, "inc", None), ok_op(0, "inc", 1),
        invoke_op(1, "read", None), ok_op(1, "read", 2),
        invoke_op(1, "read", None), ok_op(1, "read", 1),
    )
    out = SequentialChecker().check({}, bad)
    assert out["valid?"] is False
    pair = out["non-monotonic"][0]
    assert pair[0]["value"] == 2 and pair[1]["value"] == 1
    assert sequential_non_monotonic_pairs(good) == []

    assert merged_windows(2, []) == []
    assert merged_windows(2, [5]) == [[3, 7]]
    # overlapping windows merge; distant ones stay separate
    assert merged_windows(2, [5, 6, 20]) == [[3, 8], [18, 22]]


def test_dgraph_sequential_full_test_in_process():
    from jepsen_tpu.suites import dgraph

    s = FakeDgraph().start()
    try:
        t = dgraph.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 30,
                "workload": "sequential",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- zookeeper lock ----------------------------------------------------------


def test_zk_lock_client_roundtrip():
    from fake_servers import FakeZk

    from jepsen_tpu.suites import zookeeper

    s = FakeZk().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        c1 = zookeeper.ZkLockClient(opts).open({"nodes": ["n1"]}, "n1")
        c2 = zookeeper.ZkLockClient(opts).open({"nodes": ["n1"]}, "n1")
        r = c1.invoke({}, {"f": "acquire", "value": None, "type": "invoke"})
        assert r["type"] == "ok", r
        # completions carry the session identity (distinct per client)
        assert r["value"]["client"] != c2._me()["client"]
        # contender loses; holder can't double-acquire
        r = c2.invoke({}, {"f": "acquire", "value": None, "type": "invoke"})
        assert r["type"] == "fail" and r["error"] == "taken"
        r = c1.invoke({}, {"f": "acquire", "value": None, "type": "invoke"})
        assert r["type"] == "fail" and r["error"] == "already-held"
        # release without holding never touches the wire
        r = c2.invoke({}, {"f": "release", "value": None, "type": "invoke"})
        assert r["type"] == "fail" and r["error"] == "not-held"
        r = c1.invoke({}, {"f": "release", "value": None, "type": "invoke"})
        assert r["type"] == "ok", r
        # freed: the contender can take it now
        r = c2.invoke({}, {"f": "acquire", "value": None, "type": "invoke"})
        assert r["type"] == "ok", r
        c1.close({})
        c2.close({})
    finally:
        s.stop()


def test_zk_lock_full_test_in_process():
    from fake_servers import FakeZk

    from jepsen_tpu.suites import zookeeper

    s = FakeZk().start()
    try:
        t = zookeeper.test(
            {
                "nodes": ["n1", "n2"],
                "host": "127.0.0.1",
                "port": s.port,
                "time-limit": 2,
                "rate": 40,
                "workload": "lock",
                "faults": [],
            }
        )
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        r = result["results"]
        assert r["valid?"] is True, r
        hist = result["history"]
        oks = [o for o in hist if o["type"] == "ok"]
        fails = [o for o in hist if o["type"] == "fail"]
        assert any(o["f"] == "acquire" for o in oks)
        assert any(o["f"] == "release" for o in oks)
        # the lock was genuinely contended
        assert any(o.get("error") == "taken" for o in fails), (
            "no contention observed"
        )
    finally:
        s.stop()


# -- ignite bank ------------------------------------------------------------


def test_ignite_bank_client_roundtrip():
    from fake_servers import FakeIgnite

    from jepsen_tpu.suites import ignite

    s = FakeIgnite().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port}
        t = {"accounts": [0, 1, 2, 3], "total-amount": 40}
        c = ignite.IgniteBankClient(opts).open(t, "n1")
        c.setup(t)
        r = c.invoke(t, {"f": "read", "type": "invoke", "value": None})
        assert r["type"] == "ok"
        assert r["value"] == {0: 10, 1: 10, 2: 10, 3: 10}
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 0, "to": 3, "amount": 7}})
        assert r["type"] == "ok"
        r = c.invoke(t, {"f": "read", "type": "invoke", "value": None})
        assert r["value"] == {0: 3, 1: 10, 2: 10, 3: 17}
        assert sum(r["value"].values()) == 40
        # overdrafts abort like the reference's transactions
        r = c.invoke(t, {"f": "transfer", "type": "invoke",
                         "value": {"from": 0, "to": 1, "amount": 9}})
        assert r["type"] == "fail" and r["error"] == "insufficient-funds"
        # second client sees the same bank (putIfAbsent init)
        c2 = ignite.IgniteBankClient(opts).open(t, "n2")
        c2.setup(t)
        r = c2.invoke(t, {"f": "read", "type": "invoke", "value": None})
        assert sum(r["value"].values()) == 40
        c.close(t)
        c2.close(t)
    finally:
        s.stop()


def test_ignite_bank_full_test_in_process():
    from fake_servers import FakeIgnite

    from jepsen_tpu.suites import ignite

    s = FakeIgnite().start()
    try:
        t = ignite.test({
            "nodes": ["n1", "n2", "n3"],
            "host": "127.0.0.1",
            "port": s.port,
            "workload": "bank",
            "time-limit": 2,
            "rate": 50,
            "faults": [],
        })
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
        reads = [o for o in result["history"]
                 if o["type"] == "ok" and o["f"] == "read"
                 and isinstance(o["process"], int)]
        assert reads and all(
            sum(r["value"].values()) == t["total-amount"] for r in reads
        )
    finally:
        s.stop()


# -- aerospike pause --------------------------------------------------------


def test_aerospike_pause_state_machine_schedules():
    from jepsen_tpu import generator as g
    from jepsen_tpu.suites import aerospike_pause as ap

    t = {"nodes": ["n1", "n2", "n3"], "concurrency": 6}
    state = ap.PauseState(t, {"healthy-delay": 100, "pause-delay": 200})
    assert state.state == "healthy"
    assert len(state.masters) == 1
    assert state.keys == [0, 1]

    nem_gen = ap.PauseNemGen(state)
    client_gen = ap.PauseClientGen(state)
    ctx = g.context({"concurrency": 2, "nodes": t["nodes"]})

    # clients write immediately; nemesis waits out the healthy delay
    op, _ = client_gen.op(t, ctx)
    assert op["f"] == "add"
    k, v = op["value"]
    assert k in state.keys and v == 0
    res, _ = nem_gen.op(t, ctx)
    assert res == g.PENDING
    ctx2 = {**ctx, "time": ctx["time"] + int(0.2 * 1e9)}
    op, _ = nem_gen.op(t, ctx2)
    assert op["f"] == "pause" and op["value"] == state.masters

    # paused: nemesis pends; first acked add flips to wait
    state.note("paused")
    assert nem_gen.op(t, ctx2)[0] == g.PENDING
    state.add_succeeded()
    assert state.state == "wait"
    # wait: clients cease; nemesis resumes after the pause delay
    assert client_gen.op(t, ctx2)[0] == g.PENDING
    assert nem_gen.op(t, ctx2)[0] == g.PENDING
    ctx3 = {**ctx2, "time": ctx2["time"] + int(0.4 * 1e9)}
    op, _ = nem_gen.op(t, ctx3)
    assert op["f"] == "resume"

    # resume → next healthy block: fresh masters + fresh keys
    state.next_healthy(t)
    assert state.state == "healthy"
    assert state.keys == [2, 3]


def test_aerospike_pause_full_run_in_process():
    from fake_servers import FakeAerospike

    from jepsen_tpu.suites import aerospike_pause as ap

    s = FakeAerospike().start()
    try:
        t = ap.pause_test({
            "nodes": ["n1", "n2", "n3"],
            "host": "127.0.0.1", "port": s.port,
            "concurrency": 3,
            "healthy-delay": 200, "pause-delay": 300,
            "final-settle": 0.2,
            "time-limit": 3,
        })
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        hist = result["history"]
        nem_fs = [o["f"] for o in hist
                  if o["process"] == "nemesis" and o["type"] == "info"]
        # the machine cycled: pauses and resumes both fired
        assert "pause" in nem_fs and "resume" in nem_fs, nem_fs
        reads = [o for o in hist if o["type"] == "ok"
                 and o["f"] == "read"]
        assert reads, "final read phase never ran"
        # nothing was actually paused (fake server): no lost writes
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- yugabyte ysql.append-table ---------------------------------------------


def test_yb_append_table_client_roundtrip():
    from fake_servers import FakePg

    from jepsen_tpu.suites import yugabyte

    s = FakePg().start()
    try:
        opts = {"host": "127.0.0.1", "port": s.port, "user": "yugabyte",
                "append-table-key": "count"}
        c = yugabyte.AppendTableClient(opts).open({"nodes": ["n1"]}, "n1")
        # lazy creation: the first txn hits a missing table and retries
        r = c.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["append", 7, 1], ["r", 7, None]]})
        assert r["type"] == "ok", r
        assert r["value"] == [["append", 7, 1], ["r", 7, [1]]]
        r = c.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["append", 7, 2], ["append", 7, 3],
                                    ["r", 7, None]]})
        assert r["value"][-1] == ["r", 7, [1, 2, 3]]
        # distinct keys live in distinct tables
        r = c.invoke({}, {"f": "txn", "type": "invoke",
                          "value": [["r", 8, None]]})
        assert r["value"] == [["r", 8, []]]
        c.close({})
    finally:
        s.stop()


def test_yb_append_table_full_test_in_process():
    from fake_servers import FakePg

    from jepsen_tpu.suites import yugabyte

    s = FakePg().start()
    try:
        t = yugabyte.test({
            "nodes": ["n1", "n2", "n3"],
            "host": "127.0.0.1", "port": s.port, "user": "yugabyte",
            "append-table-key": "count",
            "workload": "ysql.append-table",
            "time-limit": 2, "rate": 30, "concurrency": 2,
            "faults": [],
        })
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        s.stop()


# -- galera / percona dirty-reads -------------------------------------------


def test_dirty_reads_checker_flags_filthy_and_inconsistent():
    from jepsen_tpu.suites.dirty_reads_sql import DirtyReadsChecker

    c = DirtyReadsChecker()
    res = c.check({}, h(invoke_op(0, "read"),
                        ok_op(0, "read", [3, 3, 3])))
    assert res["valid?"] is True

    # a failed write's value visible → dirty read
    res = c.check({}, h(
        invoke_op(0, "write", 7),
        fail_op(0, "write", 7),
        invoke_op(1, "read"),
        ok_op(1, "read", [7, 7, 7]),
    ))
    assert res["valid?"] is False and res["dirty-reads"]

    # rows disagree → inconsistent (recorded, not invalid by itself)
    res = c.check({}, h(
        invoke_op(0, "read"),
        ok_op(0, "read", [1, 2, 1]),
    ))
    assert res["valid?"] is True and res["inconsistent-reads"]


def test_galera_dirty_reads_full_test_in_process():
    from fake_servers import FakeMysql

    from jepsen_tpu.suites import galera

    s = FakeMysql().start()
    try:
        t = galera.test({
            "nodes": ["n1", "n2", "n3"],
            "host": "127.0.0.1", "port": s.port, "user": "root",
            "password": "pw",
            "workload": "dirty-reads",
            "time-limit": 2, "rate": 40, "concurrency": 4,
            "faults": [],
        })
        t["db"] = db_mod.noop()
        t["ssh"] = {"dummy?": True}
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
        writes = [o for o in result["history"]
                  if o["type"] == "ok" and o["f"] == "write"]
        reads = [o for o in result["history"]
                 if o["type"] == "ok" and o["f"] == "read"]
        assert writes and reads
    finally:
        s.stop()


def test_percona_dirty_reads_assembles():
    from jepsen_tpu.suites import percona
    from jepsen_tpu.suites.dirty_reads_sql import DirtyReadsClient

    t = percona.test({"nodes": ["n1"], "workload": "dirty-reads",
                      "faults": []})
    assert t["name"] == "percona-dirty-reads"
    assert isinstance(t["client"], DirtyReadsClient)
