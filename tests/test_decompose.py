"""P-compositionality decomposition tests (jepsen_tpu/engine/decompose.py
+ the models partition protocol).

The contract under test: decomposed verdicts are byte-identical to the
undecomposed path on every partition-declaring model — the partition
protocol's soundness means the pass may only ever change WHERE a
history is checked (tight per-partition sub-rows vs one big search),
never WHAT the verdict is.  A failing decomposed history must name its
failing partition, deterministically (first partition order, never
settle order).
"""

import random

import pytest

from jepsen_tpu import models as m
from jepsen_tpu import obs
from jepsen_tpu.checker import linear
from jepsen_tpu.engine import decompose
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.ops import wgl
from jepsen_tpu.synth import generate_mr_history


def h(*ops) -> History:
    return History(list(ops)).index_ops()


def gen_multi_mutex_history(rng, n_locks=3, n_ops=24, corrupt=False):
    """Lock soup over named locks; valid by construction unless
    corrupt (a double-acquire on one lock)."""
    names = [chr(ord("a") + i) for i in range(n_locks)]
    ops = []
    held = set()
    p = 0
    for _ in range(n_ops):
        name = rng.choice(names)
        p = (p + 1) % 5
        if name in held:
            ops.append(invoke_op(p, "release", name))
            ops.append(ok_op(p, "release", name))
            held.discard(name)
        else:
            ops.append(invoke_op(p, "acquire", name))
            ops.append(ok_op(p, "acquire", name))
            held.add(name)
    if corrupt:
        name = rng.choice(names)
        if name in held:
            ops.append(invoke_op(7, "acquire", name))
            ops.append(ok_op(7, "acquire", name))
        else:
            ops.append(invoke_op(7, "release", name))
            ops.append(ok_op(7, "release", name))
    return History(ops).index_ops()


def gen_mr_multimop_history(rng, n_keys=3, n_ops=10, corrupt=False):
    """Atomic same-key read-then-write txns (two mops per op) — the
    shape a plain Register op cannot express but the single-key
    sub-model can."""
    state = {k: 0 for k in range(n_keys)}
    ops = []
    for _ in range(n_ops):
        k = rng.randrange(n_keys)
        v = rng.randrange(1, 4)
        ops.append(invoke_op(0, "txn", [("r", k, None), ("w", k, v)]))
        ops.append(ok_op(0, "txn", [("r", k, state[k]), ("w", k, v)]))
        state[k] = v
    if corrupt and ops:
        i = rng.randrange(len(ops) // 2) * 2 + 1
        op = ops[i]
        (_r, k, _obs), w = op.value
        ops[i] = op.copy(value=[("r", k, 7), w])
    return History(ops).index_ops()


def gen_queue_history(rng, n_values=6, n_ops=20, corrupt=False):
    ops = []
    in_q = []
    for _ in range(n_ops):
        if in_q and rng.random() < 0.45:
            v = in_q.pop(rng.randrange(len(in_q)))
            ops.append(invoke_op(0, "dequeue", None))
            ops.append(ok_op(0, "dequeue", v))
        else:
            v = rng.randrange(n_values)
            in_q.append(v)
            ops.append(invoke_op(0, "enqueue", v))
            ops.append(ok_op(0, "enqueue", v))
    if corrupt:
        ops.append(invoke_op(1, "dequeue", None))
        ops.append(ok_op(1, "dequeue", 99))  # never enqueued
    return History(ops).index_ops()


# ---------------------------------------------------------------------------
# the partition protocol on the models
# ---------------------------------------------------------------------------


def test_base_models_declare_no_partition():
    for model in (m.register(0), m.cas_register(0), m.mutex(),
                  m.fifo_queue(), m.NoOp()):
        assert decompose.partitioner(model) is None


def test_multi_register_protocol():
    model = m.multi_register({0: 7, 1: 0})
    w = invoke_op(0, "txn", [("w", 0, 5)])
    r = invoke_op(0, "txn", [("r", 1, 3)])
    rw_same = invoke_op(0, "txn", [("r", 0, None), ("w", 0, 2)])
    cross = invoke_op(0, "txn", [("w", 0, 1), ("w", 1, 2)])
    assert model.partition_key(w) == 0
    assert model.partition_key(r) == 1
    # an atomic multi-mop txn still decomposes when every mop touches
    # the SAME key — only cross-key txns disable decomposition
    assert model.partition_key(rw_same) == 0
    assert model.partition_key(cross) is None
    assert model.partition_key(invoke_op(0, "txn", None)) is None
    assert model.partition_key(invoke_op(0, "txn", [])) is None
    # sub-model: the single-key register slice, seeded from this
    # key's state (K=1 multi-register IS the register automaton)
    assert model.subhistory_model(0) == m.multi_register({0: 7})
    assert model.subhistory_model(9) == m.multi_register({9: None})
    # ops pass through unchanged (a Register op could not express an
    # atomic read-then-write)
    assert model.partition_op(w, 0) is w


def test_multi_mutex_model_and_protocol():
    mm = m.multi_mutex()
    s = mm.step(invoke_op(0, "acquire", "a"))
    assert not s.is_inconsistent
    assert s.step(invoke_op(1, "acquire", "a")).is_inconsistent
    assert not s.step(invoke_op(1, "acquire", "b")).is_inconsistent
    assert s.step(invoke_op(0, "release", "a")) == m.multi_mutex()
    assert mm.step(invoke_op(0, "release", "a")).is_inconsistent
    assert mm.step(invoke_op(0, "acquire", None)).is_inconsistent
    assert mm.partition_key(invoke_op(0, "acquire", "a")) == "a"
    assert mm.partition_key(invoke_op(0, "frob", "a")) is None
    assert s.subhistory_model("a") == m.Mutex(True)
    assert s.subhistory_model("b") == m.Mutex(False)


def test_unordered_queue_protocol():
    q = m.UnorderedQueue(frozenset({(3, 2), (5, 1)}))
    assert q.partition_key(invoke_op(0, "enqueue", 3)) == 3
    assert q.partition_key(invoke_op(0, "dequeue", None)) is None
    assert q.partition_key(invoke_op(0, "peek", 3)) is None
    assert q.subhistory_model(3) == m.UnorderedQueue(frozenset({(3, 2)}))
    assert q.subhistory_model(8) == m.unordered_queue()


# ---------------------------------------------------------------------------
# split_history
# ---------------------------------------------------------------------------


def test_split_history_pairs_and_orders():
    model = m.multi_register({0: 0, 1: 0})
    hist = h(
        invoke_op(0, "txn", [("w", 0, 1)]),
        invoke_op(1, "txn", [("w", 1, 2)]),
        ok_op(1, "txn", [("w", 1, 2)]),
        ok_op(0, "txn", [("w", 0, 1)]),
        invoke_op(0, "txn", [("r", 0, None)]),
        ok_op(0, "txn", [("r", 0, 1)]),
    )
    parts = decompose.split_history(model, hist)
    assert [k for k, _sub, _h in parts] == [0, 1]  # first-seen order
    by_key = {k: sh for k, _sub, sh in parts}
    assert [op.type for op in by_key[0]] == ["invoke", "ok", "invoke", "ok"]
    assert [op.value[0][0] for op in by_key[0]] == ["w", "w", "r", "r"]
    assert all(op.value[0][1] == 0 for op in by_key[0])
    assert len(by_key[1]) == 2


def test_split_history_key_resolves_from_completion():
    """A dequeue's partition lives on the ok event, not the invoke."""
    q = m.unordered_queue()
    hist = h(
        invoke_op(0, "enqueue", 4), ok_op(0, "enqueue", 4),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 4),
    )
    parts = decompose.split_history(q, hist)
    assert parts is not None and len(parts) == 1
    # single partition: the engine passes it through, but the split
    # itself must have routed the dequeue to value 4's partition
    assert len(parts[0][2]) == 4


def test_split_history_undecomposable_and_dropped_events():
    model = m.multi_register({0: 0, 1: 0})
    cross = h(
        invoke_op(0, "txn", [("w", 0, 1), ("w", 1, 2)]),
        ok_op(0, "txn", [("w", 0, 1), ("w", 1, 2)]),
    )
    assert decompose.split_history(model, cross) is None
    # failed pairs drop; nemesis (non-int process) events are skipped
    from jepsen_tpu.history import Op

    hist = h(
        invoke_op(0, "txn", [("w", 0, 1)]),
        ok_op(0, "txn", [("w", 0, 1)]),
        invoke_op(1, "txn", [("w", 1, 9)]),
        Op("fail", 1, "txn", [("w", 1, 9)]),
        Op("invoke", "nemesis", "kill", None),
        invoke_op(2, "txn", [("r", 1, None)]),
        ok_op(2, "txn", [("r", 1, 0)]),
    )
    parts = decompose.split_history(model, hist)
    keys = [k for k, _s, _h in parts]
    assert keys == [0, 1]
    by_key = {k: sh for k, _s, sh in parts}
    # the failed write to key 1 vanished entirely
    assert [op.type for op in by_key[1]] == ["invoke", "ok"]


def test_submodel_cache_bounded_with_eviction_counter():
    obs.enable(reset=True)
    model = m.multi_register({k: 0 for k in range(8)})
    cache = decompose.SubmodelCache(model, cap=4)
    for k in range(8):
        cache.get(k)
    assert cache.evictions == 4
    assert obs.registry().value(
        "jepsen_engine_decompose_cache_evictions_total") == 4
    # evicted entries rebuild correctly
    assert cache.get(0) == m.multi_register({0: 0})
    obs.enable(reset=True)


def test_oracle_partitions_multi_mop_single_key_txns():
    """Regression (review finding): atomic same-key multi-mop txns must
    keep decomposing in the CPU oracle — the pre-protocol
    _partition_by_key handled them, and the protocol must too."""
    model = m.multi_register({0: 0, 1: 0})
    hist = h(
        invoke_op(0, "txn", [("r", 0, None), ("w", 0, 2)]),
        ok_op(0, "txn", [("r", 0, 0), ("w", 0, 2)]),
        invoke_op(1, "txn", [("w", 1, 5)]),
        ok_op(1, "txn", [("w", 1, 5)]),
    )
    parts = linear._partition_by_key(model, *linear.prepare(hist))
    assert parts is not None and len(parts) == 2
    assert linear.analysis(model, hist)["valid?"] is True
    # engine path decomposes it too
    out = wgl.check_batch(model, [hist])[0]
    assert out["valid?"] is True and out["partitions"] == 2


# ---------------------------------------------------------------------------
# AND-at-settle merge
# ---------------------------------------------------------------------------


def test_merge_partition_results_first_false_wins():
    parts = [
        ("a", {"valid?": True, "engine": "tpu", "kernel": "dense"}),
        ("b", {"valid?": False, "engine": "tpu", "kernel": "dense",
               "failed-event": 3}),
        ("c", {"valid?": False, "engine": "oracle-fallback"}),
        ("d", {"valid?": "unknown", "engine": "oracle-overflow"}),
    ]
    out = decompose.merge_partition_results(parts)
    assert out["valid?"] is False
    assert out["failed-partition"] == "b"  # first False in partition order
    assert out["failed-event"] == 3
    assert out["partitions"] == 4


def test_merge_partition_results_unknown_and_true():
    unk = decompose.merge_partition_results([
        ("a", {"valid?": True, "engine": "tpu"}),
        ("b", {"valid?": "unknown", "engine": "oracle-overflow"}),
    ])
    assert unk["valid?"] == "unknown" and unk["failed-partition"] == "b"
    ok_uniform = decompose.merge_partition_results([
        ("a", {"valid?": True, "engine": "tpu", "kernel": "dense"}),
        ("b", {"valid?": True, "engine": "tpu", "kernel": "dense"}),
    ])
    assert ok_uniform == {"valid?": True, "engine": "tpu",
                          "partitions": 2, "kernel": "dense"}
    mixed = decompose.merge_partition_results([
        ("a", {"valid?": True, "engine": "tpu", "kernel": "dense"}),
        ("b", {"valid?": True, "engine": "oracle-routed",
               "algorithm": "direct-mutex"}),
    ])
    assert mixed["engine"] == "mixed" and "kernel" not in mixed


def test_failing_partition_named_end_to_end():
    """The regression the ISSUE pins: a single failing partition yields
    valid? = False with the partition named — through the full engine
    path, at both window sizes."""
    model = m.multi_register({k: 0 for k in range(6)})
    good_mops = [("w", k, 1) for k in range(6)]
    ops = []
    for k, mop in enumerate(good_mops):
        ops.append(invoke_op(0, "txn", [mop]))
        ops.append(ok_op(0, "txn", [mop]))
    ops.append(invoke_op(1, "txn", [("r", 4, 9)]))  # 9 never written to 4
    ops.append(ok_op(1, "txn", [("r", 4, 9)]))
    hist = History(ops).index_ops()
    for window in (1, 4):
        out = wgl.check_batch(model, [hist], window=window)[0]
        assert out["valid?"] is False
        assert out["failed-partition"] == 4
        assert out["partitions"] == 6
        assert wgl.check_batch(
            model, [hist], window=window, decomposed=False
        )[0]["valid?"] is False


# ---------------------------------------------------------------------------
# verdict identity: decomposed ≡ undecomposed (oracle-level property)
# ---------------------------------------------------------------------------


def _undecomposed_verdict(model, hist):
    """The pass-through baseline: the fast search on the WHOLE history
    (deliberately bypassing _partition_by_key)."""
    events, ops = linear.prepare(hist)
    return linear._search_fast(
        model, events, ops, linear.DEFAULT_MAX_CONFIGS, None, None
    )["valid?"]


def _decomposed_verdict(model, hist):
    parts = decompose.split_history(model, hist)
    if parts is None:
        return _undecomposed_verdict(model, hist)
    sub = [
        (k, linear.analysis(submodel, sh)) for k, submodel, sh in parts
    ]
    return decompose.merge_partition_results(sub)["valid?"]


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_verdict_identity_oracle_level(seed):
    """≥ 1k op-soup cases across the three partitionable models: the
    protocol-decomposed verdict must equal the whole-history search's,
    case by case."""
    rng = random.Random(1000 + seed)
    cases = []
    mr_model = m.multi_register({k: 0 for k in range(4)})
    for i in range(100):
        cases.append((mr_model, generate_mr_history(
            rng, n_procs=4, n_ops=14, n_keys=4, n_values=3,
            crash_p=0.1, corrupt=(i % 3 == 0),
        )))
    for i in range(40):
        cases.append((mr_model, gen_mr_multimop_history(
            rng, n_keys=3, n_ops=8, corrupt=(i % 3 == 0),
        )))
    mm_model = m.multi_mutex()
    for i in range(80):
        cases.append((mm_model, gen_multi_mutex_history(
            rng, n_locks=3, n_ops=16, corrupt=(i % 3 == 0),
        )))
    uq_model = m.unordered_queue()
    for i in range(80):
        cases.append((uq_model, gen_queue_history(
            rng, n_values=5, n_ops=16, corrupt=(i % 3 == 0),
        )))
    n_decomposed = 0
    for model, hist in cases:
        dec = _decomposed_verdict(model, hist)
        und = _undecomposed_verdict(model, hist)
        assert dec == und, (type(model).__name__, dec, und, list(hist))
        if decompose.split_history(model, hist) is not None:
            n_decomposed += 1
    assert n_decomposed > len(cases) // 2  # the fuzz actually decomposes


# ---------------------------------------------------------------------------
# verdict identity through the full engine (device path)
# ---------------------------------------------------------------------------


def engine_corpus(seed=45100):
    rng = random.Random(seed)
    mr_model = m.multi_register({k: 0 for k in range(6)})
    mr = [
        generate_mr_history(
            rng, n_procs=4, n_ops=24, n_keys=6, n_values=3,
            crash_p=0.05, corrupt=(i % 3 == 0),
        )
        for i in range(12)
    ]
    # cross-key txn: pass-through lane inside a decomposed batch
    mr.append(h(
        invoke_op(0, "txn", [("w", 0, 1), ("w", 1, 2)]),
        ok_op(0, "txn", [("w", 0, 1), ("w", 1, 2)]),
    ))
    # slot-cap buster: oracle-fallback lane
    mr.append(History(
        [invoke_op(p, "txn", [("w", p % 6, 1)]) for p in range(40)]
    ).index_ops())
    mm = [
        gen_multi_mutex_history(rng, n_locks=4, n_ops=20,
                                corrupt=(i % 3 == 0))
        for i in range(6)
    ]
    uq = [
        gen_queue_history(rng, n_values=6, n_ops=18, corrupt=(i % 3 == 0))
        for i in range(6)
    ]
    return [(mr_model, mr), (m.multi_mutex(), mm),
            (m.unordered_queue(), uq)]


def test_engine_decomposed_verdicts_match_passthrough():
    for model, hists in engine_corpus():
        obs.enable(reset=True)
        dec = wgl.check_batch(model, hists, slot_cap=32)
        reg = obs.registry()
        parts_total = reg.value("jepsen_engine_partitions_total")
        routed_dec = reg.value(
            "jepsen_engine_decomposed_total", route="decomposed")
        obs.enable(reset=True)
        und = wgl.check_batch(
            model, hists, slot_cap=32, decomposed=False)
        obs.enable(reset=True)
        assert [r["valid?"] for r in dec] == [r["valid?"] for r in und], (
            type(model).__name__
        )
        if isinstance(model, m.UnorderedQueue):
            # direct-first spec: the engine routing gate keeps the
            # pass OFF (the per-value direct checker already factors
            # internally; splitting would multiply oracle tasks by
            # the fanout — measured ~12x slower)
            assert not parts_total and not routed_dec
        else:
            assert (parts_total or 0) >= 2, type(model).__name__
            assert (routed_dec or 0) >= 1, type(model).__name__
        assert True in [r["valid?"] for r in dec]
        assert False in [r["valid?"] for r in dec]


def test_direct_first_models_skip_engine_decomposition():
    """The routing gate itself: a model whose spec is in
    wgl.DIRECT_FIRST_SPECS never decomposes engine-side even though it
    declares the partition protocol (the oracle's direct checker
    already factors per partition internally), while protocol models
    off that list do."""
    assert not decompose.routing_gain_possible(m.unordered_queue())
    assert decompose.routing_gain_possible(m.multi_register({0: 0}))
    assert decompose.routing_gain_possible(m.multi_mutex())
    run = decompose.DecomposedRun(
        m.unordered_queue(),
        [h(invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
           invoke_op(0, "enqueue", 2), ok_op(0, "enqueue", 2))],
    )
    assert run.n_decomposed == 0 and run.sub_ctx is None


def test_merge_carries_oracle_partition_count():
    """A mixed-route decomposed history must not hide its oracle load:
    merge_partition_results counts oracle-routed sub-verdicts so
    routing accounting (bench --decompose, decompose-smoke) sees
    engine='mixed' rows."""
    merged = decompose.merge_partition_results([
        ("a", {"valid?": True, "engine": "tpu", "kernel": "dense"}),
        ("b", {"valid?": True, "engine": "oracle"}),
    ])
    assert merged["engine"] == "mixed"
    assert merged["oracle-partitions"] == 1
    merged_f = decompose.merge_partition_results([
        ("a", {"valid?": False, "engine": "oracle-budget"}),
        ("b", {"valid?": True, "engine": "tpu"}),
    ])
    assert merged_f["failed-partition"] == "a"
    assert merged_f["oracle-partitions"] == 1
    clean = decompose.merge_partition_results([
        ("a", {"valid?": True, "engine": "tpu", "kernel": "dense"}),
    ])
    assert "oracle-partitions" not in clean


def test_engine_decomposition_disabled_by_env(monkeypatch):
    model = m.multi_register({0: 0, 1: 0})
    hist = h(
        invoke_op(0, "txn", [("w", 0, 5)]), ok_op(0, "txn", [("w", 0, 5)]),
        invoke_op(1, "txn", [("r", 1, 0)]), ok_op(1, "txn", [("r", 1, 0)]),
    )
    monkeypatch.setenv("JEPSEN_TPU_ENGINE_DECOMPOSE", "0")
    out = wgl.check_batch(model, [hist])[0]
    assert "partitions" not in out
    monkeypatch.delenv("JEPSEN_TPU_ENGINE_DECOMPOSE")
    out2 = wgl.check_batch(model, [hist])[0]
    assert out2["partitions"] == 2
    assert out["valid?"] is out2["valid?"] is True


def test_decomposed_wide_keyspace_moves_off_the_oracle():
    """The routing claim: a keyspace whose product automaton is
    unencodable (CPU-oracle-bound) checks on the dense kernel once
    decomposed."""
    rng = random.Random(9)
    model = m.multi_register({k: 0 for k in range(12)})
    hists = [
        generate_mr_history(rng, n_procs=4, n_ops=30, n_keys=12,
                            n_values=3, crash_p=0.0)
        for _ in range(4)
    ]
    und = wgl.check_batch(model, hists, decomposed=False)
    assert all(r["engine"] == "oracle-fallback" for r in und)
    dec = wgl.check_batch(model, hists)
    assert all(r["engine"] == "tpu" and r["kernel"] == "dense"
               for r in dec)
    assert [r["valid?"] for r in dec] == [r["valid?"] for r in und]


# ---------------------------------------------------------------------------
# the service path
# ---------------------------------------------------------------------------


def test_service_parity_and_wire_form_for_decomposed_models():
    from jepsen_tpu.serve import CheckerDaemon, ServiceClient, protocol

    mm = m.multi_mutex()
    wire = protocol.model_from_wire(
        protocol.decode_body(protocol.encode_body(
            protocol.model_to_wire(m.MultiMutex(frozenset({"a"})))))
    )
    assert wire == m.MultiMutex(frozenset({"a"}))

    rng = random.Random(3)
    hists = [
        gen_multi_mutex_history(rng, n_locks=3, n_ops=16,
                                corrupt=(i % 2 == 0))
        for i in range(4)
    ]
    expected = wgl.check_batch(mm, hists, slot_cap=32)
    daemon = CheckerDaemon(port=0)
    daemon.start(block=False)
    try:
        got = ServiceClient(port=daemon.port).check_batch(
            mm, hists, slot_cap=32)
        assert [(r.get("valid?"), r.get("partitions"),
                 r.get("failed-partition")) for r in got] == [
            (r.get("valid?"), r.get("partitions"),
             r.get("failed-partition")) for r in expected
        ]
    finally:
        daemon.stop()


def test_lazy_feed_is_incremental_and_matches_eager():
    """The streaming split (pipeline stage 0): a lazy DecomposedRun
    classifies histories one at a time — after the first feed step
    only the first history's partitions exist — and a fully-driven
    lazy run ends in exactly the eager run's state."""
    rng = random.Random(11)
    hists = [
        generate_mr_history(rng, n_procs=3, n_ops=12, n_keys=3,
                            n_values=4, crash_p=0.0, corrupt=(i == 1))
        for i in range(4)
    ]
    model = m.multi_register({k: 0 for k in range(3)})
    eager = decompose.DecomposedRun(model, hists)

    lazy = decompose.DecomposedRun(model, hists, lazy=True)
    feed = lazy.feed()
    first_ctx, first_idx = next(feed)
    # only history 0 is split so far: the serial-preamble behavior
    # (split everything, then plan) is gone
    assert lazy.n_decomposed + len(lazy._pass_idx) == 1
    assert first_idx == 0
    seen = [(first_ctx, first_idx)] + list(feed)
    # same partition structure, same sub-histories, same order
    assert lazy.n_decomposed == eager.n_decomposed
    assert lazy.n_partitions == eager.n_partitions
    assert lazy._pass_idx == eager._pass_idx
    assert {k: [s for s in v] for k, v in lazy._parts_of.items()} == {
        k: [s for s in v] for k, v in eager._parts_of.items()
    }
    assert len(seen) == sum(
        len(c.histories) for c in lazy.contexts
    )
    if eager.sub_ctx is not None:
        assert [list(h) for h in lazy.sub_ctx.histories] == [
            list(h) for h in eager.sub_ctx.histories
        ]


def test_lazy_feed_abandoned_midway_recovers_via_results():
    """A consumer that abandons the feed mid-way (error paths) still
    gets the complete split from results()/streams()."""
    rng = random.Random(12)
    hists = [
        generate_mr_history(rng, n_procs=3, n_ops=12, n_keys=3,
                            n_values=4, crash_p=0.0)
        for i in range(3)
    ]
    model = m.multi_register({k: 0 for k in range(3)})
    lazy = decompose.DecomposedRun(model, hists, lazy=True)
    next(lazy.feed())  # drive one step, then abandon
    eager = decompose.DecomposedRun(model, hists)
    assert len(lazy.streams()) == len(eager.streams())
    assert lazy.n_partitions == eager.n_partitions
